"""Runtime sanitizers: `GGRS_SANITIZE=1` turns "unexpected recompile",
"the tick path started allocating" and "something synced the device
mid-dispatch" from perf mysteries into pointed reports.

Three companions live here, sharing one lifecycle idea — *freeze* at
the end of warmup, then treat any violation of the steady-state
contract as a recorded (or raised) event with provenance:

  RetraceSanitizer      wraps jax.jit; post-freeze compiles are flagged
                        with call-site stacks (details below).
  AllocationSanitizer   `freeze_allocations()` budgets net allocator
                        growth per host tick (sys.getallocatedblocks
                        delta); a tick that exceeds the budget records a
                        flight event with a tracemalloc top-5 diff and
                        bumps `ggrs_alloc_budget_trips_total`; every
                        tick feeds the `ggrs_alloc_per_tick` histogram.
                        Trips record, never raise — the host keeps
                        serving while the operator gets the leak's
                        provenance.
  transfer_guard_scope  wraps the post-warmup dispatch/drive regions;
                        while the retrace sanitizer is installed AND
                        frozen, an implicit device->host read
                        (`ArrayImpl._value` / `.item`, i.e. float(),
                        bool(), np.asarray-via-__array__ on a device
                        buffer) raises typed ImplicitHostTransfer with
                        the call site, and jax's own
                        transfer_guard_device_to_host("disallow") is
                        entered for device backends where the XLA layer
                        sees transfers Python can't. Known gap:
                        `np.asarray` on a fully-replicated CPU array
                        can take the buffer-protocol fast path without
                        touching `_value`; on real device backends the
                        jax guard covers it.

Retrace sanitizer detail:

The static pass (TRC004) catches per-call jit caches it can see; this is
the dynamic complement. When installed, `jax.jit` is wrapped so every
returned compiled function is a thin proxy that, after each call, checks
the underlying compile-cache size: growth means a trace just happened,
and the sanitizer records WHO (the jitted function), WHERE (the
non-jax stack frames of the call site) and WHEN (the running compile
index). After `freeze()` — called at the end of warmup, when every
program the steady state dispatches is supposed to exist — any further
compile is an *unexpected recompile*: it lands in the flight recorder,
increments `ggrs_recompiles_total` (both exporters, `host.telemetry()`
snapshots), and is listed with full provenance in `report()`.

`check_dispatch_budget` is the mid-serve assertion the megabatch layer
calls (MultiSessionDeviceCore.dispatch): the (row bucket x depth bucket)
grid bounds the jit cache at `dispatch_bucket_budget()` programs, and
with the sanitizer active a dispatch that grows past the bound raises
RetraceBudgetExceeded naming every compile that got it there — instead
of silently compiling mid-serve until the fleet stalls.

Overhead when not installed: zero (nothing is patched). Installed, each
jitted call pays one `_cache_size()` read. Install/uninstall are
idempotent and restore the original `jax.jit`, so tests can sandwich a
scenario without leaking the patch.
"""

from __future__ import annotations

import os
import sys
import tracemalloc
import traceback
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..errors import ImplicitHostTransfer, RetraceBudgetExceeded


@dataclass
class CompileEvent:
    index: int  # running compile count across all sanitized functions
    fn_name: str
    fn_compiles: int  # this function's cache size after the compile
    after_freeze: bool
    stack: List[str] = field(default_factory=list)  # "file:line in func"

    def provenance(self) -> str:
        return self.stack[-1] if self.stack else "<unknown>"

    def render(self) -> str:
        tag = "RECOMPILE" if self.after_freeze else "compile"
        lines = [
            f"[{self.index}] {tag} of {self.fn_name} "
            f"(cache size now {self.fn_compiles})"
        ]
        lines.extend(f"    at {frame}" for frame in self.stack[-6:])
        return "\n".join(lines)


def _call_stack() -> List[str]:
    frames = []
    for f in traceback.extract_stack():
        fn = f.filename
        if "/jax/" in fn or "jax_graft" in fn or fn.endswith("sanitize.py"):
            continue
        frames.append(f"{fn}:{f.lineno} in {f.name}")
    return frames


class _SanitizedJit:
    """Proxy over one jitted function: forwards everything, watches the
    compile-cache size after each call."""

    def __init__(self, inner: Any, sanitizer: "RetraceSanitizer", name: str):
        self._ggrs_inner = inner
        self._ggrs_sanitizer = sanitizer
        self._ggrs_name = name
        self._ggrs_seen = 0

    def __call__(self, *args, **kwargs):
        out = self._ggrs_inner(*args, **kwargs)
        self._ggrs_note()
        return out

    def _ggrs_note(self) -> None:
        size_fn = getattr(self._ggrs_inner, "_cache_size", None)
        if size_fn is None:
            return
        n = size_fn()
        while self._ggrs_seen < n:
            self._ggrs_seen += 1
            self._ggrs_sanitizer._on_compile(self._ggrs_name, self._ggrs_seen)

    def _cache_size(self) -> int:
        size_fn = getattr(self._ggrs_inner, "_cache_size", None)
        return size_fn() if size_fn else 0

    def __getattr__(self, name):
        return getattr(self._ggrs_inner, name)


class RetraceSanitizer:
    def __init__(self):
        self.events: List[CompileEvent] = []
        self.frozen_at: Optional[int] = None
        self.freeze_label: Optional[str] = None
        self._installed = False
        self._orig_jit = None
        self._m_compiles = None
        self._m_recompiles = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def install(self) -> "RetraceSanitizer":
        if self._installed:
            return self
        import jax

        from ..obs import GLOBAL_TELEMETRY

        reg = GLOBAL_TELEMETRY.registry
        self._m_compiles = reg.counter(
            "ggrs_program_compiles_total",
            "program compiles observed by the retrace sanitizer",
        )
        self._m_recompiles = reg.counter(
            "ggrs_recompiles_total",
            "compiles after the sanitizer froze (post-warmup steady state "
            "should never compile)",
        )
        self._orig_jit = jax.jit
        sanitizer = self

        def sanitized_jit(fun=None, **kwargs):
            if fun is None:
                # keyword-only partial form: jax.jit(static_argnums=...)(f)
                def bind(f):
                    return sanitized_jit(f, **kwargs)

                return bind
            inner = sanitizer._orig_jit(fun, **kwargs)
            name = getattr(fun, "__qualname__", None) or getattr(
                fun, "__name__", repr(fun)
            )
            return _SanitizedJit(inner, sanitizer, name)

        jax.jit = sanitized_jit
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        import jax

        jax.jit = self._orig_jit
        self._orig_jit = None
        self._installed = False

    @property
    def installed(self) -> bool:
        return self._installed

    # ------------------------------------------------------------------
    # recording
    # ------------------------------------------------------------------

    def _on_compile(self, fn_name: str, fn_compiles: int) -> None:
        from ..obs import GLOBAL_TELEMETRY

        after_freeze = self.frozen_at is not None
        ev = CompileEvent(
            index=len(self.events) + 1,
            fn_name=fn_name,
            fn_compiles=fn_compiles,
            after_freeze=after_freeze,
            stack=_call_stack(),
        )
        self.events.append(ev)
        tel = GLOBAL_TELEMETRY
        if tel.enabled:
            self._m_compiles.inc()
            tel.record(
                "program_compile", fn=fn_name, compiles=fn_compiles,
                provenance=ev.provenance(),
            )
            if after_freeze:
                self._m_recompiles.inc()
                tel.record(
                    "unexpected_recompile", fn=fn_name,
                    compiles=fn_compiles, provenance=ev.provenance(),
                    frozen_label=self.freeze_label,
                )

    def freeze(self, label: str = "steady-state") -> None:
        """Declare warmup complete: every compile from here on is an
        unexpected recompile."""
        self.frozen_at = len(self.events)
        self.freeze_label = label

    def thaw(self) -> None:
        self.frozen_at = None
        self.freeze_label = None

    # ------------------------------------------------------------------
    # queries / assertions
    # ------------------------------------------------------------------

    @property
    def compiles(self) -> List[CompileEvent]:
        return list(self.events)

    @property
    def recompiles(self) -> List[CompileEvent]:
        return [e for e in self.events if e.after_freeze]

    def check_dispatch_budget(
        self, fns: Dict[str, Any], budget: int, context: str = "dispatch"
    ) -> None:
        """Assert the summed compile-cache sizes of `fns` stay within
        `budget` programs; raise RetraceBudgetExceeded with per-compile
        provenance otherwise."""
        sizes = {
            name: getattr(fn, "_cache_size", lambda: 0)()
            for name, fn in fns.items()
        }
        total = sum(sizes.values())
        if total <= budget:
            return
        relevant = [
            e for e in self.events
            if any(e.fn_name.endswith(name) for name in sizes)
        ] or self.events
        trail = "\n".join(e.render() for e in relevant[-24:])
        raise RetraceBudgetExceeded(
            f"{context}: {total} compiled programs across {sizes} exceed "
            f"the dispatch-bucket budget ({budget}); the jit cache is no "
            f"longer bounded by the (row x depth) grid.\nCompile trail:\n"
            f"{trail}"
        )

    def report(self) -> str:
        lines = [
            f"retrace sanitizer: {len(self.events)} compiles observed"
            + (
                f", {len(self.recompiles)} after freeze "
                f"('{self.freeze_label}')"
                if self.frozen_at is not None
                else " (never frozen)"
            )
        ]
        for e in self.events:
            lines.append(e.render())
        return "\n".join(lines)

    def reset(self) -> None:
        self.events.clear()
        self.frozen_at = None
        self.freeze_label = None


_SANITIZER: Optional[RetraceSanitizer] = None


def install_sanitizer() -> RetraceSanitizer:
    global _SANITIZER
    if _SANITIZER is None:
        _SANITIZER = RetraceSanitizer()
    _SANITIZER.install()
    return _SANITIZER


def uninstall_sanitizer() -> None:
    if _SANITIZER is not None:
        _SANITIZER.uninstall()


def active_sanitizer() -> Optional[RetraceSanitizer]:
    """The installed sanitizer, or None (the common, zero-cost case)."""
    s = _SANITIZER
    return s if s is not None and s.installed else None


@contextmanager
def warmup_scope(label: str):
    """THE warmup protocol, in one place: lift any standing freeze for
    the duration of a backend's warmup (a later backend compiling its
    grid is legitimate, not a mid-serve recompile), then re-freeze under
    `label` on exit EVEN IF THE WARMUP RAISES — a process that keeps
    serving other warm cores must keep recompile detection armed, not
    silently disarm it exactly when something went wrong. A no-op
    (including the re-freeze) when no sanitizer is installed."""
    san = active_sanitizer()
    if san is not None:
        san.thaw()
    try:
        yield
    finally:
        # looked up again: the sanitizer may have been installed or
        # uninstalled while the warmup ran
        san = active_sanitizer()
        if san is not None:
            san.freeze(label)


def maybe_install_from_env() -> Optional[RetraceSanitizer]:
    """`GGRS_SANITIZE=1` opts the process in; called from
    ggrs_tpu.tpu.__init__ so every device-backend entry point is wrapped
    before any program is built."""
    if os.environ.get("GGRS_SANITIZE") == "1":
        return install_sanitizer()
    return None


# ----------------------------------------------------------------------
# allocation sanitizer — the dynamic complement to the ALLOC pass
# ----------------------------------------------------------------------

# steady-state headroom in allocator blocks per tick: the tick path's
# contract is zero *retained* allocation, but transient churn (event
# dicts handed to the caller, device-array wrappers replacing last
# tick's) nets out with jitter, and tracemalloc itself books traces.
# A leak regresses by thousands of blocks per tick, so the default sits
# an order of magnitude above observed steady-state noise while staying
# an order below any real regression.
DEFAULT_ALLOC_BUDGET_BLOCKS = 512


@dataclass
class AllocTripEvent:
    tick: int        # sanitizer-local tick index (since freeze)
    blocks: int      # net allocator-block growth this tick
    budget: int
    label: str
    top: List[str] = field(default_factory=list)  # "file:line +sizeKiB (+N blocks)"

    def provenance(self) -> str:
        return self.top[0] if self.top else "<no tracemalloc diff>"

    def render(self) -> str:
        lines = [
            f"[tick {self.tick}] ALLOC BUDGET TRIP: +{self.blocks} blocks "
            f"(budget {self.budget}, frozen as '{self.label}')"
        ]
        lines.extend(f"    {t}" for t in self.top)
        return "\n".join(lines)


class AllocationSanitizer:
    """Per-tick allocation budget for the post-warmup steady state.

    `freeze(label)` snapshots `sys.getallocatedblocks()` and starts
    tracemalloc; each `note_tick()` (SessionHost.tick calls it once per
    cycle) books the net block delta into `ggrs_alloc_per_tick` and,
    when the delta exceeds the budget, records a flight event carrying
    the tracemalloc top-5 growth sites since the last clean point, bumps
    `ggrs_alloc_budget_trips_total`, and REBASES — one leaking callsite
    produces a trip per leaking tick, each pointing at the fresh growth,
    not one giant diff that smears provenance across the run."""

    def __init__(self, budget_blocks: Optional[int] = None):
        env = os.environ.get("GGRS_ALLOC_BUDGET")
        self.budget = (
            budget_blocks if budget_blocks is not None
            else int(env) if env else DEFAULT_ALLOC_BUDGET_BLOCKS
        )
        self.trips: List[AllocTripEvent] = []
        self.ticks_seen = 0
        self.freeze_label: Optional[str] = None
        self._frozen = False
        self._last_blocks = 0
        self._base_snapshot = None
        self._started_tracemalloc = False
        self._m_per_tick = None
        self._m_trips = None

    # -- lifecycle ------------------------------------------------------

    def freeze(self, label: str = "steady-state") -> "AllocationSanitizer":
        from ..obs import GLOBAL_TELEMETRY, LOG2_BUCKETS

        reg = GLOBAL_TELEMETRY.registry
        self._m_per_tick = reg.histogram(
            "ggrs_alloc_per_tick",
            "net allocator-block growth per host tick post-freeze "
            "(negative deltas clip to 0)",
            buckets=LOG2_BUCKETS,
        )
        self._m_trips = reg.counter(
            "ggrs_alloc_budget_trips_total",
            "host ticks whose net allocation exceeded the frozen budget",
        )
        self.freeze_label = label
        # a freeze opens a new steady-state epoch: stats from an earlier
        # freeze (a previous backend's serve, a previous test) are that
        # epoch's story, not this one's
        self.trips.clear()
        self.ticks_seen = 0
        if not tracemalloc.is_tracing():
            tracemalloc.start()
            self._started_tracemalloc = True
        self._base_snapshot = tracemalloc.take_snapshot()
        self._last_blocks = sys.getallocatedblocks()
        self._frozen = True
        return self

    def thaw(self) -> None:
        self._frozen = False
        self.freeze_label = None
        self._base_snapshot = None
        if self._started_tracemalloc and tracemalloc.is_tracing():
            tracemalloc.stop()
        self._started_tracemalloc = False

    @property
    def frozen(self) -> bool:
        return self._frozen

    # -- the per-tick probe (host hot path: no allocation off-trip) -----

    def note_tick(self) -> None:
        if not self._frozen:
            return
        now = sys.getallocatedblocks()
        delta = now - self._last_blocks
        self._last_blocks = now
        if delta < 0:
            delta = 0
        self.ticks_seen += 1
        self._m_per_tick.observe(delta)
        if delta > self.budget:
            self._trip_alloc_budget(delta)

    def _trip_alloc_budget(self, delta: int) -> None:
        """Cold arm: tracemalloc diff, flight event, rebase."""
        from ..obs import GLOBAL_TELEMETRY

        top: List[str] = []
        if self._base_snapshot is not None:
            snap = tracemalloc.take_snapshot()
            stats = snap.compare_to(self._base_snapshot, "lineno")
            for st in stats:
                if st.size_diff <= 0:
                    continue
                tb = st.traceback[0]
                fn = tb.filename
                if fn.endswith("sanitize.py") or "tracemalloc" in fn:
                    continue  # the probe's own bookkeeping is not the leak
                top.append(
                    f"{fn}:{tb.lineno} +{st.size_diff / 1024:.1f}KiB "
                    f"(+{st.count_diff} blocks)"
                )
                if len(top) >= 5:
                    break
            self._base_snapshot = snap  # rebase: next trip diffs fresh growth
        ev = AllocTripEvent(
            tick=self.ticks_seen, blocks=delta, budget=self.budget,
            label=self.freeze_label or "", top=top,
        )
        self.trips.append(ev)
        self._m_trips.inc()
        tel = GLOBAL_TELEMETRY
        if tel.enabled:
            tel.record(
                "alloc_budget_trip", tick=self.ticks_seen, blocks=delta,
                budget=self.budget, provenance=ev.provenance(),
            )
        # the block count moved while we took the snapshot; re-anchor so
        # the NEXT tick is charged for its own growth only
        self._last_blocks = sys.getallocatedblocks()

    def report(self) -> str:
        lines = [
            f"allocation sanitizer: {self.ticks_seen} ticks observed, "
            f"{len(self.trips)} budget trip(s) "
            f"(budget {self.budget} blocks/tick)"
        ]
        lines.extend(ev.render() for ev in self.trips)
        return "\n".join(lines)


_ALLOC_SANITIZER: Optional[AllocationSanitizer] = None


def freeze_allocations(
    budget_blocks: Optional[int] = None, label: str = "steady-state"
) -> AllocationSanitizer:
    """Declare warmup complete for the ALLOCATOR: from here, every host
    tick is budgeted. Call after the backend's warmup_scope closes (the
    first ticks through a cold core legitimately allocate programs,
    pools and rings). Idempotent: re-freezing re-anchors the baseline."""
    global _ALLOC_SANITIZER
    if _ALLOC_SANITIZER is None:
        _ALLOC_SANITIZER = AllocationSanitizer(budget_blocks)
    elif budget_blocks is not None:
        _ALLOC_SANITIZER.budget = budget_blocks
    _ALLOC_SANITIZER.freeze(label)
    return _ALLOC_SANITIZER


def thaw_allocations() -> None:
    if _ALLOC_SANITIZER is not None:
        _ALLOC_SANITIZER.thaw()


def active_alloc_sanitizer() -> Optional[AllocationSanitizer]:
    """The frozen allocation sanitizer, or None (the zero-cost case —
    the host tick's probe is one None check)."""
    s = _ALLOC_SANITIZER
    return s if s is not None and s.frozen else None


# ----------------------------------------------------------------------
# transfer guard — implicit device->host syncs become typed errors
# ----------------------------------------------------------------------

# module state rather than a class: the patch target (ArrayImpl) is
# process-global, so the guard is too. depth counts nested scopes; the
# class methods are swapped in when the first scope opens and restored
# when the last closes, so an unsanitized process never pays for it.
_TRANSFER_DEPTH = 0
_TRANSFER_ORIG_VALUE = None
_TRANSFER_ORIG_ITEM = None
_TRANSFER_CLS = None
_M_TRANSFER_TRIPS = None


def _transfer_trip(api: str, context: str) -> None:
    from ..obs import GLOBAL_TELEMETRY

    frames = _call_stack()
    prov = frames[-1] if frames else "<unknown>"
    tel = GLOBAL_TELEMETRY
    if tel.enabled:
        if _M_TRANSFER_TRIPS is not None:
            _M_TRANSFER_TRIPS.inc()
        tel.record(
            "implicit_host_transfer", api=api, context=context,
            provenance=prov,
        )
    raise ImplicitHostTransfer(
        f"implicit device->host transfer via {api} inside the "
        f"post-warmup '{context}' region at {prov} — a host read here "
        "serializes the dispatch pipeline; stage through the pooled "
        "host buffers (mailbox/drain pass) or move the read off the "
        "tick path"
    )


def _patch_transfer_guard(context: str) -> None:
    global _TRANSFER_ORIG_VALUE, _TRANSFER_ORIG_ITEM, _TRANSFER_CLS
    from jax._src import array as jax_array

    cls = jax_array.ArrayImpl
    orig_value = cls.__dict__.get("_value")
    orig_item = cls.__dict__.get("item")
    _TRANSFER_CLS = cls
    _TRANSFER_ORIG_VALUE = orig_value
    _TRANSFER_ORIG_ITEM = orig_item
    fget = orig_value.fget if isinstance(orig_value, property) else orig_value

    def _guarded_value(self):
        if _TRANSFER_DEPTH > 0:
            _transfer_trip("ArrayImpl._value", context)
        return fget(self)

    def _guarded_item(self, *args):
        if _TRANSFER_DEPTH > 0:
            _transfer_trip("ArrayImpl.item", context)
        return orig_item(self, *args)

    cls._value = property(_guarded_value)
    if orig_item is not None:
        cls.item = _guarded_item


def _unpatch_transfer_guard() -> None:
    global _TRANSFER_ORIG_VALUE, _TRANSFER_ORIG_ITEM, _TRANSFER_CLS
    cls = _TRANSFER_CLS
    if cls is None:
        return
    if _TRANSFER_ORIG_VALUE is not None:
        cls._value = _TRANSFER_ORIG_VALUE
    if _TRANSFER_ORIG_ITEM is not None:
        cls.item = _TRANSFER_ORIG_ITEM
    _TRANSFER_CLS = None
    _TRANSFER_ORIG_VALUE = None
    _TRANSFER_ORIG_ITEM = None


@contextmanager
def transfer_guard_scope(context: str = "dispatch"):
    """Guard a dispatch/drive region against implicit device->host
    syncs. Active ONLY when the retrace sanitizer is installed
    (GGRS_SANITIZE=1) AND frozen — during warmup, jax itself reads
    buffers while compiling, and an unsanitized process takes the
    no-patch fast path (one global read, no allocation).

    Two layers: the ArrayImpl patch catches Python-visible reads
    (float()/bool()/.item()/__array__ -> _value) on EVERY backend
    including CPU, where jax's own guard exempts same-device transfers;
    jax's transfer_guard_device_to_host("disallow") additionally covers
    XLA-level implicit transfers on real device backends. Explicit
    jax.device_get stays legal under the jax guard — the drain pass's
    pooled readback is the sanctioned path (it runs outside this
    scope)."""
    global _TRANSFER_DEPTH
    san = active_sanitizer()
    if san is None or san.frozen_at is None:
        yield
        return
    import jax

    global _M_TRANSFER_TRIPS
    if _M_TRANSFER_TRIPS is None:
        from ..obs import GLOBAL_TELEMETRY

        _M_TRANSFER_TRIPS = GLOBAL_TELEMETRY.registry.counter(
            "ggrs_transfer_guard_trips_total",
            "implicit device->host transfers caught inside guarded "
            "post-warmup dispatch/drive regions",
        )
    _TRANSFER_DEPTH += 1
    if _TRANSFER_DEPTH == 1:
        _patch_transfer_guard(context)
    try:
        with jax.transfer_guard_device_to_host("disallow"):
            yield
    finally:
        _TRANSFER_DEPTH -= 1
        if _TRANSFER_DEPTH == 0:
            _unpatch_transfer_guard()
