"""Frame-advantage averaging for client speed throttling
(reference: src/time_sync.rs)."""

from __future__ import annotations

from .obs import FRAME_ADVANTAGE_BUCKETS, GLOBAL_TELEMETRY

FRAME_WINDOW_SIZE = 30


class TimeSync:
    """Sliding windows of local/remote frame advantage; the average drives
    WaitRecommendation events (src/time_sync.rs:3-39)."""

    def __init__(self, peer_label: str = "?") -> None:
        self.local = [0] * FRAME_WINDOW_SIZE
        self.remote = [0] * FRAME_WINDOW_SIZE
        # telemetry: the raw advantage distribution per peer — the average
        # below feeds throttling, the histogram shows how skewed the raw
        # samples are (a wide distribution means flappy pacing)
        self._m_advantage = GLOBAL_TELEMETRY.registry.histogram(
            "ggrs_frame_advantage",
            "per-sample local frame advantage vs this peer",
            ("peer",),
            buckets=FRAME_ADVANTAGE_BUCKETS,
        ).labels(peer_label)

    def advance_frame(self, frame: int, local_adv: int, remote_adv: int) -> None:
        self.local[frame % FRAME_WINDOW_SIZE] = local_adv
        self.remote[frame % FRAME_WINDOW_SIZE] = remote_adv
        if GLOBAL_TELEMETRY.enabled:
            self._m_advantage.observe(local_adv)

    def average_frame_advantage(self) -> int:
        local_avg = sum(self.local) / FRAME_WINDOW_SIZE
        remote_avg = sum(self.remote) / FRAME_WINDOW_SIZE
        # meet in the middle; truncation toward zero matches the reference's
        # `as i32` cast (src/time_sync.rs:30-39)
        return int((remote_avg - local_avg) / 2.0)
