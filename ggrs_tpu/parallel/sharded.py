"""Sharded-world rollback: entity-sharded state + beam-sharded speculation
over a device mesh, with the checksum as an explicit cross-shard psum.

This is the multi-chip configuration (BASELINE.json configs[4]: 64k-component
state over 4 chips with a psum checksum): the world's SoA arrays are sharded
over the `entity` mesh axis, candidate input futures over the `beam` axis.
The step function itself is embarrassingly parallel over entities (no
cross-entity interactions in the flagship model), so the only collective in
the hot loop is the checksum reduction — exactly the shape that rides ICI
well. GSPMD partitions the jitted scan from the input shardings; the
checksum's cross-shard sum is additionally expressed explicitly with
shard_map + psum in `sharded_checksum` for the desync-detection path.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.fixed_point import GOLDEN32

# jax moved shard_map from jax.experimental to the top level; depending on
# the installed version only one spelling exists (0.4.x raises
# AttributeError on jax.shard_map through its deprecation machinery). THE
# one compat alias — every shard_map consumer in the package imports it
# from here instead of hardcoding a spelling.
try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, **kwargs):
        # the experimental module predates the check_rep -> check_vma
        # rename: translate so call sites can use the modern spelling
        # (dropping the flag instead is NOT equivalent — legacy
        # check_rep=True hits NotImplementedError on these bodies)
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental_shard_map(f, **kwargs)


def state_specs(state):
    """PartitionSpec pytree for a game-state pytree: entity arrays split
    over the `entity` axis on axis 0, scalars replicated. THE sharded-state
    placement policy as specs — shard_state places with it, and every
    shard_map consumer (ShardedPallasTiledCore, ShardedPallasTickCore)
    must build its in/out specs from here so the contract can't drift."""
    return jax.tree.map(lambda x: P("entity") if x.ndim >= 1 else P(), state)


def ring_specs(ring):
    """PartitionSpec pytree for a snapshot-ring pytree (state leaves with a
    leading slot axis): entity dims split over `entity` on axis 1, per-slot
    scalars replicated. The ring twin of `state_specs`."""
    return jax.tree.map(
        lambda x: P(None, "entity") if x.ndim >= 2 else P(), ring
    )


def shard_state(state, mesh: Mesh):
    """Place a game-state pytree on the mesh per `state_specs` (every
    consumer — ResimCore, TpuSyncTestSession, the beam rollout — must route
    through here or `shard_ring` so the contract can't drift between
    components): every non-scalar state leaf has entities on axis 0,
    divisible by the `entity` axis size."""
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        state,
        state_specs(state),
    )


def shard_ring(ring, mesh: Mesh):
    """Place a snapshot-ring pytree on the mesh per `ring_specs` — the
    ring twin of `shard_state`."""
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        ring,
        ring_specs(ring),
    )


def entity_shardable(num_entities: int, mesh: Mesh, lane: int = 128) -> bool:
    """THE divisibility rule for running one local entity-tiled pallas
    kernel per mesh device: the world must split into `entity`-axis shards
    of 128-lane-aligned size. Shared by ResimCore's backend auto-selection
    and the sharded cores' constructor asserts so the two can't drift."""
    if "entity" not in mesh.axis_names:
        return False
    return num_entities % (mesh.shape["entity"] * lane) == 0


def sharded_checksum(state, mesh: Mesh, keys=None):
    """Order-invariant checksum of an entity-sharded state with an explicit
    psum across the `entity` axis (the on-device replacement for the
    reference's host-side fletcher16, ex_game.rs:42-52).

    Bit-identical to the single-device `_checksum_generic`: word weights run
    continuously across the model's concatenation order `keys` + frame
    using GLOBAL word indices, and the replicated `frame` scalar is folded
    in exactly once (on entity-shard 0) — so a sharded peer and a
    single-chip peer exchanging desync-detection reports always agree.
    `keys` must be the model's declared checksum order (its
    `checksum_keys` class attribute, e.g. ExGame.checksum_keys — the same
    source _checksum_generic reads); defaults to ex_game's.
    """
    if keys is None:
        from ..models.ex_game import CHECKSUM_KEYS as keys
    keys = list(keys)
    offsets = {}
    off = 0
    for k in keys:
        offsets[k] = off
        off += int(np.prod(state[k].shape))
    frame_offset = off

    entity_state = {k: state[k] for k in keys}
    flat_specs = {k: P("entity") for k in keys}

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(flat_specs, P()),
        out_specs=(P(), P()),
    )
    def _cs(local_state, frame):
        idx = jax.lax.axis_index("entity")
        hi = jnp.uint32(0)
        lo = jnp.uint32(0)
        for k in keys:
            # axis-0 sharding + row-major flatten => shard s owns the
            # contiguous global word range [s*n_local, (s+1)*n_local)
            words = local_state[k].astype(jnp.uint32).reshape(-1)
            n_local = words.shape[0]
            start = jnp.uint32(offsets[k]) + idx.astype(jnp.uint32) * jnp.uint32(n_local)
            gidx = jnp.arange(n_local, dtype=jnp.uint32) + start + jnp.uint32(1)
            hi = hi + jnp.sum(words * (gidx * GOLDEN32), dtype=jnp.uint32)
            lo = lo + jnp.sum(words, dtype=jnp.uint32)
        # frame is replicated: fold it in on one shard only
        fw = frame.astype(jnp.uint32)
        fg = jnp.uint32(frame_offset + 1)
        on_shard0 = (idx == 0).astype(jnp.uint32)
        hi = hi + on_shard0 * (fw * (fg * GOLDEN32))
        lo = lo + on_shard0 * fw
        hi = jax.lax.psum(hi, "entity")
        lo = jax.lax.psum(lo, "entity")
        return hi, lo

    return _cs(entity_state, state["frame"])


# ---------------------------------------------------------------------------
# stacked (serving) placement: the session axis of MultiSessionDeviceCore's
# stacked pytrees split over a `session` mesh axis, entity arrays optionally
# split further over `entity`. THE placement policy for the sharded serving
# core — ShardedMultiSessionDeviceCore places with these specs and every
# consumer (host scheduler affinity, the explicit checksum pass, tests)
# derives shard geometry from the same functions so the contract can't
# drift from the single-world policy above.
# ---------------------------------------------------------------------------


def _mesh_has_entity(mesh: Mesh) -> bool:
    return "entity" in mesh.axis_names and mesh.shape["entity"] > 1


def stacked_state_specs(stacked_state, mesh: Mesh):
    """PartitionSpec pytree for a STACKED game-state pytree (leading
    session axis on every leaf): sessions split over `session` on axis 0;
    entity arrays (ndim >= 2) additionally split over `entity` on axis 1
    when the mesh carries one. The serving twin of `state_specs`."""
    ent = _mesh_has_entity(mesh)
    return jax.tree.map(
        lambda x: P("session", "entity") if ent and x.ndim >= 2 else P("session"),
        stacked_state,
    )


def stacked_ring_specs(stacked_ring, mesh: Mesh):
    """PartitionSpec pytree for a STACKED snapshot-ring pytree (leading
    session axis, then the ring-slot axis): sessions over `session`,
    entity dims (ndim >= 3) over `entity` on axis 2, ring slots always
    local. The serving twin of `ring_specs`."""
    ent = _mesh_has_entity(mesh)
    return jax.tree.map(
        lambda x: (
            P("session", None, "entity") if ent and x.ndim >= 3 else P("session")
        ),
        stacked_ring,
    )


def shard_stacked_state(stacked_state, mesh: Mesh):
    """Place a stacked game-state pytree on the mesh per
    `stacked_state_specs`. The leading (session) axis must divide the
    `session` axis size — the sharded core pads its dummy-slot tail so
    it does."""
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        stacked_state,
        stacked_state_specs(stacked_state, mesh),
    )


def shard_stacked_ring(stacked_ring, mesh: Mesh):
    """Place a stacked snapshot-ring pytree on the mesh per
    `stacked_ring_specs` — the ring twin of `shard_stacked_state`."""
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        stacked_ring,
        stacked_ring_specs(stacked_ring, mesh),
    )


def mailbox_spec() -> P:
    """Partition spec for the device-resident input mailbox's [S, K, L]
    row ring (and its [S] watermark vector): the slot axis splits over
    the mesh's `session` axis, virtual-tick and control-word axes stay
    local — a lane's whole fill cycle lives with the shard that owns its
    world, so the resident driver's per-vtick row reads never cross
    ICI."""
    return P("session")


def shard_mailbox(rows, mesh: Mesh):
    """Place a mailbox row ring (or watermark vector) on the mesh per
    `mailbox_spec` — the resident-loop twin of `shard_stacked_state`."""
    return jax.device_put(rows, NamedSharding(mesh, mailbox_spec()))


def stacked_sharded_checksum(stacked_state, mesh: Mesh, keys=None):
    """Per-slot order-invariant checksums of a session-stacked (and
    optionally entity-sharded) state pytree, with the cross-shard word
    reduction expressed EXPLICITLY as shard_map + psum over the `entity`
    axis — the stacked twin of `sharded_checksum`, and the serving
    core's desync-detection spot-check for big entity-sharded worlds
    (the megabatch programs' own [B, W] checksums ride the same
    concat-free partial sums under GSPMD; this pass pins the collective
    shape by hand so a partitioner regression is caught against it).

    Returns (hi[S], lo[S]) uint32 arrays, slot-aligned with the stack.
    Bit-identical to vmapping the model's `_checksum_generic` over the
    slots: word weights run continuously across `keys` + frame with
    GLOBAL word indices, and the replicated `frame` scalar folds in
    exactly once (on entity-shard 0). `keys` defaults to ex_game's
    declared checksum order."""
    if keys is None:
        from ..models.ex_game import CHECKSUM_KEYS as keys
    keys = list(keys)
    ent = _mesh_has_entity(mesh)
    offsets = {}
    off = 0
    for k in keys:
        offsets[k] = off
        off += int(np.prod(stacked_state[k].shape[1:]))
    frame_offset = off

    entity_state = {k: stacked_state[k] for k in keys}
    in_state_specs = {
        k: (P("session", "entity") if ent else P("session")) for k in keys
    }

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(in_state_specs, P("session")),
        out_specs=(P("session"), P("session")),
    )
    def _cs(local_state, frames):
        eidx = jax.lax.axis_index("entity") if ent else jnp.uint32(0)
        s_loc = frames.shape[0]
        hi = jnp.zeros((s_loc,), jnp.uint32)
        lo = jnp.zeros((s_loc,), jnp.uint32)
        for k in keys:
            # entity axis-0-of-the-slot sharding + row-major flatten =>
            # entity shard e owns the contiguous per-slot word range
            # [e * n_local, (e + 1) * n_local) of this key
            words = local_state[k].astype(jnp.uint32).reshape(s_loc, -1)
            n_local = words.shape[1]
            start = (
                jnp.uint32(offsets[k])
                + eidx.astype(jnp.uint32) * jnp.uint32(n_local)
            )
            gidx = jnp.arange(n_local, dtype=jnp.uint32) + start + jnp.uint32(1)
            hi = hi + jnp.sum(
                words * (gidx * GOLDEN32)[None, :], axis=1, dtype=jnp.uint32
            )
            lo = lo + jnp.sum(words, axis=1, dtype=jnp.uint32)
        # frame is replicated across entity shards: fold in on shard 0 only
        fw = frames.astype(jnp.uint32)
        fg = jnp.uint32(frame_offset + 1)
        on_shard0 = (eidx == 0).astype(jnp.uint32)
        hi = hi + on_shard0 * (fw * (fg * GOLDEN32))
        lo = lo + on_shard0 * fw
        if ent:
            hi = jax.lax.psum(hi, "entity")
            lo = jax.lax.psum(lo, "entity")
        return hi, lo

    return _cs(entity_state, stacked_state["frame"])


def make_sharded_beam_rollout(game, mesh: Mesh, window: int):
    """jit-compiled W-frame beam rollout over a (beam x entity) mesh.

    state: entity-sharded pytree (replicated across beam)
    beam_inputs u8[B, W, P, I], beam_statuses i32[B, W, P]: beam-sharded
    returns final states [B, ...] (beam x entity sharded) and per-beam
    checksums (via GSPMD-partitioned reduction).
    """

    def rollout_one(state, inputs, statuses):
        def body(s, xs):
            inp, stat = xs
            return game.step(s, inp, stat), None

        final, _ = jax.lax.scan(body, state, (inputs, statuses))
        hi, lo = game.checksum(final)
        return final, hi, lo

    vmapped = jax.vmap(rollout_one, in_axes=(None, 0, 0))
    beam_sharding = NamedSharding(mesh, P("beam"))

    @jax.jit
    def run(state, beam_inputs, beam_statuses):
        beam_inputs = jax.lax.with_sharding_constraint(beam_inputs, beam_sharding)
        beam_statuses = jax.lax.with_sharding_constraint(beam_statuses, beam_sharding)
        return vmapped(state, beam_inputs, beam_statuses)

    return run
