"""Device-mesh construction helpers for the sharded rollback configs."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Tuple[str, str] = ("beam", "entity"),
    beam_axis: Optional[int] = None,
) -> Mesh:
    """Build a 2D (beam x entity) mesh over the first n devices.

    `beam` is the speculative-universe axis (data-parallel analog: replicated
    world, different input futures). `entity` shards the world state itself
    (tensor-parallel analog). Collectives over `entity` (the checksum psum)
    ride ICI; the beam axis needs no communication at all.
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    assert n <= len(devices), f"requested {n} devices, have {len(devices)}"
    if beam_axis is None:
        beam_axis = 2 if n % 2 == 0 and n > 2 else 1
    assert n % beam_axis == 0
    dev_array = np.asarray(devices[:n]).reshape(beam_axis, n // beam_axis)
    return Mesh(dev_array, axis_names)


def make_session_mesh(
    n_devices: Optional[int] = None, entity_axis: int = 1
) -> Mesh:
    """Build the SERVING mesh: a 2D (session x entity) mesh over the
    first n devices.

    `session` splits the stacked session worlds of
    ShardedMultiSessionDeviceCore (data-parallel analog: independent
    worlds, no communication on this axis). `entity_axis` > 1 additionally
    shards each world's entity arrays (tensor-parallel analog, for big
    worlds) — the per-slot checksum reduction is then the only collective
    in the hot loop and rides ICI, exactly like the single-world `entity`
    axis of `make_mesh`."""
    devices = jax.devices()
    n = n_devices or len(devices)
    assert n <= len(devices), f"requested {n} devices, have {len(devices)}"
    assert entity_axis >= 1 and n % entity_axis == 0, (
        f"entity_axis {entity_axis} must divide the {n}-device mesh"
    )
    dev_array = np.asarray(devices[:n]).reshape(n // entity_axis, entity_axis)
    return Mesh(dev_array, ("session", "entity"))
