"""Input compression: XOR delta against a reference input, then byte-level RLE.

Same two-stage scheme as the reference (src/network/compression.rs:3-57):
consecutive frames of input are usually near-identical, so XORing every
pending input against the last acked input yields mostly zero bytes, which
run-length encoding then collapses. The RLE container is our own format
(the reference uses the bitfield-rle crate): a token stream of
LEB128 varints `v` where `v & 3` selects {0: literal bytes follow,
1: run of 0x00, 2: run of 0xFF} and `v >> 2` is the length. A C++
implementation of the identical format lives in native/ (used when built;
this module is the always-available fallback and the format oracle).
"""

from __future__ import annotations

from typing import Iterable, List

from ..errors import DataFormatError


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, off: int) -> tuple[int, int]:
    shift = 0
    v = 0
    while True:
        if off >= len(buf):
            raise DataFormatError("truncated varint")
        b = buf[off]
        off += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, off
        shift += 7
        if shift > 35:
            raise DataFormatError("varint too long")


TOKEN_LITERAL = 0
TOKEN_ZEROS = 1
TOKEN_ONES = 2

# Runs shorter than this are cheaper inline as literals.
_MIN_RUN = 3


def rle_encode(data: bytes) -> bytes:
    """Collapse runs of 0x00 / 0xFF; everything else is literal."""
    out = bytearray()
    n = len(data)
    i = 0
    lit_start = 0

    def flush_literal(end: int) -> None:
        nonlocal lit_start
        while lit_start < end:
            chunk = min(end - lit_start, 1 << 20)
            _write_varint(out, (chunk << 2) | TOKEN_LITERAL)
            out.extend(data[lit_start : lit_start + chunk])
            lit_start += chunk

    while i < n:
        b = data[i]
        if b == 0x00 or b == 0xFF:
            j = i + 1
            while j < n and data[j] == b:
                j += 1
            run = j - i
            if run >= _MIN_RUN:
                flush_literal(i)
                token = TOKEN_ZEROS if b == 0x00 else TOKEN_ONES
                _write_varint(out, (run << 2) | token)
                i = j
                lit_start = i
                continue
            i = j
        else:
            i += 1
    flush_literal(n)
    return bytes(out)


# Decoded-output ceiling for untrusted streams: a few bytes of hostile RLE
# can claim a multi-gigabyte run (a decompression bomb), so decoding is
# always bounded. Protocol callers pass a tight wire-derived limit.
MAX_DECODE_OUTPUT = 1 << 26


def rle_decode(data: bytes, max_output: int = MAX_DECODE_OUTPUT) -> bytes:
    out = bytearray()
    off = 0
    while off < len(data):
        v, off = _read_varint(data, off)
        kind = v & 3
        length = v >> 2
        if len(out) + length > max_output:
            raise DataFormatError("decoded output exceeds limit")
        if kind == TOKEN_LITERAL:
            if off + length > len(data):
                raise DataFormatError("truncated literal run")
            out += data[off : off + length]
            off += length
        elif kind == TOKEN_ZEROS:
            out += b"\x00" * length
        elif kind == TOKEN_ONES:
            out += b"\xff" * length
        else:
            raise DataFormatError("invalid RLE token")
    return bytes(out)


def delta_encode(reference: bytes, pending: Iterable[bytes]) -> bytes:
    """XOR each pending input against the same reference
    (src/network/compression.rs:13-30)."""
    out = bytearray()
    for inp in pending:
        assert len(inp) == len(reference), "input size mismatch"
        out += bytes(a ^ b for a, b in zip(reference, inp))
    return bytes(out)


def delta_decode(reference: bytes, data: bytes) -> List[bytes]:
    """(src/network/compression.rs:49-57)"""
    if len(reference) == 0 or len(data) % len(reference) != 0:
        raise DataFormatError(
            "delta payload not a multiple of the reference size"
        )
    out = []
    for i in range(0, len(data), len(reference)):
        chunk = data[i : i + len(reference)]
        out.append(bytes(a ^ b for a, b in zip(reference, chunk)))
    return out


def encode(reference: bytes, pending: Iterable[bytes]) -> bytes:
    """delta + RLE (src/network/compression.rs:3-11). Dispatches to the C++
    kernels when built (native/); this module is the format oracle."""
    from .. import native as _native

    if _native.available():
        return _native.rle_encode(_native.delta_encode(reference, list(pending)))
    return rle_encode(delta_encode(reference, pending))


def decode(
    reference: bytes, data: bytes, max_output: int = MAX_DECODE_OUTPUT
) -> List[bytes]:
    """(src/network/compression.rs:32-40). `max_output` bounds the decoded
    size — pass the largest legitimate payload when decoding wire data."""
    from .. import native as _native

    if _native.available():
        raw = _native.rle_decode(data, max_len=max_output)
        return _native.delta_decode(reference, raw)
    return delta_decode(reference, rle_decode(data, max_output))
