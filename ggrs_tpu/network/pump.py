"""Batched wire pump: fleet-wide decode/apply/send in pooled passes.

The per-message hot path the per-tick loops used to pay —
`decode_message`'s dataclass construction, one struct unpack per field,
one `handle_message` per datagram, one `sendto` per queued message — is
replaced with one POOLED pass per pump cycle. Every datagram received
this pass lands in one staging byte pool; headers and fixed-size bodies
are extracted with vectorized numpy gathers, ONE pass per message type
(the wire twin of tpu/backend.py's plan-cached one-pass request parser);
the decoded fields are then applied to the owning endpoints in arrival
order through `PeerEndpoint.handle_decoded`, so no Message/dataclass
objects exist on the hot path at all. Sends mirror it: every endpoint's
queued wire drains into one per-socket batch shipped via
`send_wire_batch` (a sendmmsg-style drain: one Python call, N
datagrams).

Decode order is free (decoding is pure), apply order is not: records are
applied in per-socket arrival order, so every endpoint state machine
sees exactly the sequence the legacy per-message loop fed it. Bit parity
with the legacy path is by construction — `handle_decoded` and
`handle_message` share the same appliers — and pinned by
tests/test_wire_pump.py's fuzz/parity suite.

Fence note (analysis/fence.py FEN001): the pooled offset/length scratch
in `PumpStaging` is shared mutable state reused across pump passes; only
`batch_decode` (via `PumpStaging.ensure`) may grow or rebind it. The
byte pool itself is each pass's joined datagram buffer (immutable
bytes), so field gathers and payload slices can alias it safely.
"""

from __future__ import annotations

import struct as _struct
import time as _time
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..errors import GGRSError
from ..obs import GLOBAL_TELEMETRY, LOG2_BUCKETS, LOG2_BUCKETS_MS
from .messages import (
    MSG_CHECKSUM_REPORT,
    MSG_INPUT,
    MSG_INPUT_ACK,
    MSG_KEEP_ALIVE,
    MSG_QUALITY_REPLY,
    MSG_QUALITY_REPORT,
    MSG_SYNC_REPLY,
    MSG_SYNC_REQUEST,
    WIRE_CHECKSUM_BODY_SIZE,
    WIRE_HEADER_SIZE,
    WIRE_INPUT_HEAD_SIZE,
    WIRE_STATUS_SIZE,
)

# fixed body sizes (bytes past the 3-byte header) per message type; INPUT
# is variable (head + n_status * status + u16-length-prefixed payload)
_FIXED_BODY = {
    MSG_SYNC_REQUEST: 4,
    MSG_SYNC_REPLY: 4,
    MSG_INPUT_ACK: 4,
    MSG_QUALITY_REPORT: 9,
    MSG_QUALITY_REPLY: 8,
    MSG_CHECKSUM_REPORT: WIRE_CHECKSUM_BODY_SIZE,
    MSG_KEEP_ALIVE: 0,
}

# packed little-endian connect-status entry: disconnected u8 + last_frame
# i32 — itemsize must equal the wire layout or the vectorized status
# decode below would stride off the format
_STATUS_DTYPE = np.dtype([("disc", "u1"), ("last", "<i4")])
assert _STATUS_DTYPE.itemsize == WIRE_STATUS_SIZE

# scalar decode structs (the small-pass twin below)
_HDR_AT = _struct.Struct("<HB").unpack_from
_U32_AT = _struct.Struct("<I").unpack_from
_I32_AT = _struct.Struct("<i").unpack_from
_U64_AT = _struct.Struct("<Q").unpack_from
_QREPORT_AT = _struct.Struct("<bQ").unpack_from
_INPUT_HEAD_AT = _struct.Struct("<iiBB").unpack_from
_STATUS_ITER = _struct.Struct("<Bi").iter_unpack

# passes at or below this many datagrams decode scalar: numpy's fixed
# per-op cost (~15 array ops minimum) dwarfs a handful of messages —
# measured ~10x SLOWER than struct unpacks at 3 datagrams, ~2.4x FASTER
# at 512. The crossover sits around a few dozen; idle test meshes and
# single low-traffic sessions live far below it, hosted fleets far above.
SMALL_BATCH = 24


def decode_record(wire: bytes) -> Optional[tuple]:
    """Scalar twin of batch_decode for small passes: same record layout
    (kind, magic, a, b, c, statuses, payload), same drop semantics, no
    numpy and no Message/dataclass objects — just struct unpacks."""
    n = len(wire)
    if n < WIRE_HEADER_SIZE:
        return None
    magic, kind = _HDR_AT(wire, 0)
    body = _FIXED_BODY.get(kind)
    if body is not None:
        if n < WIRE_HEADER_SIZE + body:
            return None
        if kind == MSG_INPUT_ACK:
            return (kind, magic, _I32_AT(wire, 3)[0], 0, 0, (), b"")
        if kind == MSG_QUALITY_REPORT:
            adv, ping = _QREPORT_AT(wire, 3)
            return (kind, magic, adv, ping, 0, (), b"")
        if kind == MSG_QUALITY_REPLY:
            return (kind, magic, _U64_AT(wire, 3)[0], 0, 0, (), b"")
        if kind in (MSG_SYNC_REQUEST, MSG_SYNC_REPLY):
            return (kind, magic, _U32_AT(wire, 3)[0], 0, 0, (), b"")
        if kind == MSG_CHECKSUM_REPORT:
            return (
                kind, magic, _I32_AT(wire, 3)[0],
                int.from_bytes(wire[7:23], "little"), 0, (), b"",
            )
        return (kind, magic, 0, 0, 0, (), b"")  # MSG_KEEP_ALIVE
    if kind == MSG_INPUT:
        if n < WIRE_HEADER_SIZE + WIRE_INPUT_HEAD_SIZE:
            return None
        sf, af, fl, ns = _INPUT_HEAD_AT(wire, 3)
        so = WIRE_HEADER_SIZE + WIRE_INPUT_HEAD_SIZE
        po = so + ns * WIRE_STATUS_SIZE
        if po + 2 > n:
            return None  # truncated statuses / length prefix
        blen = wire[po] | (wire[po + 1] << 8)
        pe = po + 2 + blen
        if pe > n:
            return None  # truncated input payload
        statuses = (
            tuple(_STATUS_ITER(wire[so:po])) if ns else ()
        )
        return (MSG_INPUT, magic, sf, af, fl, statuses, wire[po + 2 : pe])
    return None  # unknown body type


class PumpStaging:
    """Pooled decode staging: offset/length scratch grown geometrically
    and reused for every pump pass (the byte pool itself is the pass's
    joined datagram buffer — one C-speed join, viewed zero-copy)."""

    __slots__ = ("offs", "lens")

    def __init__(self, msgs: int = 256):
        self.offs = np.empty(msgs + 1, dtype=np.int64)
        self.lens = np.empty(msgs, dtype=np.int64)

    def ensure(self, n_msgs: int) -> None:
        if self.lens.shape[0] < n_msgs:
            cap = self.lens.shape[0]
            while cap < n_msgs:
                cap *= 2
            self.offs = np.empty(cap + 1, dtype=np.int64)
            self.lens = np.empty(cap, dtype=np.int64)


def _gather(pool: np.ndarray, starts: np.ndarray, size: int) -> np.ndarray:
    """[N, size] uint8 matrix of `size` bytes at each start offset — a
    fancy-index COPY (contiguous), safe to .view() typed fields out of."""
    return pool[starts[:, None] + np.arange(size, dtype=np.int64)]


def batch_decode(
    datagrams: Sequence[Tuple[Any, Any, bytes]],
    staging: Optional[PumpStaging] = None,
) -> List[Optional[tuple]]:
    """One-pass batched decode of a whole pump pass's datagrams.

    `datagrams` is [(tag, addr, wire)] in arrival order (tag/addr are
    opaque routing keys the caller uses at apply time). Returns a list
    parallel to the input: entry i is None when datagram i is
    undecodable (same drop semantics as messages.decode_all — short
    packet, unknown body type, truncated body), else the record tuple

        (kind, magic, a, b, c, statuses, payload)

    whose positional fields match PeerEndpoint.handle_decoded: `a`/`b`/
    `c` carry the type's scalar fields (e.g. INPUT: a=start_frame,
    b=ack_frame, c=flags; CHECKSUM_REPORT: a=frame, b=checksum),
    `statuses` is [(disconnected, last_frame)] and `payload` the
    compressed input bytes for INPUT messages, else ()/b""."""
    n = len(datagrams)
    records: List[Optional[tuple]] = [None] * n
    if n == 0:
        return records
    staging = staging if staging is not None else _SHARED_STAGING

    # staging fill: ONE C-speed join into the pass's byte pool (a Python
    # per-datagram copy loop costs more than the whole vectorized decode)
    # + pooled offset/length scratch
    wires = [w for _, _, w in datagrams]
    joined = b"".join(wires)
    pool = np.frombuffer(joined, dtype=np.uint8)
    staging.ensure(n)
    offs, lens = staging.offs, staging.lens
    lens_n = lens[:n]
    lens_n[:] = [len(w) for w in wires]
    offs[0] = 0
    np.cumsum(lens_n, out=offs[1 : n + 1])
    offs_n = offs[:n]
    valid = np.flatnonzero(lens_n >= WIRE_HEADER_SIZE)
    if valid.shape[0] == 0:
        return records
    vo = offs_n[valid]
    magic = pool[vo].astype(np.int64) | (pool[vo + 1].astype(np.int64) << 8)
    btype = pool[vo + 2]

    # -- fixed-size bodies: one vectorized extraction pass per type ----
    for kind, body in _FIXED_BODY.items():
        sel = btype == kind
        if not sel.any():
            continue
        ok = sel & (lens_n[valid] >= WIRE_HEADER_SIZE + body)
        idxs = valid[ok]
        if idxs.shape[0] == 0:
            continue
        starts = offs_n[idxs] + WIRE_HEADER_SIZE
        mags = magic[ok].tolist()
        rows = idxs.tolist()
        if kind in (MSG_SYNC_REQUEST, MSG_SYNC_REPLY):
            vals = _gather(pool, starts, 4).view("<u4").ravel().tolist()
            for i, m, v in zip(rows, mags, vals):
                records[i] = (kind, m, v, 0, 0, (), b"")
        elif kind == MSG_INPUT_ACK:
            vals = _gather(pool, starts, 4).view("<i4").ravel().tolist()
            for i, m, v in zip(rows, mags, vals):
                records[i] = (kind, m, v, 0, 0, (), b"")
        elif kind == MSG_QUALITY_REPORT:
            advs = pool[starts].astype(np.int8).tolist()
            pings = _gather(pool, starts + 1, 8).view("<u8").ravel().tolist()
            for i, m, adv, ping in zip(rows, mags, advs, pings):
                records[i] = (kind, m, adv, ping, 0, (), b"")
        elif kind == MSG_QUALITY_REPLY:
            vals = _gather(pool, starts, 8).view("<u8").ravel().tolist()
            for i, m, v in zip(rows, mags, vals):
                records[i] = (kind, m, v, 0, 0, (), b"")
        elif kind == MSG_CHECKSUM_REPORT:
            frames = _gather(pool, starts, 4).view("<i4").ravel().tolist()
            for i, m, f, st in zip(rows, mags, frames, starts.tolist()):
                records[i] = (
                    kind, m, f,
                    int.from_bytes(joined[st + 4 : st + 20], "little"),
                    0, (), b"",
                )
        else:  # MSG_KEEP_ALIVE
            for i, m in zip(rows, mags):
                records[i] = (kind, m, 0, 0, 0, (), b"")

    # -- INPUT: vectorized head, per-message statuses + payload --------
    sel = (btype == MSG_INPUT) & (
        lens_n[valid] >= WIRE_HEADER_SIZE + WIRE_INPUT_HEAD_SIZE
    )
    idxs = valid[sel]
    if idxs.shape[0]:
        starts = offs_n[idxs] + WIRE_HEADER_SIZE
        head = _gather(pool, starts, WIRE_INPUT_HEAD_SIZE)
        start_frames = head[:, 0:4].copy().view("<i4").ravel().tolist()
        ack_frames = head[:, 4:8].copy().view("<i4").ravel().tolist()
        flags = head[:, 8].tolist()
        n_statuses = head[:, 9].tolist()
        mags = magic[sel].tolist()
        ends = (offs_n[idxs] + lens_n[idxs]).tolist()
        sstarts = (starts + WIRE_INPUT_HEAD_SIZE).tolist()
        for i, m, sf, af, fl, ns, so, end in zip(
            idxs.tolist(), mags, start_frames, ack_frames, flags,
            n_statuses, sstarts, ends,
        ):
            po = so + ns * WIRE_STATUS_SIZE
            if po + 2 > end:
                continue  # truncated statuses / length prefix
            blen = joined[po] | (joined[po + 1] << 8)
            pe = po + 2 + blen
            if pe > end:
                continue  # truncated input payload
            statuses = (
                pool[so:po].view(_STATUS_DTYPE).tolist() if ns else ()
            )
            records[i] = (
                MSG_INPUT, m, sf, af, fl, statuses,
                joined[po + 2 : pe],
            )
    return records


_SHARED_STAGING = PumpStaging()


def record_to_message(rec: tuple, wire: bytes):
    """Rebuild the legacy Message object a record denotes — the parity
    seam the fuzz suite compares against decode_all (never on the hot
    path)."""
    from ..sync_layer import ConnectionStatus
    from .messages import (
        ChecksumReport,
        InputAck,
        InputMsg,
        KeepAlive,
        Message,
        QualityReply,
        QualityReport,
        SyncReply,
        SyncRequest,
    )

    kind, magic, a, b, c, statuses, payload = rec
    if kind == MSG_SYNC_REQUEST:
        body = SyncRequest(a)
    elif kind == MSG_SYNC_REPLY:
        body = SyncReply(a)
    elif kind == MSG_INPUT:
        body = InputMsg(
            peer_connect_status=[
                ConnectionStatus(bool(d), f) for d, f in statuses
            ],
            disconnect_requested=bool(c & 1),
            start_frame=a,
            ack_frame=b,
            bytes_=payload,
        )
    elif kind == MSG_INPUT_ACK:
        body = InputAck(a)
    elif kind == MSG_QUALITY_REPORT:
        body = QualityReport(a, b)
    elif kind == MSG_QUALITY_REPLY:
        body = QualityReply(a)
    elif kind == MSG_CHECKSUM_REPORT:
        body = ChecksumReport(checksum=b, frame=a)
    elif kind == MSG_KEEP_ALIVE:
        body = KeepAlive()
    else:
        raise ValueError(f"unknown record kind {kind}")
    return Message(magic, body, _wire=bytes(wire))


def host_tax_histogram():
    """Get-or-create THE ggrs_host_tax_ms instrument — one definition
    shared by WirePump (phase=pump) and SessionHost (parse/drain), so
    the help text and buckets cannot drift between registration sites."""
    return GLOBAL_TELEMETRY.registry.histogram(
        "ggrs_host_tax_ms",
        "host-side tax per tick, split by phase "
        "(pump = socket drain + batched decode/apply + protocol "
        "timers + batched send; parse = request-grammar staging; "
        "drain = checksum-ledger/fence drains)",
        ("phase",),
        buckets=LOG2_BUCKETS_MS,
    )


class WirePump:
    """Reusable fleet pump: drain every session's socket, batch-decode
    the union in one pooled pass, apply records in arrival order, then
    run each session's timer/event phase and ship the queued sends as
    per-socket batches. One instance serves a whole SessionHost (or a
    single standalone session via the module-default pump).

    A session participates through three small hooks (P2PSession and
    SpectatorSession both provide them):
      - `_pump_routes()` -> {addr: ((endpoint, handle_decoded|None,
        handle_wire|None), ...)} — the per-address dispatch table;
      - `_pump_post(wire_out)` — frame-advantage update, endpoint
        timers, event handling, and send drain into `wire_out` (or the
        legacy per-message send when `wire_out` is None);
      - `socket` — must expose receive_all_wire/send_wire_batch for the
        batched path; anything else falls back to the session's legacy
        `_poll_legacy()` loop, unbatched but identical in behavior."""

    __slots__ = ("staging", "_m_batch", "_m_tax")

    def __init__(self):
        self.staging = PumpStaging()
        _reg = GLOBAL_TELEMETRY.registry
        self._m_batch = _reg.histogram(
            "ggrs_pump_batch_msgs",
            "datagrams decoded per batched pump pass",
            buckets=LOG2_BUCKETS,
        )
        self._m_tax = host_tax_histogram().labels("pump")

    def pump(
        self, sessions: Sequence[Any], isolate: bool = False
    ) -> List[Tuple[Any, Exception]]:
        """One batched pump pass over `sessions` (any mix of P2P and
        spectator sessions). With `isolate=False` (standalone use) a
        GGRSError from a session's protocol handlers propagates, exactly
        like the legacy per-session poll; `isolate=True` (SessionHost
        fleets) quarantines it to the raising session and returns the
        (session, error) pairs so the rest of the fleet keeps pumping."""
        tel = GLOBAL_TELEMETRY
        t0 = _time.perf_counter() if tel.enabled else 0.0
        errors: List[Tuple[Any, Exception]] = []

        datagrams: List[Tuple[int, Any, bytes]] = []
        batched: List[Any] = []
        for s in sessions:
            recv = getattr(s.socket, "receive_all_wire", None)
            if recv is None or not s.batched_pump:
                try:
                    s._poll_legacy()
                except GGRSError as exc:
                    if not isolate:
                        raise
                    errors.append((s, exc))
                continue
            si = len(batched)
            batched.append(s)
            for addr, wire in recv():
                datagrams.append((si, addr, wire))

        failed: set = set()
        if datagrams:
            if len(datagrams) <= SMALL_BATCH:
                records = [decode_record(w) for _, _, w in datagrams]
            else:
                records = batch_decode(datagrams, self.staging)
            route_cache: List[Optional[dict]] = [None] * len(batched)
            for (si, addr, wire), rec in zip(datagrams, records):
                if rec is None or si in failed:
                    continue
                routes = route_cache[si]
                if routes is None:
                    routes = route_cache[si] = batched[si]._pump_routes()
                try:
                    for _ep, fast, raw in routes.get(addr, ()):
                        if fast is not None:
                            fast(
                                rec[0], rec[1], len(wire),
                                rec[2], rec[3], rec[4], rec[5], rec[6],
                            )
                        elif raw is not None:
                            raw(wire)
                except GGRSError as exc:
                    if not isolate:
                        raise
                    failed.add(si)
                    errors.append((batched[si], exc))

        for si, s in enumerate(batched):
            if si in failed:
                continue
            try:
                sink = getattr(s.socket, "send_wire_batch", None)
                if sink is None:
                    s._pump_post(None)
                else:
                    out: List[Tuple[bytes, Any]] = []
                    s._pump_post(out)
                    if out:
                        sink(out)
            except GGRSError as exc:
                if not isolate:
                    raise
                errors.append((s, exc))

        if tel.enabled:
            self._m_batch.observe(len(datagrams))
            self._m_tax.observe((_time.perf_counter() - t0) * 1000.0)
        return errors


# module-default pump: standalone sessions (no SessionHost) share one —
# the staging pool then serves every session in the process exactly as
# the host's does for its fleet
GLOBAL_PUMP = WirePump()
