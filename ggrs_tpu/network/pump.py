"""Batched wire pump: fleet-wide decode/apply/send in pooled passes.

The per-message hot path the per-tick loops used to pay —
`decode_message`'s dataclass construction, one struct unpack per field,
one `handle_message` per datagram, one `sendto` per queued message — is
replaced with one POOLED pass per pump cycle. Every datagram received
this pass lands in one staging byte pool; headers and fixed-size bodies
are extracted with vectorized numpy gathers, ONE pass per message type
(the wire twin of tpu/backend.py's plan-cached one-pass request parser);
the decoded fields are then applied to the owning endpoints in arrival
order through `PeerEndpoint.handle_decoded`, so no Message/dataclass
objects exist on the hot path at all. Sends mirror it: every endpoint's
queued wire drains into one per-socket batch shipped via
`send_wire_batch` (a sendmmsg-style drain: one Python call, N
datagrams).

Decode order is free (decoding is pure), apply order is not: records are
applied in per-socket arrival order, so every endpoint state machine
sees exactly the sequence the legacy per-message loop fed it. Bit parity
with the legacy path is by construction — `handle_decoded` and
`handle_message` share the same appliers — and pinned by
tests/test_wire_pump.py's fuzz/parity suite.

Fence note (analysis/fence.py FEN001): the pooled offset/length scratch
in `PumpStaging` is shared mutable state reused across pump passes; only
`batch_decode` (via `PumpStaging.ensure`) may grow or rebind it. The
byte pool itself is each pass's joined datagram buffer (immutable
bytes), so field gathers and payload slices can alias it safely.
"""

from __future__ import annotations

import struct as _struct
import time as _time
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..errors import DataFormatError, GGRSError
from ..obs import GLOBAL_TELEMETRY, LOG2_BUCKETS, LOG2_BUCKETS_MS
from .endpoint_batch import SMALL_FLEET, EndpointFleet
from .messages import (
    MSG_CHECKSUM_REPORT,
    MSG_INPUT,
    MSG_INPUT_ACK,
    MSG_KEEP_ALIVE,
    MSG_QUALITY_REPLY,
    MSG_QUALITY_REPORT,
    MSG_SYNC_REPLY,
    MSG_SYNC_REQUEST,
    WIRE_CHECKSUM_BODY_SIZE,
    WIRE_HEADER_SIZE,
    WIRE_INPUT_HEAD_SIZE,
    WIRE_STATUS_SIZE,
)

# fixed body sizes (bytes past the 3-byte header) per message type; INPUT
# is variable (head + n_status * status + u16-length-prefixed payload)
_FIXED_BODY = {
    MSG_SYNC_REQUEST: 4,
    MSG_SYNC_REPLY: 4,
    MSG_INPUT_ACK: 4,
    MSG_QUALITY_REPORT: 9,
    MSG_QUALITY_REPLY: 8,
    MSG_CHECKSUM_REPORT: WIRE_CHECKSUM_BODY_SIZE,
    MSG_KEEP_ALIVE: 0,
}

# packed little-endian connect-status entry: disconnected u8 + last_frame
# i32 — itemsize must equal the wire layout or the vectorized status
# decode below would stride off the format
_STATUS_DTYPE = np.dtype([("disc", "u1"), ("last", "<i4")])
assert _STATUS_DTYPE.itemsize == WIRE_STATUS_SIZE

# scalar decode structs (the small-pass twin below)
_HDR_AT = _struct.Struct("<HB").unpack_from
_U32_AT = _struct.Struct("<I").unpack_from
_I32_AT = _struct.Struct("<i").unpack_from
_U64_AT = _struct.Struct("<Q").unpack_from
_QREPORT_AT = _struct.Struct("<bQ").unpack_from
_INPUT_HEAD_AT = _struct.Struct("<iiBB").unpack_from
_STATUS_ITER = _struct.Struct("<Bi").iter_unpack

# passes at or below this many datagrams decode scalar: numpy's fixed
# per-op cost (~15 array ops minimum) dwarfs a handful of messages —
# measured ~10x SLOWER than struct unpacks at 3 datagrams, ~2.4x FASTER
# at 512. The crossover sits around a few dozen; idle test meshes and
# single low-traffic sessions live far below it, hosted fleets far above.
SMALL_BATCH = 24


def decode_record(wire: bytes) -> Optional[tuple]:
    """Scalar twin of batch_decode for small passes: same record layout
    (kind, magic, a, b, c, statuses, payload), same drop semantics, no
    numpy and no Message/dataclass objects — just struct unpacks."""
    n = len(wire)
    if n < WIRE_HEADER_SIZE:
        return None
    magic, kind = _HDR_AT(wire, 0)
    body = _FIXED_BODY.get(kind)
    if body is not None:
        if n < WIRE_HEADER_SIZE + body:
            return None
        if kind == MSG_INPUT_ACK:
            return (kind, magic, _I32_AT(wire, 3)[0], 0, 0, (), b"")
        if kind == MSG_QUALITY_REPORT:
            adv, ping = _QREPORT_AT(wire, 3)
            return (kind, magic, adv, ping, 0, (), b"")
        if kind == MSG_QUALITY_REPLY:
            return (kind, magic, _U64_AT(wire, 3)[0], 0, 0, (), b"")
        if kind in (MSG_SYNC_REQUEST, MSG_SYNC_REPLY):
            return (kind, magic, _U32_AT(wire, 3)[0], 0, 0, (), b"")
        if kind == MSG_CHECKSUM_REPORT:
            return (
                kind, magic, _I32_AT(wire, 3)[0],
                int.from_bytes(wire[7:23], "little"), 0, (), b"",
            )
        return (kind, magic, 0, 0, 0, (), b"")  # MSG_KEEP_ALIVE
    if kind == MSG_INPUT:
        if n < WIRE_HEADER_SIZE + WIRE_INPUT_HEAD_SIZE:
            return None
        sf, af, fl, ns = _INPUT_HEAD_AT(wire, 3)
        so = WIRE_HEADER_SIZE + WIRE_INPUT_HEAD_SIZE
        po = so + ns * WIRE_STATUS_SIZE
        if po + 2 > n:
            return None  # truncated statuses / length prefix
        blen = wire[po] | (wire[po + 1] << 8)
        pe = po + 2 + blen
        if pe > n:
            return None  # truncated input payload
        statuses = (
            tuple(_STATUS_ITER(wire[so:po])) if ns else ()
        )
        return (MSG_INPUT, magic, sf, af, fl, statuses, wire[po + 2 : pe])
    return None  # unknown body type


class PumpStaging:
    """Pooled decode staging: offset/length scratch grown geometrically
    and reused for every pump pass (the byte pool itself is the pass's
    joined datagram buffer — one C-speed join, viewed zero-copy)."""

    __slots__ = ("offs", "lens")

    def __init__(self, msgs: int = 256):
        self.offs = np.empty(msgs + 1, dtype=np.int64)
        self.lens = np.empty(msgs, dtype=np.int64)

    def ensure(self, n_msgs: int) -> None:
        if self.lens.shape[0] < n_msgs:
            cap = self.lens.shape[0]
            while cap < n_msgs:
                cap *= 2
            self.offs = np.empty(cap + 1, dtype=np.int64)
            self.lens = np.empty(cap, dtype=np.int64)


def _gather(pool: np.ndarray, starts: np.ndarray, size: int) -> np.ndarray:
    """[N, size] uint8 matrix of `size` bytes at each start offset — a
    fancy-index COPY (contiguous), safe to .view() typed fields out of."""
    return pool[starts[:, None] + np.arange(size, dtype=np.int64)]


def batch_decode(
    datagrams: Sequence[Tuple[Any, Any, bytes]],
    staging: Optional[PumpStaging] = None,
) -> List[Optional[tuple]]:
    """One-pass batched decode of a whole pump pass's datagrams.

    `datagrams` is [(tag, addr, wire)] in arrival order (tag/addr are
    opaque routing keys the caller uses at apply time). Returns a list
    parallel to the input: entry i is None when datagram i is
    undecodable (same drop semantics as messages.decode_all — short
    packet, unknown body type, truncated body), else the record tuple

        (kind, magic, a, b, c, statuses, payload)

    whose positional fields match PeerEndpoint.handle_decoded: `a`/`b`/
    `c` carry the type's scalar fields (e.g. INPUT: a=start_frame,
    b=ack_frame, c=flags; CHECKSUM_REPORT: a=frame, b=checksum),
    `statuses` is [(disconnected, last_frame)] and `payload` the
    compressed input bytes for INPUT messages, else ()/b""."""
    n = len(datagrams)
    records: List[Optional[tuple]] = [None] * n
    if n == 0:
        return records
    staging = staging if staging is not None else _SHARED_STAGING

    # staging fill: ONE C-speed join into the pass's byte pool (a Python
    # per-datagram copy loop costs more than the whole vectorized decode)
    # + pooled offset/length scratch
    wires = [w for _, _, w in datagrams]
    joined = b"".join(wires)
    pool = np.frombuffer(joined, dtype=np.uint8)
    staging.ensure(n)
    offs, lens = staging.offs, staging.lens
    lens_n = lens[:n]
    lens_n[:] = [len(w) for w in wires]
    offs[0] = 0
    np.cumsum(lens_n, out=offs[1 : n + 1])
    offs_n = offs[:n]
    valid = np.flatnonzero(lens_n >= WIRE_HEADER_SIZE)
    if valid.shape[0] == 0:
        return records
    vo = offs_n[valid]
    magic = pool[vo].astype(np.int64) | (pool[vo + 1].astype(np.int64) << 8)
    btype = pool[vo + 2]

    # -- fixed-size bodies: one vectorized extraction pass per type ----
    for kind, body in _FIXED_BODY.items():
        sel = btype == kind
        if not sel.any():
            continue
        ok = sel & (lens_n[valid] >= WIRE_HEADER_SIZE + body)
        idxs = valid[ok]
        if idxs.shape[0] == 0:
            continue
        starts = offs_n[idxs] + WIRE_HEADER_SIZE
        mags = magic[ok].tolist()
        rows = idxs.tolist()
        if kind in (MSG_SYNC_REQUEST, MSG_SYNC_REPLY):
            vals = _gather(pool, starts, 4).view("<u4").ravel().tolist()
            for i, m, v in zip(rows, mags, vals):
                records[i] = (kind, m, v, 0, 0, (), b"")
        elif kind == MSG_INPUT_ACK:
            vals = _gather(pool, starts, 4).view("<i4").ravel().tolist()
            for i, m, v in zip(rows, mags, vals):
                records[i] = (kind, m, v, 0, 0, (), b"")
        elif kind == MSG_QUALITY_REPORT:
            advs = pool[starts].astype(np.int8).tolist()
            pings = _gather(pool, starts + 1, 8).view("<u8").ravel().tolist()
            for i, m, adv, ping in zip(rows, mags, advs, pings):
                records[i] = (kind, m, adv, ping, 0, (), b"")
        elif kind == MSG_QUALITY_REPLY:
            vals = _gather(pool, starts, 8).view("<u8").ravel().tolist()
            for i, m, v in zip(rows, mags, vals):
                records[i] = (kind, m, v, 0, 0, (), b"")
        elif kind == MSG_CHECKSUM_REPORT:
            frames = _gather(pool, starts, 4).view("<i4").ravel().tolist()
            for i, m, f, st in zip(rows, mags, frames, starts.tolist()):
                records[i] = (
                    kind, m, f,
                    int.from_bytes(joined[st + 4 : st + 20], "little"),
                    0, (), b"",
                )
        else:  # MSG_KEEP_ALIVE
            for i, m in zip(rows, mags):
                records[i] = (kind, m, 0, 0, 0, (), b"")

    # -- INPUT: vectorized head, per-message statuses + payload --------
    sel = (btype == MSG_INPUT) & (
        lens_n[valid] >= WIRE_HEADER_SIZE + WIRE_INPUT_HEAD_SIZE
    )
    idxs = valid[sel]
    if idxs.shape[0]:
        starts = offs_n[idxs] + WIRE_HEADER_SIZE
        head = _gather(pool, starts, WIRE_INPUT_HEAD_SIZE)
        start_frames = head[:, 0:4].copy().view("<i4").ravel().tolist()
        ack_frames = head[:, 4:8].copy().view("<i4").ravel().tolist()
        flags = head[:, 8].tolist()
        n_statuses = head[:, 9].tolist()
        mags = magic[sel].tolist()
        ends = (offs_n[idxs] + lens_n[idxs]).tolist()
        sstarts = (starts + WIRE_INPUT_HEAD_SIZE).tolist()
        for i, m, sf, af, fl, ns, so, end in zip(
            idxs.tolist(), mags, start_frames, ack_frames, flags,
            n_statuses, sstarts, ends,
        ):
            po = so + ns * WIRE_STATUS_SIZE
            if po + 2 > end:
                continue  # truncated statuses / length prefix
            blen = joined[po] | (joined[po + 1] << 8)
            pe = po + 2 + blen
            if pe > end:
                continue  # truncated input payload
            statuses = (
                pool[so:po].view(_STATUS_DTYPE).tolist() if ns else ()
            )
            records[i] = (
                MSG_INPUT, m, sf, af, fl, statuses,
                joined[po + 2 : pe],
            )
    return records


_SHARED_STAGING = PumpStaging()


def record_to_message(rec: tuple, wire: bytes):
    """Rebuild the legacy Message object a record denotes — the parity
    seam the fuzz suite compares against decode_all (never on the hot
    path)."""
    from ..sync_layer import ConnectionStatus
    from .messages import (
        ChecksumReport,
        InputAck,
        InputMsg,
        KeepAlive,
        Message,
        QualityReply,
        QualityReport,
        SyncReply,
        SyncRequest,
    )

    kind, magic, a, b, c, statuses, payload = rec
    if kind == MSG_SYNC_REQUEST:
        body = SyncRequest(a)
    elif kind == MSG_SYNC_REPLY:
        body = SyncReply(a)
    elif kind == MSG_INPUT:
        body = InputMsg(
            peer_connect_status=[
                ConnectionStatus(bool(d), f) for d, f in statuses
            ],
            disconnect_requested=bool(c & 1),
            start_frame=a,
            ack_frame=b,
            bytes_=payload,
        )
    elif kind == MSG_INPUT_ACK:
        body = InputAck(a)
    elif kind == MSG_QUALITY_REPORT:
        body = QualityReport(a, b)
    elif kind == MSG_QUALITY_REPLY:
        body = QualityReply(a)
    elif kind == MSG_CHECKSUM_REPORT:
        body = ChecksumReport(checksum=b, frame=a)
    elif kind == MSG_KEEP_ALIVE:
        body = KeepAlive()
    else:
        raise DataFormatError(f"unknown record kind {kind}")
    return Message(magic, body, _wire=bytes(wire))


def host_tax_histogram():
    """Get-or-create THE ggrs_host_tax_ms instrument — one definition
    shared by WirePump (phase=pump/endpoint/encode) and SessionHost
    (parse/drain), so the help text and buckets cannot drift between
    registration sites."""
    return GLOBAL_TELEMETRY.registry.histogram(
        "ggrs_host_tax_ms",
        "host-side tax per tick, split by phase "
        "(pump = socket drain + batched decode/apply; endpoint = "
        "frame-advantage/timer/event/checksum phase, vectorized or "
        "scalar; encode = send drain + batched socket ship; parse = "
        "request-grammar staging; drain = checksum-ledger/fence drains)",
        ("phase",),
        buckets=LOG2_BUCKETS_MS,
    )


class WirePump:
    """Reusable fleet pump: drain every session's socket, batch-decode
    the union in one pooled pass, apply records in arrival order, then
    run each session's timer/event phase and ship the queued sends as
    per-socket batches. One instance serves a whole SessionHost (or a
    single standalone session via the module-default pump).

    A session participates through a few small hooks (P2PSession and
    SpectatorSession both provide them):
      - `_pump_routes()` -> {addr: ((endpoint, handle_decoded|None,
        handle_wire|None), ...)} — the per-address dispatch table;
      - `_pump_now()` — one hoisted clock read for the whole pass;
      - `_pump_endpoint(now)` / `_pump_encode(wire_out)` — the scalar
        timer/event phase and send drain (`_pump_post` composes them
        for the legacy loop);
      - `_fleet_size()` / `_fleet_profile()` / `_fleet_state` — the
        vectorized protocol plane's adoption seam (endpoint_batch.py);
      - `socket` — must expose receive_all_wire/send_wire_batch for the
        batched path; anything else falls back to the session's legacy
        `_poll_legacy()` loop, unbatched but identical in behavior;
      - `_pump_recv` — session-owned cache slot (init None) where the
        pump pins the bound `receive_all_wire` after first resolution.

    Endpoint-phase routing mirrors the decode crossover: passes with at
    least `small_fleet` endpoints run the fleet's one-array-program
    phases (adopting sessions on first contact); smaller passes — a
    standalone 2-peer session, a fleet-of-one host — keep the verbatim
    scalar twin, which is faster there for the same reason scalar
    decode wins below SMALL_BATCH. Cross-session phase ordering (all
    endpoint phases, then all encodes) is parity-safe: every receive
    already landed in the recv/apply phase above, sessions share no
    protocol state, and per-destination send order is preserved."""

    __slots__ = (
        "staging", "fleet", "small_fleet",
        "_m_batch", "_m_tax", "_m_tax_endpoint", "_m_tax_encode",
    )

    def __init__(self):
        self.staging = PumpStaging()
        self.fleet = EndpointFleet()
        self.small_fleet = SMALL_FLEET
        _reg = GLOBAL_TELEMETRY.registry
        self._m_batch = _reg.histogram(
            "ggrs_pump_batch_msgs",
            "datagrams decoded per batched pump pass",
            buckets=LOG2_BUCKETS,
        )
        _tax = host_tax_histogram()
        self._m_tax = _tax.labels("pump")
        self._m_tax_endpoint = _tax.labels("endpoint")
        self._m_tax_encode = _tax.labels("encode")

    def pump(
        self, sessions: Sequence[Any], isolate: bool = False
    ) -> List[Tuple[Any, Exception]]:
        """One batched pump pass over `sessions` (any mix of P2P and
        spectator sessions). With `isolate=False` (standalone use) a
        GGRSError from a session's protocol handlers propagates, exactly
        like the legacy per-session poll; `isolate=True` (SessionHost
        fleets) quarantines it to the raising session and returns the
        (session, error) pairs so the rest of the fleet keeps pumping."""
        tel = GLOBAL_TELEMETRY
        t0 = _time.perf_counter() if tel.enabled else 0.0
        errors: List[Tuple[Any, Exception]] = []

        datagrams: List[Tuple[int, Any, bytes]] = []
        batched: List[Any] = []
        for s in sessions:
            # bound receive_all_wire is cached on the session (sockets
            # are pinned at construction); sessions without the batch
            # hook re-resolve each pass on the legacy path
            recv = s._pump_recv
            if recv is None and s.batched_pump:
                recv = getattr(s.socket, "receive_all_wire", None)
                if recv is not None:
                    s._pump_recv = recv
            if recv is None or not s.batched_pump:
                try:
                    s._poll_legacy()
                except GGRSError as exc:
                    if not isolate:
                        raise
                    errors.append((s, exc))
                continue
            si = len(batched)
            batched.append(s)
            for addr, wire in recv():
                datagrams.append((si, addr, wire))

        # per-session hoisted clock: every timer/stats touch of this
        # pass — apply AND endpoint phase — observes one instant (read
        # lazily so sessions with independent clocks each get their own)
        nows: List[Optional[int]] = [None] * len(batched)
        failed: set = set()
        if datagrams:
            if len(datagrams) <= SMALL_BATCH:
                records = [decode_record(w) for _, _, w in datagrams]
            else:
                records = batch_decode(datagrams, self.staging)
            route_cache: List[Optional[dict]] = [None] * len(batched)
            for (si, addr, wire), rec in zip(datagrams, records):
                if rec is None or si in failed:
                    continue
                routes = route_cache[si]
                if routes is None:
                    routes = route_cache[si] = batched[si]._pump_routes()
                now = nows[si]
                if now is None:
                    now = nows[si] = batched[si]._pump_now()
                try:
                    for _ep, fast, raw in routes.get(addr, ()):
                        if fast is not None:
                            fast(
                                rec[0], rec[1], len(wire),
                                rec[2], rec[3], rec[4], rec[5], rec[6],
                                now,
                            )
                        elif raw is not None:
                            raw(wire)
                except GGRSError as exc:
                    if not isolate:
                        raise
                    failed.add(si)
                    errors.append((batched[si], exc))
        if tel.enabled:
            self._m_batch.observe(len(datagrams))
            t1 = _time.perf_counter()
            self._m_tax.observe((t1 - t0) * 1000.0)

        post: List[Tuple[Any, int]] = []
        # hosted fleets share one clock object: memoize the read so an
        # idle 64-session pump costs one now_ms, not 64 (each session's
        # cached `_pump_clock` makes the identity check safe — equal
        # clock object, equal instant, bit-identical to per-session reads)
        memo_clock: Any = None
        memo_now = 0
        for si, s in enumerate(batched):
            if si in failed:
                continue
            now = nows[si]
            if now is None:
                c = getattr(s, "_pump_clock", None)
                if c is not None and c is memo_clock:
                    now = memo_now
                else:
                    now = s._pump_now()
                    memo_clock = getattr(s, "_pump_clock", None)
                    memo_now = now
            post.append((s, now))

        # ---- endpoint phase: vectorized above the crossover ----------
        fleet = self.fleet
        fleet_sessions: List[Any] = []
        fleet_nows: List[int] = []
        scalar_sessions: List[Tuple[Any, int]] = []
        # crossover with hysteresis: the O(sessions) size sum only runs
        # while nothing is adopted; once the fleet is live, every pass
        # takes the fleet branch (adopt() itself is the identity check,
        # and retirement on detach drains live_sessions back to zero)
        if fleet.live_sessions or (
            sum(s._fleet_size() for s, _ in post) >= self.small_fleet
        ):
            for s, now in post:
                st = getattr(s, "_fleet_state", None)
                if st is not None and st.fleet is fleet:
                    fleet_sessions.append(s)
                    fleet_nows.append(now)
                elif fleet.adopt(s):
                    fleet_sessions.append(s)
                    fleet_nows.append(now)
                else:
                    scalar_sessions.append((s, now))
        else:
            scalar_sessions = post

        post_failed: set = set()
        if fleet_sessions:
            fleet.endpoint_phase(
                fleet_sessions, fleet_nows, isolate, errors, post_failed
            )
        for s, now in scalar_sessions:
            try:
                s._pump_endpoint(now)
            except GGRSError as exc:
                if not isolate:
                    raise
                post_failed.add(s)
                errors.append((s, exc))
        if tel.enabled:
            t2 = _time.perf_counter()
            self._m_tax_endpoint.observe((t2 - t1) * 1000.0)

        # ---- encode phase: drain queued sends into per-socket batches -
        if fleet_sessions:
            live = [
                s for s in fleet_sessions if s not in post_failed
            ]
            # quiescent pumps (no endpoint queued a send this pass) skip
            # the sink/out plumbing and the encode pass entirely
            if fleet.pending_sends(live):
                sinks = [
                    getattr(s.socket, "send_wire_batch", None)
                    for s in live
                ]
                outs: List[Optional[List[Tuple[bytes, Any]]]] = [
                    ([] if sink is not None else None) for sink in sinks
                ]
                fleet.encode_phase(live, outs, isolate, errors, post_failed)
                for s, sink, out in zip(live, sinks, outs):
                    if sink is not None and out and s not in post_failed:
                        sink(out)
        for s, _now in scalar_sessions:
            if s in post_failed:
                continue
            try:
                sink = getattr(s.socket, "send_wire_batch", None)
                if sink is None:
                    s._pump_encode(None)
                else:
                    out: List[Tuple[bytes, Any]] = []
                    s._pump_encode(out)
                    if out:
                        sink(out)
            except GGRSError as exc:
                if not isolate:
                    raise
                errors.append((s, exc))

        if tel.enabled:
            self._m_tax_encode.observe(
                (_time.perf_counter() - t2) * 1000.0
            )
        return errors


# module-default pump: standalone sessions (no SessionHost) share one —
# the staging pool then serves every session in the process exactly as
# the host's does for its fleet
GLOBAL_PUMP = WirePump()
