"""Connection-quality observability (reference: src/network/network_stats.rs).

Extended over the reference with the receive direction and link-quality
estimates: `kbps_recv` (received payload + UDP header bytes over the stats
window), `jitter_ms` (RFC 3550-style EWMA of RTT variation) and
`packets_lost` (estimated from gaps in the peer's fixed-cadence
quality-report stream — no wire-format change, so Python and native C++
peers interoperate unchanged).
"""

from __future__ import annotations

from dataclasses import dataclass


# IP + UDP header bytes per datagram, for kbps accounting (the one
# definition protocol.py and the native shim both rate against)
UDP_HEADER_SIZE = 28


@dataclass
class NetworkStats:
    """A point-in-time snapshot; field provenance under the vectorized
    protocol plane (network/endpoint_batch.py): `ping_ms`,
    `local_frames_behind` and `remote_frames_behind` read the fleet's
    hot columns through the endpoint's row view, the byte/packet rates
    and jitter/loss estimators stay per-endpoint scalars (touched only
    on actual message traffic, never scanned by the pump), so the
    snapshot is identical whether the endpoint is fleet-adopted or
    standalone."""

    send_queue_len: int = 0
    ping_ms: int = 0
    kbps_sent: int = 0
    local_frames_behind: int = 0
    remote_frames_behind: int = 0
    # receive direction + link-quality estimates (beyond the reference)
    kbps_recv: int = 0
    jitter_ms: int = 0
    packets_lost: int = 0

    @classmethod
    def from_endpoint(cls, ep, seconds: int) -> "NetworkStats":
        """Rate the endpoint's counters over a `seconds`-long window.
        Validation (sync state, window age) stays with the caller —
        this is pure field arithmetic, shared by every snapshot site."""
        total_sent = ep.bytes_sent + ep.packets_sent * UDP_HEADER_SIZE
        total_recv = ep.bytes_recv + ep.packets_recv * UDP_HEADER_SIZE
        return cls(
            send_queue_len=len(ep.pending_output),
            ping_ms=ep.round_trip_time,
            kbps_sent=(total_sent // int(seconds)) // 1024,
            local_frames_behind=ep.local_frame_advantage,
            remote_frames_behind=ep.remote_frame_advantage,
            kbps_recv=(total_recv // int(seconds)) // 1024,
            jitter_ms=int(round(ep.jitter_ms)),
            packets_lost=ep.packets_lost,
        )
