"""Connection-quality observability (reference: src/network/network_stats.rs).

Extended over the reference with the receive direction and link-quality
estimates: `kbps_recv` (received payload + UDP header bytes over the stats
window), `jitter_ms` (RFC 3550-style EWMA of RTT variation) and
`packets_lost` (estimated from gaps in the peer's fixed-cadence
quality-report stream — no wire-format change, so Python and native C++
peers interoperate unchanged).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class NetworkStats:
    send_queue_len: int = 0
    ping_ms: int = 0
    kbps_sent: int = 0
    local_frames_behind: int = 0
    remote_frames_behind: int = 0
    # receive direction + link-quality estimates (beyond the reference)
    kbps_recv: int = 0
    jitter_ms: int = 0
    packets_lost: int = 0
