"""Connection-quality observability (reference: src/network/network_stats.rs)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class NetworkStats:
    send_queue_len: int = 0
    ping_ms: int = 0
    kbps_sent: int = 0
    local_frames_behind: int = 0
    remote_frames_behind: int = 0
