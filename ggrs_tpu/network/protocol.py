"""Per-peer reliability endpoint: the protocol state machine.

Behavioral parity with the reference's UdpProtocol
(src/network/protocol.rs:127-743): random-nonce sync handshake with
magic-based packet auth, cumulative-ack input resend of the whole un-acked
window with delta+RLE compression, 200ms timer family (sync retry, input
resend, keep-alive, quality report), RTT estimation, frame-advantage
exchange feeding TimeSync, disconnect notify/timeout detection, and checksum
report intake for desync detection. Timers run off an injectable Clock so
tests can drive them deterministically.
"""

from __future__ import annotations

import enum
import random as _random
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from ..errors import NotSynchronized, StatsWindowTooYoung
from ..frame_info import PlayerInput
from ..obs import GLOBAL_TELEMETRY
from ..sync_layer import ConnectionStatus
from ..time_sync import TimeSync
from ..types import NULL_FRAME, Frame, PlayerHandle
from ..utils.clock import Clock
from . import compression
from .messages import (
    MSG_CHECKSUM_REPORT,
    MSG_INPUT,
    MSG_INPUT_ACK,
    MSG_KEEP_ALIVE,
    MSG_QUALITY_REPLY,
    MSG_QUALITY_REPORT,
    MSG_SYNC_REPLY,
    MSG_SYNC_REQUEST,
    ChecksumReport,
    InputAck,
    InputMsg,
    KeepAlive,
    Message,
    QualityReply,
    QualityReport,
    SyncReply,
    SyncRequest,
    encode_message,
)
from .network_stats import UDP_HEADER_SIZE, NetworkStats
from .sockets import NonBlockingSocket

NUM_SYNC_PACKETS = 5
UDP_SHUTDOWN_TIMER_MS = 5000
PENDING_OUTPUT_SIZE = 128
SYNC_RETRY_INTERVAL_MS = 200
RUNNING_RETRY_INTERVAL_MS = 200
KEEP_ALIVE_INTERVAL_MS = 200
QUALITY_REPORT_INTERVAL_MS = 200
MAX_PAYLOAD = 467  # 512 safe UDP payload minus packet overhead
MAX_CHECKSUM_HISTORY_SIZE = 32


class ProtocolState(enum.Enum):
    INITIALIZING = 0
    SYNCHRONIZING = 1
    RUNNING = 2
    DISCONNECTED = 3
    SHUTDOWN = 4


# ----------------------------------------------------------------------
# hot-state storage (the vectorized protocol plane's seam)
# ----------------------------------------------------------------------
#
# The per-peer fields the fleet-wide pump pass (network/endpoint_batch.py)
# needs as numpy columns: timer deadlines, clocks, frame-advantage inputs
# and the receive watermark. PeerEndpoint reads/writes them through the
# generated properties below, which indirect through `self._hot` — a
# plain `_ScalarHot` record for standalone endpoints (the scalar twin),
# swapped for a `_FleetRow` view over the fleet arrays when a WirePump's
# EndpointFleet adopts the endpoint. Protocol code is storage-agnostic:
# the same method bodies run bit-identically on either backing, which is
# what makes batched-vs-scalar parity hold by construction.

_HOT_INT_FIELDS = (
    "last_send_time",
    "last_recv_time",
    "last_sync_request_time",
    "running_last_input_recv",
    "running_last_quality_report",
    "shutdown_timeout",
    "round_trip_time",
    "local_frame_advantage",
    "remote_frame_advantage",
    "recv_frame",  # highest received input frame (watermark, NULL_FRAME=-1)
    "disconnect_timeout_ms",
    "disconnect_notify_start_ms",
    "fps",
    "magic",
)
_HOT_BOOL_FIELDS = (
    "disconnect_notify_sent",
    "disconnect_event_sent",
)


class _ScalarHot:
    """Standalone backing store for the hot fields: one plain slot per
    field, zero indirection beyond the attribute itself."""

    __slots__ = _HOT_INT_FIELDS + _HOT_BOOL_FIELDS + ("state",)


class _SignalDeque(deque):
    """deque that flips a fleet dirty flag on append. Standalone (cols
    is None) the append costs one None-check; adopted, it marks the
    owning row so the vectorized pass visits ONLY endpoints that
    actually queued something — the O(live peers) scan the fleet pass
    replaces with O(dirty peers)."""

    __slots__ = ("cols", "row", "flag")

    def __init__(self):
        super().__init__()
        self.cols = None
        self.row = 0
        self.flag = ""

    def bind(self, cols, row: int, flag: str) -> None:
        self.cols = cols
        self.row = row
        self.flag = flag
        if self:  # queued before adoption: visible to the next pass
            cols[flag][row] = True

    def unbind(self) -> None:
        self.cols = None

    def append(self, item) -> None:
        c = self.cols
        if c is not None:
            c[self.flag][self.row] = True
        deque.append(self, item)


# cumulative input-window resends fired by the RUNNING retry timer —
# fleet-wide (the vectorized pass and the scalar twin both count here)
_m_resends = GLOBAL_TELEMETRY.registry.counter(
    "ggrs_endpoint_resends_total",
    "input windows re-sent by the RUNNING retry timer "
    "(cumulative-ack resend of the whole un-acked window)",
)


# Endpoint -> session events (src/network/protocol.rs:96-116)


@dataclass(frozen=True)
class EvSynchronizing:
    total: int
    count: int


@dataclass(frozen=True)
class EvSynchronized:
    pass


@dataclass(frozen=True)
class EvInput:
    input: PlayerInput
    player: PlayerHandle


@dataclass(frozen=True)
class EvDisconnected:
    pass


@dataclass(frozen=True)
class EvNetworkInterrupted:
    disconnect_timeout_ms: int


@dataclass(frozen=True)
class EvNetworkResumed:
    pass


class PeerEndpoint:
    """One reliability endpoint per unique remote address; multiple player
    handles may share it (src/sessions/builder.rs:276-293)."""

    def __init__(
        self,
        handles: Sequence[PlayerHandle],
        peer_addr: Any,
        num_players: int,
        local_players: int,
        max_prediction: int,
        disconnect_timeout_ms: int,
        disconnect_notify_start_ms: int,
        fps: int,
        input_size: int,
        clock: Optional[Clock] = None,
        rng: Optional[_random.Random] = None,
    ):
        # hot-field backing store FIRST: every property write below lands
        # in it (swapped for a fleet-array row view on adoption)
        self._hot: Any = _ScalarHot()

        self.clock = clock or Clock()
        rng = rng or _random.Random()
        magic = 0
        while magic == 0:
            magic = rng.randrange(1, 1 << 16)
        self.magic = magic
        self._rng = rng

        self.handles = sorted(handles)
        self.peer_addr = peer_addr
        self.num_players = num_players
        self.local_players = local_players
        self.max_prediction = max_prediction
        self.input_size = input_size
        self.fps = fps

        self.send_queue: Deque[Message] = _SignalDeque()
        self.event_queue: Deque[Any] = _SignalDeque()

        self.state = ProtocolState.INITIALIZING
        self.sync_remaining_roundtrips = NUM_SYNC_PACKETS
        self.sync_random_requests: set[int] = set()
        now = self.clock.now_ms()
        self.running_last_quality_report = now
        self.running_last_input_recv = now
        self.disconnect_notify_sent = False
        self.disconnect_event_sent = False

        self.disconnect_timeout_ms = disconnect_timeout_ms
        self.disconnect_notify_start_ms = disconnect_notify_start_ms
        self.shutdown_timeout = now

        self.remote_magic = 0
        self.peer_connect_status = [ConnectionStatus() for _ in range(num_players)]

        # input transmission: whole un-acked window, frame->bytes
        # (bytes = concatenation of this side's players' inputs for the frame)
        self.pending_output: Deque[Tuple[Frame, bytes]] = deque()
        self.last_acked_input: Tuple[Frame, bytes] = (
            NULL_FRAME,
            bytes(input_size * local_players),
        )
        # received input history for delta decoding; recv_frame is the
        # hoisted max(recv_inputs) watermark the fleet pass reads as a
        # column (maintained at the sole insert site in _on_input_fields)
        self.recv_inputs: Dict[Frame, bytes] = {
            NULL_FRAME: bytes(input_size * len(self.handles))
        }
        self.recv_frame = NULL_FRAME

        self.time_sync = TimeSync(peer_label=str(peer_addr))
        self.local_frame_advantage = 0
        self.remote_frame_advantage = 0

        self.stats_start_time = 0
        self.packets_sent = 0
        self.bytes_sent = 0
        self.round_trip_time = 0
        self.last_send_time = now
        self.last_recv_time = now
        self.last_sync_request_time = now

        # receive direction + link-quality estimators (NetworkStats
        # kbps_recv / jitter_ms / packets_lost). Plain fields are always
        # maintained — integer adds, cheap enough to never gate; the
        # registry mirrors below only move behind GLOBAL_TELEMETRY.enabled.
        self.packets_recv = 0
        self.bytes_recv = 0
        # RFC 3550-style interarrival jitter over RTT samples:
        # J += (|D| - J) / 16 per quality reply
        self.jitter_ms = 0.0
        self._last_rtt_sample: Optional[int] = None
        # loss estimate from sequence gaps in the peer's quality-report
        # stream: reports carry the sender's strictly-increasing clock and
        # fire on a fixed 200ms cadence, so a gap of k intervals means
        # k - 1 reports never arrived. No wire change — native C++ peers
        # speak the identical format.
        self.packets_lost = 0
        self._last_quality_ping: Optional[int] = None

        # pre-bound telemetry children (valid across Telemetry.reset());
        # creation is a few dict entries, so it is not gated on enabled
        _label = str(peer_addr)
        _reg = GLOBAL_TELEMETRY.registry
        self._m_packets_sent = _reg.counter(
            "ggrs_peer_packets_sent_total", "packets queued to this peer", ("peer",)
        ).labels(_label)
        self._m_bytes_sent = _reg.counter(
            "ggrs_peer_bytes_sent_total", "wire payload bytes queued to this peer", ("peer",)
        ).labels(_label)
        self._m_packets_recv = _reg.counter(
            "ggrs_peer_packets_recv_total", "packets accepted from this peer", ("peer",)
        ).labels(_label)
        self._m_bytes_recv = _reg.counter(
            "ggrs_peer_bytes_recv_total", "wire payload bytes accepted from this peer", ("peer",)
        ).labels(_label)
        self._m_rtt = _reg.gauge(
            "ggrs_peer_rtt_ms", "last round-trip time to this peer", ("peer",)
        ).labels(_label)
        self._m_jitter = _reg.gauge(
            "ggrs_peer_jitter_ms", "EWMA RTT jitter to this peer (RFC 3550 style)", ("peer",)
        ).labels(_label)
        self._m_lost = _reg.counter(
            "ggrs_peer_packets_lost_total",
            "packets estimated lost from quality-report sequence gaps", ("peer",)
        ).labels(_label)

        self.checksum_history: Dict[Frame, int] = {}
        self.last_added_checksum_frame: Frame = NULL_FRAME

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def synchronize(self) -> None:
        assert self.state == ProtocolState.INITIALIZING
        self.state = ProtocolState.SYNCHRONIZING
        self.sync_remaining_roundtrips = NUM_SYNC_PACKETS
        self.stats_start_time = self.clock.now_ms()
        self._send_sync_request()

    def disconnect(self) -> None:
        if self.state == ProtocolState.SHUTDOWN:
            return
        self.state = ProtocolState.DISCONNECTED
        self.shutdown_timeout = self.clock.now_ms() + UDP_SHUTDOWN_TIMER_MS

    def resume_after_pause(self, now: Optional[int] = None) -> None:
        """Rebase the receive baseline after the OWNING side was
        suspended (live migration handoff, host kill→restore): the
        endpoint was not polled during the blackout, so on the first
        post-resume poll `last_recv_time` can be a full pause behind —
        and if the peer's packets were ALSO lost during the blackout
        (a killed host receives nothing), the disconnect timeout would
        fire instantly against a peer that is alive and already
        retransmitting. Granting a fresh full timeout window is the
        correct bias: a genuinely dead peer still times out one
        `disconnect_timeout_ms` later, while a live one replays its
        backlog on the very next pump. Send-side timers are deliberately
        NOT touched — stale send baselines make the first post-resume
        poll immediately resend pending output, keep-alive and a quality
        report, which is exactly the wake-up the peers need."""
        if now is None:
            now = self.clock.now_ms()
        self.last_recv_time = max(self.last_recv_time, now)

    def is_synchronized(self) -> bool:
        return self.state in (
            ProtocolState.RUNNING,
            ProtocolState.DISCONNECTED,
            ProtocolState.SHUTDOWN,
        )

    def is_running(self) -> bool:
        return self.state == ProtocolState.RUNNING

    def is_handling_message(self, addr: Any) -> bool:
        return self.peer_addr == addr

    def average_frame_advantage(self) -> int:
        return self.time_sync.average_frame_advantage()

    # ------------------------------------------------------------------
    # timers (src/network/protocol.rs:351-404)
    # ------------------------------------------------------------------

    def poll(
        self, connect_status: Sequence[ConnectionStatus],
        now: Optional[int] = None,
    ) -> List[Any]:
        """`now` lets a fleet-wide pump pass hoist the clock read out of
        its per-endpoint loop (one read per pass, not per endpoint)."""
        if now is None:
            now = self.clock.now_ms()
        self._poll_timers(connect_status, now)
        events = list(self.event_queue)
        self.event_queue.clear()
        return events

    def _poll_timers(
        self, connect_status: Sequence[ConnectionStatus], now: int
    ) -> None:
        """The timer family, factored out of poll() so the vectorized
        fleet pass (network/endpoint_batch.py) can run it verbatim on
        mask-selected candidates: the fleet's boolean masks are a
        SUPERSET snapshot of these conditions, and re-evaluating the
        exact scalar conditions here keeps both paths bit-identical
        (e.g. a resend that refreshes last_send_time must suppress the
        keep-alive the snapshot mask still flagged)."""
        state = self.state
        if state == ProtocolState.SYNCHRONIZING:
            # Deliberate divergence from the reference (protocol.rs:353):
            # retries key off the last sync REQUEST, not the last send of
            # anything. A Synchronizing endpoint also answers the running
            # peer's 200ms quality reports, and on the reference's condition
            # each QualityReply refreshes last_send_time — permanently
            # starving handshake retries once the final SyncReply is lost
            # (a livelock our tampering fuzz exposed).
            if self.last_sync_request_time + SYNC_RETRY_INTERVAL_MS < now:
                self._send_sync_request(now)
        elif state == ProtocolState.RUNNING:
            if self.running_last_input_recv + RUNNING_RETRY_INTERVAL_MS < now:
                if self.pending_output and GLOBAL_TELEMETRY.enabled:
                    _m_resends.inc()
                self._send_pending_output(connect_status, now)
                self.running_last_input_recv = now
            if self.running_last_quality_report + QUALITY_REPORT_INTERVAL_MS < now:
                self._send_quality_report(now)
            if self.last_send_time + KEEP_ALIVE_INTERVAL_MS < now:
                self._queue_message(KeepAlive(), now)
            if (
                not self.disconnect_notify_sent
                and self.last_recv_time + self.disconnect_notify_start_ms < now
            ):
                remaining = self.disconnect_timeout_ms - self.disconnect_notify_start_ms
                self.event_queue.append(EvNetworkInterrupted(remaining))
                self.disconnect_notify_sent = True
            if (
                not self.disconnect_event_sent
                and self.last_recv_time + self.disconnect_timeout_ms < now
            ):
                self.event_queue.append(EvDisconnected())
                self.disconnect_event_sent = True
        elif state == ProtocolState.DISCONNECTED:
            if self.shutdown_timeout < now:
                self.state = ProtocolState.SHUTDOWN

    # ------------------------------------------------------------------
    # sending
    # ------------------------------------------------------------------

    def send_all_messages(self, socket: NonBlockingSocket) -> None:
        if self.state == ProtocolState.SHUTDOWN:
            self.send_queue.clear()
            return
        while self.send_queue:
            socket.send_to(self.send_queue.popleft(), self.peer_addr)

    def drain_sends(self, out: List[Tuple[bytes, Any]]) -> None:
        """Batched twin of send_all_messages: append every queued
        message's wire bytes (already encoded once by _queue_message's
        byte accounting) as (wire, peer_addr) pairs; the pump ships the
        whole pass's batch through one socket.send_wire_batch call."""
        if self.state == ProtocolState.SHUTDOWN:
            self.send_queue.clear()
            return
        addr = self.peer_addr
        q = self.send_queue
        while q:
            out.append((encode_message(q.popleft()), addr))

    def send_input(
        self,
        inputs: Dict[PlayerHandle, PlayerInput],
        connect_status: Sequence[ConnectionStatus],
    ) -> None:
        """Append this frame's local inputs to the un-acked window and send
        the whole window (src/network/protocol.rs:439-466)."""
        if self.state != ProtocolState.RUNNING:
            return

        frame, data = self._inputs_to_bytes(inputs)
        self.time_sync.advance_frame(
            frame, self.local_frame_advantage, self.remote_frame_advantage
        )
        self.pending_output.append((frame, data))
        if len(self.pending_output) > PENDING_OUTPUT_SIZE:
            # a spectator that never acks: disconnect it (:459-463)
            self.event_queue.append(EvDisconnected())
        self._send_pending_output(connect_status)

    def _inputs_to_bytes(
        self, inputs: Dict[PlayerHandle, PlayerInput]
    ) -> Tuple[Frame, bytes]:
        """Ascending-handle concatenation (src/network/protocol.rs:61-79)."""
        frame = NULL_FRAME
        chunks = []
        for handle in sorted(inputs):
            pi = inputs[handle]
            if pi.frame != NULL_FRAME:
                assert frame in (NULL_FRAME, pi.frame)
                frame = pi.frame
            chunks.append(pi.buf)
        return frame, b"".join(chunks)

    def _send_pending_output(
        self, connect_status: Sequence[ConnectionStatus],
        now: Optional[int] = None,
    ) -> None:
        """(src/network/protocol.rs:468-493)

        Divergence from the reference, which asserts the encoded window fits
        467 bytes (protocol.rs:26,485) and would crash a session whose
        un-acked window grew during a stall: we send the longest window
        *prefix* that fits the budget (protocol-legal — the receiver acks
        the prefix and the rest rides the next resend), and a single
        oversized frame is sent anyway (UDP handles fragmentation) rather
        than killing the session.
        """
        if not self.pending_output:
            return
        first_frame, _ = self.pending_output[0]
        ack_frame, ack_bytes = self.last_acked_input
        assert ack_frame == NULL_FRAME or ack_frame + 1 == first_frame

        count = len(self.pending_output)
        pending = list(self.pending_output)
        payload = compression.encode(ack_bytes, (d for _, d in pending))
        while len(payload) > MAX_PAYLOAD and count > 1:
            count = max(1, count // 2)
            payload = compression.encode(ack_bytes, (d for _, d in pending[:count]))

        body = InputMsg(
            peer_connect_status=[
                ConnectionStatus(s.disconnected, s.last_frame) for s in connect_status
            ],
            disconnect_requested=self.state == ProtocolState.DISCONNECTED,
            start_frame=first_frame,
            ack_frame=self._last_recv_frame(),
            bytes_=payload,
        )
        self._queue_message(body, now)

    def _send_input_ack(self, now: Optional[int] = None) -> None:
        self._queue_message(InputAck(ack_frame=self._last_recv_frame()), now)

    def _send_sync_request(self, now: Optional[int] = None) -> None:
        self.last_sync_request_time = now if now is not None else self.clock.now_ms()
        nonce = self._rng.getrandbits(32)
        self.sync_random_requests.add(nonce)
        self._queue_message(SyncRequest(random_request=nonce), now)

    def _send_quality_report(self, now: Optional[int] = None) -> None:
        if now is None:
            now = self.clock.now_ms()
        self.running_last_quality_report = now
        adv = max(-128, min(127, self.local_frame_advantage))
        self._queue_message(QualityReport(frame_advantage=adv, ping=now), now)

    def send_checksum_report(self, frame_to_send: Frame, checksum: int) -> None:
        self._queue_message(ChecksumReport(checksum=checksum, frame=frame_to_send))

    def _queue_message(self, body: Any, now: Optional[int] = None) -> None:
        msg = Message(magic=self.magic, body=body)
        self.packets_sent += 1
        self.last_send_time = now if now is not None else self.clock.now_ms()
        wire_len = len(encode_message(msg))
        self.bytes_sent += wire_len
        if GLOBAL_TELEMETRY.enabled:
            self._m_packets_sent.inc()
            self._m_bytes_sent.inc(wire_len)
        self.send_queue.append(msg)

    # ------------------------------------------------------------------
    # receiving (src/network/protocol.rs:544-722)
    # ------------------------------------------------------------------

    def handle_message(self, msg: Message) -> None:
        """Object-level receive (tests, hand-built messages, transports
        without a wire lane): maps the Message onto the field-level
        handle_decoded, THE one applier both paths share — the batched
        pump (network/pump.py) calls handle_decoded with fields gathered
        straight out of the pooled decode staging, so divergence between
        the two receive paths is impossible by construction."""
        # wire-decoded messages carry their bytes (decode_message stamps
        # _wire); hand-built ones (tests) pay one cached encode
        wire_len = len(msg._wire) if msg._wire is not None else len(encode_message(msg))
        body = msg.body
        if isinstance(body, InputMsg):
            self.handle_decoded(
                MSG_INPUT, msg.magic, wire_len,
                body.start_frame, body.ack_frame,
                1 if body.disconnect_requested else 0,
                [(s.disconnected, s.last_frame)
                 for s in body.peer_connect_status],
                body.bytes_,
            )
        elif isinstance(body, InputAck):
            self.handle_decoded(MSG_INPUT_ACK, msg.magic, wire_len, body.ack_frame)
        elif isinstance(body, QualityReport):
            self.handle_decoded(
                MSG_QUALITY_REPORT, msg.magic, wire_len,
                body.frame_advantage, body.ping,
            )
        elif isinstance(body, QualityReply):
            self.handle_decoded(MSG_QUALITY_REPLY, msg.magic, wire_len, body.pong)
        elif isinstance(body, SyncRequest):
            self.handle_decoded(
                MSG_SYNC_REQUEST, msg.magic, wire_len, body.random_request
            )
        elif isinstance(body, SyncReply):
            self.handle_decoded(
                MSG_SYNC_REPLY, msg.magic, wire_len, body.random_reply
            )
        elif isinstance(body, ChecksumReport):
            self.handle_decoded(
                MSG_CHECKSUM_REPORT, msg.magic, wire_len,
                body.frame, body.checksum,
            )
        elif isinstance(body, KeepAlive):
            self.handle_decoded(MSG_KEEP_ALIVE, msg.magic, wire_len)

    def handle_decoded(
        self, kind: int, magic: int, wire_len: int,
        a: int = 0, b: int = 0, c: int = 0,
        statuses: Sequence[Tuple[Any, int]] = (), payload: bytes = b"",
        now: Optional[int] = None,
    ) -> None:
        """Field-level receive: one decoded datagram's worth of scalars,
        positionally matched to network/pump.py's record layout (kind,
        magic, a, b, c, statuses, payload). Branches ordered by live
        traffic frequency. Scalar meanings: INPUT a=start_frame,
        b=ack_frame, c=flags; INPUT_ACK a=ack_frame; QUALITY_REPORT
        a=frame_advantage, b=ping; QUALITY_REPLY a=pong; SYNC_* a=nonce;
        CHECKSUM_REPORT a=frame, b=checksum. `now` is the pump pass's
        hoisted clock — every timer/stats touch this datagram causes
        observes the same instant (one clock read per pass, not per
        message)."""
        if self.state == ProtocolState.SHUTDOWN:
            return
        # packet auth: filter foreign magics once the peer is known
        if self.remote_magic != 0 and magic != self.remote_magic:
            return
        if now is None:
            now = self.clock.now_ms()
        self.last_recv_time = now
        self.packets_recv += 1
        self.bytes_recv += wire_len
        if GLOBAL_TELEMETRY.enabled:
            self._m_packets_recv.inc()
            self._m_bytes_recv.inc(wire_len)
        if self.disconnect_notify_sent and self.state == ProtocolState.RUNNING:
            self.disconnect_notify_sent = False
            self.event_queue.append(EvNetworkResumed())

        if kind == MSG_INPUT:
            self._on_input_fields(a, b, bool(c & 1), statuses, payload, now)
        elif kind == MSG_INPUT_ACK:
            self._pop_pending_output(a)
        elif kind == MSG_QUALITY_REPORT:
            self._on_quality_report_fields(a, b, now)
        elif kind == MSG_QUALITY_REPLY:
            self._on_quality_reply_pong(a, now)
        elif kind == MSG_SYNC_REQUEST:
            self._queue_message(SyncReply(random_reply=a), now)
        elif kind == MSG_SYNC_REPLY:
            self._on_sync_reply_nonce(magic, a, now)
        elif kind == MSG_CHECKSUM_REPORT:
            self._on_checksum_report_fields(a, b)
        # MSG_KEEP_ALIVE: nothing beyond the recv-time update

    def _on_sync_reply_nonce(
        self, magic: int, nonce: int, now: Optional[int] = None
    ) -> None:
        if self.state != ProtocolState.SYNCHRONIZING:
            return
        if nonce not in self.sync_random_requests:
            return
        self.sync_random_requests.discard(nonce)
        self.sync_remaining_roundtrips -= 1
        if self.sync_remaining_roundtrips > 0:
            self.event_queue.append(
                EvSynchronizing(
                    total=NUM_SYNC_PACKETS,
                    count=NUM_SYNC_PACKETS - self.sync_remaining_roundtrips,
                )
            )
            self._send_sync_request(now)
        else:
            self.state = ProtocolState.RUNNING
            self.event_queue.append(EvSynchronized())
            self.remote_magic = magic  # peer is now authorized

    def _on_input_fields(
        self, start_frame: Frame, ack_frame: Frame,
        disconnect_requested: bool,
        statuses: Sequence[Tuple[Any, int]], payload: bytes,
        now: Optional[int] = None,
    ) -> None:
        """(src/network/protocol.rs:616-689) — `statuses` items are
        (disconnected, last_frame) pairs straight off the wire decode."""
        self._pop_pending_output(ack_frame)

        if disconnect_requested:
            if self.state != ProtocolState.DISCONNECTED and not self.disconnect_event_sent:
                self.event_queue.append(EvDisconnected())
                self.disconnect_event_sent = True
        else:
            mine_all = self.peer_connect_status
            n_mine = len(mine_all)
            for i, (disc, last_frame) in enumerate(statuses):
                if i >= n_mine:
                    break
                mine = mine_all[i]
                mine.disconnected = bool(disc) or mine.disconnected
                if last_frame > mine.last_frame:
                    mine.last_frame = last_frame

        last_recv = self._last_recv_frame()
        # a start_frame beyond last_recv+1 means the peer encoded against an
        # input we never received — unrecoverable for this packet, but the
        # value is network-controlled, so drop it rather than abort (parity
        # with the C++ endpoint, endpoint.cpp on_input)
        if last_recv != NULL_FRAME and start_frame > last_recv + 1:
            return
        # before any input arrived, a legitimate first packet starts within
        # the sender's pending window (its first queued frame, bounded by
        # the 128-slot queue); a huge spoofed start_frame would otherwise
        # permanently poison recv_inputs and blackhole all real inputs
        if last_recv == NULL_FRAME and not (
            0 <= start_frame <= PENDING_OUTPUT_SIZE
        ):
            return
        # ...and frame arithmetic must stay inside int32 in either direction
        # (parity with the C++ endpoint, where overflow would be UB)
        if not (0 <= start_frame <= (1 << 31) - 1 - 2 * PENDING_OUTPUT_SIZE):
            return

        decode_frame = NULL_FRAME if last_recv == NULL_FRAME else start_frame - 1
        ref = self.recv_inputs.get(decode_frame)
        if ref is None:
            return
        self.running_last_input_recv = (
            now if now is not None else self.clock.now_ms()
        )

        # bound the decode at the largest legitimate payload — the sender
        # never has more than PENDING_OUTPUT_SIZE un-acked frames in flight —
        # so a hostile run-length claim can't balloon memory; and a payload
        # that fails to decode is a dropped datagram, not a session crash
        # (parity with the C++ endpoint, endpoint.cpp on_input)
        try:
            decoded = compression.decode(
                ref, payload, max_output=len(ref) * (PENDING_OUTPUT_SIZE + 1)
            )
        except ValueError:
            return
        per_player = self.input_size
        for i, inp_bytes in enumerate(decoded):
            inp_frame = start_frame + i
            if inp_frame <= self.recv_frame:
                continue  # already have it
            self.recv_inputs[inp_frame] = inp_bytes
            self.recv_frame = inp_frame  # watermark: inserts are ascending
            # re-split the endpoint-level bytes into per-player inputs
            assert len(inp_bytes) == per_player * len(self.handles)
            for j, handle in enumerate(self.handles):
                buf = inp_bytes[j * per_player : (j + 1) * per_player]
                self.event_queue.append(
                    EvInput(input=PlayerInput(inp_frame, buf), player=handle)
                )

        self._send_input_ack(now)

        # GC received inputs beyond 2x the prediction window
        horizon = self._last_recv_frame() - 2 * self.max_prediction
        self.recv_inputs = {
            f: b for f, b in self.recv_inputs.items() if f >= horizon or f == NULL_FRAME
        }

    def _pop_pending_output(self, ack_frame: Frame) -> None:
        while self.pending_output and self.pending_output[0][0] <= ack_frame:
            self.last_acked_input = self.pending_output.popleft()

    def _on_quality_report_fields(
        self, frame_advantage: int, ping: int, now: Optional[int] = None
    ) -> None:
        self.remote_frame_advantage = frame_advantage
        # packet-loss estimate from sequence gaps: the peer's reports fire
        # every QUALITY_REPORT_INTERVAL_MS carrying its strictly-increasing
        # clock, so a ping-gap of k intervals means k - 1 reports (and
        # statistically the same fraction of all its traffic) were dropped.
        # ping is network-controlled: ignore non-monotonic values outright.
        if self._last_quality_ping is not None and ping > self._last_quality_ping:
            gap = ping - self._last_quality_ping
            # floor, not round: reports fire on the sender's poll at >=200ms,
            # so a slow-polling peer (e.g. 300ms cadence) stretches gaps to
            # 1.5 intervals with zero real loss — flooring forgives that
            # quantization while a genuinely dropped report (>=2 intervals)
            # still counts
            missed = gap // QUALITY_REPORT_INTERVAL_MS - 1
            if missed > 0:
                self.packets_lost += missed
                if GLOBAL_TELEMETRY.enabled:
                    self._m_lost.inc(missed)
        self._last_quality_ping = max(self._last_quality_ping or 0, ping)
        self._queue_message(QualityReply(pong=ping), now)

    def _on_quality_reply_pong(self, pong: int, now: Optional[int] = None) -> None:
        if now is None:
            now = self.clock.now_ms()
        # network-controlled value: a pong from the future (clock skew or a
        # crafted packet) must not produce a negative RTT or crash the
        # session (parity with the C++ endpoint, endpoint.cpp)
        self.round_trip_time = now - pong if now >= pong else 0
        # RFC 3550-style jitter over consecutive RTT samples; the first
        # sample only seeds the baseline (comparing against the initial 0
        # would inject a phantom |RTT|/16 spike on every fresh connection)
        if self._last_rtt_sample is not None:
            self.jitter_ms += (
                abs(self.round_trip_time - self._last_rtt_sample) - self.jitter_ms
            ) / 16.0
        self._last_rtt_sample = self.round_trip_time
        if GLOBAL_TELEMETRY.enabled:
            self._m_rtt.set(self.round_trip_time)
            self._m_jitter.set(self.jitter_ms)

    def _on_checksum_report_fields(self, frame: Frame, checksum: int) -> None:
        if self.last_added_checksum_frame < frame:
            if len(self.checksum_history) > MAX_CHECKSUM_HISTORY_SIZE:
                keep_after = self.last_added_checksum_frame - MAX_CHECKSUM_HISTORY_SIZE
                self.checksum_history = {
                    f: c for f, c in self.checksum_history.items() if f > keep_after
                }
            self.last_added_checksum_frame = frame
            self.checksum_history[frame] = checksum

    # ------------------------------------------------------------------
    # frame advantage / stats
    # ------------------------------------------------------------------

    def update_local_frame_advantage(self, local_frame: Frame) -> None:
        """Estimate the remote's current frame from its last input plus
        half-RTT (src/network/protocol.rs:268-277). The vectorized twin
        (network/endpoint_batch.py) runs the identical arithmetic over
        the fleet's recv_frame / round_trip_time columns."""
        recv_frame = self.recv_frame
        if local_frame == NULL_FRAME or recv_frame == NULL_FRAME:
            return
        ping = self.round_trip_time // 2
        remote_frame = recv_frame + (ping * self.fps) // 1000
        self.local_frame_advantage = remote_frame - local_frame

    def network_stats(self, now: Optional[int] = None) -> NetworkStats:
        if self.state not in (ProtocolState.SYNCHRONIZING, ProtocolState.RUNNING):
            raise NotSynchronized()
        if now is None:
            now = self.clock.now_ms()
        seconds = (now - self.stats_start_time) // 1000
        if seconds == 0:
            # distinguishable from the unsynchronized case — but only once
            # the endpoint actually IS synchronized: mid-handshake, "not
            # synchronized" stays the truthful (plain) error even though
            # the window is also young
            if self.state == ProtocolState.RUNNING:
                raise StatsWindowTooYoung()
            raise NotSynchronized()
        return NetworkStats.from_endpoint(self, seconds)

    def _last_recv_frame(self) -> Frame:
        return self.recv_frame


# ----------------------------------------------------------------------
# hot-field properties: PeerEndpoint.<field> indirects through the
# swappable backing store (see the _HOT_* tables above). Installed after
# the class body so the method sources above read like plain attribute
# code — which is exactly what they compile to on the _ScalarHot twin.
# ----------------------------------------------------------------------


def _hot_property(name: str) -> property:
    def _get(self, _n=name):
        return getattr(self._hot, _n)

    def _set(self, value, _n=name):
        setattr(self._hot, _n, value)

    return property(_get, _set)


for _name in _HOT_INT_FIELDS + _HOT_BOOL_FIELDS + ("state",):
    setattr(PeerEndpoint, _name, _hot_property(_name))
del _name
