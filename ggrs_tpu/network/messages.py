"""Wire messages and their flat binary codec.

Message bodies mirror the reference protocol (src/network/messages.rs:6-106).
Where the reference leans on bincode's derived serialization
(src/network/udp_socket.rs:32,42), we define an explicit little-endian flat
format (struct-packed, length-prefixed) so the C++ runtime can speak the same
bytes without a serde dependency.

Layout: every packet is `magic:u16 | body_type:u8 | body`. Integers are
little-endian; frames are i32; checksums are u128 (16 bytes LE).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import List, Tuple, Union

from ..errors import GGRSError, TypeContractError
from ..sync_layer import ConnectionStatus
from ..types import NULL_FRAME, Frame

MSG_SYNC_REQUEST = 0
MSG_SYNC_REPLY = 1
MSG_INPUT = 2
MSG_INPUT_ACK = 3
MSG_QUALITY_REPORT = 4
MSG_QUALITY_REPLY = 5
MSG_CHECKSUM_REPORT = 6
MSG_KEEP_ALIVE = 7

_HEADER = struct.Struct("<HB")
_U32 = struct.Struct("<I")
_I32 = struct.Struct("<i")
_U64 = struct.Struct("<Q")
_INPUT_HEAD = struct.Struct("<iiBB")
_STATUS = struct.Struct("<Bi")
_QUALITY_REPORT = struct.Struct("<bQ")
_CHECKSUM_REPORT = struct.Struct("<i16s")

# Named wire-layout sizes, shared by this codec and the batched pump
# (network/pump.py, which extracts fields straight out of pooled byte
# staging at these offsets) and cross-checked against the C++ endpoint's
# twins by the WIRE parity lint (analysis/wire_contract.py) — an offset
# drift between the three decoders would silently desync the stacks.
WIRE_HEADER_SIZE = _HEADER.size            # magic u16 + body_type u8
WIRE_INPUT_HEAD_SIZE = _INPUT_HEAD.size    # start/ack i32 + flags u8 + n u8
WIRE_STATUS_SIZE = _STATUS.size            # disconnected u8 + last_frame i32
WIRE_CHECKSUM_BODY_SIZE = _CHECKSUM_REPORT.size  # frame i32 + checksum u128

# The largest compressed-input payload an InputMsg may carry, derived so
# the WORST-CASE encoded message (16 connect statuses — the native stack's
# MAX_HANDLES) exactly fits the transport's MAX_DATAGRAM_SIZE (65507, UDP's
# own payload ceiling; network/sockets.py). The old inline cap (0xFFFF)
# admitted payloads the codec would happily encode and every send path
# would then reject — the bound must live where the bytes are built.
# Cross-checked by the wire-contract lint (analysis/wire_contract.py
# WIRE003) and tests/test_wire_contract.py.
_UDP_MAX_PAYLOAD = 65507
INPUT_MSG_OVERHEAD = (
    _HEADER.size + _INPUT_HEAD.size + 16 * _STATUS.size + 2
)  # 2 = the u16 payload length prefix
MAX_INPUT_PAYLOAD = _UDP_MAX_PAYLOAD - INPUT_MSG_OVERHEAD


@dataclass(frozen=True)
class SyncRequest:
    random_request: int  # u32 nonce; peer must echo it back


@dataclass(frozen=True)
class SyncReply:
    random_reply: int


@dataclass
class InputMsg:
    """Compressed input batch (src/network/messages.rs:29-48): the whole
    un-acked window, delta+RLE encoded against the last acked input."""

    peer_connect_status: List[ConnectionStatus] = field(default_factory=list)
    disconnect_requested: bool = False
    start_frame: Frame = NULL_FRAME
    ack_frame: Frame = NULL_FRAME
    bytes_: bytes = b""


@dataclass(frozen=True)
class InputAck:
    ack_frame: Frame


@dataclass(frozen=True)
class QualityReport:
    frame_advantage: int  # i8, frame advantage of the other player
    ping: int  # u64 ms timestamp, echoed back in QualityReply


@dataclass(frozen=True)
class QualityReply:
    pong: int


@dataclass(frozen=True)
class ChecksumReport:
    checksum: int  # u128
    frame: Frame


@dataclass(frozen=True)
class KeepAlive:
    pass


Body = Union[
    SyncRequest, SyncReply, InputMsg, InputAck, QualityReport, QualityReply,
    ChecksumReport, KeepAlive,
]


@dataclass
class Message:
    magic: int  # u16 sender id, packet-auth filter (src/network/protocol.rs:551-553)
    body: Body
    # wire-encoding memo: a message is encoded once (for byte accounting in
    # the endpoint) and sent later by the socket; bodies are never mutated
    # after queuing, so caching is safe and halves hot-path serialization
    _wire: bytes | None = field(default=None, repr=False, compare=False)


def encode_message(msg: Message) -> bytes:
    if msg._wire is None:
        msg._wire = _encode_message_uncached(msg)
    return msg._wire


def _encode_message_uncached(msg: Message) -> bytes:
    body = msg.body
    if isinstance(body, SyncRequest):
        return _HEADER.pack(msg.magic, MSG_SYNC_REQUEST) + _U32.pack(body.random_request)
    if isinstance(body, SyncReply):
        return _HEADER.pack(msg.magic, MSG_SYNC_REPLY) + _U32.pack(body.random_reply)
    if isinstance(body, InputMsg):
        out = bytearray(_HEADER.pack(msg.magic, MSG_INPUT))
        out += _INPUT_HEAD.pack(
            body.start_frame,
            body.ack_frame,
            1 if body.disconnect_requested else 0,
            len(body.peer_connect_status),
        )
        for st in body.peer_connect_status:
            out += _STATUS.pack(1 if st.disconnected else 0, st.last_frame)
        # MAX_INPUT_PAYLOAD assumes the 16-status worst case (the native
        # stack's MAX_HANDLES); a wider pure-Python session tightens the
        # cap by its extra statuses so the ACTUAL encoded datagram can
        # never exceed what the transport carries
        payload_cap = MAX_INPUT_PAYLOAD - max(
            0, len(body.peer_connect_status) - 16
        ) * _STATUS.size
        if len(body.bytes_) > payload_cap:
            # a real exception (not an assert) so the guard survives
            # `python -O`, mirroring sockets.check_datagram_size
            from ..errors import InvalidRequest

            raise InvalidRequest(
                f"InputMsg payload of {len(body.bytes_)} bytes exceeds "
                f"the {payload_cap}-byte cap "
                f"({len(body.peer_connect_status)} connect statuses): the "
                "encoded datagram could not survive the transport — "
                "shrink the un-acked window or the input size"
            )
        out += struct.pack("<H", len(body.bytes_)) + body.bytes_
        return bytes(out)
    if isinstance(body, InputAck):
        return _HEADER.pack(msg.magic, MSG_INPUT_ACK) + _I32.pack(body.ack_frame)
    if isinstance(body, QualityReport):
        return _HEADER.pack(msg.magic, MSG_QUALITY_REPORT) + _QUALITY_REPORT.pack(
            body.frame_advantage, body.ping
        )
    if isinstance(body, QualityReply):
        return _HEADER.pack(msg.magic, MSG_QUALITY_REPLY) + _U64.pack(body.pong)
    if isinstance(body, ChecksumReport):
        return _HEADER.pack(msg.magic, MSG_CHECKSUM_REPORT) + _CHECKSUM_REPORT.pack(
            body.frame, body.checksum.to_bytes(16, "little")
        )
    if isinstance(body, KeepAlive):
        return _HEADER.pack(msg.magic, MSG_KEEP_ALIVE)
    raise TypeContractError(f"unknown message body {body!r}")


class DecodeError(GGRSError, ValueError):
    """Undecodable wire bytes (EXC001-typed; ValueError face keeps the
    drop-the-datagram callers working)."""


def decode_message(buf: bytes) -> Message:
    msg = _decode_message_body(buf)
    # stamp the wire bytes: received-byte accounting (NetworkStats
    # kbps_recv) then costs a len(), not a re-encode, per packet
    msg._wire = bytes(buf)
    return msg


def _decode_message_body(buf: bytes) -> Message:
    if len(buf) < _HEADER.size:
        raise DecodeError("short packet")
    magic, body_type = _HEADER.unpack_from(buf, 0)
    off = _HEADER.size
    try:
        if body_type == MSG_SYNC_REQUEST:
            (v,) = _U32.unpack_from(buf, off)
            return Message(magic, SyncRequest(v))
        if body_type == MSG_SYNC_REPLY:
            (v,) = _U32.unpack_from(buf, off)
            return Message(magic, SyncReply(v))
        if body_type == MSG_INPUT:
            start_frame, ack_frame, flags, n_status = _INPUT_HEAD.unpack_from(buf, off)
            off += _INPUT_HEAD.size
            statuses = []
            for _ in range(n_status):
                disc, last_frame = _STATUS.unpack_from(buf, off)
                off += _STATUS.size
                statuses.append(ConnectionStatus(bool(disc), last_frame))
            (blen,) = struct.unpack_from("<H", buf, off)
            off += 2
            payload = bytes(buf[off : off + blen])
            if len(payload) != blen:
                raise DecodeError("truncated input payload")
            return Message(
                magic,
                InputMsg(
                    peer_connect_status=statuses,
                    disconnect_requested=bool(flags & 1),
                    start_frame=start_frame,
                    ack_frame=ack_frame,
                    bytes_=payload,
                ),
            )
        if body_type == MSG_INPUT_ACK:
            (v,) = _I32.unpack_from(buf, off)
            return Message(magic, InputAck(v))
        if body_type == MSG_QUALITY_REPORT:
            adv, ping = _QUALITY_REPORT.unpack_from(buf, off)
            return Message(magic, QualityReport(adv, ping))
        if body_type == MSG_QUALITY_REPLY:
            (v,) = _U64.unpack_from(buf, off)
            return Message(magic, QualityReply(v))
        if body_type == MSG_CHECKSUM_REPORT:
            frame, csum = _CHECKSUM_REPORT.unpack_from(buf, off)
            return Message(magic, ChecksumReport(int.from_bytes(csum, "little"), frame))
        if body_type == MSG_KEEP_ALIVE:
            return Message(magic, KeepAlive())
    except struct.error as exc:
        raise DecodeError(str(exc)) from exc
    raise DecodeError(f"unknown body type {body_type}")


def decode_all(pairs):
    """Decode (addr, wire) pairs, dropping undecodable datagrams — the
    one garbage filter every transport shares (the reference's bincode
    deserialization failure analog, src/network/udp_socket.rs:44-50)."""
    out = []
    for addr, wire in pairs:
        try:
            out.append((addr, decode_message(wire)))
        except DecodeError:
            continue
    return out
