"""Authenticated transport: an opt-in MAC layer the reference never had.

The wire's only packet filter in the reference is the 16-bit magic
(src/network/protocol.rs:551-553); our fuzz suite (tests/test_wire_fuzz.py)
pins the consequence — in-stream tampering that keeps the magic valid can
stall a stream (forged acks) or corrupt inputs. `AuthenticatedSocket`
closes that hole at the transport seam: every datagram carries an 8-byte
SipHash-2-4 tag over its bytes under a 128-bit pre-shared key; receivers
verify before anything else parses, so tampered or unkeyed packets are
indistinguishable from loss (which the reliability layer already absorbs).

Layering: wraps any NonBlockingSocket (UDP, in-memory fault net) and is
transparent to every session stack — Python or native C++ — because all
wire bytes pass through the socket seam. The tag math runs in C++ when the
native library is built (ggrs_native.cpp ggrs_siphash24); the Python
implementation below is the oracle (tests assert tag parity).

Both peers must wrap (or neither): a keyed peer silently drops all
unkeyed traffic, so a key mismatch looks like a dead network — sessions
simply never leave SYNCHRONIZING.

Format note: tags are computed over a 1-byte mode domain plus the
payload (see `_domain` below). This supersedes the round-1 format that
tagged the bare wire bytes — peers on the two formats drop each other's
traffic exactly like a key mismatch. The change is deliberate: an empty
plain-mode domain would be splicable into the replay-protected mode.

Scope: by default this authenticates packet CONTENT only — no direction,
sequence or freshness binding — so an on-path attacker can still REPLAY
previously captured datagrams. Replayed input packets are absorbed by the
protocol's own idempotence (frames <= last_recv are skipped; stale acks
are monotonic), but replayed quality reports can feed stale RTT/advantage
into throttling. Forgery and bit-flip tampering are fully blocked.

`replay_protect=True` closes the replay window too: every datagram then
carries a random 8-byte sender id plus a monotonically increasing 8-byte
counter, both under the MAC. The receiver accepts each (sender id,
counter) at most once via an IPsec-style sliding window of
`_ReplayWindow.WINDOW` (1024) counters; anything older or repeated is
dropped as loss. The sender runs one counter stream across all of its
destinations, so a receiver behind a P-way fan-out (host + P-1 other
peers/spectators) tolerates genuine reorder of about WINDOW/P datagrams
— 1024 counters of skew at P=1, ~60 datagrams at P=17. Receivers drop
datagrams bearing their OWN sender id (reflection of captured outbound
traffic cannot poison the windows). Windows are keyed by the
authenticated sender id — never by the UDP source address, which is
spoofable — so only actual key-holders can allocate window state. The
two modes use distinct equal-length MAC domain bytes, so a mode mismatch
(or a splice between modes) fails tag verification outright, same as a
key mismatch. Residual on-path power: an attacker can still re-route a
sender's packets between that sender's peers to advance a window and
shadow in-flight traffic older than the window — indistinguishable from
the packet drops an on-path attacker can always inflict.
"""

from __future__ import annotations

import hmac
import os
from typing import Any, List, Tuple

from ..errors import ConfigError, TypeContractError
from .messages import Message, decode_all, encode_message

TAG_LEN = 8
KEY_LEN = 16
CTR_LEN = 8  # replay-protection counter, little-endian, under the MAC
SENDER_ID_LEN = 8  # random per-socket id; replay windows key on it

_MASK = (1 << 64) - 1


def _rotl(x: int, b: int) -> int:
    return ((x << b) | (x >> (64 - b))) & _MASK


def siphash24(key: bytes, data: bytes) -> int:
    """SipHash-2-4 (the reference PRF for short untrusted inputs); 64-bit
    tag under a 128-bit key. Pure-Python oracle for the C++ kernel."""
    assert len(key) == KEY_LEN
    k0 = int.from_bytes(key[:8], "little")
    k1 = int.from_bytes(key[8:], "little")
    v0 = 0x736F6D6570736575 ^ k0
    v1 = 0x646F72616E646F6D ^ k1
    v2 = 0x6C7967656E657261 ^ k0
    v3 = 0x7465646279746573 ^ k1

    def rounds(n: int) -> None:
        nonlocal v0, v1, v2, v3
        for _ in range(n):
            v0 = (v0 + v1) & _MASK
            v1 = _rotl(v1, 13) ^ v0
            v0 = _rotl(v0, 32)
            v2 = (v2 + v3) & _MASK
            v3 = _rotl(v3, 16) ^ v2
            v0 = (v0 + v3) & _MASK
            v3 = _rotl(v3, 21) ^ v0
            v2 = (v2 + v1) & _MASK
            v1 = _rotl(v1, 17) ^ v2
            v2 = _rotl(v2, 32)

    n = len(data)
    for off in range(0, n - n % 8, 8):
        m = int.from_bytes(data[off : off + 8], "little")
        v3 ^= m
        rounds(2)
        v0 ^= m
    last = int.from_bytes(data[n - n % 8 :], "little") | ((n & 0xFF) << 56)
    v3 ^= last
    rounds(2)
    v0 ^= last
    v2 ^= 0xFF
    rounds(4)
    return (v0 ^ v1 ^ v2 ^ v3) & _MASK


def _resolve_tag_fn():
    """Pick the tag backend once (per AuthenticatedSocket) — not per packet."""
    from .. import native as _native

    if _native.available():
        return _native.siphash24
    return lambda key, data: siphash24(key, data).to_bytes(TAG_LEN, "little")


class _ReplayWindow:
    """IPsec-style sliding-window anti-replay: accepts each counter at most
    once, tolerating reorder within the window. Counters start at 1, so the
    zero-initial `top` never collides with a real packet."""

    # sized for fan-out: the sender runs ONE counter stream across all
    # destinations, so a host broadcasting to P peers/spectators consumes
    # ~P counters per tick and a receiver must tolerate reorder×P of
    # counter skew. 1024 bits ≈ 60 datagrams of genuine reorder at a
    # 17-way fan-out; the mask is one Python big-int, so width is cheap
    WINDOW = 1024

    def __init__(self) -> None:
        self.top = 0  # highest counter accepted so far
        self.mask = 1  # bit i set => counter (top - i) already seen

    def check_and_update(self, ctr: int) -> bool:
        if ctr > self.top:
            shift = ctr - self.top
            # clamp before shifting: ctr is attacker-influenced u64, and an
            # unclamped `mask << 2**60` materializes a 2**60-bit big-int
            if shift >= self.WINDOW:
                self.mask = 1
            else:
                self.mask = ((self.mask << shift) | 1) & ((1 << self.WINDOW) - 1)
            self.top = ctr
            return True
        off = self.top - ctr
        if off >= self.WINDOW:
            return False  # too old to distinguish from a replay
        bit = 1 << off
        if self.mask & bit:
            return False  # replay
        self.mask |= bit
        return True


class AuthenticatedSocket:
    """Wraps a NonBlockingSocket; appends/verifies per-datagram MAC tags.
    Invalid tags are dropped silently — to the protocol they are packet
    loss, which it already handles."""

    def __init__(
        self,
        inner: Any,
        key: bytes,
        replay_protect: bool = False,
        sender_id: bytes | None = None,
    ):
        if len(key) != KEY_LEN:
            raise ConfigError(f"key must be {KEY_LEN} bytes, got {len(key)}")
        # tags cover exact wire bytes, so the inner transport must expose
        # them (a message-level-only socket re-decodes before we could
        # verify); both shipped transports do
        if not hasattr(inner, "receive_all_wire") or not hasattr(inner, "send_wire"):
            raise TypeContractError(
                "AuthenticatedSocket requires a wire-capable socket"
            )
        self.inner = inner
        self.key = bytes(key)
        self.dropped = 0  # observability: tag-verification failures
        self.replayed = 0  # observability: replay-window rejections
        self.replay_protect = replay_protect
        self._send_ctr = 0  # one stream for all peers; per-peer view stays monotonic
        self._recv_windows: dict = {}  # authenticated sender id -> _ReplayWindow
        if sender_id is None:
            sender_id = os.urandom(SENDER_ID_LEN)
        elif len(sender_id) != SENDER_ID_LEN:
            raise ConfigError(f"sender_id must be {SENDER_ID_LEN} bytes")
        self.sender_id = bytes(sender_id)
        # domain separation, equal-length in both modes: without it a mode
        # mismatch would still MAC-verify and mis-frame trailing bytes, and
        # an empty plain-mode domain would let a plain packet starting with
        # the protected domain byte be spliced across modes
        self._domain = b"\x01" if replay_protect else b"\x00"
        self._tag = _resolve_tag_fn()

    def __getattr__(self, name: str):
        # delegate everything else (local_port, close, ...) to the transport
        return getattr(self.inner, name)

    # -- sending --------------------------------------------------------

    def send_wire(self, wire: bytes, addr: Any) -> None:
        if self.replay_protect:
            self._send_ctr += 1
            body = wire + self.sender_id + self._send_ctr.to_bytes(CTR_LEN, "little")
        else:
            body = wire
        self.inner.send_wire(body + self._tag(self.key, self._domain + body), addr)

    def send_wire_batch(self, batch) -> None:
        """Batched drain: each datagram still gets its own MAC (and
        replay counter) — authentication is per-datagram by design."""
        for wire, addr in batch:
            self.send_wire(wire, addr)

    def send_to(self, msg: Message, addr: Any) -> None:
        self.send_wire(encode_message(msg), addr)

    # -- receiving ------------------------------------------------------

    def _verify(self, blob: bytes) -> bytes | None:
        trailer = SENDER_ID_LEN + CTR_LEN if self.replay_protect else 0
        if len(blob) < TAG_LEN + trailer:
            self.dropped += 1
            return None
        body, tag = blob[:-TAG_LEN], blob[-TAG_LEN:]
        # constant-time compare: an early-exit != would leak tag-prefix
        # match length through verify latency
        if not hmac.compare_digest(self._tag(self.key, self._domain + body), tag):
            self.dropped += 1
            return None
        if not self.replay_protect:
            return body
        # replay state touched only AFTER the MAC verifies — unauthenticated
        # datagrams must not be able to advance windows or allocate them
        wire = body[:-trailer]
        sender = body[-trailer:-CTR_LEN]
        ctr = int.from_bytes(body[-CTR_LEN:], "little")
        if sender == self.sender_id:
            # our own outbound traffic reflected back at us
            self.replayed += 1
            return None
        window = self._recv_windows.get(sender)
        if window is None:
            # keyed by the MAC-covered sender id, so only key-holders can
            # allocate window state — a spoofed UDP source address cannot
            window = self._recv_windows[sender] = _ReplayWindow()
        if not window.check_and_update(ctr):
            self.replayed += 1
            return None
        return wire

    def receive_all_wire(self) -> List[Tuple[Any, bytes]]:
        out = []
        for addr, blob in self.inner.receive_all_wire():
            wire = self._verify(blob)
            if wire is not None:
                out.append((addr, wire))
        return out

    def receive_all_messages(self) -> List[Tuple[Any, Message]]:
        return decode_all(self.receive_all_wire())
