"""Authenticated transport: an opt-in MAC layer the reference never had.

The wire's only packet filter in the reference is the 16-bit magic
(src/network/protocol.rs:551-553); our fuzz suite (tests/test_wire_fuzz.py)
pins the consequence — in-stream tampering that keeps the magic valid can
stall a stream (forged acks) or corrupt inputs. `AuthenticatedSocket`
closes that hole at the transport seam: every datagram carries an 8-byte
SipHash-2-4 tag over its bytes under a 128-bit pre-shared key; receivers
verify before anything else parses, so tampered or unkeyed packets are
indistinguishable from loss (which the reliability layer already absorbs).

Layering: wraps any NonBlockingSocket (UDP, in-memory fault net) and is
transparent to every session stack — Python or native C++ — because all
wire bytes pass through the socket seam. The tag math runs in C++ when the
native library is built (ggrs_native.cpp ggrs_siphash24); the Python
implementation below is the oracle (tests assert tag parity).

Both peers must wrap (or neither): a keyed peer silently drops all
unkeyed traffic, so a key mismatch looks like a dead network — sessions
simply never leave SYNCHRONIZING.

Scope: this authenticates packet CONTENT only — no direction, sequence or
freshness binding — so an on-path attacker can still REPLAY previously
captured datagrams. Replayed input packets are absorbed by the protocol's
own idempotence (frames <= last_recv are skipped; stale acks are
monotonic), but replayed quality reports can feed stale RTT/advantage
into throttling. Forgery and bit-flip tampering are fully blocked.
"""

from __future__ import annotations

import hmac
from typing import Any, List, Tuple

from .messages import Message, decode_all, encode_message

TAG_LEN = 8
KEY_LEN = 16

_MASK = (1 << 64) - 1


def _rotl(x: int, b: int) -> int:
    return ((x << b) | (x >> (64 - b))) & _MASK


def siphash24(key: bytes, data: bytes) -> int:
    """SipHash-2-4 (the reference PRF for short untrusted inputs); 64-bit
    tag under a 128-bit key. Pure-Python oracle for the C++ kernel."""
    assert len(key) == KEY_LEN
    k0 = int.from_bytes(key[:8], "little")
    k1 = int.from_bytes(key[8:], "little")
    v0 = 0x736F6D6570736575 ^ k0
    v1 = 0x646F72616E646F6D ^ k1
    v2 = 0x6C7967656E657261 ^ k0
    v3 = 0x7465646279746573 ^ k1

    def rounds(n: int) -> None:
        nonlocal v0, v1, v2, v3
        for _ in range(n):
            v0 = (v0 + v1) & _MASK
            v1 = _rotl(v1, 13) ^ v0
            v0 = _rotl(v0, 32)
            v2 = (v2 + v3) & _MASK
            v3 = _rotl(v3, 16) ^ v2
            v0 = (v0 + v3) & _MASK
            v3 = _rotl(v3, 21) ^ v0
            v2 = (v2 + v1) & _MASK
            v1 = _rotl(v1, 17) ^ v2
            v2 = _rotl(v2, 32)

    n = len(data)
    for off in range(0, n - n % 8, 8):
        m = int.from_bytes(data[off : off + 8], "little")
        v3 ^= m
        rounds(2)
        v0 ^= m
    last = int.from_bytes(data[n - n % 8 :], "little") | ((n & 0xFF) << 56)
    v3 ^= last
    rounds(2)
    v0 ^= last
    v2 ^= 0xFF
    rounds(4)
    return (v0 ^ v1 ^ v2 ^ v3) & _MASK


def _resolve_tag_fn():
    """Pick the tag backend once (per AuthenticatedSocket) — not per packet."""
    from .. import native as _native

    if _native.available():
        return _native.siphash24
    return lambda key, data: siphash24(key, data).to_bytes(TAG_LEN, "little")


class AuthenticatedSocket:
    """Wraps a NonBlockingSocket; appends/verifies per-datagram MAC tags.
    Invalid tags are dropped silently — to the protocol they are packet
    loss, which it already handles."""

    def __init__(self, inner: Any, key: bytes):
        if len(key) != KEY_LEN:
            raise ValueError(f"key must be {KEY_LEN} bytes, got {len(key)}")
        # tags cover exact wire bytes, so the inner transport must expose
        # them (a message-level-only socket re-decodes before we could
        # verify); both shipped transports do
        if not hasattr(inner, "receive_all_wire") or not hasattr(inner, "send_wire"):
            raise TypeError("AuthenticatedSocket requires a wire-capable socket")
        self.inner = inner
        self.key = bytes(key)
        self.dropped = 0  # observability: tag-verification failures
        self._tag = _resolve_tag_fn()

    def __getattr__(self, name: str):
        # delegate everything else (local_port, close, ...) to the transport
        return getattr(self.inner, name)

    # -- sending --------------------------------------------------------

    def send_wire(self, wire: bytes, addr: Any) -> None:
        self.inner.send_wire(wire + self._tag(self.key, wire), addr)

    def send_to(self, msg: Message, addr: Any) -> None:
        self.send_wire(encode_message(msg), addr)

    # -- receiving ------------------------------------------------------

    def _verify(self, blob: bytes) -> bytes | None:
        if len(blob) < TAG_LEN:
            self.dropped += 1
            return None
        wire, tag = blob[:-TAG_LEN], blob[-TAG_LEN:]
        # constant-time compare: an early-exit != would leak tag-prefix
        # match length through verify latency
        if not hmac.compare_digest(self._tag(self.key, wire), tag):
            self.dropped += 1
            return None
        return wire

    def receive_all_wire(self) -> List[Tuple[Any, bytes]]:
        out = []
        for addr, blob in self.inner.receive_all_wire():
            wire = self._verify(blob)
            if wire is not None:
                out.append((addr, wire))
        return out

    def receive_all_messages(self) -> List[Tuple[Any, Message]]:
        return decode_all(self.receive_all_wire())
