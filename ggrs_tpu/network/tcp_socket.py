"""TCP-backed transport behind the unreliable-datagram seam.

The reference ecosystem swaps transports behind its NonBlockingSocket trait
(WebRTC data channels via matchbox, README.md:50-55). This is our second
real transport witness: datagram semantics over TCP streams — the shape a
WebRTC/relay/stream transport takes — implementing the exact socket
protocol (`send_to`/`receive_all_messages` plus the wire-level API the
authenticated wrapper and native endpoints compose with).

Design:
- one listening socket per peer; outgoing connections are created lazily on
  first send and complete asynchronously (writes buffer until the stream
  opens — "never block" is the seam's contract).
- frames are [2-byte BE length][1-byte type][payload]; type 1 is a HELLO
  carrying the sender's canonical listen port, sent once per outgoing
  connection, so received messages are attributed to the peer's LISTEN
  address (sessions route by address; the ephemeral source port of an
  accepted stream would never match the configured remote).
- a dead stream drops its buffered frames and the connection — exactly the
  loss the datagram seam already tolerates; the endpoint protocol's
  ack/resend machinery recovers.
"""

from __future__ import annotations

import socket as _socket
from typing import Any, Dict, List, Optional, Tuple

from .messages import Message, decode_all, encode_message

_DATA = 0
_HELLO = 1
_MAX_FRAME = 65532


class _Conn:
    def __init__(self, sock: _socket.socket, peer: Optional[Tuple[str, int]]):
        self.sock = sock
        # canonical (numeric IP, listen_port); None until HELLO. User-facing
        # attribution resolves through the socket's alias map at READ time
        # (not latched here): the alias may only be registered by a later
        # outgoing send.
        self.peer = peer
        self.outbuf = bytearray()
        self.inbuf = bytearray()
        self.dead = False

    # a stalled stream must behave like a full UDP socket buffer: new
    # datagrams are LOST, not queued without bound (unbounded queueing leaks
    # memory and floods the peer with minutes-old packets on recovery)
    MAX_OUTBUF = 256 * 1024

    def queue(self, kind: int, payload: bytes) -> None:
        n = len(payload) + 1
        assert n <= _MAX_FRAME + 1, "frame too large for 2-byte framing"
        if len(self.outbuf) > self.MAX_OUTBUF:
            return  # datagram loss, the seam's contract
        self.outbuf += n.to_bytes(2, "big") + bytes([kind]) + payload

    def flush(self) -> None:
        while self.outbuf and not self.dead:
            try:
                sent = self.sock.send(self.outbuf)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self.dead = True
                return
            if sent <= 0:
                return
            del self.outbuf[:sent]

    def read_frames(self) -> List[Tuple[int, bytes]]:
        while not self.dead:
            try:
                chunk = self.sock.recv(65536)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self.dead = True
                break
            if not chunk:  # orderly close
                self.dead = True
                break
            self.inbuf += chunk
        frames = []
        while len(self.inbuf) >= 2:
            n = int.from_bytes(self.inbuf[:2], "big")
            if len(self.inbuf) < 2 + n:
                break
            body = bytes(self.inbuf[2 : 2 + n])
            del self.inbuf[: 2 + n]
            if body:
                frames.append((body[0], body[1:]))
        return frames


class TcpDatagramSocket:
    """Datagram-seam socket over TCP. Addresses are (host, port) tuples
    naming the peer's LISTEN port, like the UDP transport."""

    def __init__(self, port: int, host: str = "0.0.0.0"):
        self._listener = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        self._listener.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self._listener.setblocking(False)
        self._all: List[_Conn] = []  # every live stream (polled for reads)
        self._conns: Dict[Tuple[str, int], _Conn] = {}  # canonical -> send route
        self._resolved: Dict[str, str] = {}  # hostname -> numeric IP cache
        # canonical -> the address form the user sent to: sessions route
        # inbound messages by their CONFIGURED address, so attribution must
        # echo that form back, not the resolved IP
        self._alias: Dict[Tuple[str, int], Any] = {}

    @property
    def local_port(self) -> int:
        return self._listener.getsockname()[1]

    def _canon(self, addr: Any) -> Tuple[str, int]:
        """Canonical route key: (numeric IP, port). Incoming messages are
        attributed to (getpeername() IP, HELLO listen port) — numeric — so
        a session configured with a hostname ('localhost') must resolve to
        the same key or its inbound traffic would never match the send
        route. Resolution is cached: this runs on every send."""
        host, port = tuple(addr)
        ip = self._resolved.get(host)
        if ip is None:
            try:
                ip = _socket.gethostbyname(host)
            except OSError:
                # transient DNS failure: do NOT cache it — the next send
                # retries resolution (a cached failure would blackhole the
                # peer for the socket's lifetime); meanwhile the verbatim
                # key just loses this datagram, the seam's contract
                return (host, int(port))
            self._resolved[host] = ip
        return (ip, int(port))

    # -- outgoing ----------------------------------------------------------

    def _connect(self, addr: Tuple[str, int]) -> _Conn:
        sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        sock.setblocking(False)
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        try:
            sock.connect(addr)
        except (BlockingIOError, InterruptedError, OSError):
            pass  # in progress (or refused: surfaces as a dead stream)
        conn = _Conn(sock, tuple(addr))
        conn.queue(_HELLO, int(self.local_port).to_bytes(2, "big"))
        self._conns[tuple(addr)] = conn
        self._all.append(conn)
        return conn

    def send_wire(self, wire: bytes, addr: Any) -> None:
        orig = tuple(addr)
        canon = self._canon(orig)
        self._alias.setdefault(canon, orig)
        conn = self._conns.get(canon)
        if conn is not None and conn.dead:
            # the stream to this IP died: drop the cached resolution and
            # re-resolve, so a hostname that now points elsewhere (DNS
            # failover, container restart with a new IP) routes the
            # reconnect to the CURRENT address instead of the stale one
            # for the socket's lifetime (r3 advisor)
            self._resolved.pop(orig[0], None)
            new_canon = self._canon(orig)
            if new_canon != canon:
                self._alias.setdefault(new_canon, orig)
                canon = new_canon
                conn = self._conns.get(canon)
        if conn is None or conn.dead:
            conn = self._connect(canon)
        conn.queue(_DATA, wire)
        conn.flush()

    def send_wire_batch(self, batch) -> None:
        """Batched drain: per-datagram framing on the stream, one call."""
        for wire, addr in batch:
            self.send_wire(wire, addr)

    def send_to(self, msg: Message, addr: Any) -> None:
        self.send_wire(encode_message(msg), addr)

    # -- incoming ----------------------------------------------------------

    def _accept_new(self) -> None:
        while True:
            try:
                sock, _src = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setblocking(False)
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            self._all.append(_Conn(sock, None))

    def receive_all_wire(self) -> List[Tuple[Any, bytes]]:
        self._accept_new()
        received: List[Tuple[Any, bytes]] = []

        for conn in list(self._all):
            for kind, payload in conn.read_frames():
                if kind == _HELLO and len(payload) == 2:
                    try:
                        host = conn.sock.getpeername()[0]
                    except OSError:
                        conn.dead = True
                        break
                    canon = (host, int.from_bytes(payload, "big"))
                    conn.peer = canon
                    # most-recent HELLO wins the send route: a peer that
                    # silently restarted (no FIN/RST — its old stream looks
                    # alive for the TCP retransmit window, ~minutes) dials
                    # back in and must take over immediately; duplicates
                    # (both sides dialing at once) are all still polled
                    # via _all
                    self._conns[canon] = conn
                elif kind == _DATA and conn.peer is not None:
                    received.append(
                        (self._alias.get(conn.peer, conn.peer), payload)
                    )
            conn.flush()  # opportunistic drain of queued writes

        for conn in [c for c in self._all if c.dead]:
            self._all.remove(conn)
            conn.sock.close()
        for peer in [p for p, c in self._conns.items() if c.dead]:
            del self._conns[peer]
            # a hostname cached to this now-dead IP must re-resolve on the
            # next send (DNS failover): dropping it HERE matters because
            # this reap removes the conn from _conns, which would otherwise
            # skip send_wire's dead-conn re-resolution branch entirely and
            # reconnect to the stale IP forever
            for host in [h for h, ip in self._resolved.items() if ip == peer[0]]:
                del self._resolved[host]
        return received

    def receive_all_messages(self) -> List[Tuple[Any, Message]]:
        return decode_all(self.receive_all_wire())

    def close(self) -> None:
        self._listener.close()
        for conn in self._all:
            conn.sock.close()
