"""Transport layer: the pluggable unreliable-datagram seam.

Mirrors the reference's L1 (NonBlockingSocket trait, src/lib.rs:264-279 and
UDP impl src/network/udp_socket.rs) and adds the piece the reference left
unbuilt (SURVEY.md §4): an in-memory virtual network with programmable
latency, loss, reordering and duplication driven by a seeded RNG and an
injectable clock — deterministic protocol tests without real sockets.
"""

from __future__ import annotations

import heapq
import random
import socket as _socket
from typing import Any, Dict, List, Protocol, Tuple

from ..utils.clock import Clock
from .messages import Message, decode_all, encode_message

# Sized to cover the largest datagram UDP can carry (65507 payload bytes):
# the old 4096 silently truncated any fused-input datagram that outgrew it —
# recvfrom() drops the excess without an error, and the codec then either
# rejects the tail-less message or, worse, decodes a shorter valid prefix.
# Senders enforce the same bound eagerly (check_datagram_size) so an
# overgrown message fails loudly at the encode site, not as a mystery
# truncation on the receiving peer.
RECV_BUFFER_SIZE = 65536
# the bound senders enforce: the receive buffer, capped at the largest
# payload UDP itself can carry — a datagram in (65507, 65536] would clear
# the buffer but die in sendto() with EMSGSIZE on the real transport, so
# the virtual network must reject it too
MAX_DATAGRAM_SIZE = min(RECV_BUFFER_SIZE, 65507)


def check_datagram_size(wire: bytes) -> bytes:
    """Encode-side twin of the receive buffer: every transport send path
    funnels through here so a message that could not survive recvfrom()
    (or UDP itself) raises at the sender, where the stack trace names the
    oversized message, instead of silently truncating at the receiver.
    A real exception (not an assert) so the guard survives `python -O`."""
    if len(wire) > MAX_DATAGRAM_SIZE:
        from ..errors import InvalidRequest

        raise InvalidRequest(
            f"datagram of {len(wire)} bytes exceeds MAX_DATAGRAM_SIZE "
            f"({MAX_DATAGRAM_SIZE}): it would be truncated or rejected by "
            "the real transport — split the message or grow the buffer"
        )
    return wire


class NonBlockingSocket(Protocol):
    """Unreliable, unordered datagram transport. The endpoint protocol layers
    reliability on top; implementations must never block."""

    def send_to(self, msg: Message, addr: Any) -> None: ...

    def receive_all_messages(self) -> List[Tuple[Any, Message]]: ...


class UdpNonBlockingSocket:
    """Nonblocking UDP bound to 0.0.0.0:port (src/network/udp_socket.rs:17-55).
    Addresses are (host, port) tuples."""

    def __init__(self, port: int):
        self.sock = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        self.sock.bind(("0.0.0.0", port))
        self.sock.setblocking(False)

    @property
    def local_port(self) -> int:
        return self.sock.getsockname()[1]

    def send_to(self, msg: Message, addr: Any) -> None:
        self.sock.sendto(check_datagram_size(encode_message(msg)), addr)

    def send_wire(self, wire: bytes, addr: Any) -> None:
        """Pre-encoded fast path used by native endpoints."""
        self.sock.sendto(check_datagram_size(wire), addr)

    def send_wire_batch(self, batch: List[Tuple[bytes, Any]]) -> None:
        """sendmmsg-style drain: ship one pump pass's (wire, addr) pairs
        in a single call — CPython exposes no sendmmsg(2), so this is a
        bound-method sendto loop, which still amortizes the per-message
        Python dispatch the legacy send path paid."""
        sendto = self.sock.sendto
        for wire, addr in batch:
            sendto(check_datagram_size(wire), addr)

    def receive_all_wire(self) -> List[Tuple[Any, bytes]]:
        """Raw datagrams (pre-codec): used by native endpoints and the
        authenticated-transport wrapper, which must see exact wire bytes."""
        received: List[Tuple[Any, bytes]] = []
        while True:
            try:
                buf, src = self.sock.recvfrom(RECV_BUFFER_SIZE)
            except BlockingIOError:
                return received
            except ConnectionResetError:
                continue
            received.append((src, buf))

    def receive_all_messages(self) -> List[Tuple[Any, Message]]:
        return decode_all(self.receive_all_wire())

    def close(self) -> None:
        self.sock.close()


class FaultProfile(Protocol):
    """Per-link fault model seam for InMemoryNetwork: given one datagram's
    (src, dst, now, rng), return the delivery delays in milliseconds —
    `[]` drops the datagram, one entry delivers once, N entries duplicate
    it N ways (distinct delays reorder the copies). Implementations must
    draw ONLY from the passed rng (and their own seeded state) so a run
    stays deterministic per seed. The WAN-shaped profiles (regional RTT
    matrices, Gilbert-Elliott loss bursts, reorder spikes) live in
    ggrs_tpu.serve.chaos."""

    def link(
        self, src: Any, dst: Any, now_ms: int, rng: random.Random
    ) -> List[int]: ...


class InMemoryNetwork:
    """A hub of virtual endpoints sharing one fault model and one clock.

    Two fault tiers: the flat knobs (latency/jitter/loss/duplicate — the
    original uniform model, untouched defaults) or a `profile` object
    (FaultProfile) that decides per-link, per-datagram delivery — the
    chaos loadgen's WAN shapes. `blackholed` addresses drop everything in
    AND out silently (mass-disconnect storms, dead-host simulation): the
    sender never learns, exactly like real packet loss."""

    def __init__(
        self,
        clock: Clock,
        *,
        latency_ms: int = 0,
        jitter_ms: int = 0,
        loss: float = 0.0,
        duplicate: float = 0.0,
        seed: int = 0,
        profile: "FaultProfile | None" = None,
    ):
        self.clock = clock
        self.latency_ms = latency_ms
        self.jitter_ms = jitter_ms
        self.loss = loss
        self.duplicate = duplicate
        self.rng = random.Random(seed)
        self.profile = profile
        self.blackholed: set = set()
        # addr -> heap of (deliver_at_ms, seq, (src, wire_bytes))
        self.queues: Dict[Any, List[Tuple[int, int, Tuple[Any, bytes]]]] = {}
        self._seq = 0

    def socket(self, addr: Any) -> "InMemorySocket":
        self.queues.setdefault(addr, [])
        return InMemorySocket(self, addr)

    def set_blackhole(self, addrs, on: bool = True) -> None:
        """Silently drop all traffic to AND from these addresses (on) or
        lift the blackout (off). Queued-but-undelivered datagrams are
        left to deliver: they were already 'in the air'."""
        if on:
            self.blackholed.update(addrs)
        else:
            self.blackholed.difference_update(addrs)

    def _deliver(self, src: Any, dst: Any, wire: bytes) -> None:
        if src in self.blackholed or dst in self.blackholed:
            return
        if self.profile is not None:
            delays = self.profile.link(
                src, dst, self.clock.now_ms(), self.rng
            )
        else:
            if self.rng.random() < self.loss:
                return
            copies = 2 if self.rng.random() < self.duplicate else 1
            delays = []
            for _ in range(copies):
                delay = self.latency_ms
                if self.jitter_ms:
                    delay += self.rng.randint(0, self.jitter_ms)
                delays.append(delay)
        now = self.clock.now_ms()
        for delay in delays:
            self._seq += 1
            heapq.heappush(
                self.queues.setdefault(dst, []),
                (now + delay, self._seq, (src, wire)),
            )

    def _drain_wire(self, addr: Any) -> List[Tuple[Any, bytes]]:
        q = self.queues.setdefault(addr, [])
        now = self.clock.now_ms()
        out: List[Tuple[Any, bytes]] = []
        while q and q[0][0] <= now:
            _, _, (src, wire) = heapq.heappop(q)
            out.append((src, wire))
        return out

    def _drain(self, addr: Any) -> List[Tuple[Any, Message]]:
        return decode_all(self._drain_wire(addr))


class InMemorySocket:
    """One endpoint's view of an InMemoryNetwork; satisfies NonBlockingSocket."""

    def __init__(self, net: InMemoryNetwork, addr: Any):
        self.net = net
        self.addr = addr

    def send_to(self, msg: Message, addr: Any) -> None:
        # serialize through the real wire codec so fault tests cover it
        self.net._deliver(
            self.addr, addr, check_datagram_size(encode_message(msg))
        )

    def send_wire(self, wire: bytes, addr: Any) -> None:
        """Pre-encoded fast path used by native endpoints; enforces the
        same datagram bound as the real UDP socket so the virtual network
        never delivers a message the real transport would truncate."""
        self.net._deliver(self.addr, addr, check_datagram_size(wire))

    def send_wire_batch(self, batch: List[Tuple[bytes, Any]]) -> None:
        """Batched drain (UdpNonBlockingSocket.send_wire_batch's virtual
        twin): same per-datagram bound and fault model, one call."""
        deliver = self.net._deliver
        src = self.addr
        for wire, addr in batch:
            deliver(src, addr, check_datagram_size(wire))

    def receive_all_wire(self) -> List[Tuple[Any, bytes]]:
        return self.net._drain_wire(self.addr)

    def receive_all_messages(self) -> List[Tuple[Any, Message]]:
        return self.net._drain(self.addr)
