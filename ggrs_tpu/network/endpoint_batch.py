"""Vectorized protocol plane: one array program per pump for the whole
fleet's endpoints.

PR 13's device-resident loop removed ~15/16 of tick-program dispatches
yet only broke even end-to-end, because per-peer Python endpoint work —
timer scans, frame-advantage updates, ack/resend bookkeeping — still
scales O(peers) in interpreted Python on every pump pass. This module
moves that scan one layer down, exactly the way network/pump.py moved
the wire decode: the hot per-peer state of every adopted `PeerEndpoint`
lives in structured numpy columns (an `EndpointFleet`), and each pump
pass runs ONE vectorized program over the whole fleet:

  - frame-advantage update: `recv_frame + (rtt//2 * fps)//1000 - cur`
    as int64 column arithmetic, masked to RUNNING remotes;
  - timer expiry: every deadline in the 200ms family compared against
    the pass's hoisted clock in a single boolean-mask pass;
  - resend/keepalive/quality-report/disconnect candidates and
    endpoints with queued events or sends selected by `flatnonzero`
    over dirty flags the `_SignalDeque` append hook maintains.

Only the mask-selected survivors drop into per-peer Python: candidates
re-run the VERBATIM scalar timer body (`PeerEndpoint._poll_timers`), so
the masks only need to be a superset snapshot of the fire conditions —
re-evaluating the exact scalar conditions on the survivors keeps the
batched and scalar paths bit-identical by construction (the parity twin
below a `SMALL_FLEET` crossover is the unmodified per-session
`_pump_post`, auto-selected exactly like pump.py's `SMALL_BATCH` decode
routing; `batched_pump=False` pins the legacy per-message loop
end-to-end).

Adoption swaps an endpoint's `_hot` backing store (`_ScalarHot`) for a
`_FleetRow` view over its column row; retirement copies the row back
out. Protocol code never knows which backing it runs on. Sessions with
native (C++) endpoints are never adopted — their hot state lives across
the FFI boundary — and keep the scalar path.

Fence note (analysis/fence.py FEN001): the fleet columns, the row->
endpoint tables and the allocator state are shared mutable state reused
across pump passes; only the fleet's own alloc/adopt/retire entry
points may rebind them. The per-pass masks are locals derived from the
columns, so the vectorized pass itself never rebinds fleet state.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..errors import GGRSError
from ..obs import GLOBAL_TELEMETRY, LOG2_BUCKETS
from ..types import NULL_FRAME
from .protocol import (
    _HOT_BOOL_FIELDS,
    _HOT_INT_FIELDS,
    KEEP_ALIVE_INTERVAL_MS,
    QUALITY_REPORT_INTERVAL_MS,
    RUNNING_RETRY_INTERVAL_MS,
    SYNC_RETRY_INTERVAL_MS,
    ProtocolState,
    _ScalarHot,
)

# passes over fewer than this many endpoints run the scalar per-session
# `_pump_post` twin instead: the vectorized pass costs ~10 fixed numpy
# ops plus a gather per column, which dwarfs a 2-peer standalone
# session's two direct poll calls (the SMALL_BATCH story one layer up,
# measured at the same order of magnitude). Hosted fleets of >= 64
# sessions sit far above it. WirePump snapshots this at construction
# (`small_fleet`), so tests can force either route per pump instance.
SMALL_FLEET = 8

_STATES = tuple(ProtocolState)
_SYNCHRONIZING = ProtocolState.SYNCHRONIZING.value
_RUNNING = ProtocolState.RUNNING.value
_DISCONNECTED = ProtocolState.DISCONNECTED.value


class _FleetRow:
    """Thin hot-field view over one fleet-array row: the backing store a
    `PeerEndpoint` gets on adoption. Each generated property converts to
    plain Python scalars on read so fleet-adopted endpoints hand out the
    exact types the scalar twin does (wire encode, dict keys, enum
    compares)."""

    __slots__ = ("_c", "_r")

    def __init__(self, cols: Dict[str, np.ndarray], row: int):
        self._c = cols
        self._r = row


def _int_cell(name: str) -> property:
    def _get(self, _n=name):
        return int(self._c[_n][self._r])

    def _set(self, value, _n=name):
        self._c[_n][self._r] = value

    return property(_get, _set)


def _int_cell_flagged(name: str) -> property:
    """Like _int_cell, but writes also raise the fleet-wide `_adv_dirty`
    latch: the field feeds the vectorized frame-advantage program, so
    the pass can skip that block entirely while no input has changed
    (the idle-pump common case)."""

    def _get(self, _n=name):
        return int(self._c[_n][self._r])

    def _set(self, value, _n=name):
        c = self._c
        c[_n][self._r] = value
        c["_adv_dirty"][0] = True

    return property(_get, _set)


def _bool_cell(name: str) -> property:
    def _get(self, _n=name):
        return bool(self._c[_n][self._r])

    def _set(self, value, _n=name):
        self._c[_n][self._r] = bool(value)

    return property(_get, _set)


# the frame-advantage inputs: a write to any of them (or to `state`)
# invalidates the pass's advantage-skip latch below
_ADV_INPUT_FIELDS = ("recv_frame", "round_trip_time")

for _name in _HOT_INT_FIELDS:
    setattr(
        _FleetRow,
        _name,
        _int_cell_flagged(_name)
        if _name in _ADV_INPUT_FIELDS
        else _int_cell(_name),
    )
for _name in _HOT_BOOL_FIELDS:
    setattr(_FleetRow, _name, _bool_cell(_name))


def _set_state(self, value):
    c = self._c
    c["state"][self._r] = value.value
    c["_adv_dirty"][0] = True


_FleetRow.state = property(
    lambda self: _STATES[self._c["state"][self._r]],
    _set_state,
)
del _name


class _FleetSession:
    """Per-adopted-session bookkeeping: the contiguous row block, how
    many leading rows are remotes (the frame-advantage prefix), and the
    scalar hooks the per-survivor work needs."""

    __slots__ = ("fleet", "start", "n", "adv_n", "connect_status", "checksums")

    def __init__(self, fleet, start, n, adv_n, connect_status, checksums):
        self.fleet = fleet
        self.start = start
        self.n = n
        self.adv_n = adv_n
        self.connect_status = connect_status
        self.checksums = checksums


class _PassPlan:
    """Cached row geometry for a repeated session set: the concatenated
    row index array, per-session bounds into it, and the advantage
    prefix rows. A host pumps the same fleet every tick, so this
    rebuilds only on adopt/retire or a changed pass set.

    `ix` is the gather index the per-pass column reads use: a plain
    slice when the session blocks happen to be contiguous in adoption
    order (the steady hosted case — column reads are then zero-copy
    views), the fancy row array otherwise. `counts`/`adv_*`/`cks_idx`
    pre-resolve the per-session geometry so the pass scatters clocks
    with one np.repeat instead of a per-session slice loop and visits
    only checksum-carrying sessions in the drain loop."""

    __slots__ = (
        "rows", "rows_list", "bounds", "ix", "counts",
        "adv_rows", "adv_idx", "adv_counts", "cks_idx", "last_cur",
    )

    def __init__(self, rows, rows_list, bounds, ix, counts,
                 adv_rows, adv_idx, adv_counts, cks_idx):
        self.last_cur = None  # per-session current_frame of the last pass
        self.rows = rows
        self.rows_list = rows_list
        self.bounds = bounds
        self.ix = ix
        self.counts = counts
        self.adv_rows = adv_rows
        self.adv_idx = adv_idx
        self.adv_counts = adv_counts
        self.cks_idx = cks_idx


_INT_COLS = _HOT_INT_FIELDS + ("now", "cur")
_BOOL_COLS = _HOT_BOOL_FIELDS + ("send_dirty", "events_dirty")


class EndpointFleet:
    """Structured-array home for every adopted endpoint's hot state and
    the vectorized endpoint/encode phases of a pump pass. One fleet per
    WirePump: the host's pump adopts its whole session fleet; the
    module-default pump serves standalone sessions the same way once a
    pass crosses the SMALL_FLEET crossover."""

    __slots__ = (
        "cols", "eps", "emits", "top", "cap", "free_blocks",
        "gen", "live_rows", "live_sessions", "adopted_total", "passes",
        "_plan_gen", "_plan_sessions", "_plan", "_m_peers",
    )

    def __init__(self, cap: int = 64):
        self.cap = cap
        self.top = 0
        cols: Dict[str, np.ndarray] = {}
        for name in _INT_COLS:
            cols[name] = np.zeros(cap, dtype=np.int64)
        for name in _BOOL_COLS:
            cols[name] = np.zeros(cap, dtype=bool)
        cols["state"] = np.zeros(cap, dtype=np.uint8)
        # fleet-wide latch, not a row column (never grows): any write to
        # an advantage input re-arms the vectorized advantage block
        cols["_adv_dirty"] = np.ones(1, dtype=bool)
        self.cols = cols
        self.eps: List[Any] = []
        self.emits: List[Any] = []
        self.free_blocks: List[Tuple[int, int]] = []
        self.gen = 0
        self.live_rows = 0
        self.live_sessions = 0
        self.adopted_total = 0
        self.passes = 0
        self._plan_gen = -1
        self._plan_sessions: List[Any] = []
        self._plan: Optional[_PassPlan] = None
        self._m_peers = GLOBAL_TELEMETRY.registry.histogram(
            "ggrs_endpoint_batch_peers",
            "endpoints covered per vectorized protocol-plane pass",
            buckets=LOG2_BUCKETS,
        )

    # ------------------------------------------------------------------
    # adoption / retirement (the only writers of fleet storage)
    # ------------------------------------------------------------------

    def _grow(self, need: int) -> None:
        cap = self.cap
        while cap < need:
            cap *= 2
        cols = self.cols
        for name, arr in list(cols.items()):
            if name == "_adv_dirty":  # fleet-wide latch, not per-row
                continue
            grown = np.zeros(cap, dtype=arr.dtype)
            grown[: self.top] = arr[: self.top]
            # rebind IN the shared dict: every _FleetRow and bound
            # _SignalDeque resolves columns through it, so views never
            # go stale across growth
            cols[name] = grown
        self.cap = cap

    def _alloc(self, n: int) -> int:
        for bi, (bs, bn) in enumerate(self.free_blocks):
            if bn == n:
                del self.free_blocks[bi]
                return bs
        if self.top + n > self.cap:
            self._grow(self.top + n)
        start = self.top
        self.top += n
        while len(self.eps) < self.top:
            self.eps.append(None)
            self.emits.append(None)
        return start

    def adopt(self, session: Any) -> bool:
        """Hoist `session`'s endpoints into fleet rows. Returns False
        (and leaves the session scalar) when it is not fleetable —
        native endpoints, or no endpoints at all. Idempotent; a session
        adopted by another fleet (standalone pump -> host pump) is
        retired there first."""
        st = getattr(session, "_fleet_state", None)
        if st is not None:
            if st.fleet is self:
                return True
            st.fleet.retire_session(session)
        profile = session._fleet_profile()
        if profile is None:
            return False
        eps = profile["endpoints"]
        emits = profile["emits"]
        n = len(eps)
        start = self._alloc(n)
        cols = self.cols
        for i, ep in enumerate(eps):
            row = start + i
            hot = ep._hot
            cols["state"][row] = hot.state.value
            for name in _HOT_INT_FIELDS:
                cols[name][row] = getattr(hot, name)
            for name in _HOT_BOOL_FIELDS:
                cols[name][row] = getattr(hot, name)
            cols["send_dirty"][row] = False
            cols["events_dirty"][row] = False
            ep._hot = _FleetRow(cols, row)
            self.eps[row] = ep
            self.emits[row] = emits[i]
            # bind AFTER clearing the flags: a non-empty queue re-marks
            ep.send_queue.bind(cols, row, "send_dirty")
            ep.event_queue.bind(cols, row, "events_dirty")
        session._fleet_state = _FleetSession(
            self, start, n, profile["adv_n"],
            profile["connect_status"], profile["checksums"],
        )
        self.live_rows += n
        self.live_sessions += 1
        self.adopted_total += n
        self.gen += 1
        return True

    def retire_session(self, session: Any) -> None:
        """Copy the session's rows back into standalone `_ScalarHot`
        stores and free the block (host detach, fleet handoff). The
        endpoints keep working scalar — bit-identically."""
        st = getattr(session, "_fleet_state", None)
        if st is None or st.fleet is not self:
            return
        cols = self.cols
        for row in range(st.start, st.start + st.n):
            ep = self.eps[row]
            if ep is not None:
                hot = _ScalarHot()
                hot.state = _STATES[int(cols["state"][row])]
                for name in _HOT_INT_FIELDS:
                    setattr(hot, name, int(cols[name][row]))
                for name in _HOT_BOOL_FIELDS:
                    setattr(hot, name, bool(cols[name][row]))
                ep._hot = hot
                ep.send_queue.unbind()
                ep.event_queue.unbind()
            self.eps[row] = None
            self.emits[row] = None
        self.free_blocks.append((st.start, st.n))
        self.live_rows -= st.n
        self.live_sessions -= 1
        self.gen += 1
        session._fleet_state = None

    # ------------------------------------------------------------------
    # the vectorized pass
    # ------------------------------------------------------------------

    def _pass_plan(self, sessions: Sequence[Any]) -> _PassPlan:
        # cache hit on (generation, same session objects in order): an
        # element-wise identity sweep, so the steady per-pass cost is a
        # zip of `is` checks, not a 2x-per-pump key-tuple rebuild over
        # attribute chains. Element identity (not list identity) also
        # hits for the encode phase's freshly-built `live` list.
        if self._plan_gen == self.gen and len(self._plan_sessions) == len(
            sessions
        ):
            for a, b in zip(self._plan_sessions, sessions):
                if a is not b:
                    break
            else:
                return self._plan
        bounds = np.empty(len(sessions) + 1, dtype=np.int64)
        bounds[0] = 0
        counts = np.empty(len(sessions), dtype=np.int64)
        parts: List[np.ndarray] = []
        adv_parts: List[np.ndarray] = []
        adv_idx: List[int] = []
        adv_counts: List[int] = []
        cks_idx: List[int] = []
        off = 0
        contiguous = True
        expected = None
        for i, s in enumerate(sessions):
            st = s._fleet_state
            if expected is not None and st.start != expected:
                contiguous = False
            expected = st.start + st.n
            parts.append(np.arange(st.start, st.start + st.n, dtype=np.int64))
            if st.adv_n:
                adv_parts.append(
                    np.arange(st.start, st.start + st.adv_n, dtype=np.int64)
                )
                adv_idx.append(i)
                adv_counts.append(st.adv_n)
            if st.checksums:
                cks_idx.append(
                    (i, getattr(s, "_pending_checksum_report", None))
                )
            off += st.n
            bounds[i + 1] = off
            counts[i] = st.n
        rows = (
            np.concatenate(parts) if parts else np.empty(0, dtype=np.int64)
        )
        plan = _PassPlan(
            rows=rows,
            rows_list=rows.tolist(),
            bounds=bounds,
            ix=(
                slice(int(rows[0]), int(rows[0]) + rows.size)
                if contiguous and rows.size
                else rows
            ),
            counts=counts,
            adv_rows=(
                np.concatenate(adv_parts)
                if adv_parts
                else np.empty(0, dtype=np.int64)
            ),
            adv_idx=adv_idx,
            adv_counts=np.asarray(adv_counts, dtype=np.int64),
            cks_idx=cks_idx,
        )
        self._plan_gen = self.gen
        self._plan_sessions = list(sessions)
        self._plan = plan
        return plan

    def endpoint_phase(
        self,
        sessions: Sequence[Any],
        nows: Sequence[int],
        isolate: bool,
        errors: List[Tuple[Any, Exception]],
        failed: Set[int],
    ) -> None:
        """Advantage + timers + events + checksum drains for the whole
        pass in one array program; per-endpoint Python only for the
        mask-selected survivors. Scalar-twin order per session:
        advantage -> timers -> events -> checksums (the verbatim
        `_pump_endpoint` sequence)."""
        plan = self._pass_plan(sessions)
        rows = plan.rows
        if rows.size == 0:
            return
        cols = self.cols
        now_col = cols["now"]
        cur_col = cols["cur"]
        ix = plan.ix
        # clock scatter: hosted fleets share one virtual clock, so the
        # common case is a single broadcast fill; mixed clocks fall back
        # to one np.repeat over the pass geometry (never a per-session
        # loop)
        n0 = nows[0]
        uniform_now = True
        for v in nows:
            if v != n0:
                uniform_now = False
                break
        if uniform_now:
            now_col[ix] = n0
        else:
            now_col[ix] = np.repeat(
                np.asarray(nows, dtype=np.int64), plan.counts
            )

        self.passes += 1
        if GLOBAL_TELEMETRY.enabled:
            self._m_peers.observe(rows.size)

        # -- frame advantage, vectorized over every RUNNING remote -----
        # The block is a pure function of (state, recv_frame,
        # round_trip_time, fps, current_frame); writes to the first
        # three raise the fleet-wide `_adv_dirty` latch, so a pass that
        # covers every live row may skip the whole block while no input
        # changed — the idle-pump floor. Partial passes (standalone
        # sessions sharing the fleet) never trust the latch: clearing it
        # for a subset would starve the rows the pass did not cover.
        adv = plan.adv_rows
        if adv.size:
            cur_vals = [
                sessions[i].sync_layer.current_frame for i in plan.adv_idx
            ]
            adv_dirty = cols["_adv_dirty"]
            full_pass = rows.size == self.live_rows
            if (
                not full_pass
                or adv_dirty[0]
                or cur_vals != plan.last_cur
            ):
                cur_col[adv] = np.repeat(
                    np.asarray(cur_vals, dtype=np.int64),
                    plan.adv_counts,
                )
                a_state = cols["state"][adv]
                a_recv = cols["recv_frame"][adv]
                a_cur = cur_col[adv]
                mask = (
                    (a_state == _RUNNING)
                    & (a_recv != NULL_FRAME)
                    & (a_cur != NULL_FRAME)
                )
                if mask.any():
                    ping = cols["round_trip_time"][adv] >> 1
                    remote = a_recv + (ping * cols["fps"][adv]) // 1000
                    cols["local_frame_advantage"][adv[mask]] = (
                        remote - a_cur
                    )[mask]
                if full_pass:
                    adv_dirty[0] = False
                    plan.last_cur = cur_vals

        # -- timer expiry: ONE comparison pass for the 200ms family ----
        # (`ix` reads are zero-copy views on the contiguous steady path)
        state = cols["state"][ix]
        # folded form `a < now - C` (not `a + C < now`): on the shared-
        # clock path `now_r` is a Python int, so the subtraction costs
        # nothing and each family is one array compare
        now_r = n0 if uniform_now else now_col[ix]
        last_recv = cols["last_recv_time"][ix]
        cand = (state == _SYNCHRONIZING) & (
            cols["last_sync_request_time"][ix]
            < now_r - SYNC_RETRY_INTERVAL_MS
        )
        running = state == _RUNNING
        cand |= running & (
            cols["running_last_input_recv"][ix]
            < now_r - RUNNING_RETRY_INTERVAL_MS
        )
        cand |= running & (
            cols["running_last_quality_report"][ix]
            < now_r - QUALITY_REPORT_INTERVAL_MS
        )
        cand |= running & (
            cols["last_send_time"][ix] < now_r - KEEP_ALIVE_INTERVAL_MS
        )
        cand |= (
            running
            & ~cols["disconnect_notify_sent"][ix]
            & (last_recv + cols["disconnect_notify_start_ms"][ix] < now_r)
        )
        cand |= (
            running
            & ~cols["disconnect_event_sent"][ix]
            & (last_recv + cols["disconnect_timeout_ms"][ix] < now_r)
        )
        cand |= (state == _DISCONNECTED) & (
            cols["shutdown_timeout"][ix] < now_r
        )
        work = cand | cols["events_dirty"][ix]
        widx = np.flatnonzero(work)
        if widx.size:
            # per-session spans of the survivors: one searchsorted, not
            # one slice per session — and only work-carrying sessions
            # are visited at all
            pos = np.searchsorted(widx, plan.bounds)
            eps = self.eps
            emits = self.emits
            events_dirty = cols["events_dirty"]
            rows_list = plan.rows_list
            for i in np.flatnonzero(pos[1:] > pos[:-1]).tolist():
                s = sessions[i]
                st = s._fleet_state
                try:
                    span = widx[pos[i] : pos[i + 1]].tolist()
                    connect_status = st.connect_status
                    now_i = nows[i]
                    for j in span:
                        if cand[j]:
                            # survivors re-run the verbatim scalar timer
                            # body: the mask is a superset snapshot, the
                            # recheck is what keeps bitwise parity
                            eps[rows_list[j]]._poll_timers(
                                connect_status, now_i
                            )
                    pending = None
                    for j in span:
                        r = rows_list[j]
                        if events_dirty[r]:
                            events_dirty[r] = False
                            q = eps[r].event_queue
                            if q:
                                if pending is None:
                                    pending = []
                                # snapshot-then-handle, the scalar poll's
                                # list()/clear() semantics
                                pending.append((emits[r], list(q)))
                                q.clear()
                    if pending is not None:
                        for emit, evs in pending:
                            for ev in evs:
                                emit(ev)
                except GGRSError as exc:
                    if not isolate:
                        raise
                    failed.add(s)
                    errors.append((s, exc))
        # -- checksum drains: only checksum-carrying sessions, and only
        # when their pending queue is non-empty (the len() guard is the
        # same first line _pump_checksums itself runs — hoisting it here
        # keeps the steady-state pass free of per-session method calls).
        # Cross-session order relative to the survivor loop above is
        # free: sessions share no protocol state and per-destination
        # send order is fixed by the encode phase's row order.
        for i, pcr in plan.cks_idx:
            if pcr is not None and not len(pcr):
                continue
            s = sessions[i]
            if s in failed:
                continue
            try:
                s._pump_checksums()
            except GGRSError as exc:
                if not isolate:
                    raise
                failed.add(s)
                errors.append((s, exc))

    def pending_sends(self, sessions: Sequence[Any]) -> bool:
        """True when any endpoint in the pass has a dirty send queue.
        Lets the pump skip building the per-session sink/out plumbing
        (and the whole encode pass) on quiescent pumps — the common
        case between timer fires."""
        plan = self._pass_plan(sessions)
        if plan.rows.size == 0:
            return False
        return bool(self.cols["send_dirty"][plan.ix].any())

    def encode_phase(
        self,
        sessions: Sequence[Any],
        outs: Sequence[Optional[List[Tuple[bytes, Any]]]],
        isolate: bool,
        errors: List[Tuple[Any, Exception]],
        failed: Set[int],
    ) -> None:
        """Send drain for endpoints with queued wire only (`send_dirty`
        flags), in per-session endpoint order — the scalar drain loop
        minus the O(peers) empty-queue scan."""
        plan = self._pass_plan(sessions)
        rows = plan.rows
        if rows.size == 0:
            return
        send_dirty = self.cols["send_dirty"]
        widx = np.flatnonzero(send_dirty[plan.ix])
        if widx.size == 0:
            return
        pos = np.searchsorted(widx, plan.bounds)
        eps = self.eps
        rows_list = plan.rows_list
        for i in np.flatnonzero(pos[1:] > pos[:-1]).tolist():
            s = sessions[i]
            out = outs[i]
            try:
                for j in widx[pos[i] : pos[i + 1]].tolist():
                    r = rows_list[j]
                    send_dirty[r] = False
                    if out is None:
                        eps[r].send_all_messages(s.socket)
                    else:
                        eps[r].drain_sends(out)
            except GGRSError as exc:
                if not isolate:
                    raise
                failed.add(s)
                errors.append((s, exc))

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Registry-independent snapshot for host.telemetry()."""
        return {
            "rows_live": self.live_rows,
            "sessions_adopted": self.live_sessions,
            "rows_capacity": self.cap,
            "adopted_total": self.adopted_total,
            "vectorized_passes": self.passes,
        }
