"""Per-frame snapshot and input records (reference: src/frame_info.rs).

Inputs are fixed-size byte strings — the Python analog of the reference's POD
``Config::Input`` (src/lib.rs:250-255). A blank input is all-zero bytes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from .types import NULL_FRAME, Frame


@dataclass
class GameState:
    """A saved snapshot record (src/frame_info.rs:6-23). ``data`` is opaque to
    the framework: a user object on the CPU path, or a device snapshot handle
    on the TPU path. ``checksum`` is optional and only consumed by SyncTest
    and desync detection."""

    frame: Frame = NULL_FRAME
    data: Any = None
    checksum: Optional[int] = None


@dataclass(frozen=True)
class PlayerInput:
    """One player's input for one frame (src/frame_info.rs:28-66)."""

    frame: Frame
    buf: bytes

    @staticmethod
    def blank(frame: Frame, size: int) -> "PlayerInput":
        return PlayerInput(frame, bytes(size))

    def equal(self, other: "PlayerInput", input_only: bool) -> bool:
        return (input_only or self.frame == other.frame) and self.buf == other.buf
