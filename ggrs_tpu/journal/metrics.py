"""Durable-journal instruments: get-or-create helpers, one definition
each, shared by the writer, the host tap, the director's recovery
ladder and the smoke/soak gates that assert on them (the fleet/metrics
pattern). Registry-driven, so both exporters and telemetry snapshots
carry them with no extra wiring.
"""

from __future__ import annotations

from ..obs import GLOBAL_TELEMETRY


def journal_rows_total():
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_journal_rows_total",
        "confirmed frames made durable in input journals",
    )


def journal_bytes_total():
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_journal_bytes_total",
        "bytes appended to input-journal segments (records incl. framing)",
    )


def journal_segments_total():
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_journal_segments_total",
        "journal segments opened (initial + rotations)",
    )


def journal_fsyncs_total():
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_journal_fsyncs_total",
        "fsyncs issued by journal writers (cadence + rotation + sync)",
    )


def journal_stalls_total():
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_journal_stalls_total",
        "journal appends the filesystem refused (ENOSPC/EIO) — each one "
        "degrades that lane to unjournaled, never wedges the host",
    )


def journal_corrupt_segments_total():
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_journal_corrupt_segments_total",
        "journal segments quarantined by the open-time scan (CRC/framing)",
    )


def journal_recoveries_total():
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_journal_recoveries_total",
        "matches recovered per failover-ladder tier (ticket / "
        "ticket+journal / journal-only resimulation)",
        ("tier",),
    )


def journal_replayed_frames_total():
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_journal_replayed_frames_total",
        "confirmed frames resimulated from journals during recovery",
    )
