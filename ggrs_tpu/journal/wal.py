"""Segment-rotating write-ahead log of confirmed tick rows.

The durability gap this closes: every other redundancy tier keeps its
state in RAM — wire chaos recovers from retransmission queues, process
fencing from in-memory checkpoint tickets, device quarantine from ring
snapshots — so a host that dies with a stale or corrupt ticket loses
every confirmed frame since the last checkpoint. But the simulation is a
pure function of (initial state, confirmed inputs): persist the confirmed
input rows crash-consistently and TOTAL host loss becomes recoverable by
deterministic resimulation. This module is that persistence layer; the
resimulation half lives in journal/recover.py.

Format — append-only segment files `seg-XXXXXXXX.wal`, each a stream of
CRC32-framed records:

    u8 magic (0xA7) | u8 type | u32le payload_len | payload | u32le crc32

The CRC covers header + payload, so any torn or bit-flipped record fails
closed. Record types:

    META (1)  JSON: journal identity (game class, players, input size),
              the writing host's (host_id, epoch), `first_frame` of the
              segment. Every segment STARTS with one, so each file is
              self-describing and a scan can validate continuity without
              the others.
    ROWS (2)  a batch of consecutive confirmed frames in the recorder's
              packed row layout: `<IHBB` start_frame, count, players,
              input_size, then count*P*I input bytes (u8), then count*P
              statuses (i32le) — byte-identical to what
              `InputRecorder.drain_confirmed` hands over, and what
              `utils.replay.replay_to_state` consumes after decode.

Crash consistency: appends go straight to the active segment (a torn
tail is detected and truncated by the open-time scan — the atomic-write
pattern would force a whole-file rewrite per append); ROTATION uses the
`atomic_write_bytes` discipline — the new segment materializes complete
with its META record or not at all, and the finished segment is fsynced
before the writer moves on, so a SIGKILL mid-rotation leaves either the
old tail-segment alone or both files whole. `fsync_every=N` bounds how
many confirmed rows a power loss can cost (N record appends between
fsyncs; 0 = fsync only at rotation/close — SIGKILL-safe either way,
since the OS keeps dirty pages of a dead process).

Failure typing: a scan that hits a bad record in a NON-final segment
quarantines it (renamed `*.corrupt`, typed JournalCorrupt collected —
never a crash); an append that the disk refuses (ENOSPC, EIO) raises
typed JournalStalled so the host can degrade to unjournaled instead of
wedging.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..errors import (
    DataFormatError,
    InvalidRequest,
    JournalCorrupt,
    JournalStalled,
)
from .metrics import (
    journal_bytes_total,
    journal_corrupt_segments_total,
    journal_fsyncs_total,
    journal_rows_total,
    journal_segments_total,
)

_MAGIC = 0xA7
REC_META = 1
REC_ROWS = 2

_HEADER = struct.Struct("<BBI")  # magic, type, payload_len
_CRC = struct.Struct("<I")
_ROWS_HEAD = struct.Struct("<IHBB")  # start_frame, count, players, input_size

JOURNAL_FORMAT_VERSION = 1
SEGMENT_PREFIX = "seg-"
SEGMENT_SUFFIX = ".wal"


def _segment_name(index: int) -> str:
    return f"{SEGMENT_PREFIX}{index:08x}{SEGMENT_SUFFIX}"


def _frame_record(rtype: int, payload: bytes) -> bytes:
    head = _HEADER.pack(_MAGIC, rtype, len(payload))
    return head + payload + _CRC.pack(zlib.crc32(head + payload) & 0xFFFFFFFF)


_DISCONNECTED = 2  # types.InputStatus.DISCONNECTED (no jax-adjacent import)


def canonical_statuses(statuses: np.ndarray) -> np.ndarray:
    """Journal-canonical statuses: at the confirmed frontier a player's
    input is either real (CONFIRMED) or the player is DISCONNECTED —
    PREDICTED is a transient whose residue differs per PEER (a correct
    prediction is never re-advanced, so the predicting peer's last
    observation keeps the transient while the input's owner records
    CONFIRMED). Canonicalizing makes every peer of a match journal
    bit-identical rows, which is what lets recovery read ANY surviving
    peer's journal and lets cross-peer journal comparison double as a
    desync autopsy."""
    statuses = np.asarray(statuses, dtype=np.int32)
    return np.where(statuses == _DISCONNECTED, statuses, 0).astype(np.int32)


def encode_rows(start_frame: int, inputs: np.ndarray,
                statuses: np.ndarray) -> bytes:
    """One ROWS record: `inputs` u8[F, P, I], `statuses` i32[F, P]."""
    inputs = np.ascontiguousarray(inputs, dtype=np.uint8)
    statuses = np.ascontiguousarray(statuses, dtype=np.int32)
    count, players, input_size = inputs.shape
    assert statuses.shape == (count, players), (inputs.shape, statuses.shape)
    payload = (
        _ROWS_HEAD.pack(start_frame, count, players, input_size)
        + inputs.tobytes()
        + statuses.astype("<i4").tobytes()
    )
    return _frame_record(REC_ROWS, payload)


def decode_rows(payload: bytes) -> Tuple[int, np.ndarray, np.ndarray]:
    start, count, players, input_size = _ROWS_HEAD.unpack_from(payload, 0)
    off = _ROWS_HEAD.size
    n_inp = count * players * input_size
    n_st = count * players * 4
    if len(payload) != off + n_inp + n_st:
        raise DataFormatError(
            f"ROWS payload length {len(payload)} != header-implied "
            f"{off + n_inp + n_st}"
        )
    inputs = np.frombuffer(
        payload, dtype=np.uint8, count=n_inp, offset=off
    ).reshape(count, players, input_size)
    statuses = np.frombuffer(
        payload, dtype="<i4", count=count * players, offset=off + n_inp
    ).astype(np.int32).reshape(count, players)
    return start, inputs, statuses


def _has_valid_record_after(data: bytes, off: int) -> bool:
    """True when a complete, CRC-valid record exists anywhere past
    `off` — the discriminator between a TORN TAIL (a crash can only
    tear the very end: nothing valid follows) and MID-FILE CORRUPTION
    (an SDC flip leaves the records after it intact). Header-plausible
    positions are rare in random bytes, so the scan is effectively one
    cheap pass."""
    n = len(data)
    for p in range(off + 1, n - _HEADER.size - _CRC.size + 1):
        if data[p] != _MAGIC:
            continue
        magic, rtype, length = _HEADER.unpack_from(data, p)
        if rtype not in (REC_META, REC_ROWS):
            continue
        end = p + _HEADER.size + length + _CRC.size
        if end > n:
            continue
        body = data[p : p + _HEADER.size + length]
        (crc,) = _CRC.unpack_from(data, p + _HEADER.size + length)
        if crc == (zlib.crc32(body) & 0xFFFFFFFF):
            return True
    return False


def _parse_segment(data: bytes):
    """Walk one segment's records. Returns (records, good_bytes, error):
    `records` is [(type, payload)], `good_bytes` the offset of the first
    bad byte (== len(data) when clean), `error` a short reason or None.
    Never raises — the CALLER decides torn-tail vs corrupt-segment."""
    records: List[Tuple[int, bytes]] = []
    off = 0
    n = len(data)
    while off < n:
        if n - off < _HEADER.size + _CRC.size:
            return records, off, "truncated header"
        magic, rtype, length = _HEADER.unpack_from(data, off)
        if magic != _MAGIC or rtype not in (REC_META, REC_ROWS):
            return records, off, f"bad frame (magic={magic:#x}, type={rtype})"
        end = off + _HEADER.size + length + _CRC.size
        if end > n:
            return records, off, "truncated record"
        body = data[off : off + _HEADER.size + length]
        (crc,) = _CRC.unpack_from(data, off + _HEADER.size + length)
        if crc != (zlib.crc32(body) & 0xFFFFFFFF):
            return records, off, "crc mismatch"
        records.append((rtype, data[off + _HEADER.size : off + _HEADER.size + length]))
        off = end
    return records, off, None


class JournalScan:
    """The open-time scan's verdict: the contiguous confirmed row prefix
    (base_frame..next_frame), the journal meta, and everything that went
    wrong — torn tails truncated, corrupt segments quarantined as typed
    JournalCorrupt entries (never raised from the scan itself)."""

    def __init__(self) -> None:
        self.meta: Dict[str, Any] = {}
        self.base_frame = 0
        self.next_frame = 0
        self.rows: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self.corrupt: List[JournalCorrupt] = []
        self.segments: List[dict] = []
        self.torn_bytes = 0
        self.gap = False  # a quarantined segment broke frame continuity

    @property
    def frames(self) -> int:
        return self.next_frame - self.base_frame

    def script(self) -> Tuple[np.ndarray, np.ndarray]:
        """(inputs u8[F, P, I], statuses i32[F, P]) for the contiguous
        confirmed prefix — the exact arrays `replay_to_state` and the
        recovery resim consume."""
        if not self.frames:
            raise JournalCorrupt(
                "journal holds no contiguous confirmed rows",
                path=self.meta.get("path", ""),
            )
        frames = range(self.base_frame, self.next_frame)
        inputs = np.concatenate([self.rows[f][0][None] for f in frames])
        statuses = np.concatenate([self.rows[f][1][None] for f in frames])
        return inputs, statuses


def _list_segments(path: str) -> List[str]:
    try:
        names = os.listdir(path)
    except FileNotFoundError:
        return []
    return sorted(
        n for n in names
        if n.startswith(SEGMENT_PREFIX) and n.endswith(SEGMENT_SUFFIX)
    )


def scan_journal(path: str, *, repair: bool = False) -> JournalScan:
    """Read every segment, collect the contiguous confirmed prefix.
    `repair=True` additionally truncates the final segment's torn tail
    in place and renames corrupt segments to `<name>.corrupt` (the
    writer's open path); False leaves the files untouched (the
    director's seize path reads bytes it does not own)."""
    scan = JournalScan()
    names = _list_segments(path)
    first = True
    for i, name in enumerate(names):
        seg_path = os.path.join(path, name)
        with open(seg_path, "rb") as f:
            data = f.read()
        records, good, err = _parse_segment(data)
        last = i == len(names) - 1
        entry = {"name": name, "bytes": len(data), "records": len(records)}
        if err is not None and last and _has_valid_record_after(data, good):
            # valid records FOLLOW the bad bytes: this is mid-file
            # corruption of the active segment (SDC), not crash
            # tearing — quarantine like a finished segment instead of
            # silently truncating acknowledged durable rows. (A flip
            # inside the very LAST record is indistinguishable from a
            # tear and is treated as one — the one-record ambiguity a
            # framing-only format cannot close.)
            last = False
        pending_quarantine = False
        if err is not None and not last:
            # corruption: the segment quarantines aside, typed — but
            # its CRC-valid leading records are still acknowledged
            # durable rows, so THIS scan (the recovery read) keeps them
            # before declaring the gap
            exc = JournalCorrupt(
                f"journal segment failed its scan: {err}",
                path=path, segment=name, offset=good,
            )
            scan.corrupt.append(exc)
            journal_corrupt_segments_total().inc()
            entry["corrupt"] = err
            pending_quarantine = True
            if repair:
                os.replace(seg_path, seg_path + ".corrupt")
        if err is not None and last:
            # torn tail: the crash residue the framing exists to absorb
            scan.torn_bytes = len(data) - good
            entry["torn_bytes"] = scan.torn_bytes
            if repair and scan.torn_bytes:
                with open(seg_path, "r+b") as f:
                    f.truncate(good)
        scan.segments.append(entry)
        for rtype, payload in records:
            if rtype == REC_META:
                meta = json.loads(payload.decode("utf-8"))
                if first:
                    scan.meta = meta
                    scan.base_frame = int(meta.get("first_frame", 0))
                    scan.next_frame = scan.base_frame
                    first = False
                continue
            if scan.gap:
                continue  # rows past a quarantined segment: not contiguous
            start, inputs, statuses = decode_rows(payload)
            for k in range(inputs.shape[0]):
                f = start + k
                if f < scan.next_frame:
                    continue  # duplicate coverage (resumed writer overlap)
                if f > scan.next_frame:
                    scan.gap = True
                    break
                scan.rows[f] = (inputs[k], statuses[k])
                scan.next_frame = f + 1
        if pending_quarantine:
            scan.gap = True  # nothing AFTER this segment is contiguous
    return scan


def read_journal_script(path: str):
    """(inputs, statuses, meta) of the contiguous confirmed prefix —
    the recovery entry point. Raises JournalCorrupt when the journal
    holds no usable rows; a quarantinable segment does NOT raise (the
    prefix before it still recovers)."""
    scan = scan_journal(path, repair=False)
    inputs, statuses = scan.script()
    return inputs, statuses, scan.meta


def journal_files(path: str) -> Dict[str, bytes]:
    """Snapshot the journal's bytes NOW — the director's seize-at-fence
    read (the ticket discipline): whatever a fenced zombie appends after
    this read is void, because recovery runs from these bytes. Includes
    already-quarantined segments for the autopsy trail."""
    out: Dict[str, bytes] = {}
    try:
        names = sorted(os.listdir(path))
    except FileNotFoundError:
        return out
    for name in names:
        if not (name.startswith(SEGMENT_PREFIX)):
            continue
        try:
            with open(os.path.join(path, name), "rb") as f:
                out[name] = f.read()
        except OSError:
            continue
    return out


def seed_journal(path: str, files: Dict[str, bytes]) -> None:
    """Materialize seized/migrated journal bytes into a fresh directory
    (atomic per file): the receiving host's journal then CONTINUES the
    match's history from genesis instead of starting at the adoption
    frame — what keeps a second failover journal-recoverable."""
    from ..utils.checkpoint import atomic_write_bytes

    os.makedirs(path, exist_ok=True)
    for stale in sorted(os.listdir(path)):
        # a previous hosting of the same match may have left segments
        # (or quarantined residue) here; a stale higher-index segment
        # could splice into the seized history and pass the continuity
        # scan as if it were this lineage's tail — the seized bytes are
        # the WHOLE truth, so the directory starts empty
        if stale.startswith(SEGMENT_PREFIX):
            os.unlink(os.path.join(path, stale))
    for name in sorted(files):
        if "/" in name or name.startswith("."):
            raise InvalidRequest(f"journal file name {name!r} escapes dir")
        atomic_write_bytes(os.path.join(path, name), files[name])


def corrupt_segment(path: str, *, segment: int = 0,
                    offset: Optional[int] = None) -> str:
    """Chaos helper: flip one byte of segment `segment` (by sorted
    index). The next scan must quarantine it as typed JournalCorrupt —
    the storage tier's injected-corruption arm."""
    names = _list_segments(path)
    name = names[segment]
    seg_path = os.path.join(path, name)
    with open(seg_path, "r+b") as f:
        data = bytearray(f.read())
        # default: corrupt past the header record so the META (and the
        # framing up to it) stays parseable and the CRC is what catches it
        at = offset if offset is not None else min(len(data) - 5, len(data) // 2)
        data[at] ^= 0x40
        f.seek(0)
        f.write(data)
    return name


class JournalWriter:
    """Append confirmed rows durably; resume across restarts.

    Open-time behavior: scans the directory with `repair=True` (torn
    tail truncated, corrupt segments quarantined aside). A quarantine
    that broke frame continuity raises JournalCorrupt — the caller
    (host tap / fleet agent) degrades or falls back a recovery tier
    rather than appending rows no resimulation could ever reach. On a
    clean resume the scanned rows are retained as the VERIFY set:
    `verify_row` checks a redriven row bit-for-bit against what the
    journal recorded (freed as they pass), which is the "journal tail
    replay" witness — a restore-from-ticket that redrives the
    pre-crash window must reproduce the journaled bytes exactly."""

    def __init__(self, path: str, *, meta: Optional[Dict[str, Any]] = None,
                 segment_bytes: int = 1 << 18, fsync_every: int = 0):
        self.path = path
        self.segment_bytes = segment_bytes
        self.fsync_every = fsync_every
        self.meta = dict(meta or {})
        self.frames_journaled = 0
        self.appends = 0
        self.bytes_written = 0
        self.rotations = 0
        self.fsyncs = 0
        self.verified_rows = 0
        self._fd = None
        os.makedirs(path, exist_ok=True)
        scan = scan_journal(path, repair=True)
        if scan.gap:
            # chain the quarantined segment's typed error (if any) so
            # the operator sees WHICH segment broke continuity
            cause = scan.corrupt[0] if scan.corrupt else None
            raise JournalCorrupt(
                "journal frame continuity broken", path=path
            ) from cause
        names = _list_segments(path)
        self.next_frame = scan.next_frame
        self.base_frame = scan.base_frame
        self._verify = dict(scan.rows)
        self._empty = scan.frames == 0
        if names:
            # a resume must be the SAME lineage: a fresh process whose
            # key allocation collided onto a dead incarnation's path
            # would otherwise splice two matches into one "contiguous"
            # journal (or spuriously fail verify) — the self-describing
            # META exists to refuse that at the door
            for ident in ("game_cls", "num_players", "input_size",
                          "match_id"):
                if (
                    ident in scan.meta
                    and ident in self.meta
                    and scan.meta[ident] != self.meta[ident]
                ):
                    raise JournalCorrupt(
                        f"journal identity mismatch on resume: "
                        f"{ident} is {scan.meta[ident]!r} on disk, "
                        f"{self.meta[ident]!r} attaching",
                        path=path,
                    )
            self.meta = {**scan.meta, **self.meta}
            self._seg_index = int(
                names[-1][len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)], 16
            )
            seg_path = os.path.join(path, names[-1])
            self._seg_size = os.path.getsize(seg_path)
            self._fd = open(seg_path, "ab")
        else:
            if "first_frame" in self.meta:
                self.next_frame = int(self.meta["first_frame"])
                self.base_frame = self.next_frame
            self._seg_index = -1
            self._seg_size = 0
            self._rotate()
        self._since_fsync = 0

    # ------------------------------------------------------------------
    # segment lifecycle
    # ------------------------------------------------------------------

    def _meta_record(self) -> bytes:
        stamped = {
            **self.meta,
            "format": JOURNAL_FORMAT_VERSION,
            "first_frame": self.base_frame if self._seg_index < 0
            else self.next_frame,
            "segment": self._seg_index + 1,
        }
        return _frame_record(
            REC_META, json.dumps(stamped, sort_keys=True).encode("utf-8")
        )

    def _rebase_segment(self) -> None:
        """Rewrite the (row-less) active segment with a META carrying
        the rebased first_frame — atomic, so a crash mid-rebase leaves
        either the old empty segment or the new one, both row-free."""
        from ..utils.checkpoint import atomic_write_bytes

        assert self._empty
        if self._fd is not None:
            self._fd.close()
            self._fd = None
        saved = self._seg_index
        self._seg_index = -1  # _meta_record: first_frame = base_frame
        record = self._meta_record()
        self._seg_index = saved
        seg_path = os.path.join(self.path, _segment_name(self._seg_index))
        atomic_write_bytes(seg_path, record)
        self._fd = open(seg_path, "ab")
        self._seg_size = len(record)

    def _rotate(self) -> None:
        """Finish the active segment (fsync — rotation is a durability
        point regardless of cadence) and start the next one with its
        META record via the atomic-write discipline: the new file
        appears whole or not at all, so a SIGKILL mid-rotation can
        never leave a headerless segment."""
        from ..utils.checkpoint import atomic_write_bytes

        record = self._meta_record()
        if self._fd is not None:
            self._fd.flush()
            os.fsync(self._fd.fileno())
            self.fsyncs += 1
            journal_fsyncs_total().inc()
            self._fd.close()
            self._fd = None
        self._seg_index += 1
        seg_path = os.path.join(self.path, _segment_name(self._seg_index))
        atomic_write_bytes(seg_path, record)
        self._fd = open(seg_path, "ab")
        self._seg_size = len(record)
        self.bytes_written += len(record)
        self.rotations += 1
        self._since_fsync = 0
        journal_segments_total().inc()
        journal_bytes_total().inc(len(record))

    # ------------------------------------------------------------------
    # the append path
    # ------------------------------------------------------------------

    def append_rows(self, start_frame: int, inputs: np.ndarray,
                    statuses: np.ndarray) -> int:
        """Append consecutive confirmed rows starting at `start_frame`.
        Rows at frames already journaled are verified (when the resume
        scan retained them) and skipped — the redrive-after-restore
        overlap; a gap ABOVE next_frame is an InvalidRequest (the
        journal's whole value is contiguity from genesis). Returns the
        number of NEW frames made durable. Disk refusal raises typed
        JournalStalled; the torn partial record it may leave is exactly
        what the open-time scan truncates."""
        count = int(inputs.shape[0])
        if start_frame > self.next_frame and self._empty:
            # an EMPTY journal re-bases onto its first append: a
            # mid-match adopted lane starts its durable history at the
            # adoption frame (the journal then records first_frame > 0,
            # which the genesis-resim tier refuses by design — such a
            # journal supports tail recovery only). The on-disk META is
            # rewritten so a scan agrees with the rebased frames.
            self.base_frame = start_frame
            self.next_frame = start_frame
            self._rebase_segment()
        if start_frame > self.next_frame:
            raise InvalidRequest(
                f"journal append at frame {start_frame} would leave a "
                f"gap above {self.next_frame}"
            )
        skip = min(self.next_frame - start_frame, count)
        for k in range(skip):
            self.verify_row(start_frame + k, inputs[k], statuses[k])
        if skip >= count:
            return 0
        start = start_frame + skip
        record = encode_rows(start, inputs[skip:], statuses[skip:])
        if self._fd is None:
            raise JournalStalled(
                "journal append refused: writer is closed",
                path=self.path, errno=0,
            )
        try:
            self._fd.write(record)
            self._fd.flush()
            self._since_fsync += 1
            if self.fsync_every and self._since_fsync >= self.fsync_every:
                os.fsync(self._fd.fileno())
                self.fsyncs += 1
                self._since_fsync = 0
                journal_fsyncs_total().inc()
        except OSError as exc:
            raise JournalStalled(
                f"journal append refused by the filesystem: {exc}",
                path=self.path, errno=exc.errno or 0,
            ) from exc
        new = count - skip
        self.next_frame = start + new
        self._empty = False
        # once fresh rows append past the resume frontier, every stale
        # overlap row has already come and gone (observation precedes
        # confirmation): retained verify rows below the redrive floor
        # can never be checked — free them instead of holding a whole
        # seized history in RAM
        if self._verify:
            self._verify.clear()
        self.frames_journaled += new
        self.appends += 1
        self._seg_size += len(record)
        self.bytes_written += len(record)
        journal_rows_total().inc(new)
        journal_bytes_total().inc(len(record))
        if self._seg_size >= self.segment_bytes:
            try:
                self._rotate()
            except OSError as exc:
                raise JournalStalled(
                    f"journal rotation refused by the filesystem: {exc}",
                    path=self.path, errno=exc.errno or 0,
                ) from exc
        return new

    def verify_row(self, frame: int, inputs: np.ndarray,
                   statuses: np.ndarray) -> bool:
        """Check one re-confirmed row against the journaled bytes (the
        resume scan's retained rows; rows outside that set pass
        vacuously — already freed as verified). A mismatch is typed
        JournalCorrupt: the redrive and the durable record disagree,
        so one of them is wrong and recovery must not trust the pair."""
        rec = self._verify.pop(frame, None)
        if rec is None:
            return False
        j_inp, j_st = rec
        if not (
            np.array_equal(
                np.asarray(inputs, dtype=np.uint8), j_inp
            )
            and np.array_equal(
                np.asarray(statuses, dtype=np.int32), j_st
            )
        ):
            raise JournalCorrupt(
                "re-confirmed row disagrees with the journaled bytes "
                "(redrive/journal divergence)",
                path=self.path, frame=frame,
            )
        self.verified_rows += 1
        return True

    def sync(self) -> None:
        """Flush + fsync the active segment — the checkpoint/drain
        durability point, independent of the append cadence."""
        if self._fd is None:
            return
        self._fd.flush()
        os.fsync(self._fd.fileno())
        self.fsyncs += 1
        journal_fsyncs_total().inc()

    def close(self) -> None:
        if self._fd is None:
            return
        try:
            self.sync()
        finally:
            self._fd.close()
            self._fd = None

    def section(self) -> dict:
        return {
            "path": self.path,
            "next_frame": self.next_frame,
            "frames_journaled": self.frames_journaled,
            "appends": self.appends,
            "bytes_written": self.bytes_written,
            "segments": self._seg_index + 1,
            "fsyncs": self.fsyncs,
            "verified_rows": self.verified_rows,
            "unverified_rows": len(self._verify),
        }
