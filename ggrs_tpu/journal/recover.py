"""Point-in-time recovery from input journals: deterministic
resimulation riding the megabatch core.

Two consumers, one substrate:

  * `batch_resim_journals` — N lost matches' WORLDS rebuilt as one
    batched grid: each match is one slot of a MultiSessionDeviceCore,
    each dispatch advances every live match a full window of confirmed
    frames (the replay-seek showcase from the ROADMAP, pointed at
    disaster recovery first). Emits per-frame combined checksums so the
    rebuilt lineage can be pinned bitwise against a live peer's
    `local_checksum_history` — the same comparison desync detection
    makes across peers, made across TIME.
  * `scripts_from_journal` — the fleet tier-3 path: a journal's
    confirmed frame rows mapped back through the input delay to the
    per-peer SUBMIT scripts, so a match island rebuilt from its spec
    redrives from genesis submitting exactly what its players confirmed
    before the host died. The redrive itself rides `step_islands` (the
    shared megabatch drive loop), so N rebuilt matches resimulate as
    one fleet.

Both paths consume the contiguous confirmed prefix `scan_journal`
recovered; neither touches the wire — recovery is a pure function of
(spec, journal), which is the whole durability contract.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .wal import read_journal_script


def scripts_from_journal(
    inputs: np.ndarray,
    *,
    input_delay: int,
    ticks: int,
    fallback: Optional[Dict[int, List[int]]] = None,
) -> Dict[int, List[int]]:
    """Confirmed FRAME rows -> per-peer SUBMIT scripts. A submit at
    island cursor t lands at frame t + input_delay (the input queue's
    delay shift; the first `input_delay` frames play the queue's blank
    fill, which a fresh rebuild reproduces by construction), so the
    journal pins cursors 0..F-delay-1 and `fallback` (the spec-derived
    script — the harness's stand-in for live traffic resuming after
    recovery) covers the unconfirmed tail. Only 1-byte inputs (the
    island layout) are supported: wider games recover through
    `batch_resim_journals` instead of an island redrive."""
    frames, players, input_size = inputs.shape
    assert input_size == 1, "island scripts are 1-byte inputs"
    out: Dict[int, List[int]] = {}
    for k in range(players):
        script: List[int] = []
        for t in range(ticks):
            f = t + input_delay
            if f < frames:
                script.append(int(inputs[f, k, 0]))
            elif fallback is not None and k in fallback:
                script.append(fallback[k][t])
            else:
                break
        out[k] = script
    return out


def journal_coverage(inputs: np.ndarray, *, input_delay: int) -> int:
    """How many island CURSOR ticks the journal pins (the redrive's
    guaranteed-identical prefix)."""
    return max(int(inputs.shape[0]) - input_delay, 0)


def state_digest(state: Any) -> str:
    """sha256 over a state pytree's leaves in sorted key-path order —
    the canonical world-bytes witness (the island digest's `state`
    half, computable host-side on a resimulated tree)."""
    import jax

    h = hashlib.sha256()
    leaves = jax.tree_util.tree_leaves_with_path(state)
    for path, leaf in sorted(
        leaves, key=lambda pl: jax.tree_util.keystr(pl[0])
    ):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def batch_resim_journals(
    game,
    scripts: Sequence[Tuple[np.ndarray, np.ndarray]],
    *,
    max_prediction: int = 8,
    collect_checksums: bool = True,
) -> List[dict]:
    """Rebuild N matches' world states from their confirmed input
    scripts in one batched megabatch grid: match i is slot i of a
    MultiSessionDeviceCore, every dispatch carries one full-window row
    per still-live match (frames-per-dispatch = live_matches x window),
    and per-frame save checksums ride the same lazy checksum batches
    the serving host binds — resolved once at the end so the fence
    stays busy. Returns one dict per match:

        {"frames": F, "state": <host pytree at frame F>,
         "checksums": {frame: combined_checksum}}

    `state` is the canonical world alone (no snapshot ring): recovery
    saves every frame for the checksum lineage, which a live host's
    sparse cadence would not, so ring bytes are NOT comparable across
    the two — world bytes and the checksum history are."""
    import jax

    from ..errors import InvalidRequest
    from ..ops.fixed_point import combine_checksum  # noqa: F401 (parity doc)
    from ..tpu.backend import MultiSessionDeviceCore

    n = len(scripts)
    assert n > 0
    players = scripts[0][1].shape[1]
    for i, (inp, st) in enumerate(scripts):
        if inp.shape[1:] != (players, game.input_size) or st.shape[1:] != (
            players,
        ):
            # refuse the ONE mismatched journal typed instead of dying
            # as a broadcast error mid-grid and failing every match
            raise InvalidRequest(
                f"journal script {i} has shape {inp.shape} — the batch "
                f"is {players} players x input_size {game.input_size}"
            )
    device = MultiSessionDeviceCore.create(
        game, max_prediction, players, n,
    )
    core = device.core
    W, ring_len = core.window, core.ring_len
    for slot in range(n):
        device.reset_slot(slot)
    totals = [int(inp.shape[0]) for inp, _ in scripts]
    done = [0] * n
    pending: List[Tuple[Any, List[Tuple[int, int, int]]]] = []
    scratch = np.full((W,), core.scratch_slot, dtype=np.int32)
    while True:
        entries = []
        binds: List[Tuple[int, int, int]] = []  # (match, base_k, count)
        counts = []
        for slot in range(n):
            rem = totals[slot] - done[slot]
            if rem <= 0:
                continue
            count = min(W, rem)
            start = done[slot]
            inp_arr, st_arr = scripts[slot]
            inputs = np.zeros((W, players, game.input_size), np.uint8)
            statuses = np.zeros((W, players), np.int32)
            inputs[:count] = inp_arr[start : start + count]
            statuses[:count] = st_arr[start : start + count]
            if collect_checksums:
                save_slots = scratch.copy()
                for i in range(count):
                    # slot-i save snapshots the PRE-advance state
                    # (= frame start+i), exactly what desync detection
                    # checksummed live (utils/replay._replay_core's rule)
                    save_slots[i] = (start + i) % ring_len
            else:
                save_slots = scratch
            row = core.pack_tick_row(
                False, 0, inputs, statuses, save_slots, count,
                start_frame=start,
            )
            entries.append((slot, row))
            binds.append((slot, len(entries) - 1, count))
            counts.append(count)
            done[slot] = start + count
        if not entries:
            break
        batch, _bucket = device.dispatch(
            entries, last_active=max(counts)
        )
        if collect_checksums:
            pending.append((batch, binds))
    device.block_until_ready()
    results: List[dict] = []
    checksums: List[Dict[int, int]] = [dict() for _ in range(n)]
    if collect_checksums:
        rebuilt = [0] * n
        for batch, binds in pending:
            for slot, k, count in binds:
                for i in range(count):
                    checksums[slot][rebuilt[slot] + i] = batch.resolve(
                        k * W + i
                    )
                rebuilt[slot] += count
    for slot in range(n):
        payload = device.export_slot(slot)
        results.append({
            "frames": totals[slot],
            "state": jax.device_get(payload["state"]),
            "checksums": checksums[slot],
        })
    return results


def resimulate_journal_dirs(game, paths: Sequence[str], **kw) -> List[dict]:
    """`batch_resim_journals` over on-disk journals: read each
    directory's contiguous confirmed prefix, rebuild all of them as one
    grid. The recovery-time-objective bench's entry point."""
    from ..errors import JournalCorrupt

    scripts = []
    for path in paths:
        inputs, statuses, meta = read_journal_script(path)
        # a same-shape wrong-game journal would resimulate to typed-
        # valid garbage: refuse on the identity the META exists for
        for ident, want in (
            ("game_cls", type(game).__name__),
            ("input_size", game.input_size),
        ):
            if ident in meta and meta[ident] != want:
                raise JournalCorrupt(
                    f"journal was recorded on {ident}={meta[ident]!r}, "
                    f"not {want!r}",
                    path=path,
                )
        scripts.append((inputs, statuses))
    return batch_resim_journals(game, scripts, **kw)
