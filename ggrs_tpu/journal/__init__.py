"""Durable input journal + point-in-time recovery.

A segment-rotating, CRC32-framed write-ahead log of confirmed tick rows
(`journal.wal`) plus the batched deterministic resimulation that turns
those rows back into bit-exact match state (`journal.recover`). The
host tap lives in serve/host.py (`SessionHost(journal_dir=...)` /
`attach_journal`); the fleet wires journals per match island and the
director's failover ladder falls back through them (docs/DESIGN.md
"Durable recovery"). Importing this package does not import jax.
"""

from .recover import (
    batch_resim_journals,
    journal_coverage,
    resimulate_journal_dirs,
    scripts_from_journal,
    state_digest,
)
from .wal import (
    JOURNAL_FORMAT_VERSION,
    JournalScan,
    JournalWriter,
    corrupt_segment,
    decode_rows,
    encode_rows,
    journal_files,
    read_journal_script,
    scan_journal,
    seed_journal,
)

__all__ = [
    "JOURNAL_FORMAT_VERSION",
    "JournalScan",
    "JournalWriter",
    "batch_resim_journals",
    "corrupt_segment",
    "decode_rows",
    "encode_rows",
    "journal_coverage",
    "journal_files",
    "read_journal_script",
    "resimulate_journal_dirs",
    "scan_journal",
    "scripts_from_journal",
    "seed_journal",
    "state_digest",
]
