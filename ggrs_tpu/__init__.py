"""ggrs_tpu — a TPU-native rollback-networking framework.

A ground-up reimagination of GGRS (the GGPO-style P2P rollback library,
surveyed in SURVEY.md) built TPU-first: the session/protocol control plane is
host code, while the rollback hot path — "load a confirmed snapshot, then
resimulate N speculative frames" — executes as a single jit-compiled
`lax.scan` over a device-resident snapshot ring (ggrs_tpu.tpu), with
vmap-evaluated speculative input beams and on-device checksum reductions.

Importing this package does NOT import jax; the device backend lives in
`ggrs_tpu.tpu`, imported on demand.
"""

from .errors import (
    DeviceDispatchFailed,
    DeviceFault,
    GGRSError,
    HarvestTimeout,
    InvalidRequest,
    InvariantViolation,
    MismatchedChecksum,
    NotSynchronized,
    PredictionThreshold,
    SlotPoisoned,
    SpectatorTooFarBehind,
    StatsWindowTooYoung,
)
from .frame_info import GameState, PlayerInput
from .obs import GLOBAL_TELEMETRY, Telemetry, enable_global_telemetry
from .sessions.builder import SessionBuilder
from .sync_layer import ConnectionStatus, GameStateCell
from .types import (
    NULL_FRAME,
    AdvanceFrame,
    DesyncDetected,
    DesyncDetection,
    Disconnected,
    Frame,
    InputStatus,
    LoadGameState,
    NetworkInterrupted,
    NetworkResumed,
    PlayerHandle,
    PlayerType,
    SaveGameState,
    SessionState,
    Synchronized,
    Synchronizing,
    WaitRecommendation,
)

__version__ = "0.1.0"

__all__ = [
    "NULL_FRAME",
    "AdvanceFrame",
    "ConnectionStatus",
    "DesyncDetected",
    "DesyncDetection",
    "DeviceDispatchFailed",
    "DeviceFault",
    "Disconnected",
    "Frame",
    "GGRSError",
    "GLOBAL_TELEMETRY",
    "GameState",
    "GameStateCell",
    "HarvestTimeout",
    "InputStatus",
    "InvalidRequest",
    "InvariantViolation",
    "LoadGameState",
    "MismatchedChecksum",
    "NetworkInterrupted",
    "NetworkResumed",
    "NotSynchronized",
    "PlayerHandle",
    "PlayerInput",
    "PlayerType",
    "PredictionThreshold",
    "SaveGameState",
    "SessionBuilder",
    "SessionState",
    "SlotPoisoned",
    "SpectatorTooFarBehind",
    "StatsWindowTooYoung",
    "Synchronized",
    "Synchronizing",
    "Telemetry",
    "WaitRecommendation",
    "enable_global_telemetry",
]
