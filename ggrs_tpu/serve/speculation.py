"""Speculative bubble-filling: the host-side draft/verify bookkeeping.

`SessionHost._lane_ready` rejects a lane whenever the prediction-
threshold gate blocks it — remote inputs haven't arrived and the session
has speculated as far as its window allows. Before this module those
lanes simply left their megabatch rows empty (device bubbles). Now the
scheduler DRAFTS each starved lane's near future instead: a width-1
input script from the lane's learned InputHistoryModel (hazard/
transition draws, counter-based like env/opponents — never a stateful
RNG stream), rolled out on device from the lane's ring anchor as one
vmapped batch beside the confirmed work (MultiSessionDeviceCore.draft —
a ring-parked branch; confirmed state is never touched).

When the real inputs arrive and the session stages its next rows, the
VERIFY pass here compares them against the drafted script per frame:

- a full prefix hit serves the whole row from the draft via the
  resim.adopt route (one adopt dispatch instead of a full-window resim);
- a misprediction truncates to the longest-correct prefix — the adopt
  serves the prefix and resimulates only the mispredicted suffix in the
  same program — and the rest of the draft is discarded;
- a total miss (or an arrival rollback that rewrites history at or
  before the draft's anchor) discards the draft and resumes the normal
  rollback path untouched.

Every case is bitwise-identical to a never-speculating twin: the drafted
trajectory replays the lane's PLAYED rows from the same ring snapshot
(the prefix check rejects any divergence verbatim), drafted statuses are
all-CONFIRMED under the game's declared `statuses_contract =
"disconnect-only"`, and adopted ring writes/checksums come from the same
states a resim would compute. tests/test_speculation.py pins all three
arrival patterns against a non-speculating twin.

This module is pure host-side numpy bookkeeping — it never touches the
device core's fenced state (FEN001 keeps serve/ at zero allowances);
dispatches go through the owning `MultiSessionDeviceCore` methods.

Resident-loop interplay (serve/host.py `resident=True`): drafts anchor
on ring snapshots and adopts serve a lane's NEXT row, so both are
ordering barriers against the device mailbox — the host drives the
pending fill cycle before `device.draft(...)` (the rollout must read
rings that include every staged save) and before `device.adopt_slot`
(the lane's earlier rows must land first). Nothing in this module
changes: the planner's record/verify streams are host-side and see the
same segments in the same order either way, which is why a resident
speculating host adopts the exact frames its dispatch-per-tick twin
does (tests/test_resident_loop.py pins it).
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..obs import DISPATCH_DEPTH_BUCKETS, GLOBAL_TELEMETRY
from ..types import InputStatus

_DISC = int(InputStatus.DISCONNECTED)
_PRED = int(InputStatus.PREDICTED)


def speculation_instruments():
    """The four speculative-bubble-filling instruments, get-or-created on
    the global registry (registry-driven: both exporters and
    host.telemetry() carry them with no extra code): frames drafted /
    adopted / discarded counters plus the verified-prefix-length
    histogram (0 = total miss; the host section's hit rate is adopted /
    SERVEABLE frames — one member's window per draft — while the
    drafted counter measures device work across all members)."""
    reg = GLOBAL_TELEMETRY.registry
    drafted = reg.counter(
        "ggrs_spec_frames_drafted_total",
        "speculative frames drafted into megabatch bubbles for "
        "input-starved sessions",
    )
    adopted = reg.counter(
        "ggrs_spec_frames_adopted_total",
        "drafted frames served as (a prefix of) a session tick via the "
        "adopt route",
    )
    discarded = reg.counter(
        "ggrs_spec_frames_discarded_total",
        "drafted frames retired unserved (miss, truncation, stale "
        "watermark, anchor rewrite, lane detach)",
    )
    prefix = reg.histogram(
        "ggrs_spec_prefix_len",
        "verified draft prefix length per arrival (frames adopted; "
        "0 = total miss)",
        buckets=DISPATCH_DEPTH_BUCKETS,
    )
    return drafted, adopted, discarded, prefix


class StandingDraft:
    """One lane's live draft: the anchor frame, the drafted input
    scripts (host copies, the verify pass's comparison keys — member 0
    is the PLAYED-LINEAGE script that serves no-rollback recoveries,
    members 1+ are sampled switch-timing bets that serve rollback
    arrivals), and each script's member row in the device DraftBatch."""

    __slots__ = ("anchor", "scripts", "batch", "members", "watermark",
                 "fingerprint", "served", "covered")

    def __init__(self, anchor, scripts, batch, members, watermark,
                 fingerprint):
        self.anchor = anchor
        self.scripts = scripts
        self.batch = batch
        self.members = members
        self.watermark = watermark
        # per-player confirmed-input frontier at launch: any NEW
        # confirmation makes the draft stale (freshly-arrived real
        # inputs beat drawn guesses, so re-draft)
        self.fingerprint = fingerprint
        self.served = 0
        # highest verified window index so far: a rollback arrival can
        # re-verify frames an earlier full-hit adopt already served from
        # this same draft — the adopt dispatch legitimately serves them
        # again, but the DISTINCT-frame counters must not double-count
        # (hit_rate would exceed 1.0)
        self.covered = 0


class _PlayedRing:
    """Fixed-depth pooled store of a lane's played rows keyed by frame —
    the dict-of-fresh-arrays it replaces allocated two arrays per played
    frame per staged segment (the host's staging path is otherwise
    allocation-free). put() copies into preallocated storage; get()
    returns views (every caller copies or compares, never retains past
    the next put); `floor` is the prune frontier the dict's O(n) sweep
    used to maintain — an O(1) ratchet here."""

    __slots__ = ("frames", "inputs", "statuses", "floor")

    def __init__(self, depth: int, num_players: int, input_size: int):
        self.frames = np.full((depth,), np.iinfo(np.int64).min,
                              dtype=np.int64)
        self.inputs = np.zeros((depth, num_players, input_size),
                               dtype=np.uint8)
        self.statuses = np.zeros((depth, num_players), dtype=np.int32)
        self.floor = -(2 ** 60)

    def put(self, frame: int, inputs: np.ndarray,
            statuses: np.ndarray) -> None:
        i = frame % len(self.frames)
        self.frames[i] = frame
        self.inputs[i][:] = inputs
        self.statuses[i][:] = statuses

    def get(self, frame: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        if frame < self.floor:
            return None
        i = frame % len(self.frames)
        if self.frames[i] != frame:
            return None
        return self.inputs[i], self.statuses[i]


class _LaneSpec:
    """Per-lane speculation bookkeeping: the played-row history the
    prefix check and the input model learn from, the ring-slot -> frame
    map that proves a draft's anchor snapshot is live, and the standing
    draft."""

    __slots__ = ("played", "ring_frames", "model", "finalized_to",
                 "draft", "seed", "num_players")

    def __init__(self, model, seed, num_players, played: _PlayedRing):
        self.played = played
        self.ring_frames: Dict[int, int] = {}
        self.model = model
        self.finalized_to = -1
        self.draft: Optional[StandingDraft] = None
        self.seed = seed
        # the lane's REAL player count: columns at or past it are
        # host-layout padding, deterministically DISCONNECTED — not
        # player behavior, and never a reason to refuse a draft
        self.num_players = num_players


class SpeculationPlanner:
    """Host-side speculation state for one SessionHost's p2p lanes."""

    # default draft width: member 0 is the played-lineage script (wins
    # exactly the no-rollback recoveries), each extra member is an
    # independently-seeded switch-timing bet (wins rollback arrivals
    # when the sampled switch frame and value land) — all members ride
    # ONE vmapped draft dispatch, so extra width fills more of the
    # device bubble rather than adding dispatches
    DEFAULT_WIDTH = 2

    def __init__(self, *, num_players: int, input_size: int, window: int,
                 ring_len: int, max_prediction: int, seed: int = 0,
                 width: int = DEFAULT_WIDTH):
        from ..tpu.input_model import InputHistoryModel

        self.num_players = num_players
        self.input_size = input_size
        self.window = window
        self.ring_len = ring_len
        self.max_prediction = max_prediction
        self.seed = seed
        self.width = max(1, width)
        self._model_cls = InputHistoryModel
        # installed trained model (learn.ArrayInputModel): when set,
        # every lane drafts from a clone of it instead of a fresh
        # online Counter model
        self._proto = None
        self.model_version: Optional[int] = None
        self.model_swaps = 0
        self._lanes: Dict[Any, _LaneSpec] = {}
        # lifetime stats (host section + bench short line, no telemetry
        # dependency — plain ints like the host's session counters)
        self.drafts_launched = 0
        self.frames_drafted = 0
        # serveable frames: ONE member's window per draft — only one
        # member can ever serve a given frame, so the hit rate divides
        # adopted by this, not by frames_drafted (which counts device
        # work across all members and would cap the rate at 1/width)
        self.frames_draftable = 0
        self.frames_adopted = 0
        self.frames_discarded = 0
        self.spec_adopts = 0
        self.spec_misses = 0
        (self._m_drafted, self._m_adopted, self._m_discarded,
         self._m_prefix) = speculation_instruments()

    # ------------------------------------------------------------------
    # lane lifecycle
    # ------------------------------------------------------------------

    def _fresh_model(self):
        if self._proto is not None:
            return self._proto.clone()
        return self._model_cls(self.num_players, self.input_size)

    def attach(self, key: Any, *, num_players: Optional[int] = None) -> None:
        self._lanes[key] = _LaneSpec(
            self._fresh_model(),
            # per-lane counter-rng stream id: a crc of the host key (a
            # pure function of the key — hash() is process-salted and
            # the DET lint rightly rejects it)
            self.seed ^ zlib.crc32(repr(key).encode()),
            self.num_players if num_players is None else num_players,
            # live frames span [current - window - max_prediction,
            # current]: +2 keeps a put from ever colliding with a
            # still-readable slot
            _PlayedRing(self.window + self.max_prediction + 2,
                        self.num_players, self.input_size),
        )

    def drop(self, key: Any) -> None:
        ls = self._lanes.pop(key, None)
        if ls is not None and ls.draft is not None:
            self._discard(ls)

    # ------------------------------------------------------------------
    # model hot-swap (learn/ deploy seam) + migration stats carry
    # ------------------------------------------------------------------

    def install_model(self, prototype, *, version: Optional[int] = None
                      ) -> None:
        """Swap the draft model fleet-wide at a tick boundary: every
        lane gets a fresh clone of `prototype` (None reverts to per-lane
        online Counter models). Standing drafts are left STANDING — the
        verify pass consults only the played rows, never the model, so
        an in-flight draft stays exactly as adoptable as before the
        swap; the new model first matters at the next plan_draft. That
        is also the whole twin-parity argument: the model feeds nothing
        but the draft seam, and the adopt route is verify-gated, so a
        never-speculating twin cannot observe which model drafted."""
        self._proto = prototype
        self.model_version = version
        self.model_swaps += 1
        for ls in self._lanes.values():
            ls.model = self._fresh_model()
            # the fresh model's run trackers are cold: the next
            # record_segment finalization pass re-primes them row by
            # row, exactly like a newly-attached lane

    def export_model_state(self, key: Any) -> Optional[dict]:
        """The lane model's learned statistics by value (JSON-safe) —
        what a migration ticket carries so the destination's speculation
        resumes warm."""
        ls = self._lanes.get(key)
        return ls.model.state_dict() if ls is not None else None

    def import_model_state(self, key: Any, state: Optional[dict]) -> bool:
        """Load exported statistics into an attached lane's model.
        Kind/identity mismatches (online stats arriving at a lane
        drafting from a different installed model) degrade to a cold
        start — migration must never fail on prediction statistics."""
        from ..errors import ModelIncompatible

        ls = self._lanes.get(key)
        if ls is None or not state:
            return False
        try:
            ls.model.load_state_dict(state)
        except ModelIncompatible:
            return False
        return True

    # ------------------------------------------------------------------
    # per-segment bookkeeping (host._stage_segment calls this for every
    # staged p2p segment, adopted or not)
    # ------------------------------------------------------------------

    def record_segment(self, key: Any, *, load_frame: Optional[int],
                       start: int, count: int, inputs: np.ndarray,
                       statuses: np.ndarray, saves) -> None:
        """Record what the lane actually played this segment (the prefix
        check's ground truth), which ring slots now hold which frames,
        and feed newly-FINALIZED rows to the lane's input model — the
        same finalization discipline as TpuRollbackBackend: only frames
        beyond rollback reach enter the statistics, so a later
        correction can never have polluted them."""
        ls = self._lanes.get(key)
        if ls is None:
            return
        # an arrival rollback that rewrites history strictly BEFORE the
        # draft's anchor invalidates the anchor snapshot's lineage (a
        # load AT the anchor replays from the very snapshot the draft
        # rolled out of — still serveable, shift 0; the host runs verify
        # before this bookkeeping so such a segment can adopt)
        if (
            ls.draft is not None
            and load_frame is not None
            and load_frame < ls.draft.anchor
        ):
            self._discard(ls)
        for f in range(count):
            ls.played.put(start + f, inputs[f], statuses[f])
        for _slot_i, save in saves:
            ls.ring_frames[save.frame % self.ring_len] = save.frame
        current_after = start + count
        final_horizon = current_after - self.max_prediction
        horizon = current_after - self.window - self.max_prediction
        f = ls.finalized_to + 1
        if f < horizon:
            f = horizon
            for p in range(self.num_players):
                ls.model.break_run(p)
        while f < final_horizon:
            rec = ls.played.get(f)
            if rec is None:
                for p in range(ls.num_players):
                    ls.model.break_run(p)
            else:
                pin, pst = rec
                for p in range(ls.num_players):
                    if pst[p] >= _DISC:
                        ls.model.break_run(p)
                    else:
                        ls.model.observe(p, pin[p].tobytes())
            ls.finalized_to = f
            f += 1
        if horizon > ls.played.floor:
            ls.played.floor = horizon

    # ------------------------------------------------------------------
    # drafting
    # ------------------------------------------------------------------

    def plan_draft(self, key: Any, *, current_frame: int,
                   watermark: Optional[int],
                   local_pins: Optional[Dict[int, bytes]] = None,
                   confirmed_lookup=None,
                   fingerprint: Any = None):
        """Build a starved lane's draft script, or None when the lane
        cannot be drafted this tick (no confirmed watermark, anchor
        snapshot not live in the ring, played history incomplete, or a
        fresh draft already standing). A standing draft goes stale when
        the confirmed watermark moves — newly-arrived inputs may
        contradict drafted cells — and is re-drafted.

        The script covers frames anchor .. anchor + window - 1 with
        anchor = watermark + 1 (the deepest frame the arrival rollback
        can load). The PLAYED rows (anchor .. current_frame - 1) pin the
        session's played bytes VERBATIM — predictions included: the
        adopt's load reads a ring snapshot whose lineage is exactly what
        the session played, so a draft that deviates there can never be
        adopted by a no-rollback recovery. Future rows' cells draw from
        the lane's learned input model (InputHistoryModel.draft_script,
        counter-based). Two kinds of TRUTH override the defaults:
        `local_pins` (handle -> input bytes) carries the lane's PENDING
        local inputs — submitted during the starvation but not yet
        advanced, so the host already knows what the local player will
        play next — and `confirmed_lookup(p, frame)` resolves inputs
        that ARRIVED during the stall but haven't been advanced over yet
        (the session's input queues hold them). A confirmed value that
        contradicts a played prediction is safe to pin over it: that
        frame is exactly one the arrival rollback will load at or
        before, so it lands in the verify region (compared against the
        same truth), never in the played-lineage prefix. `fingerprint`
        is the per-player confirmed frontier: a standing draft goes
        stale the moment any new confirmation lands — but if the
        re-drafted script comes out byte-identical (the arrivals
        confirmed what was already drafted), the standing draft is
        refreshed in place and NO new dispatch happens."""
        ls = self._lanes.get(key)
        if ls is None or watermark is None:
            return None
        if ls.draft is not None:
            if ls.draft.fingerprint == fingerprint:
                return None  # still fresh: nothing new arrived since
            if confirmed_lookup is not None and self._standing_survives(
                ls, confirmed_lookup
            ):
                # the arrivals are consistent with at least one standing
                # member — that member can still win the verify, so keep
                # the standing draft (NO new dispatch) rather than spend
                # a rollout re-guessing what it already guessed right
                ls.draft.fingerprint = fingerprint
                return None
        anchor = watermark + 1
        S = current_frame - anchor
        D, P, I = self.window, self.num_players, self.input_size
        if S < 1 or S >= D:
            if ls.draft is not None:
                self._discard(ls)
            return None
        if ls.ring_frames.get(anchor % self.ring_len) != anchor:
            # anchor snapshot not (or no longer) in the ring
            if ls.draft is not None:
                self._discard(ls)
            return None
        n = ls.num_players
        base = np.zeros((D, P, I), dtype=np.uint8)
        # host-layout pad columns are pinned to the dummy zero input the
        # resim substitutes for them (the draft rollout marks them
        # DISCONNECTED too, see `statuses` below)
        pinned = np.zeros((D, P), dtype=bool)
        pinned[:, n:] = True
        if local_pins:
            for h, buf in local_pins.items():
                if 0 <= h < n:
                    base[S:, h] = np.frombuffer(buf, dtype=np.uint8)
                    pinned[S:, h] = True
        # two pin masks over the same base values: the LINEAGE mask pins
        # every played cell verbatim (predictions included — the ring
        # snapshot an arrival loads embodies exactly what was played, so
        # member 0 can serve any load the played history survives), the
        # BET mask leaves played PREDICTED cells free for members 1+ to
        # re-draw — a rollback arrival's first corrected frame is by
        # definition one where the played prediction was wrong, so only
        # a script that DEVIATES from it there can serve a rollback
        pin_bets = pinned
        for j in range(S):
            rec = ls.played.get(anchor + j)
            if rec is None:
                if ls.draft is not None:
                    self._discard(ls)
                return None
            pin, pst = rec
            if (pst[:n] >= _DISC).any():
                # disconnect rows are not draftable behavior
                if ls.draft is not None:
                    self._discard(ls)
                return None
            base[j, :n] = pin[:n]
            pin_bets[j, :n] = pst[:n] != _PRED
        pin_lineage = pin_bets.copy()
        pin_lineage[:S, :n] = True
        rollback_certain = False
        if confirmed_lookup is not None:
            # inputs that arrived during the stall: pin the TRUE values
            # over played predictions and drawn guesses alike. A truth
            # that CONTRADICTS a played prediction makes the arrival
            # rollback certain — the lineage member is then provably
            # dead (its pinned played history can never be the verify's
            # longest prefix), so its slot is better spent on another
            # timing bet
            for j in range(D):
                for p in range(n):
                    v = confirmed_lookup(p, anchor + j)
                    if v is not None:
                        arr = np.frombuffer(v, dtype=np.uint8)
                        if j < S and not np.array_equal(base[j, p], arr):
                            rollback_certain = True
                        base[j, p] = arr
                        pin_bets[j, p] = True
                        pin_lineage[j, p] = True
        # per-player stream state entering the window: the value played
        # at anchor - 1 and its backward run length
        init_v = np.zeros((P, I), dtype=np.uint8)
        init_h = np.ones((P,), dtype=np.int64)
        prev = ls.played.get(anchor - 1)
        if prev is not None:
            init_v[:] = prev[0]
            for p in range(P):
                run, f = 1, anchor - 2
                while run < 64:
                    rec = ls.played.get(f)
                    if rec is None or not np.array_equal(
                        rec[0][p], init_v[p]
                    ):
                        break
                    run += 1
                    f -= 1
                init_h[p] = run
        # member 0: the played-lineage script (skipped when the rollback
        # is already certain); members 1+: independently counter-seeded
        # switch-timing bets (deduped — a bet whose draws never fire
        # inside the window collapses onto an earlier member)
        scripts = []
        if not rollback_certain:
            scripts.append(
                ls.model.draft_script(
                    base.copy(), pin_lineage, anchor_frame=anchor,
                    seed=ls.seed, init_values=init_v, init_holds=init_h,
                )
            )
        m = 1
        while len(scripts) < self.width and m <= 2 * self.width:
            cand = ls.model.draft_script(
                base.copy(), pin_bets, anchor_frame=anchor,
                seed=ls.seed ^ (m * 0x9E3779B1), init_values=init_v,
                init_holds=init_h,
            )
            if not any(np.array_equal(cand, s) for s in scripts):
                scripts.append(cand)
            m += 1
        if not scripts:
            return None
        if ls.draft is not None:
            # reaching here means every standing member is contradicted
            # (or no lookup was supplied): replace it
            self._discard(ls)
        statuses = np.zeros((P,), dtype=np.int32)
        statuses[n:] = _DISC
        return anchor, scripts, statuses

    def _standing_survives(self, ls: _LaneSpec, confirmed_lookup) -> bool:
        """True while at least one standing member is consistent with
        every input confirmed so far over the drafted window — the cheap
        filter that decides redraft-vs-keep when new arrivals land: a
        surviving member can still win the verify, a fully-contradicted
        draft is worthless and worth replacing with fresh truth pinned
        in."""
        d = ls.draft
        n = ls.num_players
        D = len(d.scripts[0])
        alive = [True] * len(d.scripts)
        for j in range(D):
            for p in range(n):
                v = confirmed_lookup(p, d.anchor + j)
                if v is None:
                    continue
                arr = np.frombuffer(v, dtype=np.uint8)
                for mi, script in enumerate(d.scripts):
                    if alive[mi] and not np.array_equal(script[j, p], arr):
                        alive[mi] = False
            if not any(alive):
                return False
        return True

    def install_draft(self, key: Any, *, anchor: int, scripts,
                      batch, members, watermark: int,
                      fingerprint: Any = None) -> None:
        ls = self._lanes[key]
        assert ls.draft is None
        assert len(scripts) == len(members) >= 1
        ls.draft = StandingDraft(
            anchor, scripts, batch, members, watermark, fingerprint
        )
        self.drafts_launched += 1
        drafted = sum(len(s) for s in scripts)
        self.frames_drafted += drafted
        self.frames_draftable += max(len(s) for s in scripts)
        if GLOBAL_TELEMETRY.enabled:
            self._m_drafted.inc(drafted)

    # ------------------------------------------------------------------
    # verify-and-adopt
    # ------------------------------------------------------------------

    def verify(self, key: Any, *, load_frame: Optional[int], start: int,
               count: int, inputs: np.ndarray,
               statuses: np.ndarray) -> Optional[Tuple[StandingDraft, int, int, int]]:
        """The arrival check: compare the staged segment's real inputs
        against every member of the standing draft per frame. Returns
        (draft, member, shift, matched) for the best member that can
        serve the row via the adopt route (matched >= 1), else None.
        A full hit leaves the draft standing (the next rows keep serving
        until it exhausts); a truncation or miss discards it; exhaustion
        (the row runs past the drafted window) discards it too."""
        ls = self._lanes.get(key)
        if ls is None or ls.draft is None or count < 1:
            return None
        d = ls.draft
        # record_segment already dropped anchor-rewriting drafts; a load
        # AT the anchor is serveable (shift 0: the adopt's load reads the
        # same ring snapshot the draft anchored on)
        shift = start - d.anchor
        D = len(d.scripts[0])
        if shift < 0 or shift + count > D:
            self._discard(ls)
            return None
        n = ls.num_players
        # longest clean run of the arrival: stop before any row with a
        # real player disconnected (drafted statuses marked real players
        # CONFIRMED)
        clean = 0
        while clean < count and (statuses[clean, :n] < _DISC).all():
            clean += 1
        best_member, best_matched = -1, 0
        for member, script in zip(d.members, d.scripts):
            # the member's lineage must equal the PLAYED rows between
            # anchor and the row's start — verbatim, disconnect-free
            # among the lane's REAL players (pad columns are
            # deterministic): the adopt's load reads a ring snapshot
            # whose history is exactly what was played
            ok = True
            for j in range(shift):
                rec = ls.played.get(d.anchor + j)
                if (
                    rec is None
                    or (rec[1][:n] >= _DISC).any()
                    or not np.array_equal(script[j], rec[0])
                ):
                    ok = False
                    break
            if not ok:
                continue
            matched = 0
            while matched < clean and np.array_equal(
                script[shift + matched], inputs[matched]
            ):
                matched += 1
            if matched > best_matched:
                best_member, best_matched = member, matched
        if GLOBAL_TELEMETRY.enabled:
            self._m_prefix.observe(best_matched)
        if best_matched == 0:
            self.spec_misses += 1
            self._discard(ls)
            return None
        # DISTINCT frames this adopt serves for the first time: a
        # rollback arrival re-covering a region an earlier full-hit
        # adopt already served counts only the fresh extension
        extent = shift + best_matched
        newly = max(0, extent - d.covered)
        d.covered = max(d.covered, extent)
        d.served += newly
        self.frames_adopted += newly
        self.spec_adopts += 1
        if GLOBAL_TELEMETRY.enabled and newly:
            self._m_adopted.inc(newly)
        out = (d, best_member, shift, best_matched)
        if best_matched < count:
            # truncation: the drafted suffix diverged — the adopt
            # resimulates it; nothing past it can ever match
            self._discard(ls)
        return out

    def _discard(self, ls: _LaneSpec) -> None:
        d = ls.draft
        ls.draft = None
        if d is None:
            return
        unserved = max(sum(len(s) for s in d.scripts) - d.served, 0)
        self.frames_discarded += unserved
        if GLOBAL_TELEMETRY.enabled and unserved:
            self._m_discarded.inc(unserved)

    # ------------------------------------------------------------------

    def section(self) -> dict:
        """The host telemetry section's speculation block."""
        return {
            # draft-model provenance: None = the online Counter model
            "model_version": self.model_version,
            "model_swaps": self.model_swaps,
            "drafts": self.drafts_launched,
            "frames_drafted": self.frames_drafted,
            "frames_draftable": self.frames_draftable,
            "frames_adopted": self.frames_adopted,
            "frames_discarded": self.frames_discarded,
            "adopts": self.spec_adopts,
            "misses": self.spec_misses,
            # adopted over SERVEABLE frames (one member's window per
            # draft): prediction quality, independent of draft width —
            # frames_drafted measures device work across members
            "hit_rate": (
                round(self.frames_adopted / self.frames_draftable, 4)
                if self.frames_draftable
                else 0.0
            ),
        }
