"""WAN-shaped chaos loadgen: seeded fault profiles + fleet-level fault
injection over a HostGroup.

The plain loadgen (serve/loadgen.py) proves the megabatch path under a
uniform lossy link. Real fleets fail differently: RTT depends on which
regions the peers sit in, loss arrives in bursts (congested queues, not
coin flips), packets reorder when a spike delays one copy past its
successors, users arrive in flash crowds and leave in mass-disconnect
storms, and hosts die mid-match. This module models all of that behind
two seams:

  * `WanProfile` — a `FaultProfile` for InMemoryNetwork: a regional RTT
    matrix (peers hash to regions), Gilbert-Elliott two-state burst loss
    per directed link, jitter with occasional reorder spikes, and rare
    duplication. Every draw comes from the network's seeded rng plus the
    profile's own seeded link states, so a chaos run is bit-reproducible
    per seed.
  * `run_chaos` — the soak driver: >= N scripted sessions in 2-4-player
    matches spread over a HostGroup, driven in virtual time through a
    schedule of `ChaosEvent`s (live migrations, a host kill->restore
    cycle, mass-disconnect storms, flash-crowd arrival waves). The gates
    the report feeds: ZERO desyncs with real checksum comparisons, and a
    bounded p99 admission-queue wait.

scripts/check.sh --chaos-smoke runs a small seeded instance of exactly
this; tests/test_fleet_ops.py pins the >=64-session acceptance soak.
"""

from __future__ import annotations

import random
import time as _time
import zlib
from typing import Any, Dict, List, Optional

from ..errors import GroupSaturated, HostFull
from ..network.sockets import InMemoryNetwork
from ..sessions.builder import SessionBuilder
from ..types import DesyncDetection, PlayerType, SessionState
from ..utils.clock import FakeClock
from .faults import FaultInjector, FaultPlan
from .loadgen import FRAME_MS, build_matches, make_scripts, sync_fleet
from .migrate import HostGroup

# the device-fault kinds a WAN chaos soak fires by default: the
# TRANSIENT tier only — recovery is retry/skip/extra-drive, so the
# zero-desync and service gates still hold. The destructive tier
# (slot_bitflip, checkpoint_corrupt) needs the audit lane and
# restore-failure assertions around it: scripts/fault_smoke.py and
# tests/test_device_faults.py drive those deliberately.
CHAOS_FAULT_KINDS = ("dispatch_raise", "harvest_timeout", "mailbox_storm")


def _region_of(addr: Any, regions: int) -> int:
    """Stable, process-independent region assignment (hash() of str is
    salted per process; crc32 of the repr is not)."""
    return zlib.crc32(repr(addr).encode("utf-8")) % regions


class WanProfile:
    """Seeded WAN-shaped per-link fault model (FaultProfile).

    Latency: `intra_ms` within a region; across regions,
    `cross_base_ms + cross_step_ms * |r_src - r_dst|` — a crude but
    monotone stand-in for geographic distance. Jitter: uniform
    `[0, jitter_ms]`, plus a `reorder_spike_ms` spike with probability
    `reorder` (a spiked datagram is overtaken by its successors — real
    reordering, not just noise). Loss: Gilbert-Elliott per DIRECTED link
    — a good state losing `loss_good` and a bad (burst) state losing
    `loss_bad`, with seeded per-datagram transitions — so losses cluster
    the way congested queues make them cluster. Duplication: `duplicate`
    per datagram."""

    def __init__(self, *, regions: int = 3, intra_ms: int = 12,
                 cross_base_ms: int = 40, cross_step_ms: int = 25,
                 jitter_ms: int = 8, reorder: float = 0.01,
                 reorder_spike_ms: int = 60, loss_good: float = 0.01,
                 loss_bad: float = 0.25, p_enter_burst: float = 0.005,
                 p_exit_burst: float = 0.10, duplicate: float = 0.002,
                 seed: int = 0):
        self.regions = regions
        self.intra_ms = intra_ms
        self.cross_base_ms = cross_base_ms
        self.cross_step_ms = cross_step_ms
        self.jitter_ms = jitter_ms
        self.reorder = reorder
        self.reorder_spike_ms = reorder_spike_ms
        self.loss_good = loss_good
        self.loss_bad = loss_bad
        self.p_enter_burst = p_enter_burst
        self.p_exit_burst = p_exit_burst
        self.duplicate = duplicate
        self._link_rng = random.Random(seed ^ 0xC4A05)
        # directed link -> True while in the bursty (bad) loss state
        self._burst: Dict[Any, bool] = {}
        # observability for reports/tests
        self.delivered = 0
        self.dropped = 0
        self.duplicated = 0
        self.spiked = 0

    def base_latency_ms(self, src: Any, dst: Any) -> int:
        r_src = _region_of(src, self.regions)
        r_dst = _region_of(dst, self.regions)
        if r_src == r_dst:
            return self.intra_ms
        return self.cross_base_ms + self.cross_step_ms * abs(r_src - r_dst)

    def link(self, src: Any, dst: Any, now_ms: int,
             rng: random.Random) -> List[int]:
        # Gilbert-Elliott state step for this directed link
        key = (src, dst)
        burst = self._burst.get(key, False)
        roll = self._link_rng.random()
        if burst:
            if roll < self.p_exit_burst:
                burst = False
        else:
            if roll < self.p_enter_burst:
                burst = True
        self._burst[key] = burst
        if rng.random() < (self.loss_bad if burst else self.loss_good):
            self.dropped += 1
            return []
        delay = self.base_latency_ms(src, dst)
        if self.jitter_ms:
            delay += rng.randint(0, self.jitter_ms)
        if rng.random() < self.reorder:
            # spike one copy past its successors: genuine reordering
            delay += self.reorder_spike_ms
            self.spiked += 1
        delays = [delay]
        if rng.random() < self.duplicate:
            delays.append(delay + rng.randint(0, self.jitter_ms or 1))
            self.duplicated += 1
        self.delivered += len(delays)
        return delays

    def section(self) -> dict:
        return {
            "regions": self.regions,
            "delivered": self.delivered,
            "dropped": self.dropped,
            "duplicated": self.duplicated,
            "reorder_spikes": self.spiked,
            "links_in_burst": sum(1 for b in self._burst.values() if b),
        }


class ChaosEvent:
    """One scheduled fault: `tick` (relative to the measured drive),
    `kind`, plus kind-specific params.

    In-process kinds (run_chaos, this module): "migrate", "kill",
    "restore", "storm", "flash_crowd" — every fault is simulated inside
    one Python process.

    Process-level kinds (ggrs_tpu.fleet.chaos.run_process_chaos, which
    consumes this same event type): "sigkill" (a REAL agent process
    dies), "partition" (the control socket goes dark while the UDP/
    island data plane keeps ticking), "rpc_delay" / "rpc_dup" (director
    RPC frames held / duplicated). There `tick` is match progress, and
    recovery is the director's fenced failover rather than this
    module's polite kill→restore."""

    __slots__ = ("tick", "kind", "params")

    def __init__(self, tick: int, kind: str, **params):
        self.tick = tick
        self.kind = kind
        self.params = params

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"ChaosEvent({self.tick}, {self.kind!r}, {self.params})"


def default_schedule(ticks: int, *, migrations: int = 2,
                     kill: bool = True, kill_pause_ticks: int = 4,
                     storm_matches: int = 0,
                     flash_crowd: int = 0) -> List[ChaosEvent]:
    """The canonical soak schedule: migrations spread through the run, a
    kill->restore cycle at the midpoint, an optional flash crowd in the
    first half and an optional mass-disconnect storm in the second."""
    events: List[ChaosEvent] = []
    for i in range(migrations):
        events.append(
            ChaosEvent(int(ticks * (i + 1) / (migrations + 2)), "migrate")
        )
    if flash_crowd:
        events.append(
            ChaosEvent(int(ticks * 0.30), "flash_crowd",
                       sessions=flash_crowd)
        )
    if kill:
        k = int(ticks * 0.5)
        events.append(ChaosEvent(k, "kill"))
        events.append(ChaosEvent(k + kill_pause_ticks, "restore"))
    if storm_matches:
        events.append(
            ChaosEvent(int(ticks * 0.70), "storm", matches=storm_matches)
        )
    return sorted(events, key=lambda e: e.tick)


def _p99(samples: List[int]) -> int:
    if not samples:
        return 0
    s = sorted(samples)
    return s[min(len(s) - 1, int(0.99 * (len(s) - 1) + 0.999))]


def run_chaos(
    *,
    sessions: int = 64,
    ticks: int = 120,
    hosts: int = 2,
    entities: int = 16,
    max_players: int = 4,
    max_prediction: int = 8,
    seed: int = 0,
    profile: Optional[WanProfile] = None,
    events: Optional[List[ChaosEvent]] = None,
    migrations: int = 2,
    kill: bool = True,
    kill_pause_ticks: int = 4,
    storm_matches: int = 0,
    flash_crowd: int = 0,
    max_inflight_rows: Optional[int] = None,
    desync_interval: int = 10,
    sync_ticks: int = 800,
    warmup: bool = True,
    checkpoint_path: Optional[str] = None,
    game=None,
    device_faults: bool = False,
    fault_kinds=CHAOS_FAULT_KINDS,
    faults_per_kind: int = 1,
) -> Dict[str, Any]:
    """Drive >= `sessions` scripted peers across a `hosts`-wide HostGroup
    under a seeded WAN fault profile and a chaos schedule; returns a
    JSON-able report (strip `_group` first). Deterministic per seed.

    The default schedule injects `migrations` live migrations, one host
    kill->restore cycle (the killed host's sessions pause
    `kill_pause_ticks`, then resume from the kill-time checkpoint), and
    optionally a flash crowd and a mass-disconnect storm. The soak's
    gates: zero desyncs (with real checksum comparisons) and a bounded
    p99 admission-queue wait.

    `device_faults=True` additionally arms the DEVICE-DOMAIN fault seam
    (serve/faults.py) on every host: a seeded FaultPlan of
    `fault_kinds` (default: the transient tier — dispatch raises,
    harvest timeouts, mailbox overflow storms) fires through the run,
    and the same gates must still hold — the wire chaos and the device
    chaos compose."""
    clock = FakeClock()
    if profile is None:
        profile = WanProfile(seed=seed)
    net = InMemoryNetwork(clock, seed=seed, profile=profile)
    if game is None:
        from ..models.ex_game import ExGame

        game = ExGame(num_players=max_players, num_entities=entities)
    per_host = -(-sessions // hosts) + max_players  # room for overshoot
    group = HostGroup.build(
        game,
        hosts,
        clock=clock,
        max_prediction=max_prediction,
        num_players=max_players,
        max_sessions=per_host,
        # tight enough that bursts actually queue (the p99 gate must
        # measure something real), loose enough to keep the fleet moving
        max_inflight_rows=(
            max_inflight_rows
            if max_inflight_rows is not None
            else max(8, per_host // 2)
        ),
        idle_timeout_ms=0,
        warmup=warmup,
    )
    matches = build_matches(
        group, net, clock,
        sessions=sessions, max_prediction=max_prediction,
        desync_interval=desync_interval, seed=seed,
    )
    n_sessions = sum(len(keys) for keys in matches)
    sync_fleet(group, matches, clock, max_ticks=sync_ticks)

    # measured window starts here: sync-phase queue waits / blocked
    # flushes are warmup noise, not steady-state evidence
    for host in group.hosts:
        host.queue_waits.clear()
    for keys in matches:
        for k in keys:
            sess = group.session(k)
            if hasattr(sess, "drain_blocked_ticks"):
                sess.drain_blocked_ticks = 0

    if events is None:
        events = default_schedule(
            ticks, migrations=migrations, kill=kill,
            kill_pause_ticks=kill_pause_ticks,
            storm_matches=storm_matches, flash_crowd=flash_crowd,
        )
    by_tick: Dict[int, List[ChaosEvent]] = {}
    for ev in events:
        by_tick.setdefault(ev.tick, []).append(ev)

    own_checkpoint = checkpoint_path is None
    if own_checkpoint:
        import os as _os
        import tempfile

        fd, checkpoint_path = tempfile.mkstemp(
            prefix=f"ggrs_chaos_s{seed}_", suffix=".npz"
        )
        _os.close(fd)

    scripts = make_scripts(matches, ticks, seed)
    injectors = []
    if device_faults:
        for i, host in enumerate(group.hosts):
            plan = FaultPlan(
                seed * 131 + i, ticks, kinds=fault_kinds,
                events_per_kind=faults_per_kind,
                persist_dispatch=False,
            )
            injectors.append(FaultInjector(host, plan).install())
    rng = random.Random(seed ^ 0xCA05)
    desyncs: List[Any] = []
    stormed: set = set()
    crowd: List[Any] = []  # (gkey, match_index, peer_index, attach_tick)
    migrations_done = 0
    migrations_skipped = 0
    migration_latency_ticks: List[int] = []
    migration_wall_ms: List[float] = []
    crowd_attached = crowd_rejected = 0
    kill_stats: Dict[str, Any] = {}
    watching: List[Any] = []  # (gkey, frame_at_migration, tick)

    def collect(evs_by_key) -> None:
        for gkey, evs in evs_by_key.items():
            for e in evs:
                if type(e).__name__ == "DesyncDetected":
                    desyncs.append((gkey, e))

    def do_migrate(t: int) -> None:
        nonlocal migrations_done, migrations_skipped
        alive = [i for i in group._alive()]
        if len(alive) < 2:
            migrations_skipped += 1
            return
        src = max(alive, key=lambda i: group.hosts[i].active_sessions)
        candidates = [
            g for g in group.keys_on(src)
            if g not in stormed
            and group.session(g).current_state() == SessionState.RUNNING
            and not group._records[g].session.spectator_handles()
        ]
        if not candidates:
            migrations_skipped += 1
            return
        gkey = candidates[rng.randrange(len(candidates))]
        f0 = group.session(gkey).current_frame
        t0 = _time.perf_counter()
        try:
            group.migrate(gkey)
        except HostFull:
            migrations_skipped += 1
            return
        migration_wall_ms.append((_time.perf_counter() - t0) * 1000.0)
        migrations_done += 1
        watching.append((gkey, f0, t))

    def do_kill(t: int) -> None:
        alive = group._alive()
        if len(alive) < 2:
            return
        victim = max(alive, key=lambda i: group.hosts[i].active_sessions)
        t0 = _time.perf_counter()
        n = group.kill_host(victim, checkpoint_path)
        kill_stats.update(
            host=victim, sessions_suspended=n, killed_at_tick=t,
            kill_wall_ms=round((_time.perf_counter() - t0) * 1000.0, 2),
        )

    def do_restore(t: int) -> None:
        if "host" not in kill_stats or "restored_at_tick" in kill_stats:
            return
        t0 = _time.perf_counter()
        n = group.restore_host(kill_stats["host"], checkpoint_path)
        # the wall cost is dominated by the replacement host's warmup
        # compile of the megabatch grid — a production restore would warm
        # a standby host BEFORE taking traffic; reported so the bench can
        # separate availability cost from network-degradation cost
        kill_stats.update(
            sessions_resumed=n, restored_at_tick=t,
            restore_wall_ms=round((_time.perf_counter() - t0) * 1000.0, 2),
        )

    def do_storm(t: int, n_matches: int) -> None:
        victims = [
            m for m, keys in enumerate(matches)
            if not any(k in stormed for k in keys)
        ][-n_matches:]
        addrs = []
        for m in victims:
            for k, gkey in enumerate(matches[m]):
                stormed.add(gkey)
                addrs.append((m, k))
        net.set_blackhole(addrs)

    def do_flash_crowd(t: int, n: int) -> None:
        nonlocal crowd_attached, crowd_rejected
        pairs = -(-n // 2)  # 2-player matches
        for i in range(pairs):
            peers = []
            try:
                for k in range(2):
                    b = (
                        SessionBuilder(input_size=game.input_size)
                        .with_num_players(2)
                        .with_max_prediction_window(max_prediction)
                        .with_input_delay(1)
                        .with_desync_detection_mode(
                            DesyncDetection.on(interval=desync_interval)
                        )
                        .with_clock(clock)
                        .with_rng(random.Random(
                            (seed * 7919 + 0xFC0 + i * 131 + k) & 0xFFFF
                        ))
                    )
                    for h in range(2):
                        if h == k:
                            b = b.add_player(PlayerType.local(), h)
                        else:
                            b = b.add_player(
                                PlayerType.remote(("fc", i, h)), h
                            )
                    sess = b.start_p2p_session(net.socket(("fc", i, k)))
                    peers.append(group.attach(sess))
            except GroupSaturated:
                # a half-attached pair can never synchronize (its remote
                # was never built): release the orphan instead of letting
                # it pin a slot and skew occupancy/queue measurements...
                for gkey in peers:
                    group.detach(gkey)
                # ...and the whole remaining wave counts as rejected, not
                # just the pair that tripped saturation
                crowd_rejected += 2 * (pairs - i)
                break
            for k, gkey in enumerate(peers):
                crowd.append((gkey, i, k, t))
            crowd_attached += len(peers)

    handlers = {
        "migrate": lambda ev, t: do_migrate(t),
        "kill": lambda ev, t: do_kill(t),
        "restore": lambda ev, t: do_restore(t),
        "storm": lambda ev, t: do_storm(t, ev.params.get("matches", 1)),
        "flash_crowd": lambda ev, t: do_flash_crowd(
            t, ev.params.get("sessions", 2)
        ),
    }

    t_wall = _time.perf_counter()
    for t in range(ticks):
        for inj in injectors:
            inj.advance(t)
        for ev in by_tick.get(t, ()):
            handlers[ev.kind](ev, t)
        # scripted inputs: base matches from the pre-generated scripts,
        # crowd matches from a derived deterministic stream once RUNNING
        for m, keys in enumerate(matches):
            for k, gkey in enumerate(keys):
                if gkey in stormed:
                    continue
                group.submit_input(gkey, k, bytes([scripts[(m, k)][t]]))
        for gkey, i, k, t_attach in crowd:
            sess = group._records.get(gkey)
            if sess is None:
                continue
            if sess.session.current_state() == SessionState.RUNNING:
                group.submit_input(
                    gkey, k,
                    bytes([(seed * 31 + i * 17 + k * 7 + t) % 16]),
                )
        collect(group.tick())
        # migration latency: ticks from the handoff to the first
        # post-handoff frame advance on the destination host
        for w in list(watching):
            gkey, f0, t_mig = w
            rec = group._records.get(gkey)
            if rec is None:
                watching.remove(w)
                continue
            if rec.session.current_frame > f0:
                migration_latency_ticks.append(t - t_mig)
                watching.remove(w)
        clock.advance(FRAME_MS)

    # cooldown: let in-flight inputs and checksum reports land so the
    # final comparison intervals actually run
    for _ in range(3 * max_prediction):
        collect(group.tick())
        clock.advance(FRAME_MS)
    drive_s = _time.perf_counter() - t_wall
    if own_checkpoint:
        # the restore consumed it; a driver-owned temp file must not
        # accumulate across bench/smoke/CI runs
        import os as _os

        try:
            _os.unlink(checkpoint_path)
        except OSError:
            pass

    survivors = [
        (m, k, gkey)
        for m, keys in enumerate(matches)
        for k, gkey in enumerate(keys)
        if gkey not in stormed and gkey in group._records
    ]
    frames = [group.session(g).current_frame for _, _, g in survivors]
    checksums_published = sum(
        len(getattr(group.session(g), "local_checksum_history", ()))
        for _, _, g in survivors
    )
    waits = group.queue_waits()
    report: Dict[str, Any] = {
        "sessions": n_sessions,
        "matches": len(matches),
        "hosts": hosts,
        "ticks": ticks,
        "seed": seed,
        "desyncs": len(desyncs),
        "checksums_published": checksums_published,
        "session_ticks_per_sec": round(n_sessions * ticks / drive_s, 1),
        "min_frame": min(frames) if frames else 0,
        "max_frame": max(frames) if frames else 0,
        "migrations_done": migrations_done,
        "migrations_skipped": migrations_skipped,
        "migration_latency_ticks": migration_latency_ticks,
        "migration_wall_ms": [round(x, 2) for x in migration_wall_ms],
        "kill": kill_stats or None,
        "storm_sessions": len(stormed),
        "flash_crowd": {
            "attached": crowd_attached, "rejected": crowd_rejected,
        } if crowd_attached or crowd_rejected else None,
        "p99_queue_wait_ticks": _p99(waits),
        "max_queue_wait_ticks": max(waits) if waits else 0,
        "queue_wait_samples": len(waits),
        "drain_blocked_ticks": int(sum(
            getattr(group.session(g), "drain_blocked_ticks", 0)
            for _, _, g in survivors
        )),
        "profile": profile.section(),
        "group": group.group_section(),
        "device_faults": (
            [inj.section() for inj in injectors] if injectors else None
        ),
        "quarantines": sum(h.quarantines_total for h in group.hosts),
        "host_device_faults": sum(h.device_faults for h in group.hosts),
    }
    report["_group"] = group  # live handle for callers; strip before JSON
    return report
