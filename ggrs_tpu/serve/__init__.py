"""Multi-session serving: one device core, N sessions, cross-session
continuous batching.

    from ggrs_tpu.serve import SessionHost

    host = SessionHost(game, num_players=4, max_sessions=64, clock=clock)
    key = host.attach(session)          # HostFull past max_sessions
    host.submit_input(key, handle, buf)
    events = host.tick()                # pump + schedule + one megabatch
    snap = host.telemetry()
    host.drain(checkpoint_path="host.npz")

Importing this package does not import jax; the device core materializes
on the first SessionHost construction. The load-generator harness lives
in ggrs_tpu.serve.loadgen (imported lazily for the same reason).
"""

from ..errors import (
    DeviceDispatchFailed,
    GroupSaturated,
    HarvestTimeout,
    HostFull,
    InvariantViolation,
    SlotPoisoned,
)
from .faults import FAULT_KINDS, Fault, FaultInjector, FaultPlan
from .host import SessionHost
from .migrate import HostGroup, MigrationTicket, migrate_session

__all__ = [
    "DeviceDispatchFailed",
    "FAULT_KINDS",
    "Fault",
    "FaultInjector",
    "FaultPlan",
    "GroupSaturated",
    "HarvestTimeout",
    "HostFull",
    "HostGroup",
    "InvariantViolation",
    "MigrationTicket",
    "SessionHost",
    "SlotPoisoned",
    "migrate_session",
]
