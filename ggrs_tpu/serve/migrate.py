"""Fleet operations: live session migration, over-admission spillover,
host kill→restore — the multi-host story on top of SessionHost.

Live migration moves ONE mid-match session between two SessionHosts with
remote peers none the wiser: the source host flushes the session's staged
rows through its fence, exports the session's complete device residue
(live world + snapshot ring, `MultiSessionDeviceCore.export_slot`) into a
`MigrationTicket` together with the lane bookkeeping, and detaches; the
destination imports the slot bytes (`import_slot`, validated shape by
shape) and adopts the session at its exact frame. The session OBJECT —
protocol endpoints, input queues, pending checksum reports — rides the
ticket: its reliability state is the thing that makes the move invisible,
because peers keep talking to the same endpoint state machine at the same
address. Datagrams that arrive during the handoff wait in the socket and
REPLAY through the ordinary receive path on the first post-adoption pump,
so the peers observe one tick of extra jitter, not a resync.

`HostGroup` stacks policy on the same handoff: admission spillover
(HostFull on one host routes the attach to a sibling, bounded
retry/backoff, typed `GroupSaturated` when the whole group is full),
load-shedding migration, and kill→restore (a dying host's emergency
drain→checkpoint rebuilds as a fresh host via `load_stacked`, every
surviving session re-adopted AT ITS OLD SLOT with endpoint timers rebased
so the blackout cannot fire spurious disconnects).

The degradation ladder, in order of increasing violence: backpressure
(queue on the device-window budget) → spillover (sibling host) → evict
(idle/disconnect GC) → drain (graceful, checkpointed). docs/DESIGN.md
"Fleet operations" has the full handshake diagram.
"""

from __future__ import annotations

import random as _random
import time as _time
from typing import Any, Dict, List, Optional, Tuple

from ..errors import (
    CheckpointIncompatible,
    GroupSaturated,
    HarvestTimeout,
    HostFull,
    InvalidRequest,
)
from ..obs import GLOBAL_TELEMETRY, LOG2_BUCKETS_MS
from .host import SessionHost


def migrations_total():
    """Get-or-create THE migration counter — one definition shared by
    migrate_session and the smoke/bench gates that assert on it."""
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_migrations_total",
        "live sessions handed between SessionHosts (export+import pairs)",
    )


def migration_ms_histogram():
    return GLOBAL_TELEMETRY.registry.histogram(
        "ggrs_migration_ms",
        "wall-clock cost of one live migration "
        "(fence flush + slot export + slot import + adoption)",
        buckets=LOG2_BUCKETS_MS,
    )


def spillovers_total():
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_group_spillovers_total",
        "admissions a HostGroup routed past a full first-choice host",
    )


def saturations_total():
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_group_saturated_total",
        "admissions the whole HostGroup rejected after retry/backoff",
    )


class MigrationTicket:
    """Everything one live session needs to resume on another host: the
    session object (protocol/endpoint/input-queue state travels by
    reference — it IS the continuity the peers observe), the exported
    device slot bytes, and the lane bookkeeping. `slot_state=None` marks
    a restore-from-checkpoint ticket: the destination's stacked worlds
    already hold the bytes at `slot`. `input_stats` carries the source
    lane's learned input-model statistics by value (None when the source
    was not speculating) so speculation resumes warm on the destination
    instead of relearning every player's habits from zero."""

    __slots__ = ("session", "key", "slot", "current_frame",
                 "pending_inputs", "slot_state", "input_stats")

    def __init__(self, session, key, slot, current_frame,
                 pending_inputs, slot_state, input_stats=None):
        self.session = session
        self.key = key
        self.slot = slot
        self.current_frame = current_frame
        self.pending_inputs = frozenset(pending_inputs)
        self.slot_state = slot_state
        self.input_stats = input_stats


def _resume_endpoints(session, now_ms: int) -> None:
    """Rebase every endpoint's receive baseline after a handoff pause so
    a blackout the session itself caused (migration, host kill) cannot
    fire a spurious disconnect before the peers' backlog replays."""
    reg = getattr(session, "player_reg", None)
    endpoints = (
        list(reg.remotes.values()) + list(reg.spectators.values())
        if reg is not None
        else [session.host]  # spectator session: one host endpoint
    )
    for ep in endpoints:
        resume = getattr(ep, "resume_after_pause", None)
        if callable(resume):
            resume(now_ms)


def export_session(host: SessionHost, key: Any) -> MigrationTicket:
    """Checkpoint one live session out of `host`: flush its staged rows
    through the fence, copy its slot's world+ring to host memory, detach.
    The session stops being pumped the moment this returns — import it
    promptly (peers tolerate a pause well under their disconnect
    timeout, observing it as ordinary jitter)."""
    lane = host._lanes.get(key)
    if lane is None:
        raise InvalidRequest(f"unknown host key {key!r}")
    if lane.rows:
        # the staged rows must land on device BEFORE the export reads the
        # slot, or the exported world is behind lane.current_frame
        host._flush_ready(f"migration export of {key!r}")
    seam = getattr(host, "fault_seam", None)
    for attempt in (0, 1):
        try:
            if seam is not None:
                seam.before_harvest("migration_export")
            slot_state = host.device.export_slot(lane.slot)
            break
        except HarvestTimeout:
            # transient readback stall: the residue still exists on
            # device — block the fence and retry once, so the export
            # either completes whole or surfaces typed (never a
            # half-copied slot riding a ticket)
            host.harvest_timeouts += 1
            if attempt:
                raise
            host.device.block_until_ready()
    ticket = MigrationTicket(
        lane.session, key, lane.slot, lane.current_frame,
        set(lane.pending_inputs), slot_state,
        host.export_input_model_state(key),  # before detach drops the lane
    )
    host.detach(key)
    if GLOBAL_TELEMETRY.enabled:
        GLOBAL_TELEMETRY.record(
            "session_exported", key=str(key), slot=lane.slot,
            frame=lane.current_frame,
        )
    return ticket


def import_session(host: SessionHost, ticket: MigrationTicket, *,
                   key: Any = None, slot: Optional[int] = None) -> Any:
    """Adopt an exported session into `host` and resume it: slot bytes
    imported (or, for a restore ticket, claimed in place), lane resumed
    at the exact frame, endpoint timers rebased. The next host tick pumps
    the backlog that queued at the session's socket during the handoff —
    the input-queue replay that makes the move invisible to peers."""
    if slot is None and ticket.slot_state is None:
        slot = ticket.slot  # restore path: the worlds are already there
    new_key = host.adopt(
        ticket.session,
        current_frame=ticket.current_frame,
        slot_state=ticket.slot_state,
        pending_inputs=ticket.pending_inputs,
        key=key,
        slot=slot,
    )
    if ticket.input_stats is not None:
        # warm the destination's speculation lane; an incompatible or
        # absent planner degrades to a cold start, never a failed import
        host.import_input_model_state(new_key, ticket.input_stats)
    _resume_endpoints(ticket.session, host.clock.now_ms())
    return new_key


def migrate_session(src: SessionHost, dst: SessionHost, key: Any, *,
                    key_on_dst: Any = None) -> Any:
    """THE one-call live migration: export from `src`, import into `dst`,
    returns the session's key on `dst`. On an import failure (dst full /
    incompatible) the session is re-imported into `src` — a failed
    migration must degrade to 'nothing happened', never to a lost
    session — and the original error re-raises."""
    t0 = _time.perf_counter()
    ticket = export_session(src, key)
    try:
        new_key = import_session(dst, ticket, key=key_on_dst)
    except BaseException:
        import_session(src, ticket, key=key)  # roll back onto the source
        raise
    if GLOBAL_TELEMETRY.enabled:
        migrations_total().inc()
        migration_ms_histogram().observe(
            (_time.perf_counter() - t0) * 1000.0
        )
        GLOBAL_TELEMETRY.record(
            "session_migrated", key=str(key), to_key=str(new_key),
            frame=ticket.current_frame,
        )
    return new_key


class _GroupRecord:
    __slots__ = ("host_idx", "hkey", "session", "suspended_slot",
                 "suspended_frame", "suspended_inputs")

    def __init__(self, host_idx, hkey, session):
        self.host_idx = host_idx
        self.hkey = hkey
        self.session = session
        self.suspended_slot = None
        self.suspended_frame = None
        self.suspended_inputs = ()


class HostGroup:
    """N SessionHosts behind one admission/handoff policy. Group keys are
    stable across migrations and kill→restore cycles, so a driver
    (loadgen, chaos harness) addresses sessions without tracking which
    host currently owns them. Duck-types the slice of the SessionHost
    surface the loadgen helpers use (attach / submit_input / tick /
    session / keys / num_players / game / clock).

    Admission: `attach` tries hosts least-loaded first; HostFull routes
    to the next sibling (SPILLOVER); when every host rejects, the group
    backs off — advancing the injectable clock and ticking the fleet so
    eviction/GC can free slots — and retries up to `max_attempts` before
    raising the typed, terminal `GroupSaturated` with a per-host
    occupancy map."""

    def __init__(self, hosts: List[SessionHost], *,
                 clock=None, host_factory=None,
                 max_attempts: int = 3, backoff_ms: int = 32,
                 backoff_seed: int = 0):
        if not hosts:
            raise InvalidRequest("a HostGroup needs at least one host")
        self.hosts = list(hosts)
        self.clock = clock or hosts[0].clock
        self._host_factory = host_factory
        self.max_attempts = max_attempts
        self.backoff_ms = backoff_ms
        # seeded jitter source for the admission backoff: a FIXED
        # exponential schedule synchronizes every rejected admission in a
        # flash crowd onto the same retry instants (a retry storm that
        # re-collides forever); jitter decorrelates them, the seed keeps
        # a soak bit-reproducible
        self._backoff_rng = _random.Random(backoff_seed ^ 0xB0FF)
        self.dead: set = set()
        self._records: Dict[Any, _GroupRecord] = {}
        self._by_host: List[Dict[Any, Any]] = [dict() for _ in self.hosts]
        self._next_gkey = 0
        self._pending_events: Dict[Any, List[Any]] = {}
        self._kill_tickets: Dict[int, List[MigrationTicket]] = {}
        # lifetime stats (the group section of chaos reports)
        self.migrations = 0
        self.spillovers = 0
        self.saturations = 0
        self.kills = 0
        self.restores = 0
        self.evictions_seen = 0
        self.inputs_dropped = 0

    @classmethod
    def build(cls, game, n_hosts: int, *, clock=None,
              max_attempts: int = 3, backoff_ms: int = 32,
              backoff_seed: int = 0, **host_kw) -> "HostGroup":
        """Construct `n_hosts` identically-configured SessionHosts plus
        the factory kill→restore needs to rebuild one."""
        factory = lambda: SessionHost(game, clock=clock, **host_kw)  # noqa: E731
        hosts = [factory() for _ in range(n_hosts)]
        return cls(
            hosts, clock=clock, host_factory=factory,
            max_attempts=max_attempts, backoff_ms=backoff_ms,
            backoff_seed=backoff_seed,
        )

    # ------------------------------------------------------------------
    # loadgen-facing surface (duck-types SessionHost)
    # ------------------------------------------------------------------

    @property
    def num_players(self) -> int:
        return self.hosts[0].num_players

    @property
    def game(self):
        return self.hosts[0].game

    @property
    def active_sessions(self) -> int:
        return len(self._records)

    def keys(self) -> List[Any]:
        return list(self._records)

    def keys_on(self, host_idx: int) -> List[Any]:
        return [
            g for g, r in self._records.items() if r.host_idx == host_idx
        ]

    def session(self, gkey: Any):
        return self._records[gkey].session

    def host_of(self, gkey: Any) -> Optional[int]:
        return self._records[gkey].host_idx

    # ------------------------------------------------------------------
    # admission: spillover + bounded retry/backoff
    # ------------------------------------------------------------------

    def _alive(self) -> List[int]:
        return [i for i in range(len(self.hosts)) if i not in self.dead]

    def _occupancy(self) -> Dict[str, str]:
        return {
            f"host{i}": (
                "dead" if i in self.dead else
                f"{self.hosts[i].active_sessions}"
                f"/{self.hosts[i].max_sessions}"
            )
            for i in range(len(self.hosts))
        }

    def _register(self, host_idx: int, hkey: Any, session) -> Any:
        gkey = self._next_gkey
        self._next_gkey += 1
        self._records[gkey] = _GroupRecord(host_idx, hkey, session)
        self._by_host[host_idx][hkey] = gkey
        return gkey

    def attach(self, session) -> Any:
        attempts = 0
        for attempt in range(self.max_attempts):
            order = sorted(
                self._alive(),
                key=lambda i: self.hosts[i].active_sessions,
            )
            for rank, i in enumerate(order):
                attempts += 1
                try:
                    hkey = self.hosts[i].attach(session)
                except HostFull:
                    continue
                if rank > 0 or attempt > 0:
                    self.spillovers += 1
                    if GLOBAL_TELEMETRY.enabled:
                        spillovers_total().inc()
                        GLOBAL_TELEMETRY.record(
                            "group_spillover", host=i, attempt=attempt
                        )
                return self._register(i, hkey, session)
            if attempt + 1 < self.max_attempts:
                self._backoff(attempt)
        self.saturations += 1
        if GLOBAL_TELEMETRY.enabled:
            saturations_total().inc()
            GLOBAL_TELEMETRY.record(
                "group_saturated", attempts=attempts
            )
        raise GroupSaturated(
            f"every host in the group rejected the admission "
            f"({self._occupancy()})",
            attempts=attempts, per_host=self._occupancy(),
        )

    def backoff_delay_ms(self, attempt: int) -> int:
        """One jittered exponential backoff draw: uniform over
        [base/2, base] with base = backoff_ms << attempt. Exposed (and
        consumed in draw order) so a unit test can pin the exact retry
        schedule a seed produces."""
        base = self.backoff_ms << attempt
        return self._backoff_rng.randrange(base // 2, base + 1)

    def _backoff(self, attempt: int) -> None:
        """Between admission attempts: give eviction/disconnect GC a
        chance to free slots — tick the fleet and advance the injectable
        clock by a seeded-jittered exponential delay (backoff_delay_ms).
        Events surfaced by the backoff ticks are buffered into the next
        tick() result, not dropped."""
        advance = getattr(self.clock, "advance", None)
        if callable(advance):
            advance(self.backoff_delay_ms(attempt))
        for gkey, evs in self.tick().items():
            self._pending_events.setdefault(gkey, []).extend(evs)

    # ------------------------------------------------------------------
    # driving
    # ------------------------------------------------------------------

    def submit_input(self, gkey: Any, handle, buf: bytes) -> bool:
        """Route one local input to whichever host owns the session now.
        Inputs for a suspended (killed-host) or evicted session are
        DROPPED and counted — exactly what a user disconnected from a
        dead host experiences — never an exception in the drive loop."""
        rec = self._records.get(gkey)
        if rec is None or rec.host_idx is None:
            self.inputs_dropped += 1
            return False
        if rec.session.host_key is None:  # evicted since last tick
            self._forget(gkey)
            self.inputs_dropped += 1
            return False
        self.hosts[rec.host_idx].submit_input(rec.hkey, handle, buf)
        return True

    def tick(self) -> Dict[Any, List[Any]]:
        """Tick every alive host; returns events keyed by GROUP key.
        Reconciles evictions (disconnect GC / idle timeout on a member
        host) into the group's own bookkeeping."""
        merged: Dict[Any, List[Any]] = {}
        if self._pending_events:
            merged, self._pending_events = self._pending_events, {}
        for i in self._alive():
            for hkey, evs in self.hosts[i].tick().items():
                gkey = self._by_host[i].get(hkey)
                merged.setdefault(
                    gkey if gkey is not None else ("host", i, hkey), []
                ).extend(evs)
        for gkey, rec in list(self._records.items()):
            if rec.host_idx is not None and rec.session.host_key is None:
                self._forget(gkey)
                self.evictions_seen += 1
        return merged

    def _forget(self, gkey: Any) -> None:
        rec = self._records.pop(gkey, None)
        if rec is not None and rec.host_idx is not None:
            self._by_host[rec.host_idx].pop(rec.hkey, None)

    def detach(self, gkey: Any) -> None:
        """Remove a session from whichever host owns it and drop the
        group record (the group-level twin of SessionHost.detach)."""
        rec = self._records.get(gkey)
        if rec is None:
            raise InvalidRequest(f"unknown group key {gkey!r}")
        if rec.host_idx is not None and rec.session.host_key is not None:
            self.hosts[rec.host_idx].detach(rec.hkey)
        self._forget(gkey)

    # ------------------------------------------------------------------
    # load shedding: migration + drain-to-siblings
    # ------------------------------------------------------------------

    def pick_migration_target(self, src_idx: int) -> Optional[int]:
        """Least-loaded alive sibling with a free slot, or None."""
        candidates = [
            i for i in self._alive()
            if i != src_idx and self.hosts[i]._free_slots
            and not self.hosts[i].draining
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda i: self.hosts[i].active_sessions)

    def migrate(self, gkey: Any, to: Optional[int] = None) -> int:
        """Live-migrate one session to `to` (default: the least-loaded
        sibling). Returns the destination host index; raises HostFull
        when no sibling can take it (the caller decides whether that is
        terminal — the chaos harness just skips the event)."""
        rec = self._records[gkey]
        if rec.host_idx is None:
            raise InvalidRequest(
                f"session {gkey!r} is suspended (its host was killed)"
            )
        dst_idx = to if to is not None else (
            self.pick_migration_target(rec.host_idx)
        )
        if dst_idx is None:
            raise HostFull("no sibling host can absorb the migration")
        new_hkey = migrate_session(
            self.hosts[rec.host_idx], self.hosts[dst_idx], rec.hkey
        )
        self._by_host[rec.host_idx].pop(rec.hkey, None)
        rec.host_idx, rec.hkey = dst_idx, new_hkey
        self._by_host[dst_idx][new_hkey] = gkey
        self.migrations += 1
        return dst_idx

    def drain_host(self, host_idx: int,
                   checkpoint_path: Optional[str] = None) -> dict:
        """Evict a host from service the GRACEFUL way: live-migrate every
        session to siblings via the same handoff path admissions spill
        through (GroupSaturated if they cannot fit), then drain the empty
        host. The 'scale down one host' operation."""
        for gkey in self.keys_on(host_idx):
            try:
                self.migrate(gkey)
            except HostFull:
                self.saturations += 1
                if GLOBAL_TELEMETRY.enabled:
                    saturations_total().inc()
                raise GroupSaturated(
                    f"draining host{host_idx}: no sibling capacity for "
                    f"session {gkey!r} ({self._occupancy()})",
                    per_host=self._occupancy(),
                ) from None
        summary = self.hosts[host_idx].drain(checkpoint_path)
        self.dead.add(host_idx)
        return summary

    # ------------------------------------------------------------------
    # kill -> restore-from-checkpoint
    # ------------------------------------------------------------------

    def kill_host(self, host_idx: int, checkpoint_path: str) -> int:
        """A host 'dies': its shutdown handler manages one emergency
        drain→checkpoint (staged rows flushed, stacked worlds written to
        `checkpoint_path`), then the process is gone. Sessions are
        suspended — not pumped, not advanced, their inputs dropped —
        until restore_host() brings the host back. Returns the number of
        suspended sessions."""
        if host_idx in self.dead:
            raise InvalidRequest(
                f"kill_host({host_idx}): host is already dead"
            )
        host = self.hosts[host_idx]
        host.drain(checkpoint_path)
        tickets: List[MigrationTicket] = []
        for gkey in self.keys_on(host_idx):
            rec = self._records[gkey]
            lane = host._lanes[rec.hkey]
            tickets.append(MigrationTicket(
                rec.session, rec.hkey, lane.slot, lane.current_frame,
                set(lane.pending_inputs), None,  # bytes live in the file
            ))
            host.detach(rec.hkey)
            self._by_host[host_idx].pop(rec.hkey, None)
            rec.host_idx = None  # suspended
        self._kill_tickets[host_idx] = tickets
        self.dead.add(host_idx)
        self.kills += 1
        if GLOBAL_TELEMETRY.enabled:
            GLOBAL_TELEMETRY.record(
                "host_killed", host=host_idx, sessions=len(tickets),
                checkpoint=str(checkpoint_path),
            )
        return len(tickets)

    def restore_host(self, host_idx: int, checkpoint_path: str) -> int:
        """Rebuild a killed host from its checkpoint: fresh SessionHost
        from the factory, stacked worlds loaded back in one pass
        (`load_stacked`), every suspended session re-adopted AT ITS OLD
        SLOT with endpoint timers rebased — so the peers' backlog replays
        on the next tick instead of tripping disconnect detection.
        Returns the number of resumed sessions."""
        from ..utils.checkpoint import load_device_checkpoint

        if host_idx not in self.dead:
            raise InvalidRequest(
                f"restore_host({host_idx}): host was never killed"
            )
        if self._host_factory is None:
            raise InvalidRequest(
                "restore_host needs a host_factory (build the group via "
                "HostGroup.build, or pass host_factory=)"
            )
        host = self._host_factory()
        tree, meta = load_device_checkpoint(checkpoint_path)
        for key, want in (
            ("kind", "MultiSessionDeviceCore"),
            ("capacity", host.device.capacity),
            ("num_players", host.num_players),
            ("max_prediction", host.max_prediction),
        ):
            if meta.get(key) != want:
                raise CheckpointIncompatible(
                    f"checkpoint {checkpoint_path!r} {key} does not match "
                    "the replacement host",
                    found=meta.get(key), expected=want,
                )
        host.device.load_stacked(tree["rings"], tree["states"])
        tickets = self._kill_tickets.pop(host_idx, [])
        self.hosts[host_idx] = host
        self.dead.discard(host_idx)
        for ticket in tickets:
            # import_session rebases the endpoint timers too
            hkey = import_session(host, ticket, key=ticket.key)
            gkey = None
            for g, rec in self._records.items():
                if rec.session is ticket.session:
                    gkey = g
                    rec.host_idx, rec.hkey = host_idx, hkey
                    break
            self._by_host[host_idx][hkey] = gkey
        self.restores += 1
        if GLOBAL_TELEMETRY.enabled:
            GLOBAL_TELEMETRY.record(
                "host_restored", host=host_idx, sessions=len(tickets),
            )
        return len(tickets)

    # ------------------------------------------------------------------
    # reporting
    # ------------------------------------------------------------------

    def queue_waits(self) -> List[int]:
        """Every member host's plain queue-wait samples, pooled."""
        out: List[int] = []
        for host in self.hosts:
            out.extend(host.queue_waits)
        return out

    def group_section(self) -> dict:
        return {
            "hosts": len(self.hosts),
            "dead": sorted(self.dead),
            "sessions": len(self._records),
            "occupancy": self._occupancy(),
            "migrations": self.migrations,
            "spillovers": self.spillovers,
            "saturations": self.saturations,
            "kills": self.kills,
            "restores": self.restores,
            "evictions_seen": self.evictions_seen,
            "inputs_dropped": self.inputs_dropped,
        }
