"""SessionHost: N concurrent sessions multiplexed onto ONE shared device
core via cross-session continuous batching.

The single-session stack leaves the device idle whenever its one session
waits on remote input; a serving process cannot afford that. The host owns
many sessions (P2P and spectator), pumps all of their sockets every host
tick, and coalesces every session whose `advance_frame` produced work into
one fused cross-session MEGABATCH dispatch on a
`ggrs_tpu.tpu.backend.MultiSessionDeviceCore` — each session world is one
slot of a stacked device pytree, each session tick one packed control row,
and the whole fleet's tick is one gather → vmapped-tick → scatter program
behind the PR 1 async fence. Rows are data, so a freshly attached session,
a mid-rollback session and a quiet session all ride the same cached
program; megabatch row counts pad to a small set of bucket sizes so the
jit cache stays bounded no matter how the fleet churns. The scheduler
additionally groups ready rows by ROLLBACK DEPTH (depth-adaptive
dispatch): zero-rollback ticks — the dominant traffic — ride a dedicated
fast program that skips the ring gather/scatter and the resim scan
outright, and rollback rows ride windowed programs sized to their depth
bucket, so one deep rollback never drags the whole fleet's rows to the
full window (docs/DESIGN.md "Depth-adaptive dispatch").

Lifecycle: admission control (`max_sessions`, typed HostFull rejection),
idle-session eviction and disconnect GC driven by the injectable Clock,
and graceful drain (stop admitting, flush the fence, checkpoint the
stacked worlds via utils/checkpoint). Backpressure: when the device
window is full (`max_inflight_rows`), ready sessions queue in arrival
order and the host reports queue depth.

Telemetry rides the PR 2 obs registry: sessions active/evicted/rejected,
megabatch-size histogram, cross-session occupancy, admission-queue wait
histogram — one `host.telemetry()` snapshot folds them in with every
hosted session's own section.

`resident=True` retires even the one-dispatch-per-tick cadence: staged
rows feed a device-resident input mailbox (tpu/mailbox.py) and a jitted
`lax.while_loop` virtual-tick driver consumes up to `resident_ticks` of
them per single dispatch — the host demoted to an async feeder
(pump → mailbox write → driver dispatch → lazy harvest), bit-identical
to the dispatch-per-tick twin (docs/DESIGN.md "Device-resident serving
loop").
"""

from __future__ import annotations

import os
import time as _time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.sanitize import active_alloc_sanitizer
from ..journal.wal import canonical_statuses
from ..errors import (
    ConfigError,
    DeviceDispatchFailed,
    DrainStalled,
    GGRSError,
    HarvestTimeout,
    HostFull,
    InvalidRequest,
    InvariantViolation,
    JournalError,
    JournalStalled,
    PredictionThreshold,
    SlotPoisoned,
)
from ..obs import GLOBAL_TELEMETRY, SESSION_COUNT_BUCKETS
from ..types import (
    Event,
    InputStatus,
    LoadGameState,
    PlayerHandle,
    Request,
    SessionState,
)
from ..utils.clock import Clock
from ..utils.tracing import GLOBAL_TRACER

DEFAULT_IDLE_TIMEOUT_MS = 30_000

# _drive_resident's "the drive raised and the recovery ladder ran"
# sentinel — distinct from None, which drive_mailbox legitimately
# returns for an empty mailbox
_DRIVE_FAILED = object()

# lazily-resolved backend types (importing ggrs_tpu.serve must not pull
# jax; the per-row retire path must not re-run import machinery either)
_BACKEND_REFS = None


def _backend_refs():
    global _BACKEND_REFS
    if _BACKEND_REFS is None:
        from ..tpu.backend import SnapshotRef, _LazyChecksum

        _BACKEND_REFS = (SnapshotRef, _LazyChecksum)
    return _BACKEND_REFS


def _array_is_ready(arr) -> bool:
    global _ARRAY_IS_READY
    if _ARRAY_IS_READY is None:
        from ..tpu.backend import _array_is_ready as impl

        _ARRAY_IS_READY = impl
    return _ARRAY_IS_READY(arr)


_ARRAY_IS_READY = None

# the "no env rows for this group" sentinel (shared: the dispatch loop
# must not build a (0, []) default per megabatch pass)
_NO_ENV: Tuple[int, tuple] = (0, ())


class _StagedRow:
    """One parsed request segment awaiting its megabatch: the packed
    control row plus the SaveGameState requests whose cells get their
    lazy checksums bound when the dispatch happens. `last_active` (the
    row's 1-based last active slot) and `fast` (zero-rollback fast-path
    eligibility) are the scheduler's depth-routing keys, computed once
    at parse time so grouping never rescans rows. `adopt` (None on
    ordinary rows) marks a row the verify pass matched against a
    standing speculative draft: (DraftBatch, packed adopt row) — it
    dispatches through device.adopt_slot instead of joining a megabatch
    group, serving the matched prefix from the draft trajectory."""

    __slots__ = ("row", "saves", "start_frame", "count", "last_active",
                 "fast", "adopt")

    def __init__(self, row, saves, start_frame, count, last_active, fast,
                 adopt=None):
        self.row = row
        self.saves = saves
        self.start_frame = start_frame
        self.count = count
        self.last_active = last_active
        self.fast = fast
        self.adopt = adopt


class _JournalTap:
    """One journaled lane's durable-input pipeline: a pure-observer
    InputRecorder over the lane's request stream feeding a segment WAL
    (journal/wal.py) at the confirmed frontier. Strictly host-side —
    the session is never touched, so journaling is observationally
    neutral to the match (the twin-parity suites run with it on)."""

    __slots__ = ("writer", "recorder", "path")

    def __init__(self, writer, recorder, path):
        self.writer = writer
        self.recorder = recorder
        self.path = path


class _Lane:
    """Host-side per-session state: device slot, staged rows, scheduling
    and liveness bookkeeping."""

    # a lane stages at most two rows per advance (misprediction rollback
    # + sparse-saving keepalive segments) and cannot advance again until
    # they dispatch, and a dispatched row is host-copied into the pooled
    # bucket staging before dispatch() returns — so a 4-deep rotating
    # row pool can never hand out a buffer still staged or in flight
    ROW_POOL = 4

    __slots__ = (
        "key", "session", "slot", "kind", "num_players", "local_handles",
        "max_prediction", "rows", "current_frame", "last_activity_ms",
        "pending_inputs", "queued_since_tick", "ticks_advanced",
        "throttled_ticks", "last_error", "failed", "row_pool", "row_flip",
        "starved", "confirmed_watermark",
        # invariant monitors (always-on, cheap)
        "max_confirmed_seen", "last_progress_seen", "last_progress_tick",
        "wedge_reported",
        # durable input journal (attach_journal installs; None = off)
        "journal",
        # SDC audit lane (maintained only when the host samples audits):
        # frame -> (played inputs u8[P,I], statuses i32[P]) — rollback
        # segments overwrite predicted values with the corrected truth,
        # so the record is always what the device actually played last —
        # plus the saved frames whose ring rows can anchor a replay and
        # each save's recorded (lazy) checksum, the at-rest reference
        # the audit sweep compares recomputed ring rows against
        "audit_inputs", "saved_frames", "audit_saved_checksums",
    )

    def __init__(self, key, session, slot, kind, num_players,
                 local_handles, max_prediction, now_ms, packed_len):
        self.key = key
        self.session = session
        self.slot = slot
        self.kind = kind  # "p2p" | "spectator"
        self.num_players = num_players
        self.local_handles = frozenset(local_handles)
        self.max_prediction = max_prediction
        self.rows: deque = deque()
        self.current_frame = 0
        self.last_activity_ms = now_ms
        self.pending_inputs: set = set()
        self.queued_since_tick: Optional[int] = None
        self.ticks_advanced = 0
        self.throttled_ticks = 0
        self.last_error: Optional[str] = None
        self.failed = False  # quarantined: stops advancing, app detaches
        # input starvation (the prediction-threshold gate blocked this
        # tick) + the fresh confirmed watermark the gate computed —
        # the speculative bubble-filling scheduler's draft keys
        self.starved = False
        self.journal: Optional[_JournalTap] = None
        self.confirmed_watermark: Optional[int] = None
        self.max_confirmed_seen: Optional[int] = None
        self.last_progress_seen = 0
        self.last_progress_tick = 0
        self.wedge_reported = False
        self.audit_inputs: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self.saved_frames: set = set()
        self.audit_saved_checksums: Dict[int, Any] = {}
        # pooled packed-row buffers (pack_tick_row_into targets): staging
        # a segment allocates nothing on the steady-state path
        self.row_pool = [
            np.empty((packed_len,), dtype=np.int32)
            for _ in range(self.ROW_POOL)
        ]
        self.row_flip = 0

    def next_row_buf(self) -> np.ndarray:
        self.row_flip = (self.row_flip + 1) % len(self.row_pool)
        return self.row_pool[self.row_flip]


class SessionHost:
    """Own N sessions, one shared device core; see the module docstring.

    Usage:
        host = SessionHost(game, max_prediction=8, num_players=4,
                           max_sessions=64, clock=clock)
        key = host.attach(session)            # raises HostFull past budget
        host.submit_input(key, handle, buf)   # per local player per tick
        events = host.tick()                  # pump + schedule + megabatch
        ...
        host.drain(checkpoint_path="host.npz")

    Every session the host admits must share the host's game config (the
    stacked worlds are one pytree): same model, same input_size, and a
    player count <= the host's `num_players` layout — absent players pad
    as DISCONNECTED, which both peers of a match do identically, so
    desync detection still agrees across hosts."""

    def __init__(self, game, *, max_prediction: int = 8,
                 num_players: int = 2, max_sessions: int = 16,
                 max_inflight_rows: Optional[int] = None,
                 clock: Optional[Clock] = None,
                 idle_timeout_ms: int = DEFAULT_IDLE_TIMEOUT_MS,
                 async_inflight: int = 4, warmup: bool = False,
                 depth_routing: bool = True, batched_pump: bool = True,
                 mesh=None, speculation: bool = False,
                 speculation_seed: int = 0, resident: bool = False,
                 resident_ticks: int = 16, sdc_audit_every: int = 0,
                 wedge_limit_ticks: int = 256,
                 drive_failure_limit: int = 3,
                 shed_after_stall_ticks: int = 256,
                 strict_invariants: bool = False,
                 journal_dir: Optional[str] = None,
                 journal_fsync_every: int = 0,
                 journal_segment_bytes: int = 1 << 18):
        """`max_inflight_rows`: the device-window budget — session tick
        rows admitted past the fence before ready sessions start queuing
        (default: 2 full megabatches' worth). `idle_timeout_ms`: sessions
        with no submitted input / advanced frame for this long are
        evicted (0 disables). `warmup=True` compiles every megabatch
        bucket (the full row x depth grid under depth routing) before
        the first attach. `depth_routing=True` groups ready sessions by
        rollback depth and dispatches one megabatch per occupied depth
        bucket — zero-rollback ticks ride a dedicated fast program —
        instead of dragging every row to the full window; False pins the
        single full-window megabatch (the parity suite's reference).
        `batched_pump=True` drains the WHOLE fleet's sockets through one
        pooled batched decode pass per host tick (network/pump.py) —
        one pass per message type over the union of every session's
        datagrams — instead of N per-message `poll_remote_clients`
        loops; False pins the legacy per-session pump (the parity
        suite's reference). `async_inflight` defaults to 4 megabatches
        (was 2): a wider fence keeps the steady-state tick from ever
        blocking on the oldest dispatch while the checksum ledger drains
        off the pump pass.

        `speculation=True` turns input starvation into useful device
        work: a lane the prediction gate blocks gets a width-1 draft of
        its near future (learned input model, counter-based draws)
        rolled out on device beside the confirmed megabatch work, and
        the arriving inputs verify against the draft per frame — a full
        prefix hit serves the whole tick via one adopt dispatch instead
        of a full-window resim, a misprediction truncates to the
        longest-correct prefix (the suffix resimulates inside the same
        adopt program), a total miss falls back to the normal rollback
        path. Bitwise-identical to a speculation=False twin in every
        arrival pattern (tests/test_speculation.py pins it); requires
        the game to declare statuses_contract='disconnect-only'.
        `speculation_seed` keys the drafts' counter-based draws.

        `mesh`: a device mesh with a `session` axis
        (parallel.mesh.make_session_mesh) puts the stacked session
        worlds on the mesh via ShardedMultiSessionDeviceCore — the
        megabatch GSPMD-partitions across chips, and the scheduler adds
        slot->shard AFFINITY: admission picks slots on the least-loaded
        shard and lane packing groups each megabatch's rows by shard, so
        the dispatch's gather/scatter stays mostly shard-local instead
        of all-to-all. Everything else (sessions, envs, migration,
        checkpoints — which stay canonical and restore across layouts)
        is unchanged, and the sharded host is bit-identical to a
        single-device twin fed the same traffic.

        `resident=True` is the DEVICE-RESIDENT SERVING LOOP: the host
        becomes feed-and-harvest only. Staged session rows stop
        dispatching one megabatch per host tick; instead they append to
        a donated device-resident input mailbox (tpu/mailbox.py — one
        batched scatter per host tick), and every `resident_ticks` host
        ticks ONE jitted `lax.while_loop` virtual-tick driver dispatch
        ticks the whole fleet through its staged rows — rollbacks
        resimulating in-loop, lanes at different fill depths walking
        their own watermarks — with checksums accumulating into
        device-side [K, S, W] output rings harvested lazily behind the
        async fence. Dispatch cadence drops from >= 1 megabatch per host
        tick to ~1/K driver dispatches per tick. A lane outrunning K
        degrades to an extra dispatch (ggrs_mailbox_overflow_total),
        never a dropped input; adopts, draft launches, slot lifecycle,
        migration export, checkpoint and drain all drain the mailbox
        back to canonical form first, so every export/import,
        kill→restore and sharded↔unsharded contract survives unchanged.
        Bit-identical to a resident=False twin fed the same traffic
        (tests/test_resident_loop.py pins state, ring bytes and checksum
        histories); the dispatch-per-tick path is kept as that parity
        twin.

        DEVICE FAULT DOMAINS (docs/DESIGN.md "Device fault domains"):
        `sdc_audit_every=N` (0 = off) samples the SDC AUDIT LANE every N
        host ticks — each eligible lane's live world is double-computed
        from its last ring anchor through the full-window parity
        program and compared checksum-for-checksum; a mismatch
        quarantines the slot (typed SlotPoisoned + forensics bundle)
        within the sampling bound. A dispatch/drive raise
        (DeviceDispatchFailed — the fault seam's simulated XLA runtime
        failure, or a real one) retries once as a transient, then
        quarantines the culprit slots and re-dispatches survivors
        bit-exactly; `drive_failure_limit` LIFETIME resident-drive
        failures DEGRADE the host to its dispatch-per-tick twin instead
        of crashing (bit-identical, slower — a device whose runtime
        keeps failing is hardware-suspect, so the fallback is sticky). `shed_after_stall_ticks`
        of a wedged fence (ready queue pinned at a full device window)
        sheds admission — attach raises HostFull — until the stall
        clears. `wedge_limit_ticks` bounds the always-on invariant
        monitors (lane progress, confirmed-watermark monotonicity,
        mailbox accounting), which record typed InvariantViolations
        with forensics (`strict_invariants=True` raises them
        instead).

        DURABLE INPUT JOURNAL (docs/DESIGN.md "Durable recovery"):
        `journal_dir` journals every p2p lane's CONFIRMED input rows to
        a crash-consistent segment WAL under `journal_dir/lane<key>`
        (per-lane `attach_journal` gives a caller-chosen path — the
        fleet agent journals per match island). The tap is a pure
        observer riding the pump: each host tick drains the lane's
        confirmed frontier from an InputRecorder into the journal —
        identical traffic on both serving arms, since the staged
        request stream is arm-independent by the deterministic-publish
        contract. `journal_fsync_every` bounds power-loss exposure to N
        appends (0 = fsync at rotation/checkpoint/drain only; SIGKILL
        never loses acknowledged appends either way). Journaling is a
        durability feature, never a liveness dependency: a disk that
        refuses an append (ENOSPC) degrades THAT lane to unjournaled
        with a typed JournalStalled + invariant trip — the host keeps
        serving."""
        from ..network.pump import WirePump, host_tax_histogram
        from ..tpu.backend import MultiSessionDeviceCore

        if speculation:
            # the adopt route replays drafted frames rolled out with
            # all-CONFIRMED statuses — only correct for games whose step
            # reads statuses solely to substitute DISCONNECTED players'
            # inputs (the same contract the single-session beam enforces)
            contract = getattr(game, "statuses_contract", None)
            if contract != "disconnect-only":
                raise ConfigError(
                    "host speculation adopts drafts rolled out with "
                    "all-CONFIRMED statuses; declare statuses_contract = "
                    "'disconnect-only' on the game class to opt in "
                    f"(got {contract!r} on {type(game).__name__})"
                )
        self.mesh = mesh
        self.device = MultiSessionDeviceCore.create(
            game, max_prediction, num_players, max_sessions,
            async_inflight=async_inflight, depth_routing=depth_routing,
            mesh=mesh, speculation=speculation,
            sdc_audit=sdc_audit_every > 0,
        )
        self.depth_routing = depth_routing
        self.game = game
        self.max_sessions = max_sessions
        self.num_players = num_players
        self.max_prediction = max_prediction
        self.max_inflight_rows = (
            max_inflight_rows
            if max_inflight_rows is not None
            else 2 * max_sessions
        )
        if self.max_inflight_rows < 1:
            raise InvalidRequest(
                f"max_inflight_rows must be >= 1 "
                f"(got {self.max_inflight_rows})"
            )
        self.clock = clock or Clock()
        self.idle_timeout_ms = idle_timeout_ms
        self._lanes: Dict[Any, _Lane] = {}
        self._envs: List[Any] = []  # attached RollbackEnv blocks
        self._free_slots = list(range(max_sessions - 1, -1, -1))
        # keys with staged rows, ARRIVAL order (the backpressure queue)
        self._ready: deque = deque()
        # per-pass scratch reused across megabatch passes — the dispatch
        # loop allocates nothing per pass (ALLOC001 discipline)
        self._picked_scratch: List[Tuple[_Lane, _StagedRow]] = []
        self._adopts_scratch: List[Tuple[_Lane, _StagedRow]] = []
        self._groups_scratch: Dict[Any, List[Tuple[_Lane, _StagedRow]]] = {}
        self._draining = False
        self._drained = False
        self._tick_index = 0
        self._next_key = 0
        # lifetime stats (host section of telemetry snapshots)
        self.sessions_admitted = 0
        self.sessions_rejected = 0
        self.sessions_evicted = 0
        self.sessions_gced = 0
        self.desyncs_observed = 0
        # plain queue-wait samples (ticks a session's staged rows waited
        # before dispatch), always maintained so chaos harnesses can read
        # a p99 without telemetry; bounded so a long soak can't grow it
        self.queue_waits: List[int] = []
        _reg = GLOBAL_TELEMETRY.registry
        self._m_active = _reg.gauge(
            "ggrs_host_sessions_active", "sessions currently attached"
        )
        self._m_evicted = _reg.counter(
            "ggrs_host_sessions_evicted_total",
            "sessions evicted for idleness or disconnect GC",
        )
        self._m_rejected = _reg.counter(
            "ggrs_host_sessions_rejected_total",
            "attach attempts rejected by admission control (HostFull)",
        )
        self._m_queue_depth = _reg.gauge(
            "ggrs_host_queue_depth",
            "ready sessions waiting on the device-window budget",
        )
        self._m_queue_wait = _reg.histogram(
            "ggrs_host_queue_wait_ticks",
            "host ticks a session's staged rows waited before dispatch",
            buckets=SESSION_COUNT_BUCKETS,
        )
        # device fault domains: quarantine machinery, the sampled SDC
        # audit lane, always-on invariant monitors and the degradation
        # ladder (docs/DESIGN.md "Device fault domains")
        self.fault_seam = None  # serve/faults.py FaultInjector installs
        self._audit_every = sdc_audit_every
        self.wedge_limit_ticks = wedge_limit_ticks
        self.drive_failure_limit = drive_failure_limit
        self.shed_after_stall_ticks = shed_after_stall_ticks
        self.strict_invariants = strict_invariants
        # durable input journal (docs/DESIGN.md "Durable recovery")
        self._journal_dir = journal_dir
        self._journal_fsync_every = journal_fsync_every
        self._journal_segment_bytes = journal_segment_bytes
        self.journal_lanes_degraded = 0
        if journal_dir is not None:
            # instruments exist from construction (the exporter
            # convention), and the directory exists before the first
            # lane attaches mid-tick
            from ..journal import metrics as _jm

            _jm.journal_rows_total()
            _jm.journal_bytes_total()
            _jm.journal_segments_total()
            _jm.journal_fsyncs_total()
            _jm.journal_stalls_total()
            _jm.journal_corrupt_segments_total()
            os.makedirs(journal_dir, exist_ok=True)
        self._quarantines: List[SlotPoisoned] = []
        self.quarantines_total = 0
        self.device_faults = 0
        self.harvest_timeouts = 0
        self.invariant_trips: List[InvariantViolation] = []
        self._pending_audits: List[Tuple[Any, List[Tuple]]] = []
        self.audits_sampled = 0
        self.audit_mismatches = 0
        self._resident_degraded = False
        self._drive_failures = 0
        self._shed_admission = False
        self._stall_ticks = 0
        self.degrades = 0
        self._m_quarantines = _reg.counter(
            "ggrs_slot_quarantines_total",
            "session slots quarantined out of the shared device stack "
            "(typed SlotPoisoned + forensics bundle each)",
            ("reason",),
        )
        self._m_sdc_audits = _reg.counter(
            "ggrs_sdc_audits_total",
            "lanes double-computed by the sampled SDC audit lane",
        )
        self._m_sdc_mismatches = _reg.counter(
            "ggrs_sdc_mismatches_total",
            "SDC audit mismatches (silent corruption caught: live world "
            "vs full-window reference replay from the ring anchor)",
        )
        self._m_degraded = _reg.counter(
            "ggrs_degraded_mode_total",
            "degradation-ladder steps taken (resident loop falling back "
            "to dispatch-per-tick, admission shed under a fence stall)",
            ("mode",),
        )
        self._m_invariants = _reg.counter(
            "ggrs_invariant_trips_total",
            "always-on invariant monitor trips (typed InvariantViolation "
            "+ forensics bundle each)",
            ("invariant",),
        )
        # fleet-wide batched wire pump + host-tax attribution (the pump
        # phase's own child is observed inside WirePump.pump; the shared
        # instrument is defined once, in network/pump.py)
        self.batched_pump = batched_pump
        self._pump = WirePump()
        self._m_tax_parse = host_tax_histogram().labels("parse")
        self._m_tax_drain = host_tax_histogram().labels("drain")
        # speculative bubble-filling (serve/speculation.py): when the
        # prediction gate starves a lane, the scheduler drafts its near
        # future from the lane's learned input model into the megabatch
        # and serves the arrival rollback from the draft (verify-and-
        # adopt) — bitwise-identical to a never-speculating twin in
        # every arrival pattern. Off by default; the parity suite's
        # reference arm is a speculation=False host.
        self.speculation = speculation
        if speculation:
            from .speculation import SpeculationPlanner

            core = self.device.core
            self._spec = SpeculationPlanner(
                num_players=num_players,
                input_size=game.input_size,
                window=core.window,
                ring_len=core.ring_len,
                max_prediction=max_prediction,
                seed=speculation_seed,
            )
        else:
            self._spec = None
        # pooled draft-row buffers, grown to device capacity on first use
        self._draft_row_pool: List[np.ndarray] = []
        # device-resident serving loop: attach the input mailbox BEFORE
        # warmup so the driver variants compile with the megabatch grid
        self.resident = resident
        self.resident_ticks = resident_ticks
        self._mbox_ticks = 0  # host ticks since the last driver dispatch
        # effective drive cadence: starts at resident_ticks and tightens
        # as lanes with desync detection attach (_commit_lane) — a drive
        # must land BEFORE each lane's interval-forced checksum flush, or
        # the flush forces a synchronous mid-advance drive and the
        # harvest stops overlapping host work
        self._resident_cadence = resident_ticks
        if resident:
            if resident_ticks < 1:
                raise InvalidRequest(
                    f"resident_ticks must be >= 1 (got {resident_ticks})"
                )
            self.device.attach_mailbox(resident_ticks)
        if warmup:
            self.device.warmup()

    # ------------------------------------------------------------------
    # admission / lifecycle
    # ------------------------------------------------------------------

    def _validate_session(self, session):
        """The admission checks attach() and adopt() share: session type,
        player-layout fit, input size, prediction window. Validates
        EVERYTHING the staging path will assume, so an incompatible
        session is rejected here with a clear error instead of crashing
        tick() for the whole fleet later. Returns the lane parameters
        (kind, n_players, local_handles, max_prediction)."""
        from ..sessions.p2p_session import P2PSession
        from ..sessions.spectator_session import SpectatorSession

        if isinstance(session, P2PSession):
            kind = "p2p"
        elif isinstance(session, SpectatorSession):
            kind = "spectator"
        else:
            raise InvalidRequest(
                "only Python P2PSession/SpectatorSession can be hosted "
                f"(got {type(session).__name__}; native sessions drive "
                "their own core)"
            )
        n_players = session.num_players
        if n_players > self.num_players:
            raise InvalidRequest(
                f"session has {n_players} players; host layout is "
                f"{self.num_players}"
            )
        if session.input_size != self.game.input_size:
            raise InvalidRequest(
                f"session input_size {session.input_size} != game "
                f"input_size {self.game.input_size}"
            )
        if kind == "p2p":
            if session.max_prediction > self.max_prediction:
                raise InvalidRequest(
                    f"session max_prediction {session.max_prediction} "
                    f"exceeds the host window ({self.max_prediction})"
                )
            local_handles = session.local_player_handles()
            max_prediction = session.max_prediction
        else:
            local_handles = []
            max_prediction = self.max_prediction
        return kind, n_players, local_handles, max_prediction

    def _claim_admission(self, key: Any, slot: Optional[int]):
        """Admission-control gate shared by attach() and adopt(): raises
        HostFull (draining / out of slots), resolves the key, and claims
        a device slot — the requested one for a checkpoint-restore
        re-adoption, else the free-list head."""
        if self._draining:
            self._reject()
            raise HostFull("host is draining: not admitting sessions")
        if self._shed_admission:
            # degradation ladder: a wedged fence sheds new admissions
            # BEFORE the backlog wedges the hosted fleet
            self._reject()
            raise HostFull(
                "host is shedding admission: device fence stalled for "
                f"{self._stall_ticks} ticks at a full inflight window"
            )
        if not self._free_slots:
            self._reject()
            raise HostFull(
                f"host is at max_sessions={self.max_sessions}"
            )
        if key is None:
            key = self._next_key
            self._next_key += 1
        if key in self._lanes:
            raise InvalidRequest(f"host key {key!r} already in use")
        if slot is None:
            slot = self._pick_free_slot()
        else:
            # restore-from-checkpoint re-adoption: the stacked worlds
            # already hold this session AT ITS OLD SLOT
            try:
                self._free_slots.remove(slot)
            except ValueError:
                raise InvalidRequest(
                    f"device slot {slot} is not free on this host"
                ) from None
        return key, slot

    def _pick_free_slot(self) -> int:
        """Admission slot choice. Single device: the free-list head. On
        a session mesh: the free slot whose shard carries the FEWEST
        live worlds (lanes + attached env blocks; ties to the lowest
        shard) — slot->shard affinity's admission half, keeping the
        fleet spread so each megabatch's per-shard row groups stay
        balanced (the `ggrs_shard_imbalance` histogram is the health
        surface)."""
        if self.mesh is None:
            return self._free_slots.pop()
        return self._pick_affine_slot(self._shard_load())

    def _shard_load(self) -> List[int]:
        """Live worlds per shard (lanes + attached env blocks)."""
        dev = self.device
        load = [0] * dev.session_shards
        for lane in self._lanes.values():
            load[dev.shard_of(lane.slot)] += 1
        for env in self._envs:
            for s in env.slots:
                load[dev.shard_of(s)] += 1
        return load

    def _pick_affine_slot(self, load: List[int]) -> int:
        dev = self.device
        best = min(
            range(len(self._free_slots)),
            key=lambda i: (
                load[dev.shard_of(self._free_slots[i])],
                dev.shard_of(self._free_slots[i]),
                self._free_slots[i],  # lowest slot within a shard: a
                # fresh sharded host assigns the same slots as its
                # single-device twin (round-robin layout => ascending
                # slot order IS shard-spread order), which is what lets
                # parity tests compare canonical stacks slot-for-slot
            ),
        )
        return self._free_slots.pop(best)

    def _pick_free_slots_block(self, n: int) -> List[int]:
        """Admission's block half: `n` slots for an env block. On a mesh
        each pick is accounted as in-flight load before the next, so the
        block itself spreads over the least-loaded shards instead of
        stacking on whichever shard was lightest at entry. On a fresh
        host this yields 0..n-1 exactly like the single-device pop order
        (round-robin layout), keeping env parity tests slot-for-slot."""
        if self.mesh is None:
            return [self._free_slots.pop() for _ in range(n)]
        load = self._shard_load()
        slots = []
        for _ in range(n):
            s = self._pick_affine_slot(load)
            load[self.device.shard_of(s)] += 1
            slots.append(s)
        return slots

    def _commit_lane(self, session, key: Any, slot: int, kind: str,
                     n_players: int, local_handles, max_prediction: int,
                     current_frame: int) -> _Lane:
        if not self.batched_pump:
            # the legacy-pump host is the parity reference: its sessions
            # must pump per-message too, or the "pre-batched" arm would
            # still ride the batched single-session pump underneath
            session.batched_pump = False
        if kind == "p2p" and self.resident:
            # keep the drive cadence two ticks inside the lane's desync
            # interval: the interval-forced flush then always finds its
            # values already driven and pump-harvested, instead of
            # forcing a synchronous drive on the advance path
            det = getattr(session, "desync_detection", None)
            if det is not None and getattr(det, "enabled", False):
                self._resident_cadence = max(
                    1,
                    min(self._resident_cadence, det.interval - 2),
                )
        if kind == "p2p":
            # hosted lanes publish checksum reports at the interval-
            # forced flush ONLY (resolution still rides the pump pass):
            # publish timing is then a pure function of the frame
            # counter, not of when device values became host-ready — a
            # resident host's lazier harvest cadence would otherwise
            # shift report datagrams on the seeded wire and fork the
            # fault stream away from its dispatch-per-tick twin's
            session.checksum_publish = "interval"
        lane = _Lane(
            key, session, slot, kind, n_players, local_handles,
            max_prediction, self.clock.now_ms(),
            self.device.core._packed_len,
        )
        lane.current_frame = current_frame
        # the wedge monitor's baseline is the ATTACH tick: a session
        # admitted late into a long-lived host starts its progress
        # clock here, not at host tick 0
        lane.last_progress_tick = self._tick_index
        self._lanes[key] = lane
        self.sessions_admitted += 1
        if self._spec is not None and kind == "p2p":
            self._spec.attach(key, num_players=n_players)
        if GLOBAL_TELEMETRY.enabled:
            self._m_active.set(len(self._lanes))
        return lane

    def attach(self, session, *, key: Any = None) -> Any:
        """Admit a session; returns its host key. Raises HostFull when the
        host is at max_sessions or draining, InvalidRequest when the
        session is incompatible with the host layout or already hosted."""
        key, slot = self._claim_admission(key, None)
        try:
            kind, n_players, local_handles, max_prediction = (
                self._validate_session(session)
            )
            # attach() admits only FRESH sessions: the lane's frame
            # bookkeeping starts at 0 (mid-match sessions arrive through
            # adopt(), with their device slot riding a migration ticket)
            if kind == "p2p" and session.sync_layer.current_frame != 0:
                raise InvalidRequest(
                    "host requires a fresh session (frame 0); this one is "
                    f"at frame {session.sync_layer.current_frame} "
                    "(mid-match sessions migrate via serve.migrate)"
                )
            if kind == "spectator" and session.current_frame >= 0:
                raise InvalidRequest(
                    "host requires a fresh spectator session; this one "
                    f"already advanced to frame {session.current_frame}"
                )
            # the hook raises on double-attach BEFORE we commit the slot
            session.on_host_attach(self, key)
        except BaseException:
            self._free_slots.append(slot)
            raise
        self.device.reset_slot(slot)
        lane = self._commit_lane(
            session, key, slot, kind, n_players, local_handles,
            max_prediction, 0,
        )
        if self._journal_dir is not None and lane.kind == "p2p":
            self.attach_journal(key)
        if GLOBAL_TELEMETRY.enabled:
            GLOBAL_TELEMETRY.record(
                "host_session_attached", key=str(key), slot=slot
            )
        return key

    def adopt(self, session, *, current_frame: int, slot_state=None,
              pending_inputs=(), key: Any = None,
              slot: Optional[int] = None) -> Any:
        """Admit a MID-MATCH session — the receiving half of a live
        migration or a kill→restore re-adoption (ggrs_tpu/serve/migrate).
        `slot_state` is an `export_slot()` payload imported into the
        claimed slot (validated shape-by-shape, MigrationIncompatible on
        any mismatch); `slot_state=None` claims `slot` with the worlds
        already in place (the restore-from-checkpoint path, where
        load_stacked put every slot's bytes back at once). The lane
        resumes at `current_frame` with `pending_inputs` re-armed, so the
        first tick after adoption advances exactly where the source host
        left off."""
        key, claimed = self._claim_admission(key, slot)
        try:
            kind, n_players, local_handles, max_prediction = (
                self._validate_session(session)
            )
            if kind == "p2p" and (
                session.sync_layer.current_frame != current_frame
            ):
                raise InvalidRequest(
                    f"adopt() frame {current_frame} disagrees with the "
                    f"session's own frame "
                    f"{session.sync_layer.current_frame}"
                )
            session.on_host_attach(self, key)
            try:
                if slot_state is not None:
                    self.device.import_slot(claimed, slot_state)
            except BaseException:
                session.on_host_detach()
                raise
        except BaseException:
            self._free_slots.append(claimed)
            raise
        lane = self._commit_lane(
            session, key, claimed, kind, n_players, local_handles,
            max_prediction, current_frame,
        )
        lane.pending_inputs = set(pending_inputs)
        if self._journal_dir is not None and lane.kind == "p2p":
            self.attach_journal(key)
        if GLOBAL_TELEMETRY.enabled:
            GLOBAL_TELEMETRY.record(
                "host_session_adopted", key=str(key), slot=claimed,
                frame=current_frame,
            )
        return key

    def _reject(self) -> None:
        self.sessions_rejected += 1
        if GLOBAL_TELEMETRY.enabled:
            self._m_rejected.inc()

    def detach(self, key: Any) -> None:
        """Remove a session and recycle its device slot. Staged rows that
        never dispatched are dropped with it (the slot is reset, so no
        other session can observe the partial state)."""
        lane = self._lanes.pop(key, None)
        if lane is None:
            raise InvalidRequest(f"unknown host key {key!r}")
        if lane.journal is not None:
            # final frontier drain + fsync: a detach (migration export,
            # eviction, quarantine) must not strand confirmed rows in
            # the recorder
            try:
                self._pump_journal_lane(lane)
                if lane.journal is not None:
                    lane.journal.writer.close()
            except (JournalError, OSError):
                pass
            lane.journal = None
        if lane.queued_since_tick is not None or lane.rows:
            try:
                self._ready.remove(key)
            except ValueError:
                pass
        lane.session.on_host_detach()
        if self._spec is not None:
            self._spec.drop(key)
        self._free_slots.append(lane.slot)
        if GLOBAL_TELEMETRY.enabled:
            self._m_active.set(len(self._lanes))
            GLOBAL_TELEMETRY.record(
                "host_session_detached", key=str(key), slot=lane.slot
            )

    def attach_env(self, num_envs: int, **env_kw):
        """MIXED-TRAFFIC MODE: reserve `num_envs` device slots for a
        batched RL environment sharing this host's megabatch. The
        returned `RollbackEnv` stages its step/snapshot/restore rows
        with the host, and every `env.step()` runs ONE host tick — env
        rows join the ready sessions' depth groups, so training and
        interactive traffic dispatch as one program per group on one
        device core. Raises HostFull when the slot budget (shared with
        session admission) cannot cover the block."""
        from ..env.rollback_env import RollbackEnv

        if self._draining:
            self._reject()
            raise HostFull("host is draining: not admitting env blocks")
        if self._shed_admission:
            self._reject()
            raise HostFull(
                "host is shedding admission: device fence stalled"
            )
        if num_envs < 1 or num_envs > len(self._free_slots):
            self._reject()
            raise HostFull(
                f"env block of {num_envs} exceeds the {len(self._free_slots)}"
                " free session slots"
            )
        slots = self._pick_free_slots_block(num_envs)
        try:
            env = RollbackEnv(
                self.game,
                num_envs=num_envs,
                max_prediction=self.max_prediction,
                device=self.device,
                slots=slots,
                host=self,
                **env_kw,
            )
        except BaseException:
            # a rejected construction (bad knob combination) must not
            # leak the popped slots out of session admission
            self._free_slots.extend(slots)
            raise
        self._envs.append(env)
        if GLOBAL_TELEMETRY.enabled:
            GLOBAL_TELEMETRY.record(
                "host_env_attached", num_envs=num_envs,
                slots=f"{min(slots)}..{max(slots)}",
            )
        return env

    def detach_env(self, env) -> None:
        """Release an env block's device slots back to session admission."""
        self._envs.remove(env)
        self._free_slots.extend(env.slots)

    def session(self, key: Any):
        return self._lanes[key].session

    def keys(self) -> List[Any]:
        return list(self._lanes)

    @property
    def active_sessions(self) -> int:
        return len(self._lanes)

    @property
    def queue_depth(self) -> int:
        """Ready sessions still waiting on the device-window budget."""
        return len(self._ready)

    # ------------------------------------------------------------------
    # per-tick driving
    # ------------------------------------------------------------------

    def submit_input(self, key: Any, handle: PlayerHandle, buf: bytes) -> None:
        """Queue one local player's input for the session's next advance;
        the session advances on the next host tick once every local
        handle has input."""
        lane = self._lanes[key]
        lane.session.add_local_input(handle, buf)
        lane.pending_inputs.add(handle)
        lane.last_activity_ms = self.clock.now_ms()

    def tick(self) -> Dict[Any, List[Event]]:
        """One host cycle: pump every session's sockets, advance each
        ready session, coalesce their tick rows into megabatches under
        the device-window budget, then run eviction/GC. Returns the
        events each session surfaced this tick, keyed by host key."""
        with GLOBAL_TRACER.span("host/tick", absolute=True):
            out = self._tick_impl()
        san = active_alloc_sanitizer()
        if san is not None:
            # outside the span so the probe charges this tick's churn,
            # not the tracer's bookkeeping, to the allocation budget
            san.note_tick()
        return out

    def _tick_impl(self) -> Dict[Any, List[Event]]:
        self._tick_index += 1
        events: Dict[Any, List[Event]] = {}
        tel = GLOBAL_TELEMETRY

        # 1. pump: every session's sockets drain every host tick, even for
        # sessions that won't advance — protocol liveness (sync handshake,
        # quality reports, disconnect timers) must not depend on input.
        # Batched: ONE pooled decode pass over the union of the fleet's
        # datagrams (network/pump.py), per-session errors quarantined;
        # legacy: N per-session poll loops (the parity reference).
        with GLOBAL_TRACER.span("host/pump", absolute=True):
            lanes = list(self._lanes.values())
            if self.batched_pump:
                errors = self._pump.pump(
                    [lane.session for lane in lanes], isolate=True
                )
                for sess, exc in errors:
                    for lane in lanes:
                        if lane.session is sess:
                            lane.last_error = type(exc).__name__
                            break
            else:
                for lane in lanes:
                    try:
                        lane.session.poll_remote_clients()
                    except GGRSError as exc:  # keep serving the rest
                        lane.last_error = type(exc).__name__
            for lane in lanes:
                evs = lane.session.events()
                if evs:
                    events[lane.key] = evs
                    lane.last_activity_ms = max(
                        lane.last_activity_ms, self.clock.now_ms()
                    )
                    for ev in evs:
                        if type(ev).__name__ == "DesyncDetected":
                            self.desyncs_observed += 1

        # 1b. drain pass: retire ready fence entries and resolve every
        # host-ready checksum batch OFF the tick path — with the batched
        # checksum pump in the sessions, the steady-state tick never
        # blocks on a device->host transfer (drain_blocked_ticks == 0).
        # A HarvestTimeout (fault seam / real readback stall) is
        # transient by contract: the values still exist on device, so
        # this tick's drain is skipped and the next pass resolves them.
        t_drain = _time.perf_counter() if tel.enabled else 0.0
        try:
            if self.fault_seam is not None:
                self.fault_seam.before_harvest("drain")
            self.device.ledger.drain_ready()
            self.device.poll_retired()
        except HarvestTimeout:
            self.harvest_timeouts += 1
            if tel.enabled:
                tel.record("harvest_timeout", op="drain")
        self._resolve_audits()
        if tel.enabled:
            self._m_tax_drain.observe(
                (_time.perf_counter() - t_drain) * 1000.0
            )

        # 2. advance ready sessions and stage their rows
        t_parse = _time.perf_counter() if tel.enabled else 0.0
        with GLOBAL_TRACER.span("host/advance", absolute=True):
            for lane in list(self._lanes.values()):
                if not self._lane_ready(lane):
                    continue
                try:
                    requests = lane.session.advance_frame()
                except PredictionThreshold:
                    # spectator whose host input hasn't arrived: benign
                    lane.throttled_ticks += 1
                    continue
                except GGRSError as exc:
                    lane.last_error = type(exc).__name__
                    if GLOBAL_TELEMETRY.enabled:
                        GLOBAL_TELEMETRY.record(
                            "host_session_error",
                            key=str(lane.key),
                            error=type(exc).__name__,
                        )
                    continue
                lane.pending_inputs.clear()
                lane.ticks_advanced += 1
                lane.last_activity_ms = self.clock.now_ms()
                if lane.journal is not None:
                    # pure observer: the tap tracks the same ordered
                    # request stream the backend consumes, BEFORE any
                    # staging can fail — last-write-wins rollback
                    # corrections included
                    lane.journal.recorder.observe(requests)
                try:
                    self._stage(lane, requests)
                except Exception as exc:
                    # fleet isolation: a session whose request stream the
                    # parser rejects is QUARANTINED (its device slot may
                    # have missed a tick, so it must not keep advancing),
                    # never a crash of the whole host tick. Rows staged
                    # before the failing segment are dropped too — they
                    # will never be followed by their successors, and
                    # lingering rows would pin the lane past eviction/GC
                    # (leaking its slot until a manual detach)
                    lane.rows.clear()
                    lane.failed = True
                    lane.last_error = type(exc).__name__
                    if GLOBAL_TELEMETRY.enabled:
                        GLOBAL_TELEMETRY.record(
                            "host_session_error",
                            key=str(lane.key),
                            error=type(exc).__name__,
                            stage="parse",
                        )
                    continue
                if self.resident_active:
                    # feed-and-harvest: rows move straight into the
                    # mailbox fill cycle instead of the dispatch queue
                    self._stage_resident(lane)
                if (
                    not self.resident_active
                    and lane.rows
                    and not lane.failed
                    and lane.queued_since_tick is None
                ):
                    # dispatch-per-tick scheduling — also the DEGRADED
                    # resident host's path (and _stage_resident hands
                    # rows back here when a drive failure degrades the
                    # host mid-stage)
                    lane.queued_since_tick = self._tick_index
                    self._ready.append(lane.key)
        if tel.enabled:
            self._m_tax_parse.observe(
                (_time.perf_counter() - t_parse) * 1000.0
            )

        # 2b. durable journal: drain each journaled lane's confirmed
        # frontier into its segment WAL (a host-side pure observer —
        # rows below the frontier are final by the protocol, so the
        # journal never records a value a rollback could still change)
        self._pump_journals()

        # 3. dispatch megabatches under the device-window budget (env
        # blocks still dispatch synchronously; in resident mode session
        # lanes never enter the ready queue, so this is env-only there)
        self._pump_device()
        if self.resident_active:
            self._resident_pump()

        # 3b. speculative bubble-filling: draft the input-starved lanes'
        # futures into the device (one vmapped rollout batch riding the
        # same bucket grid) so their empty megabatch rows become standing
        # drafts the arrival tick can adopt. AFTER the confirmed
        # dispatches and capped by the budget they left over: draft work
        # fills genuinely idle device window, it never crowds a ready
        # session's row out of this tick
        if self._spec is not None and not self._draining:
            self._launch_drafts()

        # 3c. the sampled SDC audit lane: double-compute eligible lanes
        # from their ring anchors through the full-window reference
        # program, resolved lazily by the next drain passes
        if self._audit_every:
            self._maybe_audit()

        # 3d. degradation ladder, fence-stall arm: a ready queue pinned
        # at a full device window for `shed_after_stall_ticks` sheds
        # admission until the stall clears
        if self._ready and self.device.inflight_rows >= self.max_inflight_rows:
            self._stall_ticks += 1
            if (
                self.shed_after_stall_ticks
                and not self._shed_admission
                and self._stall_ticks >= self.shed_after_stall_ticks
            ):
                self._shed_admission = True
                self.degrades += 1
                if tel.enabled:
                    self._m_degraded.labels("shed_admission").inc()
                    tel.record(
                        "host_degraded", mode="shed_admission",
                        stall_ticks=self._stall_ticks,
                    )
        else:
            self._stall_ticks = 0
            if self._shed_admission:
                self._shed_admission = False
                if tel.enabled:
                    tel.record("host_admission_restored")

        # 3e. always-on invariant monitors (cheap: a handful of integer
        # compares per lane)
        self._check_invariants()

        # 4. lifecycle: disconnect GC, then idle eviction
        self._run_gc(events)
        return events

    @property
    def resident_active(self) -> bool:
        """True while the resident loop is the serving path — False on
        dispatch-per-tick hosts AND on a resident host the degradation
        ladder dropped back to its dispatch-per-tick twin."""
        return self.resident and not self._resident_degraded

    def _stage_resident(self, lane: _Lane) -> None:
        """Move a lane's freshly parsed rows into the device mailbox's
        fill cycle (the resident twin of queueing for _pump_device):
        saves bind lazy checksums against the cycle's future batch at
        their [K, S, W] harvest index, so nothing blocks. Adopt rows —
        a standing speculative draft matched this segment — force a
        driver dispatch first (the lane's earlier rows must land before
        the adopt serves its prefix), then dispatch through adopt_slot
        exactly as the twin does.

        A DeviceDispatchFailed from the forced drive inside staging runs
        the recovery ladder (_recover_drive_failure) and retries the
        row; if the ladder quarantined THIS lane its rows are gone, and
        if it degraded the host the remaining rows fall through to the
        caller's queue path."""
        SnapshotRef, _LazyChecksum = _backend_refs()
        dev = self.device
        ring_len = dev.core.ring_len
        while lane.rows and not lane.failed:
            if not self.resident_active:
                return  # degraded mid-stage: caller queues the rest
            staged = lane.rows[0]
            if staged.adopt is not None:
                if self._drive_resident() is _DRIVE_FAILED:
                    continue  # ladder ran; re-check lane/mode and retry
                if lane.failed or not self.resident_active:
                    continue
                draft_batch, packed = staged.adopt
                batch = dev.adopt_slot(lane.slot, draft_batch, packed)
                base = 0
            else:
                try:
                    batch, base = dev.stage_mailbox_row(
                        lane.slot, staged.row,
                        last_active=staged.last_active, fast=staged.fast,
                    )
                except DeviceDispatchFailed as exc:
                    # the row was NOT staged (the raise fires before any
                    # mailbox state changes): recover, then retry it
                    self._recover_drive_failure(exc)
                    continue
            lane.rows.popleft()
            for slot_i, save in staged.saves:
                lazy = _LazyChecksum(batch, base + slot_i)
                save.cell.save_lazy(
                    save.frame,
                    SnapshotRef(save.frame, save.frame % ring_len),
                    lazy,
                )
                if self._audit_every and lane.kind == "p2p":
                    lane.audit_saved_checksums[save.frame] = lazy

    def _resident_pump(self) -> None:
        """The resident scheduler's per-tick tail: land this tick's
        staged rows on the device in ONE batched mailbox transfer, then
        decide whether this tick drives. Drives fire every
        `resident_ticks` host ticks, or early when any lane is within
        two rows of the mailbox depth — the early drive keeps a
        double-row tick (misprediction rollback + keepalive segment)
        from ever overflowing in steady state, so
        ggrs_mailbox_overflow_total stays a true anomaly counter."""
        dev = self.device
        mbox = dev.mailbox
        dev.commit_mailbox()
        if not mbox.pending_rows:
            self._mbox_ticks = 0
            return
        self._mbox_ticks += 1
        if (
            self._mbox_ticks >= self._resident_cadence
            or mbox.max_fill() >= mbox.depth - 2
        ):
            self._drive_resident()
            self._mbox_ticks = 0

    # ------------------------------------------------------------------
    # device-fault recovery ladder (docs/DESIGN.md "Device fault
    # domains"): transient retry -> culprit quarantine -> degrade to the
    # dispatch-per-tick twin. Survivors keep ticking bit-exactly at
    # every rung (retries re-execute identical rows; quarantined lanes'
    # pending mailbox rows are masked off before the next drive; the
    # degraded twin is the parity reference by construction).
    # ------------------------------------------------------------------

    def _on_device_fault(self, exc: DeviceDispatchFailed) -> None:
        self.device_faults += 1
        if GLOBAL_TELEMETRY.enabled:
            GLOBAL_TELEMETRY.record(
                "device_dispatch_failed", op=exc.op,
                slots=list(exc.slots), injected=exc.injected,
            )

    def _drive_resident(self):
        """drive_mailbox behind the recovery ladder. Returns the drive's
        checksum batch (None for an empty mailbox), or _DRIVE_FAILED
        after a raise was contained — by then the ladder has retried,
        quarantined culprits and/or degraded, and the caller re-checks
        its lane/mode state and tries again."""
        try:
            return self.device.drive_mailbox()
        except DeviceDispatchFailed as exc:
            self._recover_drive_failure(exc)
            return _DRIVE_FAILED

    def _recover_drive_failure(self, exc: DeviceDispatchFailed) -> None:
        """A resident drive raised (worlds untouched by contract): retry
        once as a transient, then quarantine the culprit slots the
        failure names and drive the survivors; `drive_failure_limit`
        lifetime failures degrade the host to its dispatch-per-tick
        twin. An unattributed persistent failure re-raises — the whole
        device is suspect, and pretending otherwise would serve
        corrupt frames."""
        self._on_device_fault(exc)
        self._drive_failures += 1
        for attempt in (0, 1):
            try:
                self.device.drive_mailbox()
                break
            except DeviceDispatchFailed as exc2:
                self._on_device_fault(exc2)
                self._drive_failures += 1
                culprits = [
                    key for key, lane in self._lanes.items()
                    if lane.slot in set(exc2.slots) and not lane.failed
                ]
                if not culprits or attempt > 0:
                    raise
                for key in culprits:
                    self.quarantine(key, "drive_failed", error=exc2)
        if (
            self._drive_failures >= self.drive_failure_limit
            and not self._resident_degraded
            and self.resident
        ):
            self._degrade_resident()

    def _degrade_resident(self) -> None:
        """Drop from the resident loop to the dispatch-per-tick twin —
        bit-identical scheduling-wise (the cadence is a pure perf knob,
        pinned by test_resident_parity_any_cadence), so a host that
        keeps tripping over its driver serves slower instead of
        crashing 64 sessions. The mailbox is empty here (the recovery
        drive that brought failures past the limit just drained it)."""
        mbox = self.device.mailbox
        if mbox is not None and (mbox.pending_rows or mbox.staged_count):
            # degrading while the ring still owes rows would strand
            # them forever: surface the accounting bug typed
            raise InvariantViolation(
                f"degrade with {mbox.pending_rows} mailbox rows pending",
                invariant="degrade_with_pending_rows",
            )
        self._resident_degraded = True
        self.degrades += 1
        if GLOBAL_TELEMETRY.enabled:
            self._m_degraded.labels("dispatch_per_tick").inc()
            GLOBAL_TELEMETRY.record(
                "host_degraded", mode="dispatch_per_tick",
                drive_failures=self._drive_failures,
            )

    # ------------------------------------------------------------------
    # slot quarantine: contain a poisoned slot, keep survivors serving
    # ------------------------------------------------------------------

    def quarantine(self, key: Any, reason: str, *, error=None,
                   frame: Optional[int] = None) -> Optional[SlotPoisoned]:
        """Quarantine one hosted session's device slot: its staged rows
        and any rows the mailbox still owes it are discarded (masked off
        before the next drive — survivors' rows are untouched), the
        lane detaches, the slot's residue is scrubbed before reuse, and
        the verdict is surfaced as a typed SlotPoisoned (take_quarantines
        drains them — the fleet agent treats each like a mini-failover)
        with a forensics bundle. Returns the SlotPoisoned (None for an
        unknown key)."""
        lane = self._lanes.get(key)
        if lane is None:
            return None
        q_frame = frame if frame is not None else lane.current_frame
        lane.failed = True
        lane.last_error = reason
        lane.rows.clear()
        dropped = 0
        if self.resident and self.device.mailbox is not None:
            dropped = self.device.drop_mailbox_lane(lane.slot)
        # faults pinned on this slot stop firing: the slot is dead
        seam = self.fault_seam
        if seam is not None and hasattr(seam, "dispatch_cleared"):
            seam.dispatch_cleared(lane.slot)
        self.quarantines_total += 1
        tel = GLOBAL_TELEMETRY
        forensics = None
        if tel.enabled:
            self._m_quarantines.labels(reason).inc()
            tel.record(
                "slot_quarantined", frame=q_frame, key=str(key),
                slot=lane.slot, reason=reason, dropped_rows=dropped,
            )
            forensics = tel.write_forensics(
                "quarantine", frame=q_frame, key=str(key),
                slot=lane.slot, reason=reason,
                error=repr(error) if error is not None else None,
                dropped_rows=dropped, tick=self._tick_index,
                sessions_active=len(self._lanes),
            )
        err = SlotPoisoned(
            f"hosted session {key!r} quarantined",
            slot=lane.slot, key=key, reason=reason, frame=q_frame,
            forensics=forensics,
        )
        self._quarantines.append(err)
        slot = lane.slot
        self.detach(key)
        self.device.reset_slot(slot)
        return err

    def take_quarantines(self) -> List[SlotPoisoned]:
        """Drain the typed quarantine verdicts surfaced since the last
        call (the fleet agent polls this every step)."""
        out, self._quarantines = self._quarantines, []
        return out

    # ------------------------------------------------------------------
    # durable input journal (docs/DESIGN.md "Durable recovery")
    # ------------------------------------------------------------------

    def attach_journal(self, key: Any, path: Optional[str] = None, *,
                       meta: Optional[dict] = None,
                       fsync_every: Optional[int] = None,
                       segment_bytes: Optional[int] = None) -> Optional[str]:
        """Journal one hosted p2p lane's confirmed input rows at `path`
        (default `journal_dir/lane<key>`). Resumes an existing journal
        at the same path — the writer's open-time scan truncates a torn
        tail and retains the recorded rows, so a restore's redrive is
        VERIFIED against the durable bytes instead of re-appended.
        Returns the journal path, or None when the journal could not be
        opened (corrupt beyond continuity): the lane then serves
        unjournaled — durability degrades, serving never does."""
        from ..journal.wal import JournalWriter
        from ..utils.replay import InputRecorder

        lane = self._lanes[key]
        if lane.kind != "p2p":
            raise InvalidRequest(
                f"only p2p lanes journal (lane {key!r} is {lane.kind})"
            )
        if lane.journal is not None:
            raise InvalidRequest(f"lane {key!r} already journals")
        if path is None:
            if self._journal_dir is None:
                raise InvalidRequest(
                    "attach_journal needs a path on a host without "
                    "journal_dir"
                )
            path = os.path.join(self._journal_dir, f"lane{key}")
        base_meta = {
            "kind": "ggrs-input-journal",
            "game_cls": type(self.game).__name__,
            "num_players": lane.num_players,
            "input_size": self.game.input_size,
            "num_entities": getattr(self.game, "num_entities", None),
            **(meta or {}),
        }
        try:
            writer = JournalWriter(
                path,
                meta=base_meta,
                segment_bytes=(
                    segment_bytes
                    if segment_bytes is not None
                    else self._journal_segment_bytes
                ),
                fsync_every=(
                    fsync_every
                    if fsync_every is not None
                    else self._journal_fsync_every
                ),
            )
        except (JournalError, OSError) as exc:
            # raw OSError covers the writer's own disk touches
            # (makedirs, scan repair, segment open) — an unwritable
            # disk at attach time must degrade, not fail admission with
            # the lane already committed
            self._journal_fault(lane, exc, stage="open")
            return None
        lane.journal = _JournalTap(
            writer,
            InputRecorder(
                base_frame=writer.next_frame,
                # anchor unanchored (sparse-saving) first segments at
                # the lane's actual frame, not 0 — a mid-match adopt
                # would otherwise misfile rows
                next_frame=lane.current_frame,
            ),
            path,
        )
        if GLOBAL_TELEMETRY.enabled:
            GLOBAL_TELEMETRY.record(
                "journal_attached", key=str(key), path=path,
                resumed_frames=writer.next_frame,
            )
        return path

    def journal_frontier(self, key: Any) -> Optional[int]:
        """Frames durably journaled for a lane (None when unjournaled)
        — what the fleet heartbeat reports per match."""
        tap = self._lanes[key].journal
        return tap.writer.next_frame if tap is not None else None

    def journal_tail(self, key: Any) -> Optional[dict]:
        """Final-drain the lane's journal, then snapshot the rows NOT
        yet durable (played-but-unconfirmed at this instant) — a
        migration ticket carries them so the destination's recorder
        covers the hole between the durable frontier and the first
        frame the destination will observe itself."""
        lane = self._lanes[key]
        if lane.journal is None:
            return None
        self._pump_journal_lane(lane)
        if lane.journal is None:  # the final drain degraded it
            return None
        return lane.journal.recorder.pending_rows()

    def seed_journal_tail(self, key: Any, rows: dict) -> None:
        """Pre-observe a source recorder's pending rows into an adopted
        lane's tap (see journal_tail)."""
        tap = self._lanes[key].journal
        if tap is not None and rows:
            tap.recorder.seed_rows(rows)

    def _journal_fault(self, lane: _Lane, exc: Exception, *,
                       stage: str) -> None:
        """DEGRADE-TO-UNJOURNALED: the journal is a durability feature,
        never a liveness dependency — a refused append (ENOSPC), a
        corrupt resume or a redrive/journal divergence detaches the
        TAP, trips a typed invariant for the operator, and the lane
        keeps serving."""
        from ..journal.metrics import journal_stalls_total

        tap = lane.journal
        lane.journal = None
        if tap is not None:
            try:
                tap.writer.close()
            except (JournalError, OSError):
                pass
        self.journal_lanes_degraded += 1
        if isinstance(exc, (JournalStalled, OSError)):
            # unconditional like the wal.py counters: the disk-refusal
            # signal must not depend on the telemetry toggle
            journal_stalls_total().inc()
        self._trip_invariant(
            "journal_degraded", key=lane.key, frame=lane.current_frame,
            info=(
                f"lane {lane.key!r} journal degraded at {stage}: "
                f"{type(exc).__name__}: {exc}"
            ),
        )

    def _pump_journal_lane(self, lane: _Lane) -> None:
        """Drain one lane's confirmed frontier into its journal: rows
        the recorder re-observed below the resume watermark verify
        against the durable bytes (the restore-redrive overlap), fresh
        confirmed rows append. Every failure path degrades typed."""
        tap = lane.journal
        if tap is None:
            return
        sl = getattr(lane.session, "sync_layer", None)
        if sl is None:
            return
        # the AS-PLAYED confirmed frontier: sync_layer raises
        # last_confirmed_frame only inside advance_frame, AFTER the
        # rollback pass corrected every misprediction below it (its
        # discard assert is exactly "first_incorrect >= frame"), so
        # rows < watermark hold truth under the recorder's
        # last-write-wins rule. The LIVE min-over-peers frontier is
        # deliberately not used: an input can arrive without ever being
        # re-played (the tail of a match), leaving the recorder's row a
        # stale prediction — journaling it would diverge across peers.
        confirmed = sl.last_confirmed_frame - 1
        if confirmed < 0:
            return
        rec = tap.recorder
        rec.confirm_through(confirmed)
        try:
            if self.fault_seam is not None and hasattr(
                self.fault_seam, "before_journal_append"
            ):
                self.fault_seam.before_journal_append(tap.path)
            for f, inp, st in rec.take_stale(confirmed):
                tap.writer.verify_row(f, inp, canonical_statuses(st))
            drained = rec.drain_confirmed()
            if drained is not None:
                start, inputs, st = drained
                tap.writer.append_rows(
                    start, inputs, canonical_statuses(st)
                )
        except (JournalError, OSError, InvalidRequest) as exc:
            # InvalidRequest = a frame gap the writer refused (an
            # adoption hole no ticket tail covered): durability for
            # this lane is over, serving is not
            self._journal_fault(lane, exc, stage="append")

    def _pump_journals(self) -> None:
        for lane in self._lanes.values():
            if lane.journal is not None:
                self._pump_journal_lane(lane)

    def flush_journals(self) -> None:
        """Drain every journaled lane's frontier and fsync the active
        segments — the checkpoint/drain/export durability point."""
        for lane in list(self._lanes.values()):
            self._pump_journal_lane(lane)
            tap = lane.journal
            if tap is None:
                continue
            try:
                tap.writer.sync()
            except (JournalError, OSError) as exc:
                self._journal_fault(lane, exc, stage="sync")

    def _launch_drafts(self) -> None:
        """Collect every starved p2p lane that can be drafted this tick
        (fresh watermark, anchor snapshot live in its ring, played
        history complete) and launch ONE draft batch for all of them —
        bubbles fill as a fleet, not one dispatch per lane. Entries
        order by owning shard on a session mesh, the same lane-packing
        affinity as ordinary megabatch rows."""
        device = self.device
        core = device.core
        # the budget the confirmed dispatches left over this tick: draft
        # rows fill idle window only — a saturated device has no bubbles
        # to fill, so skip rather than add inflight work real sessions
        # will queue behind next tick
        budget = self.max_inflight_rows - device.poll_retired()
        if budget <= 0:
            return
        entries: List[Tuple[int, np.ndarray]] = []
        metas = []
        for lane in self._lanes.values():
            if (
                not lane.starved
                or lane.rows
                or lane.failed
                or lane.kind != "p2p"
            ):
                continue
            # the host already KNOWS what each local player will play
            # next — the inputs submitted during the starvation sit in
            # the session's pending map — so the draft pins them instead
            # of guessing
            pending = getattr(lane.session, "local_inputs", None) or {}
            local_pins = {
                h: pi.buf
                for h, pi in pending.items()
                if h in lane.local_handles
            }
            # inputs that ARRIVED during the stall sit confirmed in the
            # session's per-player queues (the gate blocks on the
            # watermark, not on every queue) — the draft pins those true
            # values instead of guessing, and the per-player confirmed
            # frontier is the draft's freshness fingerprint: any new
            # arrival makes the standing draft stale, so it re-drafts
            # with the fresh truth pinned in
            sl = getattr(lane.session, "sync_layer", None)
            queues = sl.input_queues if sl is not None else None
            fingerprint = (
                tuple(q.last_added_frame for q in queues)
                if queues is not None
                else None
            )

            def lookup(p, frame, _qs=queues):
                if _qs is None or p >= len(_qs):
                    return None
                q = _qs[p]
                # NativeInputQueue keeps its ring in C++ (no host-visible
                # .inputs): drafts for such a lane just guess instead of
                # pinning arrived truth — still correct, less informed
                ring = getattr(q, "inputs", None)
                if ring is None:
                    return None
                rec = ring[frame % len(ring)]
                if frame <= q.last_added_frame and rec.frame == frame:
                    return rec.buf
                return None

            plan = self._spec.plan_draft(
                lane.key,
                current_frame=lane.current_frame,
                watermark=lane.confirmed_watermark,
                local_pins=local_pins,
                confirmed_lookup=lookup,
                fingerprint=fingerprint,
            )
            if plan is None:
                continue
            anchor, scripts, statuses = plan
            metas.append((lane, anchor, scripts, statuses, fingerprint))
        if not metas:
            return
        if self.mesh is not None:
            # the same lane-packing affinity as ordinary megabatch rows:
            # a lane's member rows stay adjacent on their owning shard
            metas.sort(key=lambda m: device.shard_of(m[0].slot))
        # pack every lane's member scripts as rows of ONE draft batch,
        # capped at the device capacity (member 0 — the lineage script —
        # wins the last slots over extra bet members); rows come from a
        # host-level pool (device.draft copies them into its own pooled
        # staging, so reuse next tick is safe) — the steady-state draft
        # path allocates nothing, same discipline as _Lane.row_pool
        pool = self._draft_row_pool
        while len(pool) < device.capacity:
            pool.append(np.empty((device._draft_len,), dtype=np.int32))
        cap = min(device.capacity, budget)
        packed_metas = []
        for lane, anchor, scripts, statuses, fingerprint in metas:
            room = cap - len(entries)
            if room < 1:
                break
            members = []
            for script in scripts[:room]:
                row = pool[len(entries)]
                device.pack_draft_row_into(
                    row, anchor % core.ring_len, statuses, script
                )
                members.append(len(entries))
                entries.append((lane.slot, row))
            packed_metas.append(
                (lane, anchor, scripts[: len(members)], members,
                 fingerprint)
            )
        if self.resident_active:
            # drafts anchor on ring snapshots: rows the mailbox still
            # owes must land before the rollout reads the rings
            if self._drive_resident() is _DRIVE_FAILED:
                return  # ladder ran; draft again next tick
        batch = device.draft(entries)
        for lane, anchor, scripts, members, fingerprint in packed_metas:
            self._spec.install_draft(
                lane.key, anchor=anchor, scripts=scripts, batch=batch,
                members=members, watermark=lane.confirmed_watermark,
                fingerprint=fingerprint,
            )
        if GLOBAL_TELEMETRY.enabled:
            GLOBAL_TELEMETRY.record(
                "spec_draft_launched", lanes=len(packed_metas),
                rows=len(entries),
            )

    # ------------------------------------------------------------------
    # SDC audit lane: sampled double-compute vs the full-window
    # reference program (docs/DESIGN.md "Device fault domains")
    # ------------------------------------------------------------------

    def _build_audit_row(self, lane: _Lane):
        """One lane's audit row: load at the OLDEST ring anchor whose
        replay the record still covers, re-advance every played frame
        up to the live one, saves all scratch. The oldest anchor
        maximizes the lookback window — corruption that struck within
        the last ~max_prediction frames is caught before a post-fault
        save 'heals' the ring into consistency with the corrupt world.
        Returns (row, anchor, count) or None when the lane has no
        coverage (fresh, mid-rollback backlog, or saves out of
        range)."""
        core = self.device.core
        cur = lane.current_frame
        rec = lane.audit_inputs
        lo = max(cur - (core.ring_len - 1), 0)
        anchor = None
        for f in sorted(lane.saved_frames):
            if f < lo or f > cur:
                continue
            if cur - f > core.max_prediction + 1:
                continue  # replay must fit one packed row
            if all(g in rec for g in range(f, cur)):
                anchor = f
                break
        if anchor is None:
            return None
        count = cur - anchor
        W, P, I = core.window, self.num_players, self.game.input_size
        inputs = np.zeros((W, P, I), dtype=np.uint8)
        statuses = np.zeros((W, P), dtype=np.int32)
        save_slots = np.full((W,), core.scratch_slot, dtype=np.int32)
        for k in range(count):
            inp, st = rec[anchor + k]
            inputs[k] = inp
            statuses[k] = st
        row = core.pack_tick_row_into(
            np.empty((core._packed_len,), dtype=np.int32),
            do_load=True,
            load_slot=anchor % core.ring_len,
            inputs=inputs,
            statuses=statuses,
            save_slots=save_slots,
            advance_count=count,
            start_frame=anchor,
        )
        # the at-rest sweep's expectations: every LIVE ring row whose
        # save checksum the host recorded — (ring slot, frame, recorded
        # lazy checksum), captured by reference NOW so later saves
        # can't retroactively change what this audit compares against
        expect = [
            (f % core.ring_len, f, lane.audit_saved_checksums[f])
            for f in sorted(lane.saved_frames)
            if cur - core.ring_len < f <= cur
            and f in lane.audit_saved_checksums
        ]
        return row, anchor, count, expect

    def _maybe_audit(self) -> None:
        """Every `sdc_audit_every` host ticks, double-compute EVERY
        eligible lane (one vmapped batch on the shared bucket grid):
        detection of a flipped bit is then guaranteed within
        sdc_audit_every + the anchor lookback (~max_prediction frames)
        ticks — the sampling bound the acceptance soak pins. Results
        resolve lazily off the drain pass; a mismatch quarantines the
        slot."""
        if self._tick_index % self._audit_every:
            return
        entries: List[Tuple[int, np.ndarray]] = []
        metas = []
        for lane in self._lanes.values():
            if (
                lane.failed
                or lane.kind != "p2p"
                or lane.rows  # staged rows not yet on device: stale view
                or lane.queued_since_tick is not None
            ):
                continue
            built = self._build_audit_row(lane)
            if built is None:
                continue
            row, anchor, count, expect = built
            entries.append((lane.slot, row))
            metas.append(
                (lane.key, anchor, count, lane.current_frame, expect)
            )
            if len(entries) >= self.device.capacity:
                break
        if not entries:
            return
        if self.resident_active and self.device.mailbox.pending_rows:
            # the audit reads rings/states: rows the mailbox still owes
            # must land first (an extra drive is a pure cadence change)
            if self._drive_resident() is _DRIVE_FAILED:
                return  # ladder ran; audit again next cycle
        out = self.device.audit_rows(entries)
        self._pending_audits.append((out, metas))
        self.audits_sampled += len(entries)
        if GLOBAL_TELEMETRY.enabled:
            self._m_sdc_audits.inc(len(entries))

    def _resolve_audits(self, block: bool = False) -> None:
        """Resolve host-ready audit batches (all of them when `block`):
        a (reference replay, live world) checksum mismatch is silent
        data corruption — quarantine the slot with reason sdc_audit."""
        if not self._pending_audits:
            return
        from ..ops.fixed_point import combine_checksum

        remaining = []
        for pending in self._pending_audits:
            out, metas = pending
            ref_hi, ref_lo, live_hi, live_lo, ring_hi, ring_lo = out
            if not block and not _array_is_ready(ref_hi):
                remaining.append(pending)
                continue
            rh, rl = np.asarray(ref_hi), np.asarray(ref_lo)
            lh, ll = np.asarray(live_hi), np.asarray(live_lo)
            qh, ql = np.asarray(ring_hi), np.asarray(ring_lo)
            for k, (key, anchor, count, frame, expect) in enumerate(metas):
                verdicts = []
                if rh[k] != lh[k] or rl[k] != ll[k]:
                    # the replayed lineage and the live world disagree:
                    # one of them (or the anchor row) flipped
                    verdicts.append({
                        "check": "replay",
                        "ref": [int(rh[k]), int(rl[k])],
                        "live": [int(lh[k]), int(ll[k])],
                    })
                for rs, f, lazy in expect:
                    recomputed = combine_checksum(qh[k][rs], ql[k][rs])
                    if recomputed != lazy():
                        # a stored snapshot's bytes no longer hash to
                        # what the device computed when it SAVED them:
                        # at-rest corruption a future rollback would
                        # load and serve
                        verdicts.append({
                            "check": "ring_row", "frame": f,
                            "ring_slot": rs,
                            "recomputed": int(recomputed),
                            "recorded": int(lazy()),
                        })
                if not verdicts:
                    continue
                self.audit_mismatches += 1
                if GLOBAL_TELEMETRY.enabled:
                    self._m_sdc_mismatches.inc()
                    GLOBAL_TELEMETRY.record(
                        "sdc_mismatch", frame=frame, key=str(key),
                        anchor=anchor, replayed=count,
                        verdicts=verdicts,
                    )
                self.quarantine(key, "sdc_audit", frame=frame)
        self._pending_audits = remaining

    # ------------------------------------------------------------------
    # always-on invariant monitors
    # ------------------------------------------------------------------

    def _trip_invariant(self, invariant: str, *, key: Any = None,
                        frame: int = -1, info: str = "") -> None:
        tel = GLOBAL_TELEMETRY
        forensics = None
        if tel.enabled:
            self._m_invariants.labels(invariant).inc()
            tel.record(
                "invariant_trip", frame=frame, invariant=invariant,
                key=str(key), info=info,
            )
            forensics = tel.write_forensics(
                "invariant", frame=frame, invariant=invariant,
                key=str(key), info=info, tick=self._tick_index,
            )
        err = InvariantViolation(
            info or f"invariant {invariant} violated",
            invariant=invariant, key=key, frame=frame,
            forensics=forensics,
        )
        if len(self.invariant_trips) < 256:
            self.invariant_trips.append(err)
        if self.strict_invariants:
            raise err

    def _check_invariants(self) -> None:
        """The cheap always-on monitors — the bug class the WAN soak
        found by accident (a stale watermark permanently wedging a
        session), watched deliberately: per-lane confirmed-frame
        progress (no RUNNING lane silent past wedge_limit_ticks,
        latched until progress resumes) and resident mailbox
        accounting (staged-row count vs watermark image)."""
        tick = self._tick_index
        if self.wedge_limit_ticks:
            for lane in self._lanes.values():
                if lane.failed:
                    continue
                if lane.ticks_advanced != lane.last_progress_seen:
                    lane.last_progress_seen = lane.ticks_advanced
                    lane.last_progress_tick = tick
                    lane.wedge_reported = False
                elif (
                    not lane.wedge_reported
                    and tick - lane.last_progress_tick
                    > self.wedge_limit_ticks
                    and lane.session.current_state()
                    == SessionState.RUNNING
                ):
                    lane.wedge_reported = True
                    self._trip_invariant(
                        "lane_wedged", key=lane.key,
                        frame=lane.current_frame,
                        info=(
                            f"RUNNING lane {lane.key!r} advanced no "
                            f"frame for {tick - lane.last_progress_tick}"
                            " ticks"
                        ),
                    )
        if self.resident_active and self.device.mailbox is not None:
            mbox = self.device.mailbox
            counted = int(mbox._counts.sum())
            if mbox.pending_rows != counted or mbox.max_fill() > mbox.depth:
                self._trip_invariant(
                    "mailbox_accounting",
                    info=(
                        f"mailbox pending_rows={mbox.pending_rows} vs "
                        f"watermark image {counted} "
                        f"(max_fill={mbox.max_fill()}/{mbox.depth})"
                    ),
                )

    def _lane_ready(self, lane: _Lane) -> bool:
        lane.starved = False
        if lane.failed:  # quarantined by a staging error
            return False
        if lane.rows:  # staged rows must dispatch before the next advance
            return False
        s = lane.session
        if s.current_state() != SessionState.RUNNING:
            return False
        if lane.kind == "spectator":
            return True
        if not lane.local_handles <= lane.pending_inputs:
            return False
        # mirror sync_layer.add_local_input's prediction-threshold gate so
        # a throttled session never advances into the partially-mutated
        # PredictionThreshold raise mid-advance. The watermark must be the
        # FRESH confirmed frame (min over connected peers, what
        # advance_frame is about to set) — not the stale
        # sl.last_confirmed_frame, which only updates inside
        # advance_frame: gating on the stale value wedges a session
        # permanently once RTT exceeds the prediction window, because the
        # advance that would refresh the watermark is exactly what the
        # gate blocks (found by the WAN-profile chaos soak, where
        # cross-region links run 10+ frames of RTT). Sparse saving needs
        # no extra clamp here: set_last_confirmed_frame clamps the
        # watermark to last_saved_frame, but _check_last_saved_state runs
        # FIRST in the same advance and repairs last_saved to
        # min(confirmed, current) whenever the lag reaches the window
        # (p2p_session asserts it), so in the unrepaired region
        # current - last_saved < max_prediction and only the confirmed
        # term below can bind the in-advance PredictionThreshold raise.
        sl = s.sync_layer
        if sl.current_frame >= lane.max_prediction:
            confirmed = min(
                (
                    st.last_frame
                    for st in s.local_connect_status
                    if not st.disconnected
                ),
                default=None,
            )
            if confirmed is not None:
                # invariant monitor: the confirmed watermark is
                # monotone by protocol — a regression means a peer's
                # frame accounting (or ours) corrupted
                prev = lane.max_confirmed_seen
                if prev is not None and confirmed < prev:
                    self._trip_invariant(
                        "confirmed_regressed", key=lane.key,
                        frame=confirmed,
                        info=(
                            f"confirmed watermark regressed "
                            f"{prev} -> {confirmed} on lane {lane.key!r}"
                        ),
                    )
                else:
                    lane.max_confirmed_seen = confirmed
            if (
                confirmed is None
                or sl.current_frame - confirmed >= lane.max_prediction
            ):
                lane.throttled_ticks += 1
                # INPUT-STARVED: every local input is in but the gate
                # blocks on missing remote inputs — the lane's megabatch
                # row would be a device bubble. The speculation scheduler
                # drafts these lanes' futures instead (_launch_drafts).
                lane.starved = True
                lane.confirmed_watermark = confirmed
                return False
        return True

    # ------------------------------------------------------------------
    # request staging (parse -> packed rows)
    # ------------------------------------------------------------------

    def _stage(self, lane: _Lane, requests: List[Request]) -> None:
        # split BEFORE each LoadGameState (a load begins a new segment).
        # Steady-state traffic carries no loads, so the whole batch
        # stages as one segment with zero copies; only rollback ticks
        # pay the per-segment slice.
        if not requests:
            return
        start = 0
        for i in range(1, len(requests)):
            if isinstance(requests[i], LoadGameState):
                self._stage_segment(lane, requests[start:i])
                start = i
        self._stage_segment(
            lane, requests if start == 0 else requests[start:]
        )

    def _parse_staging(self):
        """The host-wide pooled parse triple (inputs, statuses,
        save_slots), refilled with neutral values per segment: the walk's
        output is consumed synchronously by pack_tick_row_into, so one
        triple serves the whole fleet with zero steady-state allocation."""
        core = self.device.core
        if not hasattr(self, "_parse_bufs"):
            W, P, I = core.window, self.num_players, self.game.input_size
            self._parse_bufs = (
                np.zeros((W, P, I), dtype=np.uint8),
                np.zeros((W, P), dtype=np.int32),
                np.full((W,), core.scratch_slot, dtype=np.int32),
            )
        inputs, statuses, save_slots = self._parse_bufs
        inputs.fill(0)
        statuses.fill(0)
        save_slots.fill(core.scratch_slot)
        return inputs, statuses, save_slots

    def _stage_segment(self, lane: _Lane, requests: List[Request]) -> None:
        from ..tpu.backend import parse_request_segment

        core = self.device.core
        W, P = core.window, self.num_players
        inputs, statuses, save_slots = self._parse_staging()
        if lane.num_players < P:
            # pad players beyond the session's count as DISCONNECTED: the
            # game model substitutes its deterministic dummy input, and
            # every peer of the match pads identically
            statuses[:, lane.num_players:] = int(InputStatus.DISCONNECTED)
        load, start_frame, count, saves, last_active, trailing = (
            parse_request_segment(
                requests,
                window=W,
                ring_len=core.ring_len,
                max_prediction=core.max_prediction,
                current_frame=lane.current_frame,
                inputs=inputs,
                statuses=statuses,
                save_slots=save_slots,
            )
        )
        # per-row canonical signature into the SHARED plan cache: the
        # fleet's repeated shapes coalesce across sessions
        self.device.plan_cache.note(
            (load is not None, count, last_active, trailing is not None),
            frame=start_frame,
        )
        if self._audit_every and lane.kind == "p2p":
            # SDC audit record: what the device is about to PLAY for
            # each advanced frame (rollback segments overwrite earlier
            # predicted values with the corrected truth, keeping the
            # record equal to the lineage the live bytes derive from),
            # plus the frames whose ring rows can anchor a replay
            rec = lane.audit_inputs
            for k in range(count):
                rec[start_frame + k] = (
                    inputs[k].copy(), statuses[k].copy()
                )
            for _slot_i, save in saves:
                lane.saved_frames.add(save.frame)
            floor = start_frame + count - (core.ring_len - 1)
            if len(rec) > 2 * core.window:
                for f in [f for f in rec if f < floor]:
                    del rec[f]
                lane.saved_frames = {
                    f for f in lane.saved_frames if f >= floor
                }
                for f in [
                    f for f in lane.audit_saved_checksums if f < floor
                ]:
                    del lane.audit_saved_checksums[f]
        # speculative bubble-filling: record what this lane actually
        # played (the verify pass's ground truth + the input model's
        # training stream), then check the segment against any standing
        # draft — a matched prefix turns this row into an ADOPT row
        # served from the draft trajectory instead of a resim
        adopt = None
        if self._spec is not None and lane.kind == "p2p":
            load_frame = load.frame if load is not None else None
            # verify BEFORE record_segment: the lineage check reads the
            # played rows strictly before the load point (unaffected by
            # this segment), and record_segment's stale-draft discard
            # must not kill the draft the segment is about to adopt — a
            # load AT the anchor is the deepest serveable rollback
            hit = None
            if not lane.rows:
                hit = self._spec.verify(
                    lane.key, load_frame=load_frame, start=start_frame,
                    count=count, inputs=inputs, statuses=statuses,
                )
            self._spec.record_segment(
                lane.key, load_frame=load_frame, start=start_frame,
                count=count, inputs=inputs, statuses=statuses,
                saves=saves,
            )
            if hit is not None:
                draft, member, shift, matched = hit
                packed = core.pack_adopt_row(
                    member,
                    (load.frame % core.ring_len)
                    if load is not None
                    else 0,
                    count, shift, start_frame, matched, save_slots,
                    statuses=statuses, inputs=inputs,
                )
                adopt = (draft.batch, packed)
        if adopt is not None:
            lane.rows.append(
                _StagedRow(
                    None, saves, start_frame, count, last_active, False,
                    adopt=adopt,
                )
            )
            lane.current_frame = start_frame + count
            return
        # pack straight into the lane's pooled row buffer (no per-tick
        # allocation); the scheduler's depth grouping reads the routing
        # keys off the staged row instead of rescanning it
        row = core.pack_tick_row_into(
            lane.next_row_buf(),
            do_load=load is not None,
            load_slot=(load.frame % core.ring_len) if load is not None else 0,
            inputs=inputs,
            statuses=statuses,
            save_slots=save_slots,
            advance_count=count,
            start_frame=start_frame,
        )
        lane.rows.append(
            _StagedRow(
                row, saves, start_frame, count, last_active,
                self.device.fast_eligible(row, last_active),
            )
        )
        lane.current_frame = start_frame + count

    # ------------------------------------------------------------------
    # megabatch scheduling
    # ------------------------------------------------------------------

    def _pump_device(self) -> None:
        """Coalesce the ready queue's head rows into megabatches, oldest
        arrivals first, until the device window is full or the queue is
        empty. One row per session per megabatch preserves each session's
        in-order request stream; a session with a second staged row
        (sparse-saving keepalive) keeps its queue position.

        Depth routing: each pass's picked rows split into the
        zero-rollback FAST group (no load, one advance — the dominant
        shape in real traffic) plus one group per occupied depth bucket,
        and every group dispatches as its own megabatch program sized to
        its depth — one deep-rollback session no longer drags the other
        63 sessions' rows to the full window. Groups are disjoint lanes,
        so the one-row-per-session-per-megabatch invariant holds within
        each pass.

        Mixed traffic: rows staged by attached env blocks (attach_env)
        fold into the same groups — env step rows join the fast group,
        snapshot/restore rows their depth bucket — so one dispatch
        carries training AND interactive rows. Env rows are synchronous
        training traffic (env.step blocks on this tick): when the
        inflight budget is exhausted they retire the fence and dispatch
        anyway rather than queue."""
        core = self.device.core
        # env-staged rows for this pass: gkey -> [max last_active, rows]
        env_groups: Dict[Any, List] = {}
        for env in self._envs:
            for gkey, la, entries in env._take_staged():
                slot = env_groups.get(gkey)
                if slot is None:
                    slot = env_groups[gkey] = [0, []]
                if la > slot[0]:
                    slot[0] = la
                slot[1].extend(entries)
        picked = self._picked_scratch
        adopts = self._adopts_scratch
        groups = self._groups_scratch
        while self._ready or env_groups:
            budget = self.max_inflight_rows - self.device.poll_retired()
            if budget <= 0:
                if not env_groups:
                    break
                # env rows must land THIS tick: retire the fence and
                # take the dispatch slot the budget was protecting
                self.device.block_until_ready()
            env_rows = 0
            for _la, e in env_groups.values():
                env_rows += len(e)
            take = min(
                max(budget, 0),
                len(self._ready),
                max(self.device.capacity - env_rows, 0),
            )
            picked.clear()
            adopts.clear()
            groups.clear()
            # _ready is a deque in arrival order; nothing retires (and
            # so mutates it) until the picking loop is done
            for key in self._ready:
                if take <= 0:
                    break
                take -= 1
                lane = self._lanes[key]
                staged = lane.rows[0]
                if staged.adopt is not None:
                    adopts.append((lane, staged))
                else:
                    picked.append((lane, staged))
            if not picked and not adopts and not env_groups:
                break
            # ADOPT rows first: each serves its lane's tick from a
            # standing draft in one per-slot dispatch (prefix from the
            # trajectory, mispredicted suffix resimulated in-program) —
            # the whole point of having drafted the bubble
            for lane, staged in adopts:
                draft_batch, packed = staged.adopt
                batch = self.device.adopt_slot(
                    lane.slot, draft_batch, packed
                )
                self._retire_row(lane, staged, batch, 0)
            if self.depth_routing:
                for lane, staged in picked:
                    gkey = (
                        "fast"
                        if staged.fast
                        else self.device.depth_bucket_for(staged.last_active)
                    )
                    g = groups.get(gkey)
                    if g is None:
                        g = groups[gkey] = []
                    g.append((lane, staged))
            else:
                groups[None] = picked
            for gkey, group in groups.items():
                env = env_groups.pop(gkey, None) if env_groups else None
                env_la, env_entries = env if env is not None else _NO_ENV
                if self.mesh is not None:
                    # lane-packing affinity: order each megabatch's rows
                    # by the shard that owns their world, so the staged
                    # block's session-axis partitions line up with the
                    # slots they gather/scatter (stable sorts — in-shard
                    # arrival order, and the one-row-per-slot invariant,
                    # are untouched; env rows carry no save bindings)
                    group.sort(key=self._shard_key_lane)
                    if env_entries:
                        env_entries.sort(key=self._shard_key_entry)
                batch, group = self._dispatch_group(
                    gkey, group, env_entries, env_la
                )
                for k, (lane, staged) in enumerate(group):
                    self._retire_row(lane, staged, batch, k * core.window)
            while env_groups:
                # env-only depth groups (no session row picked for their
                # bucket this pass) dispatch on their own
                gkey, (env_la, env_entries) = env_groups.popitem()
                if self.mesh is not None and env_entries:
                    env_entries.sort(key=self._shard_key_entry)
                batch, group = self._dispatch_group(
                    gkey, (), env_entries, env_la
                )
                for k, (lane, staged) in enumerate(group):
                    self._retire_row(lane, staged, batch, k * core.window)
        if GLOBAL_TELEMETRY.enabled:
            self._m_queue_depth.set(len(self._ready))

    def _shard_key_lane(self, ls):
        """Lane-packing sort key (hoisted: no per-pass lambda)."""
        return self.device.shard_of(ls[0].slot)

    def _shard_key_entry(self, e):
        return self.device.shard_of(e[0])

    def _dispatch_group(self, gkey, group, env_entries, env_la):
        """Dispatch one depth group behind the fault-containment ladder:
        a DeviceDispatchFailed (raised BEFORE the program runs — worlds
        untouched) retries once as a transient; a second raise naming
        culprit slots quarantines them and re-dispatches the survivors
        bit-exactly (identical rows, identical program); persistent AND
        unattributed re-raises — the whole device is suspect. Returns
        (checksum batch | None, surviving group) with save-binding
        positions matching the surviving entries."""
        for attempt in range(3):
            try:
                return self._dispatch_group_once(
                    gkey, group, env_entries, env_la
                )
            except DeviceDispatchFailed as exc:
                group = self._dispatch_group_fault(exc, attempt, group)
        raise DeviceDispatchFailed(
            "megabatch dispatch still failing after quarantine",
            op="megabatch",
        )

    def _dispatch_group_once(self, gkey, group, env_entries, env_la):
        """One dispatch attempt — the steady-state body: per-call scratch
        only, nothing allocated per retry iteration."""
        group = [ls for ls in group if not ls[0].failed]
        # session entries FIRST: save bindings index the batch by
        # position, and env rows need no post-dispatch binding
        entries = [(lane.slot, staged.row) for lane, staged in group]
        entries.extend(env_entries)
        if not entries:
            return None, group
        if gkey == "fast":
            batch, _bucket = self.device.dispatch(entries, fast=True)
        elif gkey is None:
            batch, _bucket = self.device.dispatch(entries)
        else:
            la = env_la
            for _, staged in group:
                if staged.last_active > la:
                    la = staged.last_active
            batch, _bucket = self.device.dispatch(entries, last_active=la)
        return batch, group

    def _dispatch_group_fault(self, exc, attempt, group):
        """The containment ladder's fault arm (cold: runs only when a
        dispatch already raised). Returns the surviving group for the
        next attempt."""
        self._on_device_fault(exc)
        if attempt == 0:
            return group  # transient: the retry re-runs identically
        slots = set(exc.slots)
        culprits = [lane for lane, _ in group if lane.slot in slots]
        if not culprits:
            raise  # unattributed: the whole device is suspect
        for lane in culprits:
            self.quarantine(lane.key, "dispatch_failed", error=exc)
        return [ls for ls in group if not ls[0].failed]

    def _retire_row(self, lane: _Lane, staged: _StagedRow, batch,
                    base: int) -> None:
        """Post-dispatch bookkeeping shared by megabatch rows and adopt
        rows: pop the staged row, bind its saves' lazy checksums at
        `base` into the dispatch's checksum batch, and settle the lane's
        queue-wait accounting when its last row dispatched."""
        SnapshotRef, _LazyChecksum = _backend_refs()
        ring_len = self.device.core.ring_len
        lane.rows.popleft()
        for slot_i, save in staged.saves:
            lazy = _LazyChecksum(batch, base + slot_i)
            save.cell.save_lazy(
                save.frame,
                SnapshotRef(save.frame, save.frame % ring_len),
                lazy,
            )
            if self._audit_every and lane.kind == "p2p":
                lane.audit_saved_checksums[save.frame] = lazy
        if not lane.rows:
            self._ready.remove(lane.key)
            waited = self._tick_index - lane.queued_since_tick
            if len(self.queue_waits) < 1 << 16:
                self.queue_waits.append(waited)
            if GLOBAL_TELEMETRY.enabled:
                self._m_queue_wait.observe(waited)
            lane.queued_since_tick = None

    # ------------------------------------------------------------------
    # eviction / GC / drain
    # ------------------------------------------------------------------

    def _run_gc(self, events: Dict[Any, List[Event]]) -> None:
        now = self.clock.now_ms()
        for lane in list(self._lanes.values()):
            if lane.rows:
                continue  # drain its staged work first
            if self._all_remotes_gone(lane):
                self._evict(lane, "disconnect_gc")
                self.sessions_gced += 1
                continue
            if (
                self.idle_timeout_ms > 0
                and now - lane.last_activity_ms >= self.idle_timeout_ms
            ):
                self._evict(lane, "idle_timeout")

    def _all_remotes_gone(self, lane: _Lane) -> bool:
        """Disconnect GC predicate: a P2P session whose every remote peer
        (players and spectators) has disconnected serves nobody; a
        spectator whose host endpoint died can never advance again."""
        from ..network.protocol import ProtocolState

        s = lane.session
        if lane.kind == "spectator":
            return s.host.state in (
                ProtocolState.DISCONNECTED, ProtocolState.SHUTDOWN
            )
        remotes = s.remote_player_handles()
        if not remotes:
            return False  # solo/local-only session: nothing to GC on
        if any(
            not s.local_connect_status[h].disconnected for h in remotes
        ):
            return False
        # spectator endpoints still alive keep the session useful
        return not any(
            ep.is_running() for ep in s.player_reg.spectators.values()
        )

    def _evict(self, lane: _Lane, reason: str) -> None:
        self.sessions_evicted += 1
        tel = GLOBAL_TELEMETRY
        if tel.enabled:
            self._m_evicted.inc()
            tel.record(
                "host_session_evicted", key=str(lane.key), reason=reason
            )
        self.detach(lane.key)

    def _flush_ready(self, reason: str, *, max_passes: int = 10_000) -> None:
        """Flush every staged row through the device — the shared tail of
        graceful drain, the non-terminal checkpoint, and a migration
        export. A queue that refuses to empty (wedged fence, broken
        budget accounting, a monkeypatched scheduler) raises the typed,
        operator-facing DrainStalled carrying the stuck depth and fence
        state — and a flight-recorder event — instead of dying as a bare
        AssertionError in a shutdown path."""
        passes = 0
        while self._ready:
            # retire the whole fence first so the budget can never pin the
            # queue: each pass then dispatches at least one megabatch.
            # block_until_ready drains the mailbox, so an armed/real
            # drive fault can surface HERE — route it through the same
            # recovery ladder as the tick path instead of letting a
            # checkpoint/migration flush crash the host
            try:
                self.device.block_until_ready()
            except DeviceDispatchFailed as exc:
                self._recover_drive_failure(exc)
            self._pump_device()
            passes += 1
            if passes >= max_passes and self._ready:
                depth = len(self._ready)
                inflight = self.device.inflight_rows
                if GLOBAL_TELEMETRY.enabled:
                    GLOBAL_TELEMETRY.record(
                        "host_drain_stalled", reason=reason,
                        queue_depth=depth, inflight_rows=inflight,
                        passes=passes,
                    )
                raise DrainStalled(
                    f"{reason}: ready queue failed to flush",
                    queue_depth=depth, inflight_rows=inflight,
                    passes=passes,
                )
        try:
            self.device.block_until_ready()
        except DeviceDispatchFailed as exc:
            self._recover_drive_failure(exc)
            self.device.block_until_ready()
        self._resolve_audits(block=True)

    def _save_checkpoint(self, path: str) -> None:
        """device.save behind the harvest-timeout recovery contract: a
        readback timeout mid-checkpoint (the kill-mid-harvest race — an
        export racing an in-flight checksum batch) blocks the fence and
        retries ONCE, so the checkpoint either completes whole or the
        typed HarvestTimeout surfaces — never a torn file (the write
        itself is atomic) and never a silently skipped save."""
        for attempt in (0, 1):
            try:
                if self.fault_seam is not None:
                    self.fault_seam.before_harvest("checkpoint")
                self.device.save(path)
                break
            except HarvestTimeout:
                self.harvest_timeouts += 1
                if GLOBAL_TELEMETRY.enabled:
                    GLOBAL_TELEMETRY.record(
                        "harvest_timeout", op="checkpoint"
                    )
                if attempt:
                    raise
                self.device.block_until_ready()
        if self.fault_seam is not None:
            self.fault_seam.after_checkpoint(path)

    def checkpoint(self, path: str) -> None:
        """Durably checkpoint the stacked device worlds WITHOUT draining:
        flush staged rows and the fence, write the .npz, keep serving.
        The periodic crash-recovery story — a kill→restore rebuilds a
        host from the latest checkpoint (serve/migrate.HostGroup)."""
        self._flush_ready("checkpoint")
        self.flush_journals()
        self._save_checkpoint(path)
        if GLOBAL_TELEMETRY.enabled:
            GLOBAL_TELEMETRY.record(
                "host_checkpointed", path=str(path),
                sessions=len(self._lanes),
            )

    def drain(self, checkpoint_path: Optional[str] = None) -> dict:
        """Graceful shutdown: stop admitting (attach raises HostFull),
        flush every staged row and the async fence, optionally checkpoint
        the stacked device worlds, and return a final summary. Sessions
        stay attached (detach them, or let the process exit). Raises
        DrainStalled (typed, with the stuck queue depth and fence state)
        if the flush cannot make progress."""
        self._draining = True
        self._flush_ready("drain")
        self.flush_journals()
        if checkpoint_path is not None:
            self._save_checkpoint(checkpoint_path)
        self._drained = True
        summary = self._host_section()
        summary["checkpoint"] = checkpoint_path
        if GLOBAL_TELEMETRY.enabled:
            GLOBAL_TELEMETRY.record(
                "host_drained", sessions=len(self._lanes),
                checkpoint=str(checkpoint_path),
            )
        return summary

    @property
    def draining(self) -> bool:
        return self._draining

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------

    def _host_section(self) -> dict:
        dev = self.device
        sessions = {}
        for key, lane in self._lanes.items():
            entry = {
                "kind": lane.kind,
                "slot": lane.slot,
                "state": lane.session.current_state().value,
                "current_frame": lane.current_frame,
                "staged_rows": len(lane.rows),
                "ticks_advanced": lane.ticks_advanced,
                "throttled_ticks": lane.throttled_ticks,
            }
            if self.mesh is not None:
                entry["shard"] = self.device.shard_of(lane.slot)
            if lane.last_error:
                entry["last_error"] = lane.last_error
            if lane.failed:
                entry["failed"] = True
            sessions[str(key)] = entry
        return {
            "active": len(self._lanes),
            "max_sessions": self.max_sessions,
            "draining": self._draining,
            "admitted": self.sessions_admitted,
            "rejected": self.sessions_rejected,
            "evicted": self.sessions_evicted,
            "disconnect_gced": self.sessions_gced,
            "desyncs_observed": self.desyncs_observed,
            "queue_depth": len(self._ready),
            "inflight_rows": dev.inflight_rows,
            "max_inflight_rows": self.max_inflight_rows,
            "megabatches": dev.megabatches,
            "rows_dispatched": dev.rows_dispatched,
            "mean_megabatch_rows": (
                round(dev.rows_dispatched / dev.megabatches, 3)
                if dev.megabatches
                else None
            ),
            "plan_signatures": len(dev.plan_cache.signatures),
            "buckets": list(dev.buckets),
            "session_shards": dev.session_shards,
            # device fault domains: quarantine/degrade/audit health
            "quarantines": self.quarantines_total,
            "device_faults": self.device_faults,
            "harvest_timeouts": self.harvest_timeouts,
            "invariant_trips": len(self.invariant_trips),
            "shedding_admission": self._shed_admission,
            # durable input journal (absent when no lane journals, so
            # old readers stay compatible)
            **(
                {
                    "journal": {
                        "lanes": sum(
                            1
                            for lane in self._lanes.values()
                            if lane.journal is not None
                        ),
                        "frames_journaled": sum(
                            lane.journal.writer.frames_journaled
                            for lane in self._lanes.values()
                            if lane.journal is not None
                        ),
                        "bytes_written": sum(
                            lane.journal.writer.bytes_written
                            for lane in self._lanes.values()
                            if lane.journal is not None
                        ),
                        "fsyncs": sum(
                            lane.journal.writer.fsyncs
                            for lane in self._lanes.values()
                            if lane.journal is not None
                        ),
                        "degraded": self.journal_lanes_degraded,
                    }
                }
                if self._journal_dir is not None
                or self.journal_lanes_degraded
                or any(
                    lane.journal is not None
                    for lane in self._lanes.values()
                )
                else {}
            ),
            **(
                {
                    "sdc_audit": {
                        "every": self._audit_every,
                        "sampled": self.audits_sampled,
                        "mismatches": self.audit_mismatches,
                        "pending": len(self._pending_audits),
                    }
                }
                if self._audit_every
                else {}
            ),
            # vectorized protocol plane (network/endpoint_batch.py):
            # row occupancy + pass counts of this host's pump fleet
            "endpoint_fleet": self._pump.fleet.stats(),
            "sessions": sessions,
            "envs": [env._env_section() for env in self._envs],
            # speculative bubble-filling hit rate and volume (absent on
            # non-speculating hosts, so old readers stay compatible)
            **(
                {"speculation": self._spec.section()}
                if self._spec is not None
                else {}
            ),
            # device-resident loop section (absent on dispatch-per-tick
            # hosts, so old readers stay compatible)
            **(
                {
                    "resident": {
                        "depth": self.resident_ticks,
                        "driver_dispatches": dev.driver_dispatches,
                        "vticks_executed": dev.vticks_executed,
                        "vticks_per_dispatch": (
                            round(
                                dev.vticks_executed
                                / dev.driver_dispatches,
                                3,
                            )
                            if dev.driver_dispatches
                            else None
                        ),
                        "mailbox_pending": dev.mailbox.pending_rows,
                        "mailbox_overflows": dev.mailbox.overflows,
                        "degraded": self._resident_degraded,
                        "drive_failures": self._drive_failures,
                    }
                }
                if self.resident
                else {}
            ),
        }

    @property
    def frames_served_from_speculation(self) -> int:
        """Frames adopted from speculative drafts (0 on a
        non-speculating host) — the gated live bench arm's headline."""
        return self._spec.frames_adopted if self._spec is not None else 0

    @property
    def spec_hit_rate(self) -> float:
        """Adopted / serveable frames (one member's window per draft;
        0.0 on a non-speculating host) — prediction quality, independent
        of the draft width."""
        if self._spec is None or not self._spec.frames_draftable:
            return 0.0
        return self._spec.frames_adopted / self._spec.frames_draftable

    # ------------------------------------------------------------------
    # input-model hot-swap (ggrs_tpu/learn/ deploy seam)
    # ------------------------------------------------------------------

    @property
    def input_model_version(self):
        """Registry version of the installed draft model (None on a
        non-speculating host or when drafting from the online model) —
        what the fleet heartbeat reports."""
        return self._spec.model_version if self._spec is not None else None

    def install_input_model(self, model, *, version=None) -> None:
        """Hot-swap the speculation draft model at a tick boundary:
        every lane drafts its NEXT draft from a clone of `model`
        (learn.ArrayInputModel — any InputHistoryModel works); None
        reverts to per-lane online models. Standing drafts keep
        standing and verify exactly as before — the model feeds only
        the draft seam, so the never-speculating twin is provably
        unaffected (the speculation parity suite pins this across the
        swap). Identity mismatches refuse typed before any lane is
        touched."""
        from ..errors import ModelIncompatible
        from ..learn.metrics import model_installs_total, model_version_gauge

        if self._spec is None:
            raise InvalidRequest(
                "install_input_model needs a speculation=True host"
            )
        if model is not None:
            found = (model.num_players, model.input_size)
            expected = (self._spec.num_players, self._spec.input_size)
            if found != expected:
                raise ModelIncompatible(
                    "input model (players, input_size) mismatch",
                    found=found, expected=expected,
                )
            if version is None:
                version = getattr(model, "version", None)
        self._spec.install_model(model, version=version)
        model_installs_total().inc()
        model_version_gauge().set(float(version or 0))
        if GLOBAL_TELEMETRY.enabled:
            GLOBAL_TELEMETRY.record(
                "input_model_installed",
                version=version,
                model_kind=getattr(model, "kind", None) if model is not None
                else "online",
                lanes=len(self._spec._lanes),
            )

    def export_input_model_state(self, key: Any) -> Optional[dict]:
        """A lane's learned input statistics by value (None when not
        speculating) — migration tickets carry this so the destination
        resumes speculation warm instead of relearning from zero."""
        if self._spec is None:
            return None
        return self._spec.export_model_state(key)

    def import_input_model_state(self, key: Any,
                                 state: Optional[dict]) -> bool:
        """Seed an adopted lane's model from exported statistics;
        incompatible exports degrade to a cold start, never an error."""
        if self._spec is None or not state:
            return False
        return self._spec.import_model_state(key, state)

    def telemetry(self) -> dict:
        """One structured snapshot: the process-wide obs snapshot
        (metrics incl. the host instruments, flight-recorder tail, tracer
        spans) plus a `host` section aggregating scheduler/lifecycle
        state and every hosted session's own session section."""
        snap = GLOBAL_TELEMETRY.snapshot()
        host = self._host_section()
        for key, lane in self._lanes.items():
            section_fn = getattr(
                lane.session, "_telemetry_session_section", None
            )
            if callable(section_fn):
                try:
                    host["sessions"][str(key)]["session"] = section_fn()
                except GGRSError:  # e.g. stats window too young
                    pass
        snap["host"] = host
        return snap
