"""Load generator for SessionHost: hundreds of scripted 2-4-player
matches over a lossy virtual network, driven in virtual time.

Every peer of every match attaches to ONE SessionHost, so the fleet's
simulation all runs on the shared device core — the megabatch-size
histogram then directly reads how well cross-session coalescing engages.
The network between peers is the seeded `InMemoryNetwork` fault model
(latency/jitter/loss), the clock a `FakeClock` the harness advances one
frame interval per host tick: the whole soak is deterministic per seed
and runs as fast as the host can pump, which is what bench and CI
smoke need.

Inputs are scripted per (match, peer, tick) from the seed, with desync
detection on — a zero-desync soak certifies that N concurrent sessions
multiplexed through one stacked device pytree stay bit-exact replicas
of each other.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from ..errors import ContractViolation
from ..network.sockets import InMemoryNetwork
from ..sessions.builder import SessionBuilder
from ..types import DesyncDetection, PlayerType, SessionState
from ..utils.clock import FakeClock
from .host import SessionHost

FRAME_MS = 16


def build_matches(
    host: SessionHost,
    net: InMemoryNetwork,
    clock,
    *,
    sessions: int,
    players_cycle=(2, 3, 4),
    max_prediction: int = 8,
    input_delay: int = 1,
    desync_interval: int = 10,
    seed: int = 0,
) -> List[List[Any]]:
    """Create full P2P constellations (every peer a hosted session) until
    at least `sessions` peers are attached; returns the host keys grouped
    by match. Match m's peer k lives at virtual address (m, k)."""
    matches: List[List[Any]] = []
    total = 0
    m = 0
    while total < sessions:
        n = players_cycle[m % len(players_cycle)]
        n = min(n, host.num_players, max(2, sessions - total))
        keys = []
        for k in range(n):
            b = (
                SessionBuilder(input_size=host.game.input_size)
                .with_num_players(n)
                .with_max_prediction_window(max_prediction)
                .with_input_delay(input_delay)
                .with_desync_detection_mode(
                    DesyncDetection.on(interval=desync_interval)
                )
                .with_clock(clock)
                .with_rng(random.Random((seed * 7919 + m * 131 + k) & 0xFFFF))
            )
            for h in range(n):
                if h == k:
                    b = b.add_player(PlayerType.local(), h)
                else:
                    b = b.add_player(PlayerType.remote((m, h)), h)
            sess = b.start_p2p_session(net.socket((m, k)))
            keys.append(host.attach(sess))
        matches.append(keys)
        total += n
        m += 1
    return matches


def sync_fleet(host, matches, clock, *, max_ticks: int = 800) -> None:
    """Pump the host until every hosted session reaches RUNNING."""
    for _ in range(max_ticks):
        host.tick()
        clock.advance(FRAME_MS)
        if all(
            host.session(k).current_state() == SessionState.RUNNING
            for keys in matches
            for k in keys
        ):
            return
    raise ContractViolation(
        f"fleet of {sum(len(m) for m in matches)} sessions failed to "
        f"synchronize within {max_ticks} ticks"
    )


def make_scripts(matches, ticks: int, seed: int) -> Dict[Any, List[int]]:
    """Deterministic per-(match, peer, tick) input scripts."""
    rng = random.Random(seed ^ 0x5EED)
    return {
        (m, k): [rng.randrange(0, 16) for _ in range(ticks)]
        for m, keys in enumerate(matches)
        for k in range(len(keys))
    }


def held_scripts(matches, ticks: int, seed: int) -> Dict[Any, List[int]]:
    """Hold-shaped per-(match, peer, tick) scripts: runs of held values
    cycling a fixed per-peer sequence — hold lengths vary (seeded, 6-18
    frames: direction keys held across a dozen frames, the shape real
    input streams have), the value TRANSITIONS are deterministic. The
    human-shaped traffic the speculation input model can actually learn:
    stalls landing inside a hold recover with the prediction intact (the
    lineage member serves them); stalls crossing a switch need a timing
    bet. THE one definition — bench_spec_bubble and spec_smoke must
    starve against identical traffic shapes."""
    out: Dict[Any, List[int]] = {}
    for m, keys in enumerate(matches):
        for k in range(len(keys)):
            rng = random.Random(seed * 7919 + m * 131 + k)
            cycle = [1, 4, 2, 8, 5][(m + k) % 3:][:3]
            vals: List[int] = []
            i = 0
            while len(vals) < ticks:
                vals += [cycle[i % len(cycle)]] * rng.randrange(6, 19)
                i += 1
            out[(m, k)] = vals[:ticks]
    return out


def starve_on_tick(net, matches, *, hole_every: int, hole_len: int):
    """`drive_scripted` on_tick hook forcing input starvation: peer 0 of
    every match goes dark (blackholed) for `hole_len` ticks every
    `hole_every` — the WAN-outage shape that stalls the other peers past
    the prediction gate. THE one definition — bench_spec_bubble,
    spec_smoke and the speculation parity suite must starve against
    identical traffic."""
    holes = [(m, 0) for m in range(len(matches))]

    def on_tick(t):
        if hole_every and t > 0 and t % hole_every == 0:
            net.set_blackhole(holes, True)
        if hole_every and t % hole_every == hole_len:
            net.set_blackhole(holes, False)

    return on_tick


def drive_scripted(host, matches, clock, scripts, ticks: int,
                   on_tick=None) -> List[Any]:
    """Submit every peer's scripted input and tick the host `ticks`
    times; returns the (key, event) DesyncDetected pairs observed. The
    shared drive loop of run_loadgen and bench.bench_serve_host.
    `on_tick(t)` runs at the top of each tick — the seam fault-injection
    harnesses hook (the full chaos driver with migrations/kills lives in
    serve/chaos.py)."""
    desyncs: List[Any] = []
    for t in range(ticks):
        if on_tick is not None:
            on_tick(t)
        for m, keys in enumerate(matches):
            for k, key in enumerate(keys):
                host.submit_input(key, k, bytes([scripts[(m, k)][t]]))
        events = host.tick()
        for key, evs in events.items():
            desyncs += [
                (key, e) for e in evs
                if type(e).__name__ == "DesyncDetected"
            ]
        clock.advance(FRAME_MS)
    return desyncs


def run_loadgen(
    *,
    sessions: int = 64,
    ticks: int = 120,
    game=None,
    entities: int = 16,
    max_players: int = 4,
    max_prediction: int = 8,
    latency_ms: int = 20,
    jitter_ms: int = 10,
    loss: float = 0.05,
    duplicate: float = 0.0,
    profile=None,
    seed: int = 0,
    host: Optional[SessionHost] = None,
    max_inflight_rows: Optional[int] = None,
    idle_timeout_ms: int = 0,
    warmup: bool = True,
    sync_ticks: int = 400,
    batched: bool = True,
) -> Dict[str, Any]:
    """Spin up >= `sessions` scripted peers in 2-4-player matches on one
    SessionHost over a seeded lossy InMemoryNetwork and drive them
    `ticks` host ticks in virtual time. Returns a JSON-able report:
    desyncs, per-session progress, megabatch shape, queue behavior.

    `host=None` builds one sized to the fleet (ExGame by default);
    passing a host lets bench arms reuse a warmed core across runs.
    `profile` plugs a per-link FaultProfile (e.g. serve.chaos.WanProfile)
    into the virtual network in place of the flat latency/jitter/loss
    knobs — WAN-shaped soaks without the full chaos schedule.
    `batched=False` builds the host with the legacy per-message pump
    (and pins every attached session legacy too) — the parity/bench
    reference arm against the batched + vectorized protocol plane."""
    clock = FakeClock()
    net = InMemoryNetwork(
        clock,
        latency_ms=latency_ms,
        jitter_ms=jitter_ms,
        loss=loss,
        duplicate=duplicate,
        seed=seed,
        profile=profile,
    )
    if host is None:
        if game is None:
            from ..models.ex_game import ExGame

            game = ExGame(num_players=max_players, num_entities=entities)
        host = SessionHost(
            game,
            max_prediction=max_prediction,
            num_players=max_players,
            max_sessions=sessions + max_players,  # room for the last match
            max_inflight_rows=max_inflight_rows,
            clock=clock,
            idle_timeout_ms=idle_timeout_ms,
            warmup=warmup,
            batched_pump=batched,
        )
    matches = build_matches(
        host,
        net,
        clock,
        sessions=sessions,
        max_prediction=max_prediction,
        seed=seed,
    )
    n_sessions = sum(len(keys) for keys in matches)

    # --- synchronization phase: pump until every session is RUNNING
    sync_fleet(host, matches, clock, max_ticks=sync_ticks)

    # --- scripted drive: every peer submits its scripted input each tick;
    # the host advances whoever is ready and megabatches the rest
    scripts = make_scripts(matches, ticks, seed)
    desyncs = drive_scripted(host, matches, clock, scripts, ticks)

    # --- cooldown: let in-flight inputs and checksum reports land so the
    # final comparison intervals actually run
    for _ in range(3 * max_prediction):
        events = host.tick()
        for key, evs in events.items():
            desyncs += [
                (key, e) for e in evs
                if type(e).__name__ == "DesyncDetected"
            ]
        clock.advance(FRAME_MS)

    dev = host.device
    frames = [host._lanes[k].current_frame for keys in matches for k in keys]
    checksums_published = sum(
        len(getattr(host.session(k), "local_checksum_history", ()))
        for keys in matches
        for k in keys
    )
    report = {
        "sessions": n_sessions,
        "matches": len(matches),
        "ticks": ticks,
        "seed": seed,
        "loss": loss,
        "latency_ms": latency_ms,
        "jitter_ms": jitter_ms,
        "desyncs": len(desyncs),
        "checksums_published": checksums_published,
        "min_frame": min(frames),
        "max_frame": max(frames),
        "megabatches": dev.megabatches,
        "rows_dispatched": dev.rows_dispatched,
        "mean_megabatch_rows": (
            round(dev.rows_dispatched / dev.megabatches, 3)
            if dev.megabatches
            else 0.0
        ),
        "max_bucket": max(
            (bucket for bucket, _, _ in dev.megabatch_programs()),
            default=0,
        ),
        "plan_signatures": len(dev.plan_cache.signatures),
        "host": host._host_section(),
    }
    report["_host"] = host  # live handle for callers; strip before JSON
    return report
