"""Deterministic device-domain fault injection: the seam between the
chaos harness and the failure modes wire-level chaos can't reach.

The WAN chaos loadgen (serve/chaos.py) makes the *wire* lie and the
fleet chaos (fleet/chaos.py) makes *processes* die, but both leave the
device data plane perfect: every dispatch succeeds, every readback
returns, every byte the accelerator computes is correct. Real
accelerators break all three — XLA runtime failures, wedged readbacks,
and silent data corruption (SDC) — and a serving stack's answer to
them is a correctness surface. This module makes those failures
injectable, seeded and replayable:

  * `FaultPlan` — the schedule: a pure function of (seed, knobs) mapping
    host tick -> faults to fire, built once at construction so a fault
    run replays bit-identically per seed. `FaultPlan.smoke()` is the
    canonical "at least one of every kind" schedule the --fault-smoke
    gate and the acceptance soak drive.
  * `FaultInjector` — the arm: installs itself as the host's and the
    device core's `fault_seam` and fires the plan's faults at the
    boundaries the core/host consult (dispatch entry, resident drive,
    harvest/readback, mailbox staging, checkpoint write) plus direct
    state corruption (`inject_slot_bitflip`).

Fault kinds (docs/DESIGN.md "Device fault domains" has the taxonomy
table and each kind's recovery ladder):

  dispatch_raise     a dispatch/drive raises DeviceDispatchFailed
                     BEFORE executing (worlds untouched) — one-shot
                     (transient: the host retries) or persistent on a
                     victim slot (the host quarantines the slot and
                     re-dispatches survivors)
  harvest_timeout    the next checksum harvest raises HarvestTimeout —
                     the host's drain pass skips a tick; checkpoint /
                     export block-and-retry
  mailbox_storm      the next N mailbox stages report their lane full —
                     a burst of forced early drives (commit overflow
                     storm); inputs are never dropped
  checkpoint_corrupt the next durable checkpoint write is truncated
                     after landing — restore must detect it as typed
                     CheckpointIncompatible, never a shape error
  slot_bitflip       one bit of a victim slot's live world (or a ring
                     row) flips on device — SDC; the sampled audit lane
                     must catch it within its sampling bound and
                     quarantine the slot

Every fault the injector fires is recorded (kind, tick, target) so a
soak can assert the blast radius: survivors bit-exact vs an unfaulted
twin, every quarantine surfaced as a typed SlotPoisoned + forensics
bundle.
"""

from __future__ import annotations

import os
import random
from typing import Any, Dict, List, Optional, Sequence

from ..errors import DeviceDispatchFailed, HarvestTimeout
from ..obs import GLOBAL_TELEMETRY

FAULT_KINDS = (
    "dispatch_raise",
    "harvest_timeout",
    "mailbox_storm",
    "checkpoint_corrupt",
    "slot_bitflip",
)

# opt-in extension kinds: not in the every-kind default schedule
# (FaultPlan()/FaultPlan.smoke() fire each of FAULT_KINDS, whose blast
# radii exist on every host), scheduled by passing `kinds=` explicitly:
#
#   journal_stall   the next N durable-journal appends are refused as
#                   if the disk were full (typed JournalStalled at the
#                   host tap) — the storage tier's ENOSPC arm; the host
#                   must degrade the lane to unjournaled with an
#                   invariant trip, never wedge. Vacuous on a host with
#                   no journaled lanes, hence opt-in.
EXTENSION_FAULT_KINDS = ("journal_stall",)
ALL_FAULT_KINDS = FAULT_KINDS + EXTENSION_FAULT_KINDS


class Fault:
    """One scheduled device fault: fire at `tick`, of `kind`, with
    kind-specific `params` (persist=, storm_len=, ...)."""

    __slots__ = ("tick", "kind", "params")

    def __init__(self, tick: int, kind: str, **params: Any):
        assert kind in ALL_FAULT_KINDS, f"unknown fault kind {kind!r}"
        self.tick = tick
        self.kind = kind
        self.params = params

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Fault({self.tick}, {self.kind!r}, {self.params})"


class FaultPlan:
    """A seeded, replayable device-fault schedule. The schedule is fully
    materialized at construction — a pure function of (seed, knobs) —
    so two runs of the same plan fire identical faults at identical
    ticks whatever the host does in between."""

    def __init__(self, seed: int, ticks: int, *,
                 kinds: Sequence[str] = FAULT_KINDS,
                 events_per_kind: int = 1,
                 start: int = 1,
                 persist_dispatch: bool = True,
                 storm_len: int = 6):
        """`events_per_kind` faults of every kind in `kinds`, spread
        over [start, ticks) at seeded-jittered positions.
        `persist_dispatch`: dispatch_raise faults pin a victim slot and
        keep firing until it is quarantined (the containment story);
        False makes them one-shot transients (the retry story).
        `storm_len`: consecutive stages each mailbox_storm forces into
        the overflow path."""
        assert ticks > start >= 0
        self.seed = seed
        self.ticks = ticks
        self.kinds = tuple(kinds)
        rng = random.Random(seed ^ 0xFA17)
        faults: List[Fault] = []
        span = max(ticks - start, 1)
        for kind in self.kinds:
            assert kind in ALL_FAULT_KINDS, f"unknown fault kind {kind!r}"
            for i in range(events_per_kind):
                # one fault per evenly-sized stripe, jittered inside it,
                # so multiple events of a kind can't pile on one tick
                lo = start + (span * i) // events_per_kind
                hi = start + (span * (i + 1)) // events_per_kind
                t = rng.randrange(lo, max(hi, lo + 1))
                params: Dict[str, Any] = {}
                if kind == "dispatch_raise":
                    params["persist"] = persist_dispatch
                elif kind == "mailbox_storm":
                    params["storm_len"] = storm_len
                faults.append(Fault(t, kind, **params))
        self._by_tick: Dict[int, List[Fault]] = {}
        for f in sorted(faults, key=lambda f: f.tick):
            self._by_tick.setdefault(f.tick, []).append(f)

    @classmethod
    def smoke(cls, seed: int, ticks: int, **kw: Any) -> "FaultPlan":
        """The canonical gate schedule: >= 1 of EVERY fault kind."""
        return cls(seed, ticks, kinds=FAULT_KINDS, **kw)

    def at(self, tick: int) -> List[Fault]:
        return self._by_tick.get(tick, [])

    def all_faults(self) -> List[Fault]:
        return [f for fs in self._by_tick.values() for f in fs]

    def section(self) -> dict:
        return {
            "seed": self.seed,
            "ticks": self.ticks,
            "schedule": [
                {"tick": f.tick, "kind": f.kind, **f.params}
                for f in self.all_faults()
            ],
        }


def faults_injected_counter():
    """Get-or-create THE injected-fault counter — shared by the
    injector and the smoke gates that assert on it."""
    return GLOBAL_TELEMETRY.registry.counter(
        "ggrs_faults_injected_total",
        "device-domain faults fired by the deterministic injection seam",
        ("kind",),
    )


class FaultInjector:
    """Arms a FaultPlan against one SessionHost: installs itself as the
    host's and the device core's `fault_seam`, then `advance(tick)` —
    called once per host tick by the drive loop — fires that tick's
    faults. Victim slots draw from the injector's own seeded rng over
    `victims` (host keys; default: every p2p lane at arm time), so the
    blast radius is confinable and the whole run replays per seed."""

    def __init__(self, host, plan: FaultPlan, *,
                 victims: Optional[Sequence[Any]] = None):
        self.host = host
        self.plan = plan
        self.victims = list(victims) if victims is not None else None
        self._rng = random.Random(plan.seed ^ 0x51C)
        self.installed = False
        # armed state the seam callbacks consume
        self._dispatch_armed: List[dict] = []  # {slot, persist}
        self._harvest_armed = 0
        self._storm_remaining = 0
        self._checkpoint_armed = 0
        self._journal_armed = 0
        # observability: everything fired, for blast-radius assertions
        self.fired: Dict[str, int] = {k: 0 for k in ALL_FAULT_KINDS}
        self.bitflips: List[dict] = []  # {tick, key, slot, frame}
        self.corrupted_checkpoints: List[str] = []
        self._m_fired = faults_injected_counter()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def install(self) -> "FaultInjector":
        assert not self.installed
        assert self.host.fault_seam is None, "host already has a seam"
        self.host.fault_seam = self
        self.host.device.fault_seam = self
        self.installed = True
        return self

    def uninstall(self) -> None:
        if self.installed:
            self.host.fault_seam = None
            self.host.device.fault_seam = None
            self.installed = False

    # ------------------------------------------------------------------
    # the per-tick arm (the drive loop's on_tick hook calls this)
    # ------------------------------------------------------------------

    def _pick_victim(self):
        """One (key, lane) draw from the victim pool — seeded, so the
        run replays. Only lanes still ACTIVELY dispatching are
        eligible: a lane wedged at the prediction gate (e.g. because an
        EARLIER fault quarantined its match sibling) stages no rows, so
        a fault pinned on it could never fire. None when nothing is
        eligible."""
        lanes = self.host._lanes
        pool = [
            k for k in (
                self.victims if self.victims is not None else list(lanes)
            )
            if k in lanes and not lanes[k].failed
            and lanes[k].kind == "p2p" and not lanes[k].starved
        ]
        if not pool:
            return None
        key = pool[self._rng.randrange(len(pool))]
        return key, lanes[key]

    def advance(self, tick: int) -> None:
        for fault in self.plan.at(tick):
            arm = getattr(self, f"_arm_{fault.kind}")
            arm(tick, fault)

    def _note(self, kind: str) -> None:
        self.fired[kind] += 1
        if GLOBAL_TELEMETRY.enabled:
            self._m_fired.labels(kind).inc()
            GLOBAL_TELEMETRY.record("fault_injected", fault=kind)

    def _arm_dispatch_raise(self, tick: int, fault: Fault) -> None:
        victim = self._pick_victim()
        # a victimless fault is ALWAYS one-shot: an unattributed
        # persistent failure has no slot for dispatch_cleared to clear
        # and no culprit for the host to quarantine, so persisting it
        # would raise out of every future dispatch and take the whole
        # host down — exactly what the ladder exists to prevent
        self._dispatch_armed.append({
            "slot": victim[1].slot if victim is not None else None,
            "key": victim[0] if victim is not None else None,
            "persist": bool(fault.params.get("persist", False))
            and victim is not None,
        })

    def _arm_harvest_timeout(self, tick: int, fault: Fault) -> None:
        self._harvest_armed += 1

    def _arm_mailbox_storm(self, tick: int, fault: Fault) -> None:
        self._storm_remaining += int(fault.params.get("storm_len", 6))

    def _arm_checkpoint_corrupt(self, tick: int, fault: Fault) -> None:
        self._checkpoint_armed += 1

    def _arm_journal_stall(self, tick: int, fault: Fault) -> None:
        self._journal_armed += int(fault.params.get("appends", 1))

    def _arm_slot_bitflip(self, tick: int, fault: Fault) -> None:
        """SDC fires immediately: flip one seeded bit of the victim's
        device residue. Default target is a SETTLED snapshot-ring row —
        a few frames behind the live one, so the next rollbacks neither
        re-save (heal) nor load it immediately — which the audit lane's
        recorded-checksum sweep catches deterministically within its
        sampling cadence (live-world flips heal at the next full-state
        rollback resim, so 'state' targets race the healing; see
        docs/DESIGN.md for the cadence math)."""
        victim = self._pick_victim()
        if victim is None:
            return
        key, lane = victim
        target = fault.params.get("target", "ring")
        ring_len = self.host.device.core.ring_len
        ring_slot = None
        if target == "ring":
            ring_slot = max(lane.current_frame - 3, 0) % ring_len
        # suspend the dispatch seam while injecting: the flip's own
        # fence/mailbox flush drives the device, and an armed dispatch
        # fault firing INSIDE advance() would raise out of the injector
        # instead of at the host's recovery ladder
        self.host.device.fault_seam = None
        try:
            desc = self.host.device.inject_slot_bitflip(
                lane.slot, seed=self._rng.randrange(1 << 30),
                target=target, ring_slot=ring_slot,
            )
        finally:
            self.host.device.fault_seam = self
        self.bitflips.append({
            "tick": tick, "key": key, "slot": lane.slot,
            "frame": lane.current_frame, **desc,
        })
        self._note("slot_bitflip")

    # ------------------------------------------------------------------
    # seam callbacks — the device core / host consult these
    # ------------------------------------------------------------------

    def before_dispatch(self, op: str, slots: Sequence[int]) -> None:
        """Device-core seam, consulted at every dispatch/drive entry
        BEFORE the program runs (worlds untouched on raise). `slots` is
        the batch's live LOGICAL slots."""
        live = set(int(s) for s in slots)
        for armed in list(self._dispatch_armed):
            slot = armed["slot"]
            if slot is not None and slot not in live:
                continue
            if not armed["persist"]:
                self._dispatch_armed.remove(armed)
            self._note("dispatch_raise")
            raise DeviceDispatchFailed(
                "injected device runtime failure",
                op=op,
                slots=() if slot is None else (slot,),
                injected=True,
            )

    def dispatch_cleared(self, slot: int) -> None:
        """The host quarantined `slot`: persistent dispatch faults
        pinned on it stop firing (the fault 'lives in the slot')."""
        self._dispatch_armed = [
            a for a in self._dispatch_armed if a["slot"] != slot
        ]

    def before_journal_append(self, path: str) -> None:
        """Host seam, consulted before each journal frontier drain:
        raises the simulated disk refusal (the host tap degrades the
        lane to unjournaled — typed, with an invariant trip — and
        serving continues untouched)."""
        if self._journal_armed > 0:
            self._journal_armed -= 1
            self._note("journal_stall")
            from ..errors import JournalStalled

            raise JournalStalled(
                "injected filesystem refusal (ENOSPC)",
                path=path, errno=28,
            )

    def before_harvest(self, op: str, pending: int = 0) -> None:
        """Host seam, consulted before checksum readbacks resolve."""
        if self._harvest_armed > 0:
            self._harvest_armed -= 1
            self._note("harvest_timeout")
            raise HarvestTimeout(
                "injected readback timeout", op=op, pending=pending,
            )

    def on_stage(self, phys: int) -> bool:
        """Device-core seam, consulted per mailbox stage: True forces
        the overflow path (note_overflow + drive first) as if the lane
        were full — the commit overflow storm."""
        if self._storm_remaining > 0:
            self._storm_remaining -= 1
            self._note("mailbox_storm")
            return True
        return False

    def after_checkpoint(self, path: str) -> None:
        """Host seam, consulted after a durable checkpoint lands:
        truncates the file to simulate a torn/corrupted write that
        slipped past the filesystem. load_device_checkpoint's manifest
        check must surface it as typed CheckpointIncompatible."""
        if self._checkpoint_armed <= 0:
            return
        self._checkpoint_armed -= 1
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
        self.corrupted_checkpoints.append(path)
        self._note("checkpoint_corrupt")

    # ------------------------------------------------------------------

    def section(self) -> dict:
        return {
            "seed": self.plan.seed,
            "fired": dict(self.fired),
            "bitflips": list(self.bitflips),
            "corrupted_checkpoints": list(self.corrupted_checkpoints),
            "armed_dispatch": len(self._dispatch_armed),
        }
