"""Device backend: fused rollback/resimulation on TPU via jit + lax.scan.

Importing this subpackage imports jax.
"""

from .backend import SnapshotRef, TpuRollbackBackend
from .resim import ResimCore
from .sync_test import TpuSyncTestSession

__all__ = ["ResimCore", "SnapshotRef", "TpuRollbackBackend", "TpuSyncTestSession"]
