"""Device backend: fused rollback/resimulation on TPU via jit + lax.scan.

Importing this subpackage imports jax.
"""

# GGRS_SANITIZE=1 wraps jax.jit BEFORE any backend constructs a program,
# so every compile in the process carries stack provenance
# (analysis/sanitize.py); a no-op otherwise
from ..analysis.sanitize import maybe_install_from_env as _maybe_sanitize

_maybe_sanitize()

from .backend import (
    MultiSessionDeviceCore,
    ShardedMultiSessionDeviceCore,
    SnapshotRef,
    TpuRollbackBackend,
)
from .resim import ResimCore
from .sync_test import TpuSyncTestSession

__all__ = [
    "MultiSessionDeviceCore",
    "ResimCore",
    "ShardedMultiSessionDeviceCore",
    "SnapshotRef",
    "TpuRollbackBackend",
    "TpuSyncTestSession",
]
