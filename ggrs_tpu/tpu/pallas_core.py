"""Pallas TPU kernel for the fused SyncTest hot loop — model-generic.

The XLA scan in TpuSyncTestSession spends most of each tick on per-op
overhead: the world state is small, so the ~60 small int ops per step plus
ring/history bookkeeping cost far more than the math. This kernel runs the
ENTIRE batch — T ticks, each with its forced `check_distance`-frame
rollback, resimulation, snapshot-ring writes, on-device checksums and
first-seen history comparison — as ONE pallas_call with every carry buffer
resident in VMEM/SMEM, written in place via input/output aliasing.

Semantics are bit-identical to TpuSyncTestSession._tick (tests enforce
carry-level parity): same masked rollback, same first-seen checksum history,
same mismatch latch, and the same step math as the model's `_step_generic`
with all-CONFIRMED statuses (the only configuration the fused SyncTest
uses). Reference semantics anchor: src/sessions/sync_test_session.rs:85-146.

The kernel scaffolding (ring, history, checksum, tick loop) is MODEL-
GENERIC; per-model code is confined to a small `PlaneAdapter` that (a)
declares how the model's state pytree packs into (N/128, 128) int32 planes
and (b) re-states the model's step on those planes. The checksum needs no
per-model code at all: its word weights are derived from the model's
`checksum_keys` declaration, reproducing `_checksum_generic` bit-for-bit.
Adapters ship for all three model families (ex_game; arena — including its
2-byte analog-throttle inputs; swarm — [N,3] vector planes); third-party
models register via `register_adapter`. The full contract is documented in
docs/DESIGN.md ("The plane-adapter contract").

Layout: entity arrays are packed to (N/128, 128) int32 tiles, the snapshot
ring to (ring_len, N/128, 128); inputs, the input ring, the checksum
history and frame/mismatch scalars live in SMEM. Unsigned checksum math is
done in int32 (two's-complement wraparound is bit-identical) and bitcast
back to uint32 at the boundary.

Supported configuration: N % 128 == 0, unsharded, any input_size. The XLA
path remains the fallback (and the sharded/multi-chip implementation).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import ConfigError, RegistryMiss
from ..ops import fixed_point as fx

GOLDEN = np.int32(np.uint32(fx.GOLDEN32).view(np.int32))

# THE single-tile envelope for reduction-phase adapters (whole world as
# one VMEM tile, in+out windows): shared by the tick, beam, and tiled
# kernels' admission asserts and by ResimCore's backend auto-selection —
# the same figure the whole-batch kernel's VMEM_BUDGET_BYTES validates.
# One constant so tuning it cannot desynchronize the kernels.
WHOLE_WORLD_TILE_BUDGET = 96 * 1024 * 1024


def _wrap_i32(x: int) -> np.int32:
    """Two's-complement int32 wrap of a Python int (numpy scalar overflow
    wraps too, but emits RuntimeWarning; this is exact and silent)."""
    x &= 0xFFFFFFFF
    return np.int32(x - (1 << 32) if x >= (1 << 31) else x)


def _exact_floor_div(a, b):
    """floor(a / b) for int32 a (|a| < 2^24), b in [1, 2^12], branch-free.

    TPU vector units have no integer divide; a float32 estimate is within a
    few ULP (even with reciprocal-based division), and three integer fixup
    rounds make the result the exact floor regardless of rounding mode —
    the determinism contract requires exactness, not speed of convergence.
    """
    q = jnp.floor(a.astype(jnp.float32) / b.astype(jnp.float32)).astype(jnp.int32)
    for _ in range(3):
        r = a - q * b
        q = q + (r >= b).astype(jnp.int32) - (r < 0).astype(jnp.int32)
    return q


def _exact_floor_div_wide(a, b):
    """floor(a / b) for int32 a (|a| < 2^30), b in [1, 2^16).

    Wider-range variant for reductions (e.g. centroid sums): the float32
    estimate can be off by ~|a|/2^23 >> 1, so ±1 fixups alone can't close
    it. Two residual re-estimates shrink the error multiplicatively to
    <= 1, then ±1 fixups make it the exact floor. All intermediates stay
    within int32 (|q*b| ~ |a| and the residual is <= b * error)."""
    q = jnp.floor(a.astype(jnp.float32) / b.astype(jnp.float32)).astype(jnp.int32)
    for _ in range(2):
        r = a - q * b
        q = q + jnp.floor(
            r.astype(jnp.float32) / b.astype(jnp.float32)
        ).astype(jnp.int32)
    for _ in range(2):
        r = a - q * b
        q = q + (r >= b).astype(jnp.int32) - (r < 0).astype(jnp.int32)
    return q


def _isqrt24(n):
    """fx.isqrt24 verbatim (12 unrolled digit iterations), jnp ops."""
    x = n
    c = jnp.zeros_like(n)
    d = 1 << 22
    for _ in range(12):
        cd = c + d
        cond = x >= cd
        x = jnp.where(cond, x - cd, x)
        c = jnp.where(cond, (c >> 1) + d, c >> 1)
        d >>= 2
    return c


def _select_by_owner(owner, values):
    """Per-entity select of a per-player value without a gather (dynamic
    gathers don't vectorize on the VPU): values is a length-P list of
    scalars/planes; returns where(owner==p, values[p])."""
    out = jnp.zeros_like(owner)
    for p, v in enumerate(values):
        out = jnp.where(owner == p, v, out)
    return out


class KernelCtx:
    """Loop-invariant planes + TPU-safe integer helpers handed to a
    PlaneAdapter's step: `gi` is the global entity index plane, `owner`
    the owning-player plane (gi % num_players)."""

    def __init__(self, gi, owner):
        self.gi = gi
        self.owner = owner
        self.floor_div = _exact_floor_div
        self.floor_div_wide = _exact_floor_div_wide
        self.isqrt24 = _isqrt24
        self.select_by_owner = _select_by_owner

    def clamp_speed(self, components, max_speed):
        """Vector-magnitude clamp, any dimensionality: scale `components`
        (a list of int32 planes) down to |v| <= max_speed via integer sqrt
        + exact floor division. Caller must keep m2 = sum(c^2) < 2^24
        (isqrt24's domain) and c*max_speed < 2^24 with the magnitude <
        2^12 (floor_div's contract) — true for every shipped model's
        speed envelope."""
        m2 = components[0] * components[0]
        for c in components[1:]:
            m2 = m2 + c * c
        mag = self.isqrt24(m2)
        over = m2 > max_speed * max_speed
        safe = jnp.where(mag == 0, 1, mag)
        return [
            jnp.where(over, self.floor_div(c * max_speed, safe), c)
            for c in components
        ]


class PlaneAdapter:
    """Maps a DeviceGame onto packed planes for the pallas kernel.

    Subclasses declare:
      planes: ordered tuple of (plane_name, state_key, component) —
        component is None for [N] state arrays, an int for [N, w] arrays.
        Plane order MUST follow the game's `checksum_keys` concatenation
        order (key by key, components 0..w-1) so the generically derived
        checksum weights reproduce the model's `_checksum_generic`
        word-for-word; __init__ validates this.
      step(planes, inputs, ctx) -> planes: the model's `_step_generic`
        re-stated on (rows, 128) int32 planes, all-CONFIRMED statuses.
        `inputs` is a [num_players][input_size] nested list of scalar int32
        bytes; `ctx` is a KernelCtx. The state's `frame` scalar is managed
        by the scaffolding (tick-frame invariant), not the adapter.
    """

    planes: Tuple[Tuple[str, str, Optional[int]], ...]
    # True iff the step is per-entity independent (no cross-entity
    # reductions): unlocks the entity-tiled kernel (pallas_tiled), which
    # runs the time loop inside per-tile VMEM at any world size
    tileable = False
    # The REDUCTION PHASE of the contract: number of cross-entity int32
    # reduction scalars the step consumes (0 = none). Adapters with
    # reduce_len > 0 implement reduce_partial (raw masked sums over the
    # VISIBLE entities — complete when the caller sees the whole world,
    # per-shard partials to be psum'd otherwise) and reduce_finalize (the
    # exact-division post-math turning complete sums into the values step
    # consumes), and accept red= in step. Kernels with whole-world
    # visibility (the whole-batch kernel, single-tile gridded kernels)
    # may run such adapters; entity-sharded/multi-tile execution may not
    # feed them local-only sums — the time-inside-tile grid order is
    # fundamentally incompatible with a frontier step that needs all
    # tiles' data (see docs/DESIGN.md).
    reduce_len = 0

    def __init__(self, game):
        self.game = game
        keys_in_order = []
        for _, key, _ in self.planes:
            if key not in keys_in_order:
                keys_in_order.append(key)
        assert tuple(keys_in_order) == tuple(game.checksum_keys), (
            f"plane order {keys_in_order} must follow checksum_keys "
            f"{game.checksum_keys}"
        )

    def step(self, planes: Dict[str, Any], inputs: List[List[Any]],
             ctx: KernelCtx, red=None) -> Dict[str, Any]:
        """`red`: finalized reduction values for the state ENTERING the
        step (reduce_finalize output). None means compute them inline from
        `planes` — only legal with whole-world visibility."""
        raise NotImplementedError

    def reduce_partial(self, planes: Dict[str, Any], ctx: KernelCtx):
        """Raw cross-entity reduction sums (list of reduce_len int32
        scalars) over the entities visible in `planes`. Sums only — they
        must commute across tiles/shards so callers can accumulate or
        psum them before finalizing."""
        raise NotImplementedError

    def reduce_finalize(self, raw, ctx: KernelCtx):
        """Turn COMPLETE reduction sums into the values step consumes
        (e.g. exact-division centroids). Pure scalar math."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Model adapters
# ---------------------------------------------------------------------------


class ExGamePlanes(PlaneAdapter):
    """ggrs_tpu.models.ex_game._step_generic on packed planes."""

    tileable = True  # pure per-entity physics, per-entity checksum terms
    planes = (
        ("px", "pos", 0), ("py", "pos", 1),
        ("vx", "vel", 0), ("vy", "vel", 1),
        ("rot", "rot", None),
    )

    def step(self, pl, inputs, ctx, red=None):
        for _ in range(getattr(self.game, "substeps", 1)):
            pl = self._substep(pl, inputs, ctx)
        return pl

    def _substep(self, pl, inputs, ctx):
        from ..models import ex_game

        px, py = pl["px"], pl["py"]
        vx, vy, rot = pl["vx"], pl["vy"], pl["rot"]
        inp = ctx.select_by_owner(ctx.owner, [b[0] for b in inputs])

        up = (inp & ex_game.INPUT_UP) != 0
        down = (inp & ex_game.INPUT_DOWN) != 0
        left = (inp & ex_game.INPUT_LEFT) != 0
        right = (inp & ex_game.INPUT_RIGHT) != 0

        vx = (vx * ex_game.FRICTION_NUM) >> 8
        vy = (vy * ex_game.FRICTION_NUM) >> 8

        thrust = jnp.where(up & ~down, 1, 0) + jnp.where(down & ~up, -1, 0)
        cos_t = fx.cos16(rot, jnp)
        sin_t = fx.sin16(rot, jnp)
        dvx = (ex_game.MOVE_SPEED * cos_t) >> fx.TRIG_SCALE_BITS
        dvy = (ex_game.MOVE_SPEED * sin_t) >> fx.TRIG_SCALE_BITS
        vx = vx + thrust * dvx
        vy = vy + thrust * dvy

        turn = jnp.where(left & ~right, -ex_game.ROT_SPEED, 0) + jnp.where(
            right & ~left, ex_game.ROT_SPEED, 0
        )
        rot = (rot + turn) & (fx.ANGLE_MOD - 1)

        vx, vy = ctx.clamp_speed([vx, vy], ex_game.MAX_SPEED)

        px = jnp.clip(px + vx, 0, ex_game.MAX_X)
        py = jnp.clip(py + vy, 0, ex_game.MAX_Y)
        return {"px": px, "py": py, "vx": vx, "vy": vy, "rot": rot}


class ArenaPlanes(PlaneAdapter):
    """ggrs_tpu.models.arena._step_generic on packed planes, including the
    cross-entity per-team centroid reductions (full-plane sums -> SMEM
    scalars -> broadcast back, the in-kernel form of the collective) and
    the optional 2-byte analog-throttle inputs.

    The centroids ride the contract's reduction phase: reduce_partial
    emits per-team [count, sum_x, sum_y] masked sums, reduce_finalize
    does the exact-division centroid math, and step accepts the result
    via red= — so kernels can cache/psum per-frame reductions instead of
    recomputing 3P full-plane sums at every (re)simulated step."""

    planes = (
        ("px", "pos", 0), ("py", "pos", 1),
        ("vx", "vel", 0), ("vy", "vel", 1),
        ("hp", "hp", None), ("energy", "energy", None),
    )

    def __init__(self, game):
        super().__init__(game)
        # the centroid division runs through _exact_floor_div_wide, whose
        # verified contract is b in [1, 2^16) and |a| < 2^30: per-team live
        # counts (the divisor) are bounded by ceil(N/P), and the centroid
        # sums by count * (ARENA_MASK >> CENTROID_SHIFT) < 2^28 under the
        # same bound — enforce it rather than assume it (an arena inside
        # the VMEM envelope can otherwise exceed both ranges)
        from ..models import arena

        per_team = -(-game.num_entities // game.num_players)  # ceil
        assert per_team < (1 << 16), (
            f"arena pallas kernel: per-team entity count {per_team} exceeds "
            "the exact-division contract (divisor must stay < 2^16); use "
            "the XLA backend or more players"
        )
        assert per_team * (arena.ARENA_MASK >> arena.CENTROID_SHIFT) < (
            1 << 30
        ), "arena pallas kernel: centroid sum exceeds the 2^30 budget"
        self.reduce_len = 3 * game.num_players  # per team: count, sx, sy

    def reduce_partial(self, pl, ctx):
        """Per-team [count, sum_x>>SHIFT, sum_y>>SHIFT] masked sums of
        living entities — the exact int32 expressions _step_generic uses,
        so cached/psum'd values are bit-identical to inline ones."""
        from ..models import arena

        out = []
        alive = pl["hp"] > 0
        for t in range(self.game.num_players):
            mask = (ctx.owner == t) & alive
            out.append(jnp.sum(mask.astype(jnp.int32)))
            out.append(
                jnp.sum(jnp.where(mask, pl["px"] >> arena.CENTROID_SHIFT, 0))
            )
            out.append(
                jnp.sum(jnp.where(mask, pl["py"] >> arena.CENTROID_SHIFT, 0))
            )
        return out

    def reduce_finalize(self, raw, ctx):
        """(cents [(cx, cy)] per team, counts [count] per team) from the
        complete sums; scalar division via the wide exact floor div —
        sums stay under 2^28 by the model's overflow budget."""
        from ..models import arena

        cents, counts = [], []
        for t in range(self.game.num_players):
            count, sx, sy = raw[3 * t], raw[3 * t + 1], raw[3 * t + 2]
            safe_count = jnp.maximum(count, 1)
            cents.append(
                (
                    ctx.floor_div_wide(sx, safe_count) << arena.CENTROID_SHIFT,
                    ctx.floor_div_wide(sy, safe_count) << arena.CENTROID_SHIFT,
                )
            )
            counts.append(count)
        return cents, counts

    def step(self, pl, inputs, ctx, red=None):
        from ..models import arena

        game = self.game
        P = game.num_players
        px, py = pl["px"], pl["py"]
        vx, vy = pl["vx"], pl["vy"]
        hp, energy = pl["hp"], pl["energy"]
        owner = ctx.owner

        inp = ctx.select_by_owner(owner, [b[0] for b in inputs])
        if game.input_size >= 2:
            throttle = ctx.select_by_owner(owner, [b[1] for b in inputs]) & 0x0F
        else:
            throttle = jnp.int32(4)

        alive = hp > 0

        # per-team centroids of living entities: from the caller's cached/
        # psum'd reduction (red=) or inline full-plane sums (whole-world
        # visibility only)
        if red is None:
            red = self.reduce_finalize(self.reduce_partial(pl, ctx), ctx)
        cents, counts = red

        own_cx = ctx.select_by_owner(owner, [c[0] for c in cents])
        own_cy = ctx.select_by_owner(owner, [c[1] for c in cents])
        enemy_cx = ctx.select_by_owner(owner, [cents[(t + 1) % P][0] for t in range(P)])
        enemy_cy = ctx.select_by_owner(owner, [cents[(t + 1) % P][1] for t in range(P)])
        enemy_exists = (
            ctx.select_by_owner(owner, [counts[(t + 1) % P] for t in range(P)]) > 0
        )

        # thrust + overdrive + energy (order matches _step_generic exactly)
        ax = jnp.where((inp & arena.INPUT_RIGHT) != 0, 1, 0) - jnp.where(
            (inp & arena.INPUT_LEFT) != 0, 1, 0
        )
        ay = jnp.where((inp & arena.INPUT_DOWN) != 0, 1, 0) - jnp.where(
            (inp & arena.INPUT_UP) != 0, 1, 0
        )
        over = ((inp & arena.INPUT_OVERDRIVE) != 0) & (energy > 0)
        accel_base = (arena.ACCEL * (throttle + 4)) >> 3
        accel = jnp.where(over, 2 * accel_base, accel_base)
        energy = jnp.where(
            over,
            energy - arena.ENERGY_DRAIN,
            jnp.minimum(energy + arena.ENERGY_REGEN, arena.ENERGY_MAX),
        )
        energy = jnp.maximum(energy, 0)
        vx = vx + ax * accel
        vy = vy + ay * accel

        # rally pull toward the own centroid
        rally = ((inp & arena.INPUT_RALLY) != 0).astype(jnp.int32)
        pull_x = jnp.clip(
            (own_cx - px) >> arena.RALLY_SHIFT, -arena.RALLY_MAX, arena.RALLY_MAX
        )
        pull_y = jnp.clip(
            (own_cy - py) >> arena.RALLY_SHIFT, -arena.RALLY_MAX, arena.RALLY_MAX
        )
        vx = vx + rally * pull_x
        vy = vy + rally * pull_y

        # friction + speed clamp
        vx = (vx * arena.FRICTION_NUM) >> 8
        vy = (vy * arena.FRICTION_NUM) >> 8
        vx, vy = ctx.clamp_speed([vx, vy], arena.MAX_SPEED)

        # dead entities stop; integrate on the torus
        alive_i = alive.astype(jnp.int32)
        vx = vx * alive_i
        vy = vy * alive_i
        px = (px + vx) & arena.ARENA_MASK
        py = (py + vy) & arena.ARENA_MASK

        # combat around the (pre-move) enemy centroid, toroidal Manhattan
        half = 1 << (arena.ARENA_BITS - 1)
        dx = ((px - enemy_cx + half) & arena.ARENA_MASK) - half
        dy = ((py - enemy_cy + half) & arena.ARENA_MASK) - half
        dist = jnp.abs(dx) + jnp.abs(dy)
        hit = alive & enemy_exists & (dist < arena.COMBAT_RANGE)
        hp = jnp.maximum(hp - hit.astype(jnp.int32) * arena.DAMAGE, 0)

        return {"px": px, "py": py, "vx": vx, "vy": vy, "hp": hp,
                "energy": energy}


class SwarmPlanes(PlaneAdapter):
    """ggrs_tpu.models.swarm._step_generic on packed planes: the contract
    witness for >2-wide per-entity vectors (pos/vel are [N, 3] — three
    planes per state key) plus a scalar battery plane. Strictly
    per-entity dynamics => tileable (entity-tiled kernel + sharded
    composition)."""

    tileable = True
    planes = (
        ("px", "pos", 0), ("py", "pos", 1), ("pz", "pos", 2),
        ("vx", "vel", 0), ("vy", "vel", 1), ("vz", "vel", 2),
        ("charge", "charge", None),
    )

    def step(self, pl, inputs, ctx, red=None):
        from ..models import swarm

        px, py, pz = pl["px"], pl["py"], pl["pz"]
        vx, vy, vz = pl["vx"], pl["vy"], pl["vz"]
        charge = pl["charge"]

        inp = ctx.select_by_owner(ctx.owner, [b[0] for b in inputs])

        dx = jnp.where((inp & swarm.INPUT_XP) != 0, 1, 0) - jnp.where(
            (inp & swarm.INPUT_XM) != 0, 1, 0
        )
        dy = jnp.where((inp & swarm.INPUT_YP) != 0, 1, 0) - jnp.where(
            (inp & swarm.INPUT_YM) != 0, 1, 0
        )
        dz = jnp.where((inp & swarm.INPUT_ZP) != 0, 1, 0) - jnp.where(
            (inp & swarm.INPUT_ZM) != 0, 1, 0
        )

        boost = ((inp & swarm.INPUT_BOOST) != 0) & (charge > 0)
        accel = jnp.where(boost, 2 * swarm.ACCEL, swarm.ACCEL)
        charge = jnp.where(
            boost,
            charge - swarm.CHARGE_DRAIN,
            jnp.minimum(charge + swarm.CHARGE_REGEN, swarm.CHARGE_MAX),
        )
        charge = jnp.maximum(charge, 0)

        vx = ((vx * swarm.FRICTION_NUM) >> 8) + dx * accel
        vy = ((vy * swarm.FRICTION_NUM) >> 8) + dy * accel
        vz = ((vz * swarm.FRICTION_NUM) >> 8) + dz * accel

        vx, vy, vz = ctx.clamp_speed([vx, vy, vz], swarm.MAX_SPEED)

        px = (px + vx) & swarm.SPACE_MASK
        py = (py + vy) & swarm.SPACE_MASK
        pz = (pz + vz) & swarm.SPACE_MASK

        return {"px": px, "py": py, "pz": pz, "vx": vx, "vy": vy, "vz": vz,
                "charge": charge}


def choose_tile_rows(n_rows: int, per_row_bytes: int, budget: int) -> int:
    """Entity-tile sizing shared by every gridded pallas kernel: the
    largest 8-multiple divisor of n_rows whose streamed windows fit the
    VMEM budget (bigger tiles = fewer grid steps); a row count with no
    such divisor falls back to one full tile. The result always satisfies
    Mosaic's 8-sublane block constraint (>= 8 or == n_rows) and divides
    n_rows."""
    budget_rows = max(1, budget // per_row_bytes)
    candidates = [
        r
        for r in range(8, n_rows + 1, 8)
        if n_rows % r == 0 and r <= budget_rows
    ]
    tile = max(candidates) if candidates else n_rows
    assert n_rows % tile == 0
    assert tile >= 8 or tile == n_rows
    return tile


def plane_groups(adapter) -> Dict[str, list]:
    """state_key -> ordered [(component, plane_name)] for an adapter's
    plane layout, with the component-order contract enforced (components
    MUST be declared 0..w-1 — out-of-order planes would silently stack
    into the wrong state columns)."""
    groups: Dict[str, list] = {}
    for name, key, c in adapter.planes:
        groups.setdefault(key, []).append((c, name))
    for key, comps in groups.items():
        if not (len(comps) == 1 and comps[0][0] is None):
            assert [c for c, _ in comps] == list(range(len(comps))), (
                f"plane components for {key!r} must be declared in order "
                f"0..{len(comps) - 1}"
            )
    return groups


def rebuild_from_planes(groups: Dict[str, list], fetch, lead: tuple, n: int):
    """Inverse of plane packing, shared by every kernel's unpack: fetch
    each plane by name, reshape to lead + (n,), and stack multi-component
    keys back into [..., n, w] arrays."""
    out = {}
    for key, comps in groups.items():
        if len(comps) == 1 and comps[0][0] is None:
            out[key] = fetch(comps[0][1]).reshape(lead + (n,))
        else:
            out[key] = jnp.stack(
                [fetch(nm).reshape(lead + (n,)) for _, nm in comps],
                axis=-1,
            )
    return out


def make_gi_owner(n_rows: int, num_players: int, offset=0):
    """Global-entity-index and owning-player planes for a packed layout —
    THE one definition of entity ownership (gi % num_players) shared by
    every pallas kernel. `offset` shifts gi for a shard's slice of the
    world (traced or static)."""
    gi = jnp.asarray(
        np.arange(n_rows, dtype=np.int32)[:, None] * 128
        + np.arange(128, dtype=np.int32)[None, :]
    ) + offset
    return gi, gi % jnp.int32(num_players)


def partial_checksum_planes(cs_entries, gi, state):
    """Per-entity partial checksum sums over packed planes with GLOBAL
    weights (no frame term — callers fold it once in their post-pass).
    THE one weight loop shared by the tiled and beam kernels; a drifted
    copy would break the bit-parity contract adoption depends on."""
    hi = jnp.int32(0)
    lo = jnp.int32(0)
    for name, w, base in cs_entries:
        hi = hi + jnp.sum(state[name] * ((w * gi + base) * GOLDEN))
        lo = lo + jnp.sum(state[name])
    return hi, lo


def derive_checksum_weights(game, adapter):
    """Generic checksum weights for a packed-plane layout: for checksum key
    k of per-entity width w at word offset off_k, plane (k, j) element gi
    sits at global word index off_k + gi*w + j (the concatenation order
    _checksum_generic flattens), weighted (index+1)*GOLDEN. THE single
    derivation shared by every pallas kernel — a drifted copy would make
    two kernels disagree on the same state's checksum.

    Returns (entries, frame_weight): entries = [(plane_name, w, wrapped
    off+j+1)], frame_weight = wrapped (total_words + 1) * GOLDEN."""
    n = game.num_entities
    widths: Dict[str, int] = {}
    for _, key, _ in adapter.planes:
        widths[key] = widths.get(key, 0) + 1
    offs: Dict[str, int] = {}
    off = 0
    for key in game.checksum_keys:
        offs[key] = off
        off += n * widths[key]
    entries = [
        (name, np.int32(widths[key]), _wrap_i32(offs[key] + (comp or 0) + 1))
        for name, key, comp in adapter.planes
    ]
    return entries, _wrap_i32((off + 1) * int(GOLDEN))


_ADAPTERS: Dict[type, Callable] = {}


def _builtin_adapters() -> Dict[type, Callable]:
    from ..models.arena import Arena
    from ..models.ex_game import ExGame
    from ..models.swarm import Swarm

    return {ExGame: ExGamePlanes, Arena: ArenaPlanes, Swarm: SwarmPlanes}


def register_adapter(game_cls: type, adapter_cls) -> None:
    """Register a PlaneAdapter for a third-party DeviceGame class. Keyed by
    class identity (not name) and resolved through the MRO, so subclasses
    inherit their base's adapter and an unrelated same-named class can
    never silently pick up the wrong dynamics."""
    _ADAPTERS[game_cls] = adapter_cls


def get_adapter(game) -> PlaneAdapter:
    if not _ADAPTERS:
        _ADAPTERS.update(_builtin_adapters())
    for cls in type(game).__mro__:
        if cls in _ADAPTERS:
            return _ADAPTERS[cls](game)
    raise RegistryMiss(
        f"no pallas PlaneAdapter registered for {type(game).__name__}; use "
        "the XLA backend or register_adapter()"
    )


# ---------------------------------------------------------------------------
# Generic core
# ---------------------------------------------------------------------------


class PallasSyncTestCore:
    """Drop-in batch executor for TpuSyncTestSession's carry (unsharded)."""

    # VMEM envelope: input+output windows for every state/ring plane plus
    # kernel temporaries must fit the ~128MB core VMEM. Past roughly this
    # budget Mosaic does NOT always fail loudly — at ~100MB of windows a
    # 512k-entity world compiled but silently read one input plane as
    # zeros (verified on v5e), so the limit is enforced here and callers
    # fall back to the XLA scan.
    VMEM_BUDGET_BYTES = 96 * 1024 * 1024

    @classmethod
    def vmem_estimate(cls, game, check_distance: int, adapter=None) -> int:
        """Bytes of VMEM windows this config needs (state + ring planes,
        in and out). THE single formula — backend='auto' consults it too,
        so the selector can never drift from what construction enforces."""
        if adapter is None:
            adapter = get_adapter(game)
        n_planes = len(adapter.planes)
        plane_bytes = game.num_entities * 4
        return 2 * n_planes * (1 + check_distance + 2) * plane_bytes

    def __init__(self, game, num_players: int, check_distance: int,
                 interpret: bool = False):
        assert game.num_entities % 128 == 0, "entity count must be 128-aligned"
        self.game = game
        self.adapter = get_adapter(game)
        vmem_est = self.vmem_estimate(game, check_distance, self.adapter)
        if not interpret and vmem_est > self.VMEM_BUDGET_BYTES:
            raise ConfigError(
                f"world too large for the VMEM-resident kernel: ~{vmem_est >> 20}MB "
                f"of plane windows exceeds the validated {self.VMEM_BUDGET_BYTES >> 20}MB "
                "budget; use the XLA backend for this configuration"
            )
        self.num_players = num_players
        self.input_size = game.input_size
        self.d = check_distance
        self.ring_len = check_distance + 2
        self.hist_len = check_distance + 2
        self.n_rows = game.num_entities // 128
        self.interpret = interpret
        self._batch = functools.lru_cache(maxsize=4)(self._build)
        self._cs_entries, self._cs_frame_weight = derive_checksum_weights(
            game, self.adapter
        )

    # -- carry packing ---------------------------------------------------

    def pack(self, carry: Dict[str, Any]):
        rows = self.n_rows

        def comp(a, c):  # state leaf -> [..., rows, 128] plane
            plane = a if c is None else a[..., c]
            return plane.reshape(plane.shape[: plane.ndim - 1] + (rows, 128))

        s, r = carry["state"], carry["ring"]
        packed = {}
        for name, key, c in self.adapter.planes:
            packed[name] = comp(s[key], c)
            packed["r_" + name] = comp(r[key], c)
        packed.update(
            {
                "r_frame": r["frame"].astype(jnp.int32),
                "iring": carry["input_ring"]
                .reshape(self.d + 2, self.num_players * self.input_size)
                .astype(jnp.int32),
                "h_tag": carry["h_tag"],
                "h_hi": jax.lax.bitcast_convert_type(carry["h_hi"], jnp.int32),
                "h_lo": jax.lax.bitcast_convert_type(carry["h_lo"], jnp.int32),
                "meta": jnp.stack(
                    [
                        carry["frame"],
                        carry["mismatch"].astype(jnp.int32),
                        carry["mismatch_frame"],
                        jnp.int32(0),
                    ]
                ),
            }
        )
        return packed

    def unpack(self, p, _unused=None) -> Dict[str, Any]:
        n = self.game.num_entities
        groups = plane_groups(self.adapter)
        state = rebuild_from_planes(groups, lambda nm: p[nm], (), n)
        state["frame"] = p["meta"][0]  # state frame == tick frame invariant
        ring = rebuild_from_planes(
            groups, lambda nm: p["r_" + nm], (self.ring_len,), n
        )
        ring["frame"] = p["r_frame"]
        return {
            "state": state,
            "ring": ring,
            "input_ring": p["iring"]
            .astype(jnp.uint8)
            .reshape(self.d + 2, self.num_players, self.input_size),
            "h_tag": p["h_tag"],
            "h_hi": jax.lax.bitcast_convert_type(p["h_hi"], jnp.uint32),
            "h_lo": jax.lax.bitcast_convert_type(p["h_lo"], jnp.uint32),
            "mismatch": p["meta"][1].astype(jnp.bool_),
            "mismatch_frame": p["meta"][2],
            "frame": p["meta"][0],
        }

    # -- kernel ----------------------------------------------------------

    def _checksum_planes(self, planes: Dict[str, Any], gi, frame):
        """The model's `_checksum_generic` bit-for-bit on the packed layout
        (int32 wraparound == uint32), weights derived in __init__."""
        hi = frame * self._cs_frame_weight
        lo = frame
        for name, w, base in self._cs_entries:
            hi = hi + jnp.sum(planes[name] * ((w * gi + base) * GOLDEN))
            lo = lo + jnp.sum(planes[name])
        return hi, lo

    def _build(self, t_ticks: int):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        d, ring_len, hist_len = self.d, self.ring_len, self.hist_len
        rows, P, I = self.n_rows, self.num_players, self.input_size
        adapter = self.adapter
        plane_names = [name for name, _, _ in adapter.planes]

        # loop-invariant entity-index planes (numpy: _build may run under jit
        # tracing via the lru_cache miss)
        gi = (
            np.arange(rows, dtype=np.int32)[:, None] * 128
            + np.arange(128, dtype=np.int32)[None, :]
        )
        owner_np = gi % P

        # VMEM carries are updated in place via input/output aliasing. SMEM
        # carries are NOT aliased: on real TPUs input_output_aliases does not
        # propagate input bytes into an SMEM output buffer (verified
        # empirically; interpret mode hides it) — so the small state flows
        # input ref -> SMEM scratch (mutated through the loop) -> output ref.
        vmem_names = plane_names + ["r_" + n_ for n_ in plane_names]
        smem_names = ["r_frame", "iring", "h_tag", "h_hi", "h_lo", "meta"]
        carry_names = vmem_names + smem_names
        smem_shapes = {
            "r_frame": (ring_len,),
            "iring": (d + 2, P * I),
            "h_tag": (hist_len,),
            "h_hi": (hist_len,),
            "h_lo": (hist_len,),
            "meta": (4,),
        }

        # reduction phase (adapters with reduce_len > 0, e.g. arena's
        # per-team centroids): a per-FRAME cache of raw reduction sums in
        # SMEM. SyncTest resim replays frames bit-identically, so the
        # reduction of a resimulated state equals the one computed when
        # that frame was first the frontier — cache slots (frame % (d+2),
        # the ring's own modulus) are seeded from the snapshot ring + live
        # state at batch start and updated once per tick at the frontier.
        # Reduction work per tick drops from (d+1) full-plane sum sets to
        # ONE (plus scalar finalize per step) — the arena family's whole
        # deficit vs the per-entity families was exactly these sums. The
        # d+3-set seed amortizes over the batch, so single-tick dispatches
        # skip the cache (red=None -> inline) and keep the pre-cache cost.
        R = getattr(adapter, "reduce_len", 0) if t_ticks > 1 else 0

        def kernel(inputs_ref, gi_ref, owner_ref, *refs):
            n_in = len(carry_names)
            ins = dict(zip(carry_names, refs[:n_in]))
            outs = dict(zip(carry_names, refs[n_in : 2 * n_in]))
            scratch = dict(
                zip(smem_names, refs[2 * n_in : 2 * n_in + len(smem_names)])
            )
            red_ref = refs[2 * n_in + len(smem_names)] if R else None
            # VMEM: out refs are aliased to the inputs; SMEM: copy in->scratch
            out = {**{n_: outs[n_] for n_ in vmem_names}, **scratch}
            for name in smem_names:
                shape = smem_shapes[name]
                if len(shape) == 1:
                    for i in range(shape[0]):
                        scratch[name][i] = ins[name][i]
                else:
                    for i in range(shape[0]):
                        for j in range(shape[1]):
                            scratch[name][i, j] = ins[name][i, j]
            ctx = KernelCtx(gi_ref[:], owner_ref[:])

            def read_state():
                return {n_: out[n_][:] for n_ in plane_names}

            def ring_slot(name, slot):
                return out[name][pl.ds(slot, 1)][0]

            def save_and_check(state, frame, mask):
                """Masked ring write + first-seen history compare, matching
                TpuSyncTestSession._save_and_check under a tree-where."""
                hi, lo = self._checksum_planes(state, ctx.gi, frame)
                slot = frame % ring_len
                for name in plane_names:
                    old = ring_slot("r_" + name, slot)
                    out["r_" + name][pl.ds(slot, 1)] = jnp.where(
                        mask, state[name], old
                    )[None]
                old_f = out["r_frame"][slot]
                # ring "frame" component records the state's frame field
                out["r_frame"][slot] = jnp.where(mask, frame, old_f)

                h = frame % hist_len
                tag, ohi, olo = out["h_tag"][h], out["h_hi"][h], out["h_lo"][h]
                seen = tag == frame
                differs = mask & seen & ((ohi != hi) | (olo != lo))
                mm, mmf = out["meta"][1], out["meta"][2]
                first = differs & (mm == 0)
                out["meta"][1] = jnp.where(differs, 1, mm)
                out["meta"][2] = jnp.where(first, frame, mmf)
                out["h_tag"][h] = jnp.where(mask, frame, tag)
                out["h_hi"][h] = jnp.where(mask & ~seen, hi, ohi)
                out["h_lo"][h] = jnp.where(mask & ~seen, lo, olo)

            def where_state(pred, a, b):
                return {
                    n_: jnp.where(pred, a[n_], b[n_]) for n_ in plane_names
                }

            if R:
                # seed the per-frame reduction cache: ring slot s holds
                # frame f with f % (d+2) == s (same modulus), so cache
                # slot s = reduce(ring slot s); the live (frame c0) state
                # overwrites its slot last. Early-session slots hold
                # zero-init states — their cached values are only consumed
                # by masked-off resim steps whose results where() discards.
                for s in range(ring_len):
                    raw = adapter.reduce_partial(
                        {n_: ring_slot("r_" + n_, s) for n_ in plane_names},
                        ctx,
                    )
                    for j in range(R):
                        red_ref[s, j] = raw[j]
                raw = adapter.reduce_partial(read_state(), ctx)
                c0slot = out["meta"][0] % (d + 2)
                for j in range(R):
                    red_ref[c0slot, j] = raw[j]

            def red_for(f):
                """Finalized reduction values for frame f's state, from
                the cache (None for adapters without a reduction phase —
                step then takes its unreduced path)."""
                if not R:
                    return None
                raw = [red_ref[f % (d + 2), j] for j in range(R)]
                return adapter.reduce_finalize(raw, ctx)

            def tick(t, _):
                c = out["meta"][0]
                do_rb = c > d
                base = jnp.maximum(c - d, 0)

                # load the rollback base snapshot (masked)
                bslot = base % ring_len
                loaded = {
                    n_: ring_slot("r_" + n_, bslot) for n_ in plane_names
                }
                state = where_state(do_rb, loaded, read_state())

                for i in range(d):
                    f = base + i
                    if i > 0:
                        save_and_check(state, f, do_rb)
                    islot = f % (d + 2)
                    inps = [
                        [out["iring"][islot, p * I + j] for j in range(I)]
                        for p in range(P)
                    ]
                    # R == 0 calls the bare 3-arg form: pre-reduction-phase
                    # third-party adapters registered via register_adapter
                    # keep working unchanged on this kernel
                    nxt = (
                        adapter.step(state, inps, ctx, red=red_for(f))
                        if R
                        else adapter.step(state, inps, ctx)
                    )
                    state = where_state(do_rb, nxt, state)

                # save current frame, record input, advance
                save_and_check(state, c, jnp.bool_(True))
                cslot = c % (d + 2)
                new_inps = [
                    [inputs_ref[t, p * I + j] for j in range(I)]
                    for p in range(P)
                ]
                for p in range(P):
                    for j in range(I):
                        out["iring"][cslot, p * I + j] = new_inps[p][j]
                state = (
                    adapter.step(state, new_inps, ctx, red=red_for(c))
                    if R
                    else adapter.step(state, new_inps, ctx)
                )
                for n_ in plane_names:
                    out[n_][:] = state[n_]
                if R:
                    # the ONE reduction set this tick pays: the new
                    # frontier state (frame c+1), cached for the next
                    # tick's frontier step and any later resim of it
                    raw = adapter.reduce_partial(state, ctx)
                    nslot = (c + 1) % (d + 2)
                    for j in range(R):
                        red_ref[nslot, j] = raw[j]
                out["meta"][0] = c + 1
                return 0

            jax.lax.fori_loop(0, t_ticks, tick, 0)

            # SMEM carries: scratch -> (non-aliased) output refs
            for name in smem_names:
                shape = smem_shapes[name]
                if len(shape) == 1:
                    for i in range(shape[0]):
                        outs[name][i] = scratch[name][i]
                else:
                    for i in range(shape[0]):
                        for j in range(shape[1]):
                            outs[name][i, j] = scratch[name][i, j]

        def spec_of(name):
            space = pltpu.VMEM if name in vmem_names else pltpu.SMEM
            return pl.BlockSpec(memory_space=space)

        def run(packed, inputs_i32):
            in_specs = (
                [pl.BlockSpec(memory_space=pltpu.SMEM),
                 pl.BlockSpec(memory_space=pltpu.VMEM),
                 pl.BlockSpec(memory_space=pltpu.VMEM)]
                + [spec_of(n) for n in carry_names]
            )
            out_specs = [spec_of(n) for n in carry_names]
            out_shapes = [
                jax.ShapeDtypeStruct(packed[n].shape, packed[n].dtype)
                for n in carry_names
            ]
            # alias only the VMEM carries (they lead carry_names)
            aliases = {3 + i: i for i in range(len(vmem_names))}
            results = pl.pallas_call(
                kernel,
                in_specs=in_specs,
                out_specs=out_specs,
                out_shape=out_shapes,
                input_output_aliases=aliases,
                scratch_shapes=[
                    pltpu.SMEM(smem_shapes[n], jnp.int32) for n in smem_names
                ]
                + ([pltpu.SMEM((d + 2, R), jnp.int32)] if R else []),
                # default scoped-vmem budget is 16MB; large VMEM-resident
                # worlds (the compute-bound regime — up to the enforced
                # envelope, ~262k entities at check_distance 2) need most
                # of the 128MB core VMEM
                compiler_params=(
                    None
                    if self.interpret
                    else pltpu.CompilerParams(
                        vmem_limit_bytes=100 * 1024 * 1024
                    )
                ),
                interpret=self.interpret,
            )(inputs_i32, jnp.asarray(gi), jnp.asarray(owner_np),
              *[packed[n] for n in carry_names])
            return dict(zip(carry_names, results))

        return run

    # -- public ----------------------------------------------------------

    def batch(self, carry: Dict[str, Any], inputs) -> Dict[str, Any]:
        """Run T ticks; carry/in/out use TpuSyncTestSession's pytree."""
        t = inputs.shape[0]
        run = self._batch(t)
        packed = self.pack(carry)
        inputs_i32 = inputs.reshape(
            t, self.num_players * self.input_size
        ).astype(jnp.int32)
        out = run(packed, inputs_i32)
        return self.unpack(out, None)
