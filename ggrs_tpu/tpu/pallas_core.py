"""Pallas TPU kernel for the fused SyncTest hot loop.

The XLA scan in TpuSyncTestSession spends most of each tick on per-op
overhead: the world state is only ~80KB, so the ~60 small int ops per step
plus ring/history bookkeeping cost far more than the math. This kernel runs
the ENTIRE batch — T ticks, each with its forced `check_distance`-frame
rollback, resimulation, snapshot-ring writes, on-device checksums and
first-seen history comparison — as ONE pallas_call with every carry buffer
resident in VMEM/SMEM, written in place via input/output aliasing.

Semantics are bit-identical to TpuSyncTestSession._tick (tests enforce
carry-level parity): same masked rollback, same first-seen checksum history,
same mismatch latch, and the same step math (ggrs_tpu/models/ex_game
_step_generic with all-CONFIRMED statuses — the only configuration the
fused SyncTest uses).

Layout: entity arrays are packed to (N/128, 128) int32 tiles (px, py, vx,
vy, rot), the snapshot ring to (ring_len, N/128, 128); inputs, the input
ring, the checksum history and frame/mismatch scalars live in SMEM.
Unsigned checksum math is done in int32 (two's-complement wraparound is
bit-identical) and bitcast back to uint32 at the boundary.

Supported configuration: input_size == 1, N % 128 == 0, unsharded. The XLA
path remains the fallback (and the sharded/multi-chip implementation).
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..models import ex_game
from ..ops import fixed_point as fx
from ..types import InputStatus

GOLDEN = np.int32(np.uint32(fx.GOLDEN32).view(np.int32))


def _wrap_i32(x: int) -> np.int32:
    """Two's-complement int32 wrap of a Python int (numpy scalar overflow
    wraps too, but emits RuntimeWarning; this is exact and silent)."""
    x &= 0xFFFFFFFF
    return np.int32(x - (1 << 32) if x >= (1 << 31) else x)


def _exact_floor_div(a, b):
    """floor(a / b) for int32 a (|a| < 2^24), b in [1, 2^12], branch-free.

    TPU vector units have no integer divide; a float32 estimate is within a
    few ULP (even with reciprocal-based division), and three integer fixup
    rounds make the result the exact floor regardless of rounding mode —
    the determinism contract requires exactness, not speed of convergence.
    """
    q = jnp.floor(a.astype(jnp.float32) / b.astype(jnp.float32)).astype(jnp.int32)
    for _ in range(3):
        r = a - q * b
        q = q + (r >= b).astype(jnp.int32) - (r < 0).astype(jnp.int32)
    return q


def _isqrt24(n):
    """fx.isqrt24 verbatim (12 unrolled digit iterations), jnp ops."""
    x = n
    c = jnp.zeros_like(n)
    d = 1 << 22
    for _ in range(12):
        cd = c + d
        cond = x >= cd
        x = jnp.where(cond, x - cd, x)
        c = jnp.where(cond, (c >> 1) + d, c >> 1)
        d >>= 2
    return c


def _step_packed(px, py, vx, vy, rot, owner, inp_scalars, num_players):
    """ex_game._step_generic on packed (rows,128) tiles, all-CONFIRMED.

    inp_scalars: length-num_players list of scalar int32 input bytes.
    """
    inp = jnp.zeros_like(px)
    for p in range(num_players):
        inp = jnp.where(owner == p, inp_scalars[p], inp)

    up = (inp & ex_game.INPUT_UP) != 0
    down = (inp & ex_game.INPUT_DOWN) != 0
    left = (inp & ex_game.INPUT_LEFT) != 0
    right = (inp & ex_game.INPUT_RIGHT) != 0

    vx = (vx * ex_game.FRICTION_NUM) >> 8
    vy = (vy * ex_game.FRICTION_NUM) >> 8

    thrust = jnp.where(up & ~down, 1, 0) + jnp.where(down & ~up, -1, 0)
    cos_t = fx.cos16(rot, jnp)
    sin_t = fx.sin16(rot, jnp)
    dvx = (ex_game.MOVE_SPEED * cos_t) >> fx.TRIG_SCALE_BITS
    dvy = (ex_game.MOVE_SPEED * sin_t) >> fx.TRIG_SCALE_BITS
    vx = vx + thrust * dvx
    vy = vy + thrust * dvy

    turn = jnp.where(left & ~right, -ex_game.ROT_SPEED, 0) + jnp.where(
        right & ~left, ex_game.ROT_SPEED, 0
    )
    rot = (rot + turn) & (fx.ANGLE_MOD - 1)

    m2 = vx * vx + vy * vy
    mag = _isqrt24(m2)
    over = m2 > ex_game.MAX_SPEED * ex_game.MAX_SPEED
    safe = jnp.where(mag == 0, 1, mag)
    vx = jnp.where(over, _exact_floor_div(vx * ex_game.MAX_SPEED, safe), vx)
    vy = jnp.where(over, _exact_floor_div(vy * ex_game.MAX_SPEED, safe), vy)

    px = jnp.clip(px + vx, 0, ex_game.MAX_X)
    py = jnp.clip(py + vy, 0, ex_game.MAX_Y)
    return px, py, vx, vy, rot


def _checksum_packed(px, py, vx, vy, rot, gi, frame, n_entities):
    """_checksum_generic bit-for-bit on the packed layout (int32 wraparound
    == uint32): word order is pos interleaved, vel interleaved, rot, frame;
    `frame` is the state's frame field (the word at index 5N)."""
    g = GOLDEN
    n = np.int32(n_entities)
    hi = (
        jnp.sum(px * ((2 * gi + 1) * g))
        + jnp.sum(py * ((2 * gi + 2) * g))
        + jnp.sum(vx * ((2 * n + 2 * gi + 1) * g))
        + jnp.sum(vy * ((2 * n + 2 * gi + 2) * g))
        + jnp.sum(rot * ((4 * n + gi + 1) * g))
        + frame * _wrap_i32((5 * int(n) + 1) * int(g))
    )
    lo = (
        jnp.sum(px) + jnp.sum(py) + jnp.sum(vx) + jnp.sum(vy) + jnp.sum(rot)
        + frame
    )
    return hi, lo


class PallasSyncTestCore:
    """Drop-in batch executor for TpuSyncTestSession's carry (unsharded)."""

    def __init__(self, game, num_players: int, check_distance: int,
                 interpret: bool = False):
        assert game.input_size == 1, "pallas core supports 1-byte inputs"
        assert game.num_entities % 128 == 0, "entity count must be 128-aligned"
        self.game = game
        self.num_players = num_players
        self.d = check_distance
        self.ring_len = check_distance + 2
        self.hist_len = check_distance + 2
        self.n_rows = game.num_entities // 128
        self.interpret = interpret
        self._batch = functools.lru_cache(maxsize=4)(self._build)

    # -- carry packing ---------------------------------------------------

    def pack(self, carry: Dict[str, Any]):
        rows = self.n_rows

        def comp(a, i):  # [..., N, 2] -> [..., rows, 128] per component
            return a[..., i].reshape(a.shape[:-2] + (rows, 128))

        s, r = carry["state"], carry["ring"]
        return {
            "px": comp(s["pos"], 0), "py": comp(s["pos"], 1),
            "vx": comp(s["vel"], 0), "vy": comp(s["vel"], 1),
            "rot": s["rot"].reshape(rows, 128),
            "r_px": comp(r["pos"], 0), "r_py": comp(r["pos"], 1),
            "r_vx": comp(r["vel"], 0), "r_vy": comp(r["vel"], 1),
            "r_rot": r["rot"].reshape(-1, rows, 128),
            "r_frame": r["frame"].astype(jnp.int32),
            "iring": carry["input_ring"][:, :, 0].astype(jnp.int32),
            "h_tag": carry["h_tag"],
            "h_hi": jax.lax.bitcast_convert_type(carry["h_hi"], jnp.int32),
            "h_lo": jax.lax.bitcast_convert_type(carry["h_lo"], jnp.int32),
            "meta": jnp.stack(
                [
                    carry["frame"],
                    carry["mismatch"].astype(jnp.int32),
                    carry["mismatch_frame"],
                    jnp.int32(0),
                ]
            ),
        }

    def unpack(self, p, frame_scalar_state) -> Dict[str, Any]:
        n = self.game.num_entities

        def merge(x, y):  # packed components -> [..., N, 2]
            lead = x.shape[:-2]
            return jnp.stack(
                [x.reshape(lead + (n,)), y.reshape(lead + (n,))], axis=-1
            )

        state = {
            "frame": p["meta"][0],  # state frame == tick frame by invariant
            "pos": merge(p["px"], p["py"]),
            "vel": merge(p["vx"], p["vy"]),
            "rot": p["rot"].reshape(n),
        }
        ring = {
            "frame": p["r_frame"],
            "pos": merge(p["r_px"], p["r_py"]),
            "vel": merge(p["r_vx"], p["r_vy"]),
            "rot": p["r_rot"].reshape(-1, n),
        }
        return {
            "state": state,
            "ring": ring,
            "input_ring": p["iring"].astype(jnp.uint8)[:, :, None],
            "h_tag": p["h_tag"],
            "h_hi": jax.lax.bitcast_convert_type(p["h_hi"], jnp.uint32),
            "h_lo": jax.lax.bitcast_convert_type(p["h_lo"], jnp.uint32),
            "mismatch": p["meta"][1].astype(jnp.bool_),
            "mismatch_frame": p["meta"][2],
            "frame": p["meta"][0],
        }

    # -- kernel ----------------------------------------------------------

    def _build(self, t_ticks: int):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        d, ring_len, hist_len = self.d, self.ring_len, self.hist_len
        rows, P = self.n_rows, self.num_players
        n_entities = self.game.num_entities

        # loop-invariant entity-index planes (numpy: _build may run under jit
        # tracing via the lru_cache miss)
        gi = (
            np.arange(rows, dtype=np.int32)[:, None] * 128
            + np.arange(128, dtype=np.int32)[None, :]
        )
        owner_np = gi % P

        # VMEM carries are updated in place via input/output aliasing. SMEM
        # carries are NOT aliased: on real TPUs input_output_aliases does not
        # propagate input bytes into an SMEM output buffer (verified
        # empirically; interpret mode hides it) — so the small state flows
        # input ref -> SMEM scratch (mutated through the loop) -> output ref.
        vmem_names = ["px", "py", "vx", "vy", "rot",
                      "r_px", "r_py", "r_vx", "r_vy", "r_rot"]
        smem_names = ["r_frame", "iring", "h_tag", "h_hi", "h_lo", "meta"]
        carry_names = vmem_names + smem_names
        smem_shapes = {
            "r_frame": (ring_len,),
            "iring": (d + 2, P),
            "h_tag": (hist_len,),
            "h_hi": (hist_len,),
            "h_lo": (hist_len,),
            "meta": (4,),
        }

        def kernel(inputs_ref, gi_ref, owner_ref, *refs):
            n_in = len(carry_names)
            ins = dict(zip(carry_names, refs[:n_in]))
            outs = dict(zip(carry_names, refs[n_in : 2 * n_in]))
            scratch = dict(zip(smem_names, refs[2 * n_in :]))
            # VMEM: out refs are aliased to the inputs; SMEM: copy in->scratch
            out = {**{n_: outs[n_] for n_ in vmem_names}, **scratch}
            for name in smem_names:
                shape = smem_shapes[name]
                if len(shape) == 1:
                    for i in range(shape[0]):
                        scratch[name][i] = ins[name][i]
                else:
                    for i in range(shape[0]):
                        for j in range(shape[1]):
                            scratch[name][i, j] = ins[name][i, j]
            gi_v = gi_ref[:]
            owner_v = owner_ref[:]

            def read_state():
                return (out["px"][:], out["py"][:], out["vx"][:],
                        out["vy"][:], out["rot"][:])

            def ring_slot(name, slot):
                return out[name][pl.ds(slot, 1)][0]

            def save_and_check(state, frame, mask):
                """Masked ring write + first-seen history compare, matching
                TpuSyncTestSession._save_and_check under a tree-where."""
                px, py, vx, vy, rot = state
                hi, lo = _checksum_packed(px, py, vx, vy, rot, gi_v, frame,
                                          n_entities)
                slot = frame % ring_len
                for name, val in (("r_px", px), ("r_py", py), ("r_vx", vx),
                                  ("r_vy", vy), ("r_rot", rot)):
                    old = ring_slot(name, slot)
                    out[name][pl.ds(slot, 1)] = jnp.where(mask, val, old)[None]
                old_f = out["r_frame"][slot]
                # ring "frame" component records the state's frame field
                out["r_frame"][slot] = jnp.where(mask, frame, old_f)

                h = frame % hist_len
                tag, ohi, olo = out["h_tag"][h], out["h_hi"][h], out["h_lo"][h]
                seen = tag == frame
                differs = mask & seen & ((ohi != hi) | (olo != lo))
                mm, mmf = out["meta"][1], out["meta"][2]
                first = differs & (mm == 0)
                out["meta"][1] = jnp.where(differs, 1, mm)
                out["meta"][2] = jnp.where(first, frame, mmf)
                out["h_tag"][h] = jnp.where(mask, frame, tag)
                out["h_hi"][h] = jnp.where(mask & ~seen, hi, ohi)
                out["h_lo"][h] = jnp.where(mask & ~seen, lo, olo)

            def step(state, inp_scalars):
                return _step_packed(*state, owner_v, inp_scalars, P)

            def tick(t, _):
                c = out["meta"][0]
                do_rb = c > d
                base = jnp.maximum(c - d, 0)

                # load the rollback base snapshot (masked)
                bslot = base % ring_len
                loaded = tuple(
                    ring_slot(n_, bslot)
                    for n_ in ("r_px", "r_py", "r_vx", "r_vy", "r_rot")
                )
                cur = read_state()
                state = tuple(
                    jnp.where(do_rb, l, s) for l, s in zip(loaded, cur)
                )

                for i in range(d):
                    f = base + i
                    if i > 0:
                        save_and_check(state, f, do_rb)
                    islot = f % (d + 2)
                    inps = [out["iring"][islot, p] for p in range(P)]
                    nxt = step(state, inps)
                    state = tuple(
                        jnp.where(do_rb, n_, s) for n_, s in zip(nxt, state)
                    )

                # save current frame, record input, advance
                save_and_check(state, c, jnp.bool_(True))
                cslot = c % (d + 2)
                new_inps = [inputs_ref[t, p] for p in range(P)]
                for p in range(P):
                    out["iring"][cslot, p] = new_inps[p]
                state = step(state, new_inps)
                out["px"][:], out["py"][:] = state[0], state[1]
                out["vx"][:], out["vy"][:] = state[2], state[3]
                out["rot"][:] = state[4]
                out["meta"][0] = c + 1
                return 0

            jax.lax.fori_loop(0, t_ticks, tick, 0)

            # SMEM carries: scratch -> (non-aliased) output refs
            for name in smem_names:
                shape = smem_shapes[name]
                if len(shape) == 1:
                    for i in range(shape[0]):
                        outs[name][i] = scratch[name][i]
                else:
                    for i in range(shape[0]):
                        for j in range(shape[1]):
                            outs[name][i, j] = scratch[name][i, j]

        def spec_of(name):
            space = pltpu.VMEM if name in vmem_names else pltpu.SMEM
            return pl.BlockSpec(memory_space=space)

        def run(packed, inputs_i32):
            in_specs = (
                [pl.BlockSpec(memory_space=pltpu.SMEM),
                 pl.BlockSpec(memory_space=pltpu.VMEM),
                 pl.BlockSpec(memory_space=pltpu.VMEM)]
                + [spec_of(n) for n in carry_names]
            )
            out_specs = [spec_of(n) for n in carry_names]
            out_shapes = [
                jax.ShapeDtypeStruct(packed[n].shape, packed[n].dtype)
                for n in carry_names
            ]
            # alias only the VMEM carries (they lead carry_names)
            aliases = {3 + i: i for i in range(len(vmem_names))}
            results = pl.pallas_call(
                kernel,
                in_specs=in_specs,
                out_specs=out_specs,
                out_shape=out_shapes,
                input_output_aliases=aliases,
                scratch_shapes=[
                    pltpu.SMEM(smem_shapes[n], jnp.int32) for n in smem_names
                ],
                interpret=self.interpret,
            )(inputs_i32, jnp.asarray(gi), jnp.asarray(owner_np),
              *[packed[n] for n in carry_names])
            return dict(zip(carry_names, results))

        return run

    # -- public ----------------------------------------------------------

    def batch(self, carry: Dict[str, Any], inputs) -> Dict[str, Any]:
        """Run T ticks; carry/in/out use TpuSyncTestSession's pytree."""
        t = inputs.shape[0]
        run = self._batch(t)
        packed = self.pack(carry)
        inputs_i32 = inputs[:, :, 0].astype(jnp.int32)
        out = run(packed, inputs_i32)
        return self.unpack(out, None)
