"""The rollback hot loop as one compiled device program.

The reference's rollback driver crosses the user boundary up to
max_prediction times per tick — load a snapshot, then N x (save + advance)
callbacks (src/sessions/p2p_session.rs:649-670). On TPU that many
host<->device round trips would dwarf the math, so the entire block is one
jit-compiled `lax.scan` over a device-resident snapshot ring:

- the ring is a pytree of [R+1, ...] arrays, R = max_prediction + 2 (the
  same capacity/addressing as the host SyncLayer ring,
  src/sync_layer.rs:61-75); slot R is a scratch slot that masked-off saves
  write into, so the scan stays branch-free.
- one tick = optional load (dynamic ring index) + W fused
  (save?, advance?) micro-slots, W = max_prediction + 2, with rollback
  depth and save slots as traced scalars — a single compilation covers
  every depth.
- the per-save checksum is computed on device in the same scan.

Buffers are donated, so the ring is updated in place across ticks.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


class ResimCore:
    """Device snapshot ring + fused (load, resimulate, save, checksum) tick.

    `game` implements the DeviceGame interface: init_state() -> pytree,
    step(state, inputs u8[P, input_size], statuses i32[P]) -> pytree,
    checksum(state) -> (u32, u32). All pure jax.
    """

    def __init__(self, game, max_prediction: int, num_players: int):
        self.game = game
        self.num_players = num_players
        self.max_prediction = max_prediction
        self.ring_len = max_prediction + 2  # parity with SavedStates
        self.scratch_slot = self.ring_len  # masked-off saves land here
        self.window = max_prediction + 2  # advances + possible trailing save

        state = game.init_state()
        self.state = state
        self.ring = jax.tree.map(
            lambda x: jnp.zeros((self.ring_len + 1,) + x.shape, x.dtype), state
        )
        self._tick_fn = jax.jit(self._tick_impl, donate_argnums=(0, 1))

    # ------------------------------------------------------------------

    def _tick_impl(
        self,
        ring,
        state,
        do_load,  # bool[]
        load_slot,  # i32[]
        inputs,  # u8[W, P, input_size]
        statuses,  # i32[W, P]
        save_slots,  # i32[W]; scratch_slot means "no save"
        advance_count,  # i32[]
    ):
        loaded = jax.tree.map(
            lambda r: jax.lax.dynamic_index_in_dim(r, load_slot, 0, keepdims=False),
            ring,
        )
        state = _tree_where(do_load, loaded, state)

        iota = jnp.arange(self.window, dtype=jnp.int32)

        def body(carry, xs):
            ring, state = carry
            i, inp, stat, save_slot = xs
            # save-then-advance: slot i snapshots the pre-advance state
            hi, lo = self.game.checksum(state)
            ring = jax.tree.map(
                lambda r, s: jax.lax.dynamic_update_index_in_dim(r, s, save_slot, 0),
                ring,
                state,
            )
            nxt = self.game.step(state, inp, stat)
            state = _tree_where(i < advance_count, nxt, state)
            return (ring, state), (hi, lo)

        (ring, state), (his, los) = jax.lax.scan(
            body, (ring, state), (iota, inputs, statuses, save_slots)
        )
        return ring, state, his, los

    # ------------------------------------------------------------------

    def tick(
        self,
        do_load: bool,
        load_slot: int,
        inputs: np.ndarray,
        statuses: np.ndarray,
        save_slots: np.ndarray,
        advance_count: int,
    ) -> Tuple[Any, Any]:
        """Run one fused tick; returns (checksum_hi[W], checksum_lo[W]) as
        device arrays (no host sync)."""
        # numpy scalars go straight into the jitted call — eager
        # jnp.asarray would dispatch a convert primitive per argument
        self.ring, self.state, his, los = self._tick_fn(
            self.ring,
            self.state,
            np.bool_(do_load),
            np.int32(load_slot),
            inputs,
            statuses,
            save_slots,
            np.int32(advance_count),
        )
        return his, los

    def fetch_state(self):
        """Device -> host copy of the live state (test/debug aid)."""
        return jax.device_get(self.state)

    def fetch_ring_slot(self, slot: int):
        return jax.device_get(
            jax.tree.map(lambda r: r[slot], self.ring)
        )
