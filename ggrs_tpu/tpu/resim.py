"""The rollback hot loop as one compiled device program.

The reference's rollback driver crosses the user boundary up to
max_prediction times per tick — load a snapshot, then N x (save + advance)
callbacks (src/sessions/p2p_session.rs:649-670). On TPU that many
host<->device round trips would dwarf the math, so the entire block is one
jit-compiled `lax.scan` over a device-resident snapshot ring:

- the ring is a pytree of [R+1, ...] arrays, R = max_prediction + 2 (the
  same capacity/addressing as the host SyncLayer ring,
  src/sync_layer.rs:61-75); slot R is a scratch slot that masked-off saves
  write into, so the scan stays branch-free.
- one tick = optional load (dynamic ring index) + W fused
  (save?, advance?) micro-slots, W = max_prediction + 2, with rollback
  depth and save slots as traced scalars — a single compilation covers
  every depth.
- the per-save checksum is computed on device in the same scan.

Buffers are donated, so the ring is updated in place across ticks.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import ContractViolation

from ..obs import DISPATCH_DEPTH_BUCKETS, GLOBAL_TELEMETRY


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def depth_dispatch_instruments():
    """The two depth-adaptive-dispatch instruments, get-or-created on the
    global registry: a histogram of the routed depth bucket (window slots
    actually executed) per dispatch, and a counter of full-window slots
    minus the slots actually dispatched — the device work depth routing
    avoided. Shared by every routed path (T=1 branchless variants, the
    lazy multi-tick scan, the cross-session megabatch): one pair of
    series makes the win — and a silent routing regression (waste
    flatlining at 0, depth pinned at the window) — visible in any
    telemetry snapshot."""
    reg = GLOBAL_TELEMETRY.registry
    depth = reg.histogram(
        "ggrs_dispatch_depth",
        "window slots actually executed by a depth-routed device dispatch",
        buckets=DISPATCH_DEPTH_BUCKETS,
    )
    waste = reg.counter(
        "ggrs_padded_slot_waste",
        "full-window slots minus active slots actually dispatched "
        "(device work avoided by depth-adaptive dispatch)",
    )
    return depth, waste


class ResimCore:
    """Device snapshot ring + fused (load, resimulate, save, checksum) tick.

    `game` implements the DeviceGame interface: init_state() -> pytree,
    step(state, inputs u8[P, input_size], statuses i32[P]) -> pytree,
    checksum(state) -> (u32, u32). All pure jax.
    """

    # worlds up to this size route lone ticks through the branchless
    # unrolled program (see the _tick_fn comment in __init__): ~0.5ms of
    # worst-case masked work buys ~2ms of control-flow dispatch overhead
    BRANCHLESS_MAX_ENTITIES = 1 << 18
    # trivial T=1 rows (no load, one advance) route through the WINDOWED
    # cond program from this world size up: below it the full cond
    # program's skipped slots cost too little device time to buy the
    # extra per-core compile (every interactive session would pay a
    # compile for a program that saves microseconds on toy worlds)
    T1_WINDOWED_MIN_ENTITIES = 1 << 11
    # worlds at or past this size route lone ticks through the pallas
    # tick kernel (as a 1-row multi dispatch) when the core has one: the
    # XLA T=1 programs run the step as unfused elementwise passes whose
    # cost grows with the world, while the kernel streams state+ring
    # through VMEM once. Measured crossover on the v5e tunnel (chained
    # dispatch, one barrier): 65k entities XLA-branchless 7.8ms vs
    # kernel 8.9ms; 262k XLA-branchless 19.5ms / XLA-cond 33.1ms vs
    # kernel 9.9ms — the kernel's cost is nearly size-flat, so route
    # everything from 128k up (including worlds past the branchless cap,
    # which previously fell back to the cond program).
    PALLAS_T1_MIN_ENTITIES = 1 << 17

    def __init__(self, game, max_prediction: int, num_players: int, mesh=None,
                 device_verify: bool = False, spec_backend: str = "auto",
                 tick_backend: str = "auto"):
        """`mesh`: optional jax Mesh with an `entity` axis — the live state
        AND the snapshot ring shard across it (BASELINE.json configs[4]), so
        a partitioned world can run inside any session that drives this
        core (the seam the reference exposes at
        src/sessions/p2p_session.rs:621-673, here executed multi-chip).
        GSPMD partitions the fused tick from the operand shardings; the
        checksum reduction is the only cross-shard collective (uint32
        wraparound sums are order-invariant, so the psum'd value is
        bit-identical to the single-chip one). Sharded-state contract: every
        non-scalar state leaf has entities on axis 0, divisible by the
        `entity` axis size. If the mesh also has a `beam` axis, speculative
        rollouts shard candidate futures across it."""
        self.game = game
        self.num_players = num_players
        self.max_prediction = max_prediction
        self.ring_len = max_prediction + 2  # parity with SavedStates
        self.scratch_slot = self.ring_len  # masked-off saves land here
        self.window = max_prediction + 2  # advances + possible trailing save
        self.mesh = mesh

        state = game.init_state()
        self._beam_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.sharded import shard_state

            assert "entity" in mesh.axis_names, "mesh needs an `entity` axis"
            state = shard_state(state, mesh)
            if "beam" in mesh.axis_names and mesh.shape["beam"] > 1:
                self._beam_sharding = NamedSharding(mesh, P("beam"))
        self.state = state
        ring = jax.tree.map(
            lambda x: jnp.zeros((self.ring_len + 1,) + x.shape, x.dtype), state
        )
        if mesh is not None:
            from ..parallel.sharded import shard_ring

            ring = shard_ring(ring, mesh)
        self.ring = ring
        # device-resident determinism verdict (opt-in): a first-seen
        # checksum history + mismatch latch updated INSIDE the fused tick,
        # mirroring the fused SyncTest session's _save_and_check. With it,
        # SyncTest-style verification needs NO per-burst host readback of
        # checksum values — on the tunneled device every readback costs a
        # ~100ms round trip, which dominates the whole interactive path.
        # Only valid for confirmed-input replay (SyncTest): P2P rollbacks
        # legitimately re-save corrected frames with different state.
        self.device_verify = device_verify
        if device_verify:
            verify = {
                "h_tag": jnp.full((self.ring_len,), -1, dtype=jnp.int32),
                "h_hi": jnp.zeros((self.ring_len,), dtype=jnp.uint32),
                "h_lo": jnp.zeros((self.ring_len,), dtype=jnp.uint32),
                # [mismatch?, first mismatching frame]
                "flag": jnp.array([0, -1], dtype=jnp.int32),
            }
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                verify = jax.tree.map(
                    lambda x: jax.device_put(x, NamedSharding(mesh, P())),
                    verify,
                )
            self.verify = verify
        else:
            self.verify = {}
        # The T=1 interactive programs. lax.cond/lax.scan control flow
        # costs ~1.5-2ms of per-dispatch overhead through the tunnel EVEN
        # WHEN THE TAKEN WORK IS TINY (measured: a scan-of-conds program
        # with trivial compute dispatches at ~3.0ms vs ~1.5ms for the same
        # I/O branchless) — but cond SKIPPING also genuinely saves device
        # work when most of the window is skipped. So lone ticks route by
        # ROW CONTENT (host-side, both programs compiled): rollback /
        # multi-advance rows — which execute most of the window anyway —
        # run the fully UNROLLED, jnp.where-MASKED program (measured
        # 3.8 -> 1.5ms for an 8-frame rollback tick at 4k entities,
        # interleaved in a quiet tunnel window); trivial rows (one
        # advance, no load) keep the cond program, whose 14-of-15-slot
        # skip beats the masked full window (measured ~1.2ms the other
        # way, same methodology — bench tunnel_floor carries both).
        # Bit-identical either way: masked saves write the OLD value back
        # to slot 0, so even the ring's scratch bytes match. Worlds past
        # BRANCHLESS_MAX_ENTITIES always run cond (masked work there is
        # real bandwidth).
        n_entities = getattr(game, "num_entities", None)
        self._tick_fn = jax.jit(
            self._tick_packed_impl, donate_argnums=(0, 1, 3)
        )
        # the windowed cond program: the same per-slot cond tick truncated
        # to a STATIC nslots. Trivial T=1 rows (no load, one advance — the
        # speculative ticks between rollbacks, the dominant interactive
        # traffic) keep cond's taken-branch economics but stop paying the
        # full window's scanned slots of control flow: their last active
        # slot is <= 2, so they dispatch the smallest variant instead of
        # W slots of cond skipping. Bit-identical to the full cond
        # program (truncated slots are provably inert).
        self._tick_windowed_fn = jax.jit(
            self._tick_windowed_impl, static_argnums=(4,),
            donate_argnums=(0, 1, 3),
        )
        # nslots is a STATIC jit key: one executable per coalesced
        # depth variant (branchless_variants), all compiled by warmup
        self._tick_branchless_fn = (
            jax.jit(
                self._tick_branchless_impl,
                static_argnums=(4,),
                donate_argnums=(0, 1, 3),
            )
            if n_entities is not None
            and n_entities <= self.BRANCHLESS_MAX_ENTITIES
            else None
        )
        # nslots is a STATIC jit key here too: the lazy multi-tick scan
        # compiles one body per coalesced depth variant (the same
        # branchless_variants family as T=1), and the backend routes a
        # buffered batch by the MAX last-active slot across its rows —
        # a buffer of zero-rollback ticks scans 3 slots per row instead
        # of the full window (depth-adaptive dispatch)
        self._tick_multi_fn = jax.jit(
            self._tick_multi_impl, static_argnums=(4,),
            donate_argnums=(0, 1, 3),
        )
        # trivial-row windowed-cond routing gate (see the constants above)
        self._t1_windowed = (
            self._tick_branchless_fn is not None
            and n_entities is not None
            and n_entities >= self.T1_WINDOWED_MIN_ENTITIES
        )
        self._speculate_fn = jax.jit(self._speculate_impl)

        def pallas_eligible(extra=lambda: True, allow_mesh=False,
                            whole_world_fits=None) -> bool:
            """Can this (game, mesh) run a pallas kernel? THE one
            eligibility predicate for both the speculation and tick
            backends — a drifted copy would send them down different paths
            for the same game. `allow_mesh`: both the tick kernel and the
            beam rollout compose with a mesh (ShardedPallasTickCore /
            ShardedPallasBeamRollout shard_map local kernels + psum
            checksum partials) for tileable adapters.
            `whole_world_fits`: for reduction-phase adapters (arena) —
            non-tileable but runnable as ONE whole-world VMEM tile,
            unsharded only — the backend's single-tile sizing predicate
            (None = that backend resolves reduce models at dispatch)."""
            if jax.devices()[0].platform != "tpu":
                return False
            if mesh is not None:
                from ..parallel.sharded import entity_shardable

                if not allow_mesh or not entity_shardable(
                    game.num_entities, mesh
                ):
                    return False
            try:
                from .pallas_core import get_adapter

                # same rejection classes _pick_backend honors: KeyError =
                # no adapter registered; AssertionError/ValueError = a
                # model-envelope bound (e.g. arena's centroid-division
                # contract) — all mean "this config runs XLA", never a
                # construction-time crash
                adapter = get_adapter(game)
            except (KeyError, AssertionError, ValueError):
                return False
            if game.num_entities % 128 != 0 or not extra():
                return False
            if getattr(adapter, "tileable", False):
                return True
            if (
                mesh is not None
                or getattr(adapter, "reduce_len", 0) <= 0
                or whole_world_fits is None
            ):
                return False
            return whole_world_fits()

        # speculation backend: the XLA vmap+scan rollout runs the step as
        # unfused elementwise passes, so B*L speculative steps tax several
        # ms of device time per tick on mid-size worlds; the entity-tiled
        # pallas rollout (pallas_beam.py) runs the same math at the fused
        # kernel's cost for tileable models. "auto" picks pallas when the
        # model supports it (falling back to XLA otherwise); results are
        # bit-identical either way (tests enforce it).
        assert spec_backend in ("auto", "xla", "pallas", "pallas-interpret")
        if spec_backend == "auto":
            # reduce-phase adapters (arena): beam width is only known at
            # speculate time, so single-tile sizing resolves at dispatch —
            # _speculate_pallas falls back to XLA if the rollout rejects.
            # Under a mesh, tileable models run ShardedPallasBeamRollout
            # (one local kernel per device over the `entity` axis, psum'd
            # checksum partials); reduce models keep the XLA path, whose
            # GSPMD-inserted psums handle their global sums.
            spec_backend = (
                "pallas"
                if pallas_eligible(
                    allow_mesh=True, whole_world_fits=lambda: True
                )
                else "xla"
            )
        self.spec_backend = spec_backend
        self._beam_rollouts = {}  # beam_width -> PallasBeamRollout
        self._speculate_pallas_fns = {}  # beam_width -> jitted wrapper
        # tick backend: the generic control-word tick (and the lazy
        # multi-tick buffer) can run on the entity-tiled pallas kernel
        # for tileable models declaring a disconnect_input row —
        # bit-identical to the XLA scan (tests enforce it), at the fused
        # kernel's device cost instead of unfused per-op overhead. Under a
        # mesh the kernel composes via ShardedPallasTickCore (one local
        # kernel per device, psum'd checksum partials).
        assert tick_backend in ("auto", "xla", "pallas", "pallas-interpret")
        if tick_backend == "auto":
            from .pallas_resim import PallasTickCore

            tick_backend = (
                "pallas"
                if pallas_eligible(
                    lambda: getattr(game, "disconnect_input", None) is not None
                    and len(game.disconnect_input) == game.input_size,
                    allow_mesh=True,
                    whole_world_fits=lambda: PallasTickCore.whole_world_fits(
                        game, self.ring_len
                    ),
                )
                else "xla"
            )
        self.tick_backend = tick_backend
        if tick_backend.startswith("pallas"):
            interpret = tick_backend.endswith("-interpret")
            if mesh is not None:
                from .pallas_resim import ShardedPallasTickCore

                core = ShardedPallasTickCore(self, mesh, interpret=interpret)
            else:
                from .pallas_resim import PallasTickCore

                core = PallasTickCore(self, interpret=interpret)
            self._tick_pallas_fn = jax.jit(
                core.tick_multi, donate_argnums=(0, 1, 3)
            )
        else:
            self._tick_pallas_fn = None
        self._adopt_fn = jax.jit(self._adopt_impl, donate_argnums=(0, 6))
        # FULL-hit adoption is pure data movement: every corrected frame
        # is served from the precomputed trajectory, so the program is
        # selects + masked ring writes + the speculation's checksums — no
        # game.step, no checksum math, no control flow. The cond/scan
        # adopt program costs ~2x the branchless dispatch floor through
        # the tunnel (the same overhead _tick_branchless_impl exists to
        # avoid) AND reruns nothing, so on full hits the unrolled program
        # is strictly cheaper; partial hits keep the cond program (their
        # suffix genuinely resimulates, and masking W steps would cost
        # more than cond's skip). Same entity-count gate as the
        # branchless tick: past it the masked gathers are real bandwidth.
        self._adopt_full_fn = (
            jax.jit(self._adopt_full_impl, donate_argnums=(0, 6))
            if n_entities is not None
            and n_entities <= self.BRANCHLESS_MAX_ENTITIES
            else None
        )
        # tick's packed control-word layout (pack site: tick(); unpack:
        # _tick_packed_impl): 4 header words (do_load, load_slot,
        # advance_count, start_frame), then save_slots[W], statuses[W*P],
        # inputs[W*P*I]. The adopt path has its OWN layout — 6 header
        # words (member, load_slot, advance_count, shift, load_frame,
        # matched), then save_slots[W], statuses[W*P], inputs[W*P*I] (the
        # suffix resim rows) — see adopt()/_adopt_impl.
        p, i = num_players, game.input_size
        self._off_save = 4
        self._off_status = self._off_save + self.window
        self._off_input = self._off_status + self.window * p
        self._packed_len = self._off_input + self.window * p * i
        self._aoff_save = 6
        self._aoff_status = self._aoff_save + self.window
        self._aoff_input = self._aoff_status + self.window * p
        self._apacked_len = self._aoff_input + self.window * p * i
        # depth-adaptive dispatch instruments (updated behind enabled
        # checks at the routing sites, the Tracer.span idiom)
        self._m_depth, self._m_waste = depth_dispatch_instruments()

    # ------------------------------------------------------------------

    def _tick_packed_impl(self, ring, state, packed, verify):
        """Unpack the single control-word array (see tick()) and run the
        fused tick. One argument means ONE host->device transfer per tick —
        on a tunneled device every transferred buffer pays a latency floor
        regardless of size, so 7 small args cost ~7 floors."""
        W, P, I = self.window, self.num_players, self.game.input_size
        do_load = packed[0] != 0
        load_slot = packed[1]
        advance_count = packed[2]
        start_frame = packed[3]
        save_slots = packed[self._off_save : self._off_status]
        statuses = packed[self._off_status : self._off_input].reshape(W, P)
        inputs = (
            packed[self._off_input : self._packed_len]
            .astype(jnp.uint8)
            .reshape(W, P, I)
        )
        return self._tick_impl(
            ring, state, do_load, load_slot, inputs, statuses, save_slots,
            advance_count, start_frame, verify,
        )

    def _tick_windowed_impl(self, ring, state, packed, verify, nslots):
        """The packed cond tick truncated to its first `nslots` window
        slots (a STATIC value): the scan body, inputs and save slots past
        `nslots` are never traced, so the compiled program's device work
        is proportional to the depth bucket, not the full window.
        Checksums zero-pad back to [W] so batch indexing (flat j*W + i)
        never changes. Bit-identical to _tick_packed_impl whenever every
        dispatched row's last active slot (advance count and highest real
        save) fits in `nslots` — the routers guarantee it, and slots past
        the last active one are provably inert in the full program
        (cond-skipped saves, cond-skipped steps, (0, 0) checksums)."""
        W, P, I = self.window, self.num_players, self.game.input_size
        do_load = packed[0] != 0
        load_slot = packed[1]
        advance_count = packed[2]
        start_frame = packed[3]
        save_slots = packed[self._off_save : self._off_save + nslots]
        statuses = packed[self._off_status : self._off_status + nslots * P]
        statuses = statuses.reshape(nslots, P)
        inputs = (
            packed[self._off_input : self._off_input + nslots * P * I]
            .astype(jnp.uint8)
            .reshape(nslots, P, I)
        )
        ring, state, verify, his, los = self._tick_impl(
            ring, state, do_load, load_slot, inputs, statuses, save_slots,
            advance_count, start_frame, verify, nslots=nslots,
        )
        pad = jnp.zeros((W - nslots,), dtype=his.dtype)
        return (
            ring,
            state,
            verify,
            jnp.concatenate([his, pad]),
            jnp.concatenate([los, pad]),
        )

    def _tick_branchless_impl(self, ring, state, packed, verify, nslots):
        """The T=1 tick with NO device control flow: `nslots` window slots
        are unrolled, every unrolled slot's checksum and step always
        execute, and masking is jnp.where selects. Same packed layout and
        bit-identical outputs to _tick_packed_impl (tests drive random
        streams through both): skipped saves emit (0, 0) checksums and
        write the OLD value back to ring slot 0; skipped steps' results
        are where()-discarded; slots past `nslots` (a STATIC jit key) are
        provably inert for the row being dispatched — the host router
        picks the smallest coalesced variant covering the row's last
        active slot (depth specialization: unrolling the full window cost
        ~1 ms of masked step+checksum work per rollback tick at 65k that
        a depth-5 rollback never needed). Rationale and the measured
        dispatch numbers: the _tick_fn comment in __init__."""
        W, P, I = self.window, self.num_players, self.game.input_size
        do_load = packed[0] != 0
        load_slot = packed[1]
        advance_count = packed[2]
        start_frame = packed[3]
        save_slots = packed[self._off_save : self._off_status]
        statuses = packed[self._off_status : self._off_input].reshape(W, P)
        inputs = (
            packed[self._off_input : self._packed_len]
            .astype(jnp.uint8)
            .reshape(W, P, I)
        )
        loaded = jax.tree.map(
            lambda r: jax.lax.dynamic_index_in_dim(
                r, load_slot, 0, keepdims=False
            ),
            ring,
        )
        state = _tree_where(do_load, loaded, state)
        his, los = [], []
        for i in range(nslots):
            save_slot = save_slots[i]
            do_save = save_slot < self.ring_len
            hi, lo = self.game.checksum(state)
            hi = jnp.where(do_save, hi, jnp.uint32(0))
            lo = jnp.where(do_save, lo, jnp.uint32(0))
            wslot = jnp.where(do_save, save_slot, 0)
            old = jax.tree.map(
                lambda r: jax.lax.dynamic_index_in_dim(
                    r, wslot, 0, keepdims=False
                ),
                ring,
            )
            ring = jax.tree.map(
                lambda r, s: jax.lax.dynamic_update_index_in_dim(
                    r, s, wslot, 0
                ),
                ring,
                _tree_where(do_save, state, old),
            )
            if self.device_verify:
                upd = self._verify_update(verify, start_frame + i, hi, lo)
                verify = _tree_where(do_save, upd, verify)
            nxt = self.game.step(state, inputs[i], statuses[i])
            state = _tree_where(i < advance_count, nxt, state)
            his.append(hi)
            los.append(lo)
        zero = [jnp.uint32(0)] * (W - nslots)
        return (
            ring,
            state,
            verify,
            jnp.stack(his + zero),
            jnp.stack(los + zero),
        )

    def branchless_variants(self):
        """The coalesced slot counts the branchless T=1 program compiles
        for (3, 6, 9, ..., W; always ends in W): a handful of variants
        covers every depth while warmup stays a few compiles, and the
        router rounds a row's last active slot UP to the next variant."""
        if not hasattr(self, "_bl_variants"):
            W = self.window
            self._bl_variants = sorted(
                {min(3 * k, W) for k in range(1, (W + 2) // 3 + 1)}
            )
        return self._bl_variants

    def _tick_multi_impl(self, ring, state, packed, verify, nslots):
        """T buffered ticks as ONE device program: a lax.scan of the packed
        tick over rows of packed[T, L]. On the tunnel each dispatch costs
        ~1ms of host time regardless of content, so batching T interactive
        ticks into one dispatch divides the request path's dominant cost
        by T (ggrs_tpu/tpu/backend.py lazy_ticks). Padding rows
        (advance_count=0, scratch-only saves) are true no-ops — the
        per-slot conds skip all work — so one buffer length compiles
        once per depth variant. `nslots` (STATIC) truncates every row's
        scan body to the depth bucket covering the buffer's deepest row:
        a buffer of zero-rollback ticks no longer pays the full window's
        scanned slots per row (cond skips the work inside a slot, but
        each traced slot still costs control flow and — under vmap's
        cond->select lowering in the megabatch — real compute)."""

        def body(carry, row):
            ring, state, verify = carry
            ring, state, verify, his, los = self._tick_windowed_impl(
                ring, state, row, verify, nslots
            )
            return (ring, state, verify), (his, los)

        (ring, state, verify), (his, los) = jax.lax.scan(
            body, (ring, state, verify), packed
        )
        return ring, state, verify, his, los

    def _tick_fast_impl(self, ring, state, row):
        """The per-slot ZERO-ROLLBACK fast tick: the single-session body
        the resident virtual-tick driver vmaps in-loop
        (MultiSessionDeviceCore._driver_fast_impl) when every row of a
        mailbox fill cycle is fast-eligible — no load, at most one
        advance, no active slot past window slot 1. The math is the
        megabatch fast program's (_dispatch_fast_impl) per slot: no ring
        gather/scatter beyond the two masked single-slot writes, no
        resim scan — one step, two checksums. Masked saves write the
        slot's OLD ring value back (the branchless trick), so even the
        ring's bytes stay bit-identical to the cond program; pad rows
        (advance 0, scratch saves) are inert. Checksums land at window
        slots 0/1 of a zero [W] batch, keeping the flat indexing."""
        W, P, I = self.window, self.num_players, self.game.input_size
        advance = row[2]
        s0 = row[self._off_save]
        s1 = row[self._off_save + 1]
        statuses0 = row[self._off_status : self._off_status + P]
        inputs0 = (
            row[self._off_input : self._off_input + P * I]
            .astype(jnp.uint8)
            .reshape(P, I)
        )
        zero = jnp.uint32(0)

        def ring_write(ring, do, wslot, value):
            old = jax.tree.map(
                lambda r: jax.lax.dynamic_index_in_dim(
                    r, wslot, 0, keepdims=False
                ),
                ring,
            )
            return jax.tree.map(
                lambda r, s: jax.lax.dynamic_update_index_in_dim(
                    r, s, wslot, 0
                ),
                ring,
                _tree_where(do, value, old),
            )

        # slot 0: masked save of the pre-step state
        hi0, lo0 = self.game.checksum(state)
        do0 = s0 < self.ring_len
        ring = ring_write(ring, do0, jnp.where(do0, s0, 0), state)
        # the one advance (masked only so pad rows stay inert)
        nxt = self.game.step(state, inputs0, statuses0)
        state = _tree_where(advance > 0, nxt, state)
        # slot 1: masked trailing save of the post-step state
        hi1, lo1 = self.game.checksum(state)
        do1 = s1 < self.ring_len
        ring = ring_write(ring, do1, jnp.where(do1, s1, 0), state)
        his = jnp.zeros((W,), dtype=hi0.dtype)
        los = jnp.zeros((W,), dtype=lo0.dtype)
        his = his.at[0].set(jnp.where(do0, hi0, zero))
        his = his.at[1].set(jnp.where(do1, hi1, zero))
        los = los.at[0].set(jnp.where(do0, lo0, zero))
        los = los.at[1].set(jnp.where(do1, lo1, zero))
        return ring, state, his, los

    def _branchless_nslots(
        self, row: np.ndarray, last_active: Optional[int] = None
    ) -> int:
        """Smallest coalesced variant covering the row's last active slot
        (its advance count and its highest real save). `last_active` is the
        caller's precomputed 1-based last active slot (the backend's parse
        already knows it), skipping the save-slot rescan."""
        if last_active is None:
            save_slots = np.asarray(row[self._off_save : self._off_status])
            last_active = max(int(row[2]), 1)
            valid = np.nonzero(save_slots < self.ring_len)[0]
            if valid.size:
                last_active = max(last_active, int(valid[-1]) + 1)
        return self.variant_for(last_active)

    def variant_for(self, last_active: int) -> int:
        """Smallest coalesced depth variant covering a 1-based last
        active slot — THE rounding rule every depth-routed path shares
        (T=1 branchless, the lazy multi-tick scan)."""
        for v in self.branchless_variants():
            if v >= last_active:
                return v
        raise ContractViolation(
            f"no variant covers {last_active} slots (variants end in window)"
        )

    def _pallas_t1(self) -> bool:
        """Do lone ticks route through the pallas tick kernel? Size-aware
        (see PALLAS_T1_MIN_ENTITIES): on big worlds the kernel's
        size-flat VMEM streaming beats every XLA T=1 program."""
        n = getattr(self.game, "num_entities", None)
        return (
            self._tick_pallas_fn is not None
            and n is not None
            and n >= self.PALLAS_T1_MIN_ENTITIES
        )

    def tick_row(
        self, row: np.ndarray, last_active: Optional[int] = None
    ) -> Tuple[Any, Any]:
        """One packed tick row through the (warmup-compiled) single-tick
        program; returns (checksum_hi[W], checksum_lo[W]). `last_active`
        (optional) is the row's 1-based last active slot, precomputed by
        the backend's parse so variant routing skips a save-slot rescan."""
        if self._pallas_t1():
            self.ring, self.state, self.verify, his, los = (
                self._tick_pallas_fn(
                    self.ring, self.state, row[None, :], self.verify
                )
            )
            return his[0], los[0]
        # row-content routing (rationale: the __init__ comment): rollback
        # / multi-advance rows run the branchless program at the smallest
        # depth variant covering the row; trivial rows keep cond
        if self._tick_branchless_fn is not None and (
            row[0] != 0 or row[2] > 1
        ):
            nslots = self._branchless_nslots(row, last_active)
            if GLOBAL_TELEMETRY.enabled:
                self._m_depth.observe(nslots)
                self._m_waste.inc(self.window - nslots)
            self.ring, self.state, self.verify, his, los = (
                self._tick_branchless_fn(
                    self.ring, self.state, row, self.verify, nslots,
                )
            )
            return his, los
        # trivial rows (mid-size worlds): the windowed cond program at
        # the smallest covering variant — same cond skipping, a fraction
        # of the scanned slots. Worlds below T1_WINDOWED_MIN_ENTITIES
        # keep the full cond program (the saved slots are not worth a
        # per-core compile there), and worlds past the branchless cap
        # keep it untouched too (their routing economics were measured
        # there; a rollback row's variant can reach W anyway).
        if self._t1_windowed:
            nslots = self._branchless_nslots(row, last_active)
            if nslots < self.window:
                if GLOBAL_TELEMETRY.enabled:
                    self._m_depth.observe(nslots)
                    self._m_waste.inc(self.window - nslots)
                self.ring, self.state, self.verify, his, los = (
                    self._tick_windowed_fn(
                        self.ring, self.state, row, self.verify, nslots,
                    )
                )
                return his, los
        self.ring, self.state, self.verify, his, los = self._tick_fn(
            self.ring, self.state, row, self.verify
        )
        return his, los

    def tick_multi(
        self, rows: np.ndarray, last_active: Optional[int] = None
    ) -> Tuple[Any, Any]:
        """Run T packed ticks (layout: see tick()) in one dispatch; returns
        (checksum_hi[T, W], checksum_lo[T, W]) as device arrays. Multi-row
        dispatches route to the pallas tick kernel when the core has one:
        streaming state + ring through VMEM amortizes over the rows, and
        the kernel wins from T=2 up (measured 2.3x at T=4, 3-4x at T=16 on
        a 65k world). T=1 stays on the XLA scan on small/mid worlds,
        whose lax.cond slot skipping beats the kernel's masked full
        window for a lone tick — but routes to the kernel from
        PALLAS_T1_MIN_ENTITIES up, where every XLA T=1 program's unfused
        passes cost more than the kernel's size-flat streaming.

        `last_active` (optional): the MAX 1-based last active slot across
        the buffered rows, precomputed by the backend's parse. The XLA
        scan then runs the depth variant covering it instead of the full
        window — bit-identical (slots past every row's last active one
        are inert) at a fraction of the scanned device work. None keeps
        the full-window program (the depth-routing-off reference). The
        pallas kernel path ignores it: the kernel's VMEM streaming is
        already window-flat."""
        if self._tick_pallas_fn is not None and (
            rows.shape[0] > 1 or self._pallas_t1()
        ):
            self.ring, self.state, self.verify, his, los = (
                self._tick_pallas_fn(self.ring, self.state, rows, self.verify)
            )
            return his, los
        nslots = (
            self.window if last_active is None else self.variant_for(last_active)
        )
        if GLOBAL_TELEMETRY.enabled and last_active is not None:
            self._m_depth.observe(nslots)
            self._m_waste.inc((self.window - nslots) * int(rows.shape[0]))
        self.ring, self.state, self.verify, his, los = self._tick_multi_fn(
            self.ring, self.state, rows, self.verify, nslots
        )
        return his, los

    def _verify_update(self, verify, frame, hi, lo):
        """First-seen history record/compare + mismatch latch (the device
        twin of the fused session's _save_and_check). Static no-op when
        device verification is off."""
        if not self.device_verify:
            return verify
        h = frame % self.ring_len
        seen = verify["h_tag"][h] == frame
        differs = seen & (
            (verify["h_hi"][h] != hi) | (verify["h_lo"][h] != lo)
        )
        first = differs & (verify["flag"][0] == 0)
        flag = verify["flag"]
        flag = flag.at[0].set(jnp.where(differs, 1, flag[0]))
        flag = flag.at[1].set(jnp.where(first, frame, flag[1]))
        return {
            "h_tag": verify["h_tag"].at[h].set(frame),
            "h_hi": verify["h_hi"].at[h].set(
                jnp.where(seen, verify["h_hi"][h], hi)
            ),
            "h_lo": verify["h_lo"].at[h].set(
                jnp.where(seen, verify["h_lo"][h], lo)
            ),
            "flag": flag,
        }

    def _tick_impl(
        self,
        ring,
        state,
        do_load,  # bool[]
        load_slot,  # i32[]
        inputs,  # u8[W, P, input_size]
        statuses,  # i32[W, P]
        save_slots,  # i32[S]; scratch_slot means "no save"
        advance_count,  # i32[]
        start_frame,  # i32[]; frame of the first window slot
        verify,  # device-verify carry ({} when disabled)
        nslots=None,  # static slot count (None = the full window)
    ):
        if nslots is None:
            nslots = self.window
        loaded = jax.tree.map(
            lambda r: jax.lax.dynamic_index_in_dim(r, load_slot, 0, keepdims=False),
            ring,
        )
        state = _tree_where(do_load, loaded, state)

        iota = jnp.arange(nslots, dtype=jnp.int32)

        def body(carry, xs):
            ring, state, verify = carry
            i, inp, stat, save_slot = xs
            # save-then-advance: slot i snapshots the pre-advance state.
            # lax.cond (not a masked select) so skipped slots cost nothing:
            # XLA executes only the taken branch, making the tick's device
            # time proportional to the ACTUAL rollback depth and save
            # count, not to the static window (a no-rollback tick runs one
            # step + one checksum instead of W of each).
            do_save = save_slot < self.ring_len

            def save(args):
                ring, state, verify = args
                hi, lo = self.game.checksum(state)
                ring = jax.tree.map(
                    lambda r, s: jax.lax.dynamic_update_index_in_dim(
                        r, s, save_slot, 0
                    ),
                    ring,
                    state,
                )
                verify = self._verify_update(verify, start_frame + i, hi, lo)
                return ring, verify, hi, lo

            def skip(args):
                ring, _, verify = args
                return ring, verify, jnp.uint32(0), jnp.uint32(0)

            ring, verify, hi, lo = jax.lax.cond(
                do_save, save, skip, (ring, state, verify)
            )
            state = jax.lax.cond(
                i < advance_count,
                lambda s: self.game.step(s, inp, stat),
                lambda s: s,
                state,
            )
            return (ring, state, verify), (hi, lo)

        (ring, state, verify), (his, los) = jax.lax.scan(
            body, (ring, state, verify), (iota, inputs, statuses, save_slots)
        )
        return ring, state, verify, his, los

    # ------------------------------------------------------------------

    def pack_tick_row(
        self,
        do_load: bool,
        load_slot: int,
        inputs: np.ndarray,
        statuses: np.ndarray,
        save_slots: np.ndarray,
        advance_count: int,
        start_frame: int = 0,
    ) -> np.ndarray:
        """Build one tick's packed control-word row (the _tick_packed_impl
        layout) — dispatched alone by tick() or buffered for a multi-tick
        dispatch by the backend's lazy batching."""
        packed = np.empty((self._packed_len,), dtype=np.int32)
        self.pack_tick_row_into(
            packed, do_load, load_slot, inputs, statuses, save_slots,
            advance_count, start_frame,
        )
        return packed

    def pack_tick_row_into(
        self,
        out: np.ndarray,
        do_load: bool,
        load_slot: int,
        inputs: np.ndarray,
        statuses: np.ndarray,
        save_slots: np.ndarray,
        advance_count: int,
        start_frame: int = 0,
    ) -> np.ndarray:
        """pack_tick_row writing into a caller-owned buffer. The async
        dispatch pipeline stages rows in a small rotating pool instead of
        allocating per tick; the buffer handed to a dispatch must not be
        reused until that dispatch's slot rotates back around (the backend's
        double-buffering guarantees it)."""
        out[0] = 1 if do_load else 0
        out[1] = load_slot
        out[2] = advance_count
        out[3] = start_frame
        out[self._off_save : self._off_status] = save_slots
        out[self._off_status : self._off_input] = statuses.reshape(-1)
        out[self._off_input :] = inputs.reshape(-1)
        return out

    def pad_tick_row(self) -> np.ndarray:
        """A true no-op tick row (no load, zero advances, scratch-only
        saves): pads a partial lazy buffer so one buffer length compiles
        once."""
        return self.pack_tick_row(
            False,
            0,
            np.zeros((self.window, self.num_players, self.game.input_size),
                     dtype=np.uint8),
            np.zeros((self.window, self.num_players), dtype=np.int32),
            np.full((self.window,), self.scratch_slot, dtype=np.int32),
            0,
        )

    def tick(
        self,
        do_load: bool,
        load_slot: int,
        inputs: np.ndarray,
        statuses: np.ndarray,
        save_slots: np.ndarray,
        advance_count: int,
        start_frame: int = 0,
    ) -> Tuple[Any, Any]:
        """Run one fused tick; returns (checksum_hi[W], checksum_lo[W]) as
        device arrays (no host sync). `start_frame` feeds the device-verify
        history (slot i saves frame start_frame + i)."""
        packed = self.pack_tick_row(
            do_load, load_slot, inputs, statuses, save_slots, advance_count,
            start_frame,
        )
        return self.tick_row(packed)

    def check_device_verdict(self) -> Tuple[bool, int]:
        """Fetch the device-verify latch: (mismatch?, first bad frame).
        ONE small host readback — the only transfer device verification
        ever makes."""
        assert self.device_verify, "core built without device_verify"
        flag = np.asarray(jax.device_get(self.verify["flag"]))
        return bool(flag[0]), int(flag[1])

    # ------------------------------------------------------------------
    # speculative beam (the north-star "rollback becomes a select"):
    # evaluate B candidate input futures from a ring snapshot ahead of
    # input confirmation; a later rollback whose corrected script matches a
    # member adopts its precomputed trajectory instead of resimulating.
    # ------------------------------------------------------------------

    def _speculate_impl(self, ring, anchor_slot, beam_inputs, beam_statuses):
        """beam_inputs u8[B, W, P, I], beam_statuses i32[B, W, P] ->
        per-member per-frame trajectories [B, W, ...], per-frame checksums
        [B, W] (of the state AFTER each step), and the anchor's checksum."""
        if (
            self._beam_sharding is not None
            and beam_inputs.shape[0] % self.mesh.shape["beam"] == 0
        ):
            beam_inputs = jax.lax.with_sharding_constraint(
                beam_inputs, self._beam_sharding
            )
            beam_statuses = jax.lax.with_sharding_constraint(
                beam_statuses, self._beam_sharding
            )
        anchor = jax.tree.map(
            lambda r: jax.lax.dynamic_index_in_dim(r, anchor_slot, 0, keepdims=False),
            ring,
        )
        a_hi, a_lo = self.game.checksum(anchor)

        def rollout_one(inputs, statuses):
            def body(s, xs):
                inp, stat = xs
                nxt = self.game.step(s, inp, stat)
                hi, lo = self.game.checksum(nxt)
                return nxt, (nxt, hi, lo)

            _, (traj, his, los) = jax.lax.scan(body, anchor, (inputs, statuses))
            return traj, his, los

        traj, his, los = jax.vmap(rollout_one)(beam_inputs, beam_statuses)
        return traj, his, los, a_hi, a_lo

    def _speculate_pallas(self, anchor_slot, beam_inputs):
        """Pallas-rollout speculation: gather the anchor snapshot, then run
        the entity-tiled beam kernel on it. Output tuple matches
        _speculate_impl bit-for-bit (all-CONFIRMED statuses). A rollout the
        kernel rejects (reduce-phase adapter whose B*L trajectory windows
        exceed the single-tile budget) demotes this core to the XLA
        speculation path permanently — same results, unfused cost."""
        B = int(beam_inputs.shape[0])
        if B not in self._beam_rollouts:
            from .pallas_beam import PallasBeamRollout, ShardedPallasBeamRollout

            try:
                if self.mesh is not None:
                    self._beam_rollouts[B] = ShardedPallasBeamRollout(
                        self.game,
                        self.num_players,
                        B,
                        self.mesh,
                        interpret=self.spec_backend.endswith("-interpret"),
                        max_rollout=self.window,
                    )
                else:
                    self._beam_rollouts[B] = PallasBeamRollout(
                        self.game,
                        self.num_players,
                        B,
                        interpret=self.spec_backend.endswith("-interpret"),
                        max_rollout=self.window,  # VMEM budget sized to worst case
                    )
            except (AssertionError, ValueError) as e:
                # narrow on purpose (r3 advisor): a broken adapter should
                # surface, only a sizing rejection falls back
                import warnings

                warnings.warn(
                    f"pallas beam rollout unavailable for "
                    f"{type(self.game).__name__} (B={B}): {e}; speculating "
                    "via the XLA path"
                )
                self.spec_backend = "xla"
                return self._speculate_fn(
                    self.ring,
                    np.int32(anchor_slot),
                    beam_inputs,
                    np.zeros(beam_inputs.shape[:3], dtype=np.int32),
                )
            rollout = self._beam_rollouts[B]

            def impl(ring, anchor_slot, beam_inputs):
                anchor = jax.tree.map(
                    lambda r: jax.lax.dynamic_index_in_dim(
                        r, anchor_slot, 0, keepdims=False
                    ),
                    ring,
                )
                a_hi, a_lo = self.game.checksum(anchor)
                traj, his, los = rollout.rollout(anchor, beam_inputs)
                return traj, his, los, a_hi, a_lo

            self._speculate_pallas_fns[B] = jax.jit(impl)
        return self._speculate_pallas_fns[B](
            self.ring, np.int32(anchor_slot), beam_inputs
        )

    def speculate(self, anchor_slot: int, beam_inputs: np.ndarray,
                  beam_statuses: np.ndarray):
        """Dispatch a beam rollout from ring slot `anchor_slot` (async).
        The pallas backend speculates under the all-CONFIRMED statuses
        contract (the only way the beam is ever used); rollouts with any
        non-CONFIRMED status fall back to the XLA path."""
        if self.spec_backend.startswith("pallas") and not np.any(
            np.asarray(beam_statuses)
        ):
            return self._speculate_pallas(anchor_slot, beam_inputs)
        return self._speculate_fn(
            self.ring, np.int32(anchor_slot), beam_inputs, beam_statuses
        )

    def _adopt_impl(self, ring, traj, spec_his, spec_los, a_hi, a_lo, verify,
                    packed):
        """Commit a beam member's trajectory as (the prefix of) this tick's
        result. The first `matched` frames are served from the speculation:
        ring slots fill with the member's precomputed per-frame states
        (slot i = state at load_frame + i = trajectory index shift+i-1) and
        their checksums come from the speculation — no step or checksum
        math reruns. Frames past `matched` RESIMULATE from the member's
        frame load+matched state with the actual corrected inputs, exactly
        like _tick_impl, in this same dispatch — one wrong byte from one
        player no longer discards an otherwise-correct trajectory, it
        costs only the mispredicted suffix (the TPU analog of the
        reference's per-player misprediction localization,
        src/input_queue.rs:167-204). `matched == advance_count` is the
        full adoption. `shift` offsets into the trajectory: the
        speculation was anchored `shift` frames BEFORE the rollback's load
        frame — depth jitter doesn't invalidate the speculation. Control
        words + suffix inputs ride one packed array for the same
        one-transfer reason as _tick_packed_impl."""
        W, P, I = self.window, self.num_players, self.game.input_size
        member = packed[0]
        load_slot = packed[1]
        advance_count = packed[2]
        shift = packed[3]
        load_frame = packed[4]
        matched = packed[5]
        save_slots = packed[self._aoff_save : self._aoff_status]
        statuses = packed[self._aoff_status : self._aoff_input].reshape(W, P)
        inputs = (
            packed[self._aoff_input : self._apacked_len]
            .astype(jnp.uint8)
            .reshape(W, P, I)
        )
        loaded = jax.tree.map(
            lambda r: jax.lax.dynamic_index_in_dim(r, load_slot, 0, keepdims=False),
            ring,
        )
        mtraj = jax.tree.map(
            lambda t: jax.lax.dynamic_index_in_dim(t, member, 0, keepdims=False),
            traj,
        )
        mhis = jax.lax.dynamic_index_in_dim(spec_his, member, 0, keepdims=False)
        mlos = jax.lax.dynamic_index_in_dim(spec_los, member, 0, keepdims=False)
        # checksums of frames anchor..anchor+rollout, windowed at shift;
        # zero-pad so dynamic_slice never clamps (entries past shift+matched
        # are never read: suffix saves compute their checksums fresh)
        pad = jnp.zeros((self.window - 1,), dtype=a_hi.dtype)
        full_hi = jnp.concatenate([a_hi[None], mhis, pad])
        full_lo = jnp.concatenate([a_lo[None], mlos, pad])
        his = jax.lax.dynamic_slice(full_hi, (shift,), (self.window,))
        los = jax.lax.dynamic_slice(full_lo, (shift,), (self.window,))

        iota = jnp.arange(self.window, dtype=jnp.int32)

        def body(carry, xs):
            ring, state, verify = carry
            i, inp, stat, save_slot, spec_hi, spec_lo = xs
            # slots i <= matched enter on the precomputed trajectory state
            # of frame load+i (idx < 0 only at shift=0, i=0: the anchor
            # snapshot itself); later slots carry the resimulated state.
            # The gather is cond-gated and fires ONLY where the trajectory
            # state is actually consumed — a saved prefix slot, or the
            # i == matched slot that seeds the resimulated suffix. Prefix
            # slots that save nothing, suffix slots and scratch padding pay
            # nothing (an ungated per-slot gather measurably made partial
            # adoption cost more device time than the resim it replaced).
            idx = shift + i - 1

            def from_traj(state):
                prev = jax.tree.map(
                    lambda t: jax.lax.dynamic_index_in_dim(
                        t, jnp.maximum(idx, 0), 0, keepdims=False
                    ),
                    mtraj,
                )
                return _tree_where(idx < 0, loaded, prev)

            need_traj = (i <= matched) & (
                (save_slot < self.ring_len) | (i == matched)
            )
            state = jax.lax.cond(need_traj, from_traj, lambda s: s, state)
            use_spec = i <= matched

            def save(args):
                ring, state, verify = args
                hi, lo = jax.lax.cond(
                    use_spec,
                    lambda s: (spec_hi, spec_lo),
                    lambda s: self.game.checksum(s),
                    state,
                )
                ring = jax.tree.map(
                    lambda r, s: jax.lax.dynamic_update_index_in_dim(
                        r, s, save_slot, 0
                    ),
                    ring,
                    state,
                )
                verify = self._verify_update(verify, load_frame + i, hi, lo)
                return ring, verify, hi, lo

            def skip(args):
                ring, _, verify = args
                return ring, verify, jnp.uint32(0), jnp.uint32(0)

            # scratch-slot writes skipped outright (same cond rationale as
            # _tick_impl: device time tracks the actual save count)
            ring, verify, hi, lo = jax.lax.cond(
                save_slot < self.ring_len, save, skip, (ring, state, verify)
            )
            # only the mispredicted suffix resimulates; served frames'
            # states come from the trajectory selects above
            state = jax.lax.cond(
                (i >= matched) & (i < advance_count),
                lambda s: self.game.step(s, inp, stat),
                lambda s: s,
                state,
            )
            return (ring, state, verify), (hi, lo)

        (ring, state, verify), (out_his, out_los) = jax.lax.scan(
            body, (ring, loaded, verify),
            (iota, inputs, statuses, save_slots, his, los),
        )
        return ring, state, verify, out_his, out_los

    def _adopt_full_impl(self, ring, traj, spec_his, spec_los, a_hi, a_lo,
                         verify, packed):
        """Branchless FULL-hit adoption: bit-identical to _adopt_impl when
        matched == advance_count (adopt() routes only that case here).
        Every slot's state is a select over the member trajectory, every
        saved checksum comes from the speculation, masked saves write the
        OLD value back to slot 0 — no scan, no cond, no game math. The
        packed layout is _adopt_impl's; the suffix input/status words ride
        along unused so both programs share one host-side pack."""
        member = packed[0]
        load_slot = packed[1]
        shift = packed[3]
        load_frame = packed[4]
        matched = packed[5]
        save_slots = packed[self._aoff_save : self._aoff_status]
        loaded = jax.tree.map(
            lambda r: jax.lax.dynamic_index_in_dim(
                r, load_slot, 0, keepdims=False
            ),
            ring,
        )
        mtraj = jax.tree.map(
            lambda t: jax.lax.dynamic_index_in_dim(t, member, 0, keepdims=False),
            traj,
        )
        mhis = jax.lax.dynamic_index_in_dim(spec_his, member, 0, keepdims=False)
        mlos = jax.lax.dynamic_index_in_dim(spec_los, member, 0, keepdims=False)
        pad = jnp.zeros((self.window - 1,), dtype=a_hi.dtype)
        full_hi = jnp.concatenate([a_hi[None], mhis, pad])
        full_lo = jnp.concatenate([a_lo[None], mlos, pad])
        his_w = jax.lax.dynamic_slice(full_hi, (shift,), (self.window,))
        los_w = jax.lax.dynamic_slice(full_lo, (shift,), (self.window,))

        his, los = [], []
        state = loaded
        for i in range(self.window):
            # with no suffix to resimulate, the state entering slot i is
            # trajectory index shift + min(i, matched) - 1 (the anchor
            # snapshot itself when that is negative: shift == 0, i == 0)
            eff = shift + jnp.minimum(i, matched) - 1
            prev = jax.tree.map(
                lambda t: jax.lax.dynamic_index_in_dim(
                    t, jnp.maximum(eff, 0), 0, keepdims=False
                ),
                mtraj,
            )
            state = _tree_where(eff < 0, loaded, prev)
            save_slot = save_slots[i]
            do_save = save_slot < self.ring_len
            hi = jnp.where(do_save, his_w[i], jnp.uint32(0))
            lo = jnp.where(do_save, los_w[i], jnp.uint32(0))
            wslot = jnp.where(do_save, save_slot, 0)
            old = jax.tree.map(
                lambda r: jax.lax.dynamic_index_in_dim(
                    r, wslot, 0, keepdims=False
                ),
                ring,
            )
            ring = jax.tree.map(
                lambda r, s: jax.lax.dynamic_update_index_in_dim(
                    r, s, wslot, 0
                ),
                ring,
                _tree_where(do_save, state, old),
            )
            if self.device_verify:
                upd = self._verify_update(verify, load_frame + i, hi, lo)
                verify = _tree_where(do_save, upd, verify)
            his.append(hi)
            los.append(lo)
        return ring, state, verify, jnp.stack(his), jnp.stack(los)

    def pack_adopt_row(self, member: int, load_slot: int,
                       advance_count: int, shift: int, load_frame: int,
                       matched: int, save_slots: np.ndarray,
                       statuses: Optional[np.ndarray] = None,
                       inputs: Optional[np.ndarray] = None) -> np.ndarray:
        """Build one adoption's packed control-word row (the _adopt_impl
        layout) — THE one definition of the adopt layout, shared by
        adopt() and the serving megabatch's per-slot adoption
        (MultiSessionDeviceCore.adopt_slot)."""
        packed = np.zeros((self._apacked_len,), dtype=np.int32)
        packed[0] = member
        packed[1] = load_slot
        packed[2] = advance_count
        packed[3] = shift
        packed[4] = load_frame
        packed[5] = matched
        packed[self._aoff_save : self._aoff_status] = save_slots
        if statuses is not None:
            packed[self._aoff_status : self._aoff_input] = statuses.reshape(-1)
        if inputs is not None:
            packed[self._aoff_input :] = inputs.reshape(-1)
        return packed

    def adopt(self, spec, member: int, load_slot: int, save_slots: np.ndarray,
              advance_count: int, shift: int = 0, load_frame: int = 0,
              inputs: Optional[np.ndarray] = None,
              statuses: Optional[np.ndarray] = None,
              matched: Optional[int] = None) -> Tuple[Any, Any]:
        """Fulfill a rollback tick from a (prefix-)matching speculation;
        returns (checksum_hi[W], checksum_lo[W]) like tick(). `shift` =
        load_frame - anchor_frame (caller guarantees the member's first
        `shift` input rows equal the inputs actually played for frames
        anchor..load). `matched` (default: advance_count, the full
        adoption) is how many corrected frames the member's rows match;
        the rest resimulate from `inputs`/`statuses` in this dispatch —
        required whenever matched < advance_count."""
        if matched is None:
            matched = advance_count
        assert matched == advance_count or inputs is not None, (
            "partial adoption needs the corrected inputs for the suffix"
        )
        traj, spec_his, spec_los, a_hi, a_lo = spec
        packed = self.pack_adopt_row(
            member, load_slot, advance_count, shift, load_frame, matched,
            save_slots, statuses=statuses, inputs=inputs,
        )
        # full hits route to the branchless pure-data-movement program
        # (see the _adopt_full_fn comment in __init__); partial hits keep
        # the cond program for its genuine suffix resimulation
        fn = (
            self._adopt_full_fn
            if matched == advance_count and self._adopt_full_fn is not None
            else self._adopt_fn
        )
        if fn is self._adopt_full_fn:
            # contract guard: _adopt_full_impl sources EVERY saved slot's
            # checksum from the speculation window (his_w[i]), while
            # _adopt_impl computes fresh checksums for slots past
            # `matched`. The two are bit-identical only because no caller
            # requests a real save past advance_count on a full hit — a
            # caller violating that would get speculation checksums for
            # frames the speculation never covered, silently.
            assert (
                save_slots[advance_count + 1 :] >= self.ring_len
            ).all(), (
                "full-hit adoption requires every save slot past "
                "advance_count to be scratch (speculation checksums do "
                "not cover frames beyond the adopted window)"
            )
        self.ring, self.state, self.verify, his, los = fn(
            self.ring, traj, spec_his, spec_los, a_hi, a_lo, self.verify,
            packed,
        )
        return his, los

    def reset(self) -> None:
        """Return the core to its initial condition (fresh world, zeroed
        ring and verify carry) WITHOUT recompiling anything — a new
        session can reuse a warmed core's compiled programs (each compile
        costs tens of seconds through the tunnel)."""
        state = self.game.init_state()
        if self.mesh is not None:
            from ..parallel.sharded import shard_state

            state = shard_state(state, self.mesh)
        self.state = state
        self.ring = jax.tree.map(jnp.zeros_like, self.ring)
        if self.device_verify:
            self.verify = {
                "h_tag": jnp.full_like(self.verify["h_tag"], -1),
                "h_hi": jnp.zeros_like(self.verify["h_hi"]),
                "h_lo": jnp.zeros_like(self.verify["h_lo"]),
                # device_put onto the existing sharding: a bare asarray
                # would drop the mesh placement __init__ applied and make
                # the next donated tick recompile (or reject the pytree)
                "flag": jax.device_put(
                    np.array([0, -1], dtype=np.int32),
                    self.verify["flag"].sharding,
                ),
            }

    def fetch_state(self):
        """Device -> host copy of the live state (test/debug aid)."""
        return jax.device_get(self.state)

    def fetch_ring_slot(self, slot: int):
        return jax.device_get(
            jax.tree.map(lambda r: r[slot], self.ring)
        )
