"""TpuRollbackBackend: fulfills a session's ordered request list on device.

This is the pluggable seam BASELINE.json prescribes: sessions
(SyncTestSession, P2PSession) keep emitting the reference's ordered
Save/Load/Advance requests (src/lib.rs:169-194), and this backend consumes
them — but instead of executing them one by one through user callbacks, it
parses the request grammar

    [Load?] (Save? Advance)* Save?

(the exact shape every session emits per tick: first-frame double save,
dense/sparse rollback blocks, trailing confirmed-frame saves) and lowers the
whole tick into ONE fused device dispatch via ResimCore. Snapshot data never
leaves the device; cells are filled with lightweight SnapshotRef handles and
lazy checksums that only force a device->host transfer when read.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..analysis.sanitize import (
    active_sanitizer,
    transfer_guard_scope,
    warmup_scope,
)
from ..errors import ConfigError, ContractViolation, TypeContractError
from ..obs import (
    GLOBAL_TELEMETRY,
    LOG2_BUCKETS,
    LOG2_BUCKETS_MS,
    SESSION_COUNT_BUCKETS,
    SHARD_IMBALANCE_BUCKETS,
)
from ..ops.fixed_point import combine_checksum
from ..types import (
    AdvanceFrame,
    Frame,
    InputStatus,
    LoadGameState,
    Request,
    SaveGameState,
)
from ..utils.tracing import GLOBAL_TRACER
from .resim import ResimCore


@dataclass(frozen=True)
class SnapshotRef:
    """Opaque handle stored in a GameStateCell: the snapshot lives in the
    device ring, addressed by frame (slot = frame % ring_len)."""

    frame: Frame
    ring_slot: int


@dataclass(frozen=True)
class DraftBatch:
    """One draft dispatch's device-resident results: per-member per-frame
    trajectories (traj pytree [B, W, ...]), per-frame post-step checksums
    (his/los [B, W]) and the anchor checksums (a_hi/a_lo [B]) — the
    "ring-parked branch" a later adopt_slot serves (a prefix of) a
    session tick from. Member k is the k-th drafted slot of the launch;
    the confirmed stacked worlds are never touched by a draft."""

    traj: Any
    his: Any
    los: Any
    a_hi: Any
    a_lo: Any
    bucket: int


def _array_is_ready(arr) -> bool:
    is_ready = getattr(arr, "is_ready", None)
    return bool(is_ready()) if callable(is_ready) else True


class _ChecksumBatch:
    """One dispatch's worth of device checksums ([W] for a single tick,
    [T, W] for a lazy multi-tick flush — lazy checksum indices are flat
    row-major either way); fetched to host at most once, and only if some
    cell's checksum is actually read. Resolution goes through the owning
    ChecksumLedger so every pending batch rides the same device->host
    transfer — on a remote/tunneled device one round trip costs ~100ms,
    so per-read transfers would dominate the whole tick."""

    def __init__(self, his, los, ledger: "ChecksumLedger"):
        self._his = his
        self._los = los
        self._np: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._prefetched = False
        self._ledger = ledger
        ledger.register(self)

    def prefetch(self) -> None:
        """Start a background device->host copy (non-blocking). Only marked
        prefetched when a copy actually started: resolve() trusts the flag
        to read per-batch without a fresh round trip, which would otherwise
        turn into per-batch blocking transfers on array types without
        copy_to_host_async (those keep the packed ledger-flush path)."""
        if self._np is None and not self._prefetched:
            started = False
            for arr in (self._his, self._los):
                copy = getattr(arr, "copy_to_host_async", None)
                if callable(copy):
                    copy()
                    started = True
            self._prefetched = started

    @property
    def ready(self) -> bool:
        """True when resolve() will not block on device work/transfer."""
        return self._np is not None or (
            _array_is_ready(self._his) and _array_is_ready(self._los)
        )

    def resolve(self, idx: int) -> int:
        if self._np is None and self._prefetched:
            # consume the async host copy directly; going through the
            # ledger's packed transfer would re-fetch what the prefetch
            # already moved. Callers prefetch a full drain period before
            # resolving, so this conversion is a host-memory read in steady
            # state (and at worst waits on the in-flight copy — still
            # cheaper than a fresh packed round trip).
            self._store(self._his, self._los)
        if self._np is None:
            self._ledger.flush()
        if self._np is None:  # evicted from the ledger before this read
            self._store(self._his, self._los)
        return combine_checksum(self._np[0][idx], self._np[1][idx])

    def _store(self, his: np.ndarray, los: np.ndarray) -> None:
        # flat row-major: multi-tick [T, W] batches index as j*W + i
        self._np = (np.asarray(his).ravel(), np.asarray(los).ravel())


class ChecksumLedger:
    """Batches checksum transfers across ticks: the first read of ANY lazy
    checksum fetches every pending batch in ONE jax.device_get. Bounded so
    sessions that never read checksums (desync detection off) don't
    accumulate stale batches; evicted batches resolve individually."""

    MAX_PENDING = 128

    def __init__(self):
        self._pending: List[_ChecksumBatch] = []

    def register(self, batch: _ChecksumBatch) -> None:
        self._pending.append(batch)
        if len(self._pending) > self.MAX_PENDING:
            del self._pending[: -self.MAX_PENDING]

    def drain_ready(self) -> int:
        """Non-blocking drain for the pump pass (the drain-free tick):
        resolve every pending batch whose device arrays are already
        host-ready — a host-memory copy, no transfer wait — and start a
        background host copy on the oldest still-executing batch so the
        next pass (or a forced flush) finds its bytes moved. Returns the
        number of batches still pending."""
        still: List[_ChecksumBatch] = []
        for b in self._pending:
            if b._np is not None:
                continue
            if b.ready:
                b._store(b._his, b._los)
            else:
                still.append(b)
        self._pending = still
        if still:
            still[0].prefetch()
        return len(still)

    def flush(self) -> None:
        todo = [b for b in self._pending if b._np is None]
        self._pending.clear()
        if not todo:
            return
        # Pack every pending value into ONE device array before fetching:
        # on a tunneled device each transferred array pays ~10ms of latency
        # regardless of size, so fetching 2N small arrays is ~2N round
        # trips while one packed array is exactly one. The batch list is
        # padded to a power-of-two so the eager concatenate only ever
        # compiles for a handful of shapes, not one per drain size.
        import jax.numpy as jnp

        parts = [jnp.ravel(b._his) for b in todo] + [
            jnp.ravel(b._los) for b in todo
        ]
        bucket = 1
        while bucket < len(parts):
            bucket *= 2
        parts += [parts[0]] * (bucket - len(parts))
        packed = np.asarray(jnp.concatenate(parts))

        counts = [p.shape[0] for p in parts]
        offsets = np.concatenate([[0], np.cumsum(counts)])
        n = len(todo)
        for i, b in enumerate(todo):
            his = packed[offsets[i] : offsets[i + 1]]
            los = packed[offsets[n + i] : offsets[n + i + 1]]
            b._store(his, los)


class _LazyChecksum:
    """Zero-arg callable stored in a GameStateCell; supports non-blocking
    readiness checks and background prefetch."""

    __slots__ = ("_batch", "_idx")

    def __init__(self, batch: _ChecksumBatch, idx: int):
        self._batch = batch
        self._idx = idx

    def __call__(self) -> int:
        return self._batch.resolve(self._idx)

    def prefetch(self) -> None:
        self._batch.prefetch()

    @property
    def ready(self) -> bool:
        return self._batch.ready

    @property
    def dispatch_pending(self) -> bool:
        """True while the owning batch's dispatch hasn't happened yet (a
        resident fill cycle's future): prefetching such a getter would
        FORCE the dispatch — deterministic-publish binding skips those
        (sync_layer.PendingChecksumReport.bind_and_prefetch)."""
        return getattr(self._batch, "dispatch_pending", False)


class _FutureChecksumBatch:
    """Checksum-batch stand-in for ticks still sitting in the lazy tick
    buffer (no dispatch has happened, so no device arrays exist yet).
    First touch forces the backend's buffer flush, which installs the real
    batch; every method then delegates. Cells handed out before the flush
    keep working unmodified — laziness composes with laziness."""

    __slots__ = ("_flush", "batch")

    def __init__(self, flush_fn):
        self._flush = flush_fn
        self.batch: Optional[_ChecksumBatch] = None

    def _ensure(self) -> _ChecksumBatch:
        if self.batch is None:
            self._flush()
            assert self.batch is not None, "flush did not materialize batch"
        return self.batch

    def resolve(self, idx: int) -> int:
        return self._ensure().resolve(idx)

    def prefetch(self) -> None:
        # dispatching the buffer is non-blocking, so an early prefetch can
        # legitimately force it: the copy then overlaps device execution
        self._ensure().prefetch()

    @property
    def ready(self) -> bool:
        return self.batch is not None and self.batch.ready

    @property
    def dispatch_pending(self) -> bool:
        return self.batch is None


class DispatchPlanCache:
    """Canonical dispatch-signature tally: (has_load, advance_count,
    last_active, trailing_save?) -> dispatch count, fronting one jit
    cache. A TpuRollbackBackend owns one by default; a SessionHost's
    MultiSessionDeviceCore shares ONE across every hosted session —
    which is the point of canonicalization: every session's rollback
    blocks of a given shape coalesce onto the same cached program, so
    the Nth session admitted compiles nothing. Bounded in practice: the
    request grammar admits O(window^2) shapes."""

    def __init__(self):
        self.signatures: dict = {}
        _reg = GLOBAL_TELEMETRY.registry
        self._m_hits = _reg.counter(
            "ggrs_dispatch_plan_hits_total",
            "request segments whose canonical signature was already cached",
        )
        self._m_misses = _reg.counter(
            "ggrs_dispatch_plan_misses_total",
            "request segments that introduced a new canonical signature",
        )

    def note(self, sig, frame: Frame = -1, *, metrics: bool = True) -> bool:
        """Tally one dispatch of canonical signature `sig`; returns
        whether the signature was already known (a plan-cache hit).
        `metrics=False` keeps the tally out of the hit/miss counters —
        for signature populations that aren't request segments (e.g.
        megabatch bucket programs), which would otherwise pollute the
        segment-canonicalization hit rate operators read."""
        hit = sig in self.signatures
        self.signatures[sig] = self.signatures.get(sig, 0) + 1
        tel = GLOBAL_TELEMETRY
        if metrics and tel.enabled:
            if hit:
                self._m_hits.inc()
            else:
                self._m_misses.inc()
                tel.record("plan_cache_miss", frame=frame, signature=str(sig))
        return hit

    def clear(self) -> None:
        self.signatures.clear()


def parse_request_segment(
    requests: List[Request],
    *,
    window: int,
    ring_len: int,
    max_prediction: int,
    current_frame: Frame,
    inputs: np.ndarray,
    statuses: np.ndarray,
    save_slots: np.ndarray,
):
    """One pass over a session's request segment — the grammar
    [Load?] (Save? Advance)* Save? — into caller-owned packed staging
    (inputs u8[W,P,I], statuses i32[W,P], save_slots i32[W], all
    pre-filled with their neutral values; P may exceed the session's
    player count, in which case the caller pre-fills the pad columns).

    Returns (load, start_frame, count, saves, last_active,
    trailing_save): `saves` is [(window_slot, SaveGameState)] for lazy-
    checksum cell binding, `last_active` the row's 1-based last active
    slot for branchless-variant routing. THE one implementation of the
    grammar, shared by TpuRollbackBackend (pooled staging, per-backend
    jit cache) and the serve host's session lanes (fresh staging, one
    shared megabatch program)."""
    load: Optional[LoadGameState] = None
    slots: List[Tuple[Optional[SaveGameState], AdvanceFrame]] = []
    pending_save: Optional[SaveGameState] = None

    for req in requests:
        if isinstance(req, LoadGameState):
            assert load is None and not slots and pending_save is None, (
                "unsupported request pattern: Load must lead a segment"
            )
            load = req
        elif isinstance(req, SaveGameState):
            if pending_save is not None:
                # first-frame double save (p2p_session.rs:270-272 + :295)
                assert pending_save.frame == req.frame
            pending_save = req
        elif isinstance(req, AdvanceFrame):
            slots.append((pending_save, req))
            pending_save = None
        else:
            raise TypeContractError(f"unknown request {req!r}")
    trailing_save = pending_save

    count = len(slots)
    assert count <= max_prediction + 1, "tick exceeds the fused window"
    assert trailing_save is None or count < window

    start_frame = load.frame if load is not None else current_frame
    saves: List[Tuple[int, SaveGameState]] = []

    for i, (save, adv) in enumerate(slots):
        if save is not None:
            assert save.frame == start_frame + i, (
                f"save of frame {save.frame} out of order "
                f"(expected {start_frame + i})"
            )
            save_slots[i] = save.frame % ring_len
            saves.append((i, save))
        for p, (buf, status) in enumerate(adv.inputs):
            inputs[i, p] = np.frombuffer(buf, dtype=np.uint8)
            statuses[i, p] = int(status)
    if trailing_save is not None:
        assert trailing_save.frame == start_frame + count
        save_slots[count] = trailing_save.frame % ring_len
        saves.append((count, trailing_save))

    last_active = max(count, 1)
    if saves:
        last_active = max(last_active, saves[-1][0] + 1)
    return load, start_frame, count, saves, last_active, trailing_save


class TpuRollbackBackend:
    """Request-fulfilling rollback backend over a device game.

    Usage:
        backend = TpuRollbackBackend(game, max_prediction=8, num_players=2)
        requests = session.advance_frame()
        backend.handle_requests(requests)
    """

    # adaptive-gate value tracking. Every time a rollback CONSULTS the
    # standing speculation, one (branch_frames_served, member0_frames_
    # served, launches_spanned) sample lands in a trailing window —
    # launches superseded before any rollback looked at them count as
    # cost. Two economic signals, one per launch width (_launch_width):
    # branch-member serves justify the FULL width; member-0 serves
    # justify the width-1 HISTORY-ONLY launch (pinned history +
    # repeat-last at 1/B the rollout FLOPs — the measured costs decide
    # what that is worth: on the tunnel per-program overhead dominates
    # at interactive sizes and the widths price nearly the same, on
    # bigger worlds the B-fold device work is real). Below
    # MIN_SERVED_PER_LAUNCH
    # on both, the beam stands down entirely, except for a PROBE BURST
    # of consecutive full-width launches every VALUE_PROBE_INTERVAL
    # value-gated ticks: a burst (not a lone probe) because a speculation
    # consulted many ticks after its launch is stale by shift and would
    # miss regardless of the input regime — recovery needs a consult of
    # a FRESH spec (and member 0 rides in every full probe, so both
    # signals stay sampled).
    VALUE_WINDOW = 32  # consult samples retained
    MIN_SERVED_PER_LAUNCH = 0.3
    # the soft bar, applied when the MEASURED idle covers the measured
    # launch cost: a budget-covered launch costs the session nothing it
    # cares about (the beam is a latency feature riding idle), so value
    # gating then only protects against pointlessness — streams where
    # speculation serves literally nothing. The hard bar above prices
    # launches that the frame budget cannot absorb. Without the split,
    # streams with RARE rollbacks (one per ~10 ticks) could never clear
    # 0.3 frames/launch even with perfect candidates — every launch
    # superseded before a rollback counts as cost — and the gate locked
    # out exactly the serves it existed to enable (measured: neutral arm
    # 0.19 served at 71% gated vs 0.56 with fresh launches).
    MIN_SERVED_IDLE = 0.02
    VALUE_MIN_SAMPLES = 8  # consults before the gate may close
    VALUE_PROBE_INTERVAL = 24
    VALUE_PROBE_BURST = 3

    # async_dispatch with lazy_ticks unset batches this many ticks per
    # fused dispatch: deep enough to amortize the per-dispatch tunnel
    # floor ~an order of magnitude, shallow enough that the live state
    # lags the session by at most ~half a max_prediction window
    ASYNC_DEFAULT_LAZY_TICKS = 8

    def __init__(self, game, max_prediction: int, num_players: int,
                 beam_width: int = 0, mesh=None, device_verify: bool = False,
                 speculation_gate: str = "always",
                 defer_speculation: bool = False, lazy_ticks: int = 0,
                 spec_backend: str = "auto", tick_backend: str = "auto",
                 async_dispatch: bool = False, async_inflight: int = 4,
                 plan_cache: Optional["DispatchPlanCache"] = None,
                 depth_routing: bool = True):
        """`mesh`: optional jax Mesh with an `entity` axis — the world and
        its snapshot ring shard across it (see ResimCore); the session-facing
        contract (requests in, SnapshotRefs + lazy checksums out) is
        unchanged, and checksums stay bit-identical to the unsharded
        backend, so sharded and unsharded peers interoperate in one P2P
        session (desync detection agrees).

        `device_verify`: keep the SyncTest first-seen checksum history and
        mismatch verdict ON DEVICE (read with check()) so determinism runs
        never pay per-burst checksum readbacks — ~100ms a pop on a
        tunneled device. Only for confirmed-input replay (SyncTest): P2P
        rollbacks legitimately re-save corrected frames.

        `speculation_gate`: "always" launches a full-width speculation
        every tick (pays B*L speculative steps of device time
        unconditionally); "adaptive" picks a LAUNCH WIDTH per tick
        (_launch_width): the full beam when (a) the measured idle time
        between ticks covers the measured full-rollout cost — on a paced
        loop with spare frame budget the beam rides idle device time for
        free — and (b) recent launches' BRANCH members are actually
        being adopted (a trailing window of branch-frames-served-per-
        launch over MIN_SERVED_PER_LAUNCH); the width-1 HISTORY-ONLY
        rollout (member 0: pinned history + repeat-last, 1/B the FLOPs)
        when branch value is absent but member-0 serves aren't —
        forced-replay workloads where the corrected script IS played
        history; nothing at all when neither width earns its cost, with
        a periodic full-width probe burst every VALUE_PROBE_INTERVAL
        gated ticks so a regime change (a player starts toggling)
        re-opens the gate. Both widths' costs are measured once in
        warmup() (required for adaptive mode); host-loop idle is the
        proxy for device idle — the tunnel's async dispatch hides true
        device occupancy from the host.

        `defer_speculation`: keep the speculation launch OFF the tick's
        critical path — handle_requests() only fulfills requests; the
        caller launches the (gated) speculation from its idle time via
        launch_pending_speculation(). The launch costs ~1ms of host time
        (candidate generation + dispatch), which a real-time loop should
        pay after presenting the frame, not before.

        `async_dispatch`: the ASYNC DEVICE-RESIDENT DISPATCH PIPELINE.
        Three coupled behaviors, all bit-identical to the eager path
        (tests/test_async_dispatch.py is the proof):
        (1) device residency — lazy_ticks defaults to
        ASYNC_DEFAULT_LAZY_TICKS when unset, so the carry/state batch
        stays on device across ticks and dispatches as fused multi-tick
        programs; host protocol code keeps consuming the same lazy
        checksum futures it already does, drained in batches only when a
        SyncTest comparison or desync report actually reads a value.
        (2) overlap — dispatches are fenced at `async_inflight` in-flight
        batches (a small double-buffered carry at the default of 2): the
        host runs the NEXT tick's message pump / input prediction /
        request generation while the device executes the previous batch,
        and only when a third batch would enter the window does the host
        wait — on the OLDEST batch, not a full drain (the stall is
        spanned as tpu/async_fence: it is exactly the device time the
        pipeline failed to overlap). The fence also bounds how far the
        dispatch queue can run ahead (an unfenced loop can queue seconds
        of device work and then pay it all inside one blocking read).
        Host-side staging (parse buffers, the flush's multi-tick row
        buffer) rotates through async_inflight+1 pooled buffers instead
        of allocating per tick — safe to reuse precisely because the
        fence proves the dispatch that read a buffer has retired before
        the pool rotates back to it.
        (3) canonicalized dispatch signatures — request lists parse once
        into packed control rows via signature-keyed plans (the parse
        knows each row's last active slot, so branchless-variant routing
        skips its rescan), and repeated rollback blocks
        (Load + N x Save/Advance) of the same shape hit the same cached
        jitted program; distinct signatures are counted in
        dispatch_signatures for inspection.

        `lazy_ticks`: > 0 enables LAZY TICK BATCHING — ticks (rollbacks
        included) accumulate as packed control words on the host and
        dispatch as ONE fused multi-tick device program when the buffer
        fills or any device result is actually needed (a checksum read,
        state_numpy(), a speculation launch, flush()). Nothing a session
        needs synchronously lives on device — checksums are already lazy —
        so on the tunnel (where every dispatch costs ~1ms of host time
        regardless of content) this divides the request path's dominant
        cost by the buffer depth. The live state lags the session by up to
        lazy_ticks frames between flushes: loops that render every frame
        call state_numpy() (or flush()) per frame and get per-tick
        dispatch behavior back automatically.

        `depth_routing`: route the lazy multi-tick flush to the depth
        variant covering the buffer's deepest row (max last-active slot
        across the staged ticks) instead of always scanning full-window
        rows — bit-identical, proportionally less device work per
        zero-rollback tick. False pins the full-window scan (the parity
        suite's reference arm)."""
        self.core = ResimCore(
            game, max_prediction, num_players, mesh=mesh,
            device_verify=device_verify, spec_backend=spec_backend,
            tick_backend=tick_backend,
        )
        if (
            beam_width
            and self.core._beam_sharding is not None
            and beam_width % mesh.shape["beam"] != 0
        ):
            raise ConfigError(
                f"beam_width={beam_width} must divide evenly over the mesh's "
                f"beam axis ({mesh.shape['beam']}) — an indivisible beam "
                "would silently run replicated, wasting every beam shard"
            )
        self.num_players = num_players
        self.input_size = game.input_size
        self.current_frame: Frame = 0
        self.ledger = ChecksumLedger()
        # Speculative input beam (north star: the rollback becomes a select).
        # With beam_width > 0, every tick additionally rolls out B candidate
        # input futures from the frame the NEXT rollback is expected to load
        # (steady-state rollback depth shifts by one per tick); when the
        # rollback arrives and its corrected input script matches a member,
        # the precomputed trajectory is adopted — no resimulation. Correct
        # for any game whose step branches on statuses only to zero out
        # DISCONNECTED players (candidates are speculated as CONFIRMED).
        if beam_width:
            # the adoption-correctness contract (documented above) is now
            # ENFORCED, not assumed: games declare it explicitly
            contract = getattr(game, "statuses_contract", None)
            if contract != "disconnect-only":
                raise ConfigError(
                    "beam speculation adopts trajectories rolled out with "
                    "all-CONFIRMED statuses, which is only correct for games "
                    "whose step reads statuses solely to substitute "
                    "DISCONNECTED players' inputs; declare statuses_contract "
                    "= 'disconnect-only' on the game class to opt in "
                    f"(got {contract!r} on {type(game).__name__})"
                )
        self.beam_width = beam_width
        self._spec = None  # (anchor_frame, beam_inputs, device results)
        self._last_segment = None  # launch args, deferred to end of tick
        self.beam_hits = 0  # full adoptions (every corrected frame served)
        self.beam_partial_hits = 0  # prefix adoptions (suffix resimulated)
        self.beam_misses = 0
        # THE adoption metric: fraction of rollback frames served from
        # speculation = rollback_frames_adopted / rollback_frames (a full
        # hit serves all of a rollback's frames, a partial hit its matched
        # prefix) — honest about partial wins in a way hit counts aren't
        self.rollback_frames = 0
        self.rollback_frames_adopted = 0
        # per-player input history feeding the branching candidate
        # generator: last row seen and the previous DISTINCT row (the
        # toggle partner). Rows with predicted values repeat the last
        # confirmed input, so observed transitions are always real ones.
        p, i = num_players, game.input_size
        self._last_inputs = np.zeros((p, i), dtype=np.uint8)
        self._prev_inputs = np.zeros((p, i), dtype=np.uint8)
        # (inputs u8[P,I], statuses i32[P]) actually played per recent
        # frame: shift-flexible adoption checks a member's pre-load rows
        # against this history (frames before the load are confirmed-
        # correct, so what was played is what happened)
        self._played: dict = {}
        # online hold-length/transition statistics per player, learned
        # from FINALIZED rows (frames beyond rollback reach, so nothing
        # a later correction can rewrite ever enters the statistics);
        # ranks the beam's branch candidates by measured likelihood
        # instead of a uniform offset sweep (input_model.py)
        from .input_model import InputHistoryModel

        self.input_model = InputHistoryModel(num_players, game.input_size)
        self._finalized_to = -1  # newest frame already fed to the model
        # observed rollback depth (current-after-tick minus load frame);
        # the next speculation anchors one frame deeper than the depth
        # predicts so ±1 jitter still lands inside the member window
        self._depth = 2
        assert speculation_gate in ("always", "adaptive")
        self.speculation_gate = speculation_gate
        self.defer_speculation = defer_speculation
        assert lazy_ticks >= 0
        assert async_inflight >= 1
        self.async_dispatch = async_dispatch
        self.async_inflight = async_inflight
        if async_dispatch and lazy_ticks == 0:
            lazy_ticks = self.ASYNC_DEFAULT_LAZY_TICKS
        self.lazy_ticks = lazy_ticks
        self.depth_routing = depth_routing
        self._tick_rows: List[np.ndarray] = []  # packed rows awaiting dispatch
        # max 1-based last active slot across the buffered rows: the lazy
        # flush routes the multi-tick scan to the depth variant covering
        # it (pad rows are inert at any variant, so only real rows count)
        self._buffered_last_active = 0
        self._tick_future: Optional[_FutureChecksumBatch] = None
        # async pipeline state: the in-flight dispatch fence (device result
        # handles, oldest first) and the rotating host staging pools —
        # parse triples reused every segment (they never escape: packing
        # copies them into the dispatch row), multi-tick flush buffers
        # reused only under the fence guarantee (they DO escape into the
        # dispatch, where jax may alias aligned host memory)
        from collections import deque as _deque

        self._inflight: "_deque" = _deque()
        self._stage_pool: Optional[list] = None
        self._stage_flip = 0
        self._multi_bufs: Optional[list] = None
        self._multi_flip = 0
        self._multi_active: Optional[np.ndarray] = None
        self._multi_count = 0
        self._pad_row: Optional[np.ndarray] = None
        # canonicalized dispatch signatures observed (async bookkeeping /
        # test hook): (has_load, advance_count, last_active, trailing?) ->
        # dispatch count, via a DispatchPlanCache (optionally shared —
        # backends fronting one jit cache should share one tally)
        self.plan_cache = plan_cache or DispatchPlanCache()
        # pre-bound telemetry instruments (updated behind enabled checks)
        _reg = GLOBAL_TELEMETRY.registry
        self._m_fence_stall = _reg.histogram(
            "ggrs_async_fence_stall_ms",
            "time the host blocked on the oldest in-flight dispatch",
            buckets=LOG2_BUCKETS_MS,
        )
        self._m_inflight = _reg.gauge(
            "ggrs_async_inflight", "dispatches currently inside the async fence"
        )
        self._m_batch = _reg.histogram(
            "ggrs_fused_batch_ticks",
            "session ticks fused into one multi-tick device dispatch",
            buckets=LOG2_BUCKETS,
        )
        self.beam_gated = 0  # ticks where the FULL-width launch was withheld
        # width-1 history-only launches (member 0: pinned history +
        # repeat-last). Under a beam-sharded mesh the minimal legal width
        # is the beam axis (an indivisible width would run replicated)
        self.beam_history_launches = 0
        self._history_width = (
            mesh.shape["beam"]
            if beam_width and self.core._beam_sharding is not None
            else 1
        )
        self._spec_cost_s: Optional[float] = None  # measured in warmup()
        self._spec_hist_cost_s: Optional[float] = None  # width-1, warmup()
        # None until the first idle sample lands: seeding the EMA from 0.0
        # made the gate stand down for the first ~20-30 ticks of a fully
        # idle loop while the blend warmed up (r3 advisor)
        self._idle_ema_s: Optional[float] = None
        self._last_tick_end: Optional[float] = None
        # value tracking for the adaptive gate: (frames_served,
        # launches_spanned) per consult — see the class-attribute comment
        from collections import deque

        self._launch_value: deque = deque(maxlen=self.VALUE_WINDOW)
        self._spec_consulted = False
        self._launches_since_consult = 0
        self._value_gated_streak = 0
        # tick counter + the tick of the standing spec's launch: value
        # samples are recorded only from FRESH consults (spec launched
        # the immediately-preceding tick). A gated stretch leaves a stale
        # spec standing, and a stale spec misses BY SHIFT regardless of
        # candidate quality — sampling those misses as evidence against
        # the candidates locked the gate shut on exactly the regimes the
        # probe bursts exist to re-open (measured: the neutral arm sat at
        # 0.19 frames-served with 71% gating while the same candidates
        # served 0.56+ when launched fresh).
        self._tick_index = 0
        self._spec_tick = -10

    # ------------------------------------------------------------------

    def handle_requests(self, requests: List[Request]) -> None:
        """A tick is usually one fused batch, but sparse-saving P2P ticks can
        legally contain two rollback blocks (misprediction rollback + ring
        keepalive rollback, p2p_session.rs:286+:792): split into one batch
        per LoadGameState and fuse each."""
        import time as _time

        if self.speculation_gate == "adaptive":
            now = _time.perf_counter()
            if self._last_tick_end is not None:
                idle = now - self._last_tick_end
                # EMA over ~10 ticks: reacts to phase changes (a pause
                # menu, a scene load) without flapping on single-frame
                # jitter; the first sample SEEDS the EMA outright
                self._idle_ema_s = (
                    idle
                    if self._idle_ema_s is None
                    else 0.9 * self._idle_ema_s + 0.1 * idle
                )
        self._tick_index += 1
        segment: List[Request] = []
        for req in requests:
            if isinstance(req, LoadGameState) and segment:
                self._run_segment(segment)
                segment = []
            segment.append(req)
        if segment:
            self._run_segment(segment)
        # one speculation per tick, from the final segment's frontier — an
        # earlier segment's beam could never be matched (only the last
        # segment defines the next tick's expected rollback anchor). A
        # fresh launch every tick keeps the candidates built from the
        # newest input history, which measures as a much higher hit rate
        # than reusing a standing rollout across ticks.
        if not self.defer_speculation:
            self.launch_pending_speculation()
        if self.speculation_gate == "adaptive":
            self._last_tick_end = _time.perf_counter()

    def launch_pending_speculation(self) -> None:
        """Launch (or gate) the speculation staged by the last tick. With
        defer_speculation=True, call this from loop idle time after the
        frame's critical path; otherwise handle_requests calls it
        automatically."""
        if self.beam_width and self._last_segment is not None:
            if self._last_segment[2] == 0:  # count: nothing to anchor on
                self._last_segment = None
                return
            width = self._launch_width()
            if width != self.beam_width:
                self.beam_gated += 1
            if width:
                if width != self.beam_width:
                    self.beam_history_launches += 1
                self._launch_speculation(*self._last_segment, width=width)
            self._last_segment = None

    def _launch_width(self) -> int:
        """The adaptive gate. Returns the width to launch at — the full
        beam, the width-1 history-only rollout, or 0 for no launch.

        BUDGET — speculation is worth launching only when the loop's idle
        time can absorb its device cost; otherwise the speculative steps
        delay the NEXT real tick by more than an adopted rollback could
        ever save. 80% slack biases toward speculating (a near-covered
        cost still wins when a deep rollback adopts). An unseeded idle
        EMA (no second tick yet) counts as affordable. The full and the
        history widths are budgeted separately (both costs measured in
        warmup()): an idle budget too thin for the B-wide rollout often
        still covers the width-1 one.

        VALUE — two signals from the consult trail, one per width. Full
        width is justified only by BRANCH-member adoptions (trailing
        branch-frames-served-per-launch >= MIN_SERVED_PER_LAUNCH); when
        that fails, a PROBE BURST of consecutive full-width launches
        every VALUE_PROBE_INTERVAL value-gated ticks keeps sampling the
        regime with fresh-at-consult specs so toggling players re-open
        it. The history width is justified by MEMBER-0 adoptions —
        SyncTest-style replays where the corrected script is played
        history and the pinned member serves it at 1/B the rollout
        FLOPs (the measured per-width costs price what that is worth);
        in P2P regimes member 0 serves nothing by construction (the load
        frame is the first incorrect frame), the history signal decays,
        and value-gated ticks stand fully down exactly as before this
        width existed (full probes keep sampling BOTH signals: member 0
        rides in every full launch)."""
        full, hist = self.beam_width, self._history_width
        if self.speculation_gate != "adaptive":
            return full
        if self._spec_cost_s is None:
            return full  # not yet measured (warmup pending): don't stall
        idle = self._idle_ema_s
        # ONE covered-by-idle predicate per width, reused by both the
        # affordability decision and the soft/hard bar choice below so
        # the two can never drift (a soft bar for a width the budget
        # then refuses to launch would be incoherent). `idle is None`
        # (no second tick yet) counts as affordable but NOT as measured
        # coverage — the soft bar requires evidence.
        full_covered = idle is not None and idle >= 0.8 * self._spec_cost_s
        full_affordable = idle is None or full_covered
        hist_cost = (
            self._spec_hist_cost_s
            if self._spec_hist_cost_s is not None
            # unmeasured (older checkpoint): assume the FULL cost. Per-
            # dispatch overhead dominates at interactive sizes, so a
            # linear width/full scaling would admit history launches into
            # idle budgets that cannot actually absorb them (r4 advisor);
            # the conservative fallback only ever under-launches until
            # warmup() measures the real width-1 cost
            else self._spec_cost_s
        )
        hist_covered = idle is not None and idle >= 0.8 * hist_cost
        hist_affordable = idle is None or hist_covered
        if len(self._launch_value) >= self.VALUE_MIN_SAMPLES:
            launches = max(sum(n for _, _, n in self._launch_value), 1)
            branch_rate = sum(b for b, _, _ in self._launch_value) / launches
            hist_rate = sum(h for _, h, _ in self._launch_value) / launches
            # bar per width: soft when measured idle covers that width's
            # measured cost (see MIN_SERVED_IDLE), hard otherwise
            full_bar = (
                self.MIN_SERVED_IDLE
                if full_covered
                else self.MIN_SERVED_PER_LAUNCH
            )
            hist_bar = (
                self.MIN_SERVED_IDLE
                if hist_covered
                else self.MIN_SERVED_PER_LAUNCH
            )
            hist_ok = hist_rate >= hist_bar
            # full width earns its keep when its MARGINAL value over the
            # history width (branch serves) clears the bar — or, in
            # blended regimes where neither signal alone clears it, when
            # the TOTAL does (the pre-split gate's signal: width-1 alone
            # would forfeit the branch share). When member-0 serves
            # dominate and the branch marginal is under the bar, full is
            # NOT ok even though the total is huge: that's exactly the
            # regime the cheaper history width exists for.
            branch_ok = branch_rate >= full_bar or (
                not hist_ok
                and branch_rate + hist_rate >= full_bar
            )
        else:
            branch_ok = hist_ok = True
        if branch_ok:
            self._value_gated_streak = 0
            if full_affordable:
                return full
            if hist_ok and hist_affordable:
                return hist
            return 0
        # full width value-gated: probe at the END of each interval (the
        # streak keeps counting through probes — it clears only when
        # branch adoptions lift the trailing ratio back over the bar)
        self._value_gated_streak += 1
        probing = (
            (self._value_gated_streak - 1) % self.VALUE_PROBE_INTERVAL
            >= self.VALUE_PROBE_INTERVAL - self.VALUE_PROBE_BURST
        )
        if probing and full_affordable:
            return full
        if hist_ok and hist_affordable:
            return hist
        return 0

    def _next_stage(self):
        """Rotate the pooled (inputs, statuses, save_slots) parse triple.
        The triple never reaches the device: every dispatch path copies it
        host-side first — pack_tick_row/pack_tick_row_into for ticks,
        adopt's own packed buffer for beam adoption — so reuse needs no
        fence and is safe in eager mode too. The pool is
        async_inflight + 1 deep only so the CURRENT segment's triple (read
        by the beam bookkeeping until the tick ends) is never the one
        being refilled; one spare would do, the depth just mirrors the
        multi-buf pool."""
        core = self.core
        if self._stage_pool is None:
            W, P, I = core.window, self.num_players, self.input_size
            self._stage_pool = [
                (
                    np.zeros((W, P, I), dtype=np.uint8),
                    np.zeros((W, P), dtype=np.int32),
                    np.full((W,), core.scratch_slot, dtype=np.int32),
                )
                for _ in range(self.async_inflight + 1)
            ]
        self._stage_flip = (self._stage_flip + 1) % len(self._stage_pool)
        inputs, statuses, save_slots = self._stage_pool[self._stage_flip]
        inputs.fill(0)
        statuses.fill(0)
        save_slots.fill(core.scratch_slot)
        return inputs, statuses, save_slots

    @property
    def dispatch_signatures(self) -> dict:
        """Signature -> dispatch count view of the plan cache (test hook /
        bookkeeping; the historical attribute name)."""
        return self.plan_cache.signatures

    def _parse_segment(self, requests: List[Request]):
        """One pass over a request segment into packed-dispatch staging
        (the shared parse_request_segment grammar walk over this backend's
        pooled staging). Returns (load, start_frame, count, inputs,
        statuses, save_slots, saves, last_active): `last_active` is the
        row's 1-based last active slot, handed to the core so
        branchless-variant routing skips its save-slot rescan; the
        (shape-level) signature is tallied in the plan cache — repeated
        rollback blocks of one shape reuse one cached jitted program."""
        core = self.core
        inputs, statuses, save_slots = self._next_stage()
        load, start_frame, count, saves, last_active, trailing_save = (
            parse_request_segment(
                requests,
                window=core.window,
                ring_len=core.ring_len,
                max_prediction=core.max_prediction,
                current_frame=self.current_frame,
                inputs=inputs,
                statuses=statuses,
                save_slots=save_slots,
            )
        )
        sig = (
            load is not None,
            count,
            last_active,
            trailing_save is not None,
        )
        self.plan_cache.note(sig, frame=start_frame)
        return (
            load, start_frame, count, inputs, statuses, save_slots, saves,
            last_active,
        )

    def _note_inflight(self, handle) -> None:
        """Fence an async dispatch: admit `handle` (any device array of the
        dispatch's result) to the in-flight window; once a dispatch beyond
        `async_inflight` would be outstanding, wait for the OLDEST — the
        host stays one-to-two batches ahead of the device instead of
        either running unboundedly ahead or draining after every batch.
        No-op in eager mode (eager callers rely on jax's own queue)."""
        if not self.async_dispatch:
            return
        self._inflight.append(handle)
        GLOBAL_TRACER.mark("tpu/async_dispatch", absolute=True)
        tel = GLOBAL_TELEMETRY
        if tel.enabled:
            self._m_inflight.set(len(self._inflight))
        while len(self._inflight) > self.async_inflight:
            oldest = self._inflight.popleft()
            with GLOBAL_TRACER.span("tpu/async_fence", absolute=True):
                t0 = time.perf_counter() if tel.enabled else 0.0
                jax.block_until_ready(oldest)
                if tel.enabled:
                    stall_ms = (time.perf_counter() - t0) * 1000.0
                    self._m_fence_stall.observe(stall_ms)
                    self._m_inflight.set(len(self._inflight))
                    tel.record(
                        "fence_stall",
                        frame=self.current_frame,
                        stall_ms=round(stall_ms, 4),
                        inflight=len(self._inflight),
                    )

    def _run_segment(self, requests: List[Request]) -> None:
        with GLOBAL_TRACER.span("tpu/host_parse", absolute=True):
            (
                load, start_frame, count, inputs, statuses, save_slots,
                saves, last_active,
            ) = self._parse_segment(requests)
        core = self.core

        his = los = None
        if load is not None:
            self.rollback_frames += count
        if load is not None and self._spec is not None:
            match = self._match_speculation(load.frame, inputs, statuses, count)
            if not self._spec_consulted and (
                self._tick_index - self._spec_tick <= 1
            ):
                # one value sample per FRESH consulted speculation (stale
                # specs — left standing by gated ticks — miss by shift
                # regardless of candidate quality and say nothing; their
                # launch cost stays in _launches_since_consult and rides
                # the next fresh sample), split by
                # WHO served: (branch_frames, member0_frames, launches
                # paid since the last consult) — superseded-unconsulted
                # launches count as cost without poisoning quiet
                # stretches. The split is the width decision's signal:
                # member-0 serves are what the width-1 history launch
                # provides at 1/B the rollout FLOPs (SyncTest-style replays,
                # where the corrected script IS played history), while
                # only branch-member adoptions justify the full width
                # (P2P toggles — there the load frame is the first
                # INCORRECT frame, so member 0's pinned rows mismatch at
                # offset 0 by construction and serve nothing)
                served = match[2] if match else 0
                is_branch = bool(match) and match[0] != 0
                self._launch_value.append(
                    (served if is_branch else 0,
                     0 if is_branch else served,
                     max(self._launches_since_consult, 1))
                )
                self._launches_since_consult = 0
                self._spec_consulted = True
            if match is not None:
                member, shift, matched = match
                if matched == count:
                    self.beam_hits += 1
                else:
                    self.beam_partial_hits += 1
                self.rollback_frames_adopted += matched
                # adoption reads the ring: buffered ticks must land first
                self.flush()
                with GLOBAL_TRACER.span("tpu/beam_adopt", absolute=True):
                    his, los = core.adopt(
                        self._spec[2],
                        member,
                        load.frame % core.ring_len,
                        save_slots,
                        count,
                        shift=shift,
                        load_frame=load.frame,
                        inputs=inputs,
                        statuses=statuses,
                        matched=matched,
                    )
                self._note_inflight(his)
            else:
                self.beam_misses += 1
        batch = None
        base_idx = 0
        if his is None and self.lazy_ticks > 0:
            # lazy tick batching: stage the packed row; the fused
            # multi-tick dispatch happens at flush() (buffer full or first
            # device-result need). Rollback rows buffer like any other —
            # the load executes in order inside the multi-tick scan.
            if self._tick_future is None:
                self._tick_future = _FutureChecksumBatch(self.flush)
            batch = self._tick_future
            self._buffered_last_active = max(
                self._buffered_last_active, last_active
            )
            if self.async_dispatch:
                # pack straight into the pooled multi-tick buffer: no
                # per-tick row allocation, no flush-time copy
                buf = self._acquire_multi_buf()
                base_idx = self._multi_count * core.window
                core.pack_tick_row_into(
                    buf[self._multi_count],
                    do_load=load is not None,
                    load_slot=(load.frame % core.ring_len)
                    if load is not None
                    else 0,
                    inputs=inputs,
                    statuses=statuses,
                    save_slots=save_slots,
                    advance_count=count,
                    start_frame=start_frame,
                )
                self._multi_count += 1
            else:
                row = core.pack_tick_row(
                    do_load=load is not None,
                    load_slot=(load.frame % core.ring_len)
                    if load is not None
                    else 0,
                    inputs=inputs,
                    statuses=statuses,
                    save_slots=save_slots,
                    advance_count=count,
                    start_frame=start_frame,
                )
                base_idx = len(self._tick_rows) * core.window
                self._tick_rows.append(row)
        elif his is None:
            with GLOBAL_TRACER.span("tpu/fused_tick", absolute=True):
                row = core.pack_tick_row(
                    do_load=load is not None,
                    load_slot=(load.frame % core.ring_len) if load is not None else 0,
                    inputs=inputs,
                    statuses=statuses,
                    save_slots=save_slots,
                    advance_count=count,
                    start_frame=start_frame,
                )
                his, los = core.tick_row(row, last_active)
            self._note_inflight(his)
        self.current_frame = start_frame + count

        if batch is None:
            batch = _ChecksumBatch(his, los, self.ledger)
        for idx, save in saves:
            ref = SnapshotRef(save.frame, save.frame % core.ring_len)
            save.cell.save_lazy(
                save.frame, ref, _LazyChecksum(batch, base_idx + idx)
            )
        if len(self._tick_rows) + self._multi_count >= self.lazy_ticks > 0:
            self.flush()

        if self.beam_width:
            # the speculation survives the tick UNLESS this rollback rewrote
            # history at or before its anchor (the anchor snapshot is then
            # stale); divergence after the anchor is handled by the played-
            # prefix match, since trajectories are deterministic in the
            # anchor state + candidate rows
            if (
                self._spec is not None
                and load is not None
                and load.frame <= self._spec[0]
            ):
                self._spec = None
            # only the shape survives the tick (the staging triple is
            # pooled and will be reused): the deferred launch needs the
            # frontier frame and count, nothing from the input rows
            self._last_segment = (load, start_frame, count)
            if load is not None:
                self._depth = count  # observed rollback depth
            for f in range(count):
                changed = (inputs[f] != self._last_inputs).any(axis=1)
                if changed.any():
                    self._prev_inputs[changed] = self._last_inputs[changed]
                    self._last_inputs[changed] = inputs[f][changed]
                self._played[start_frame + f] = (
                    inputs[f].copy(),
                    statuses[f].copy(),
                )
            # feed the input model every newly-FINALIZED frame, in order:
            # a rollback can load at most max_prediction behind the
            # current frame, so rows older than that are what really
            # happened — even rows played as predictions (never corrected
            # means correct). Disconnected cells break the run instead of
            # polluting the hold statistics with dummy inputs.
            final_horizon = self.current_frame - core.max_prediction
            f = self._finalized_to + 1
            # a gap (restored checkpoint, pre-beam history) can't be
            # learned from: jump past it, severing runs so stale run
            # state never bridges unobserved frames. `horizon` (below)
            # is the _played GC cutoff — the jump guard must use the
            # same expression or the two drift.
            horizon = self.current_frame - core.window - core.max_prediction
            oldest_kept = horizon
            if f < oldest_kept:
                f = oldest_kept
                for p in range(self.num_players):
                    self.input_model.break_run(p)
            while f < final_horizon:
                rec = self._played.get(f)
                if rec is None:
                    for p in range(self.num_players):
                        self.input_model.break_run(p)
                else:
                    pin, pst = rec
                    for p in range(self.num_players):
                        if pst[p] >= int(InputStatus.DISCONNECTED):
                            self.input_model.break_run(p)
                        else:
                            self.input_model.observe(p, pin[p].tobytes())
                self._finalized_to = f
                f += 1
            for key in [k for k in self._played if k < horizon]:
                del self._played[key]

    # ------------------------------------------------------------------
    # speculative beam
    # ------------------------------------------------------------------

    def _match_speculation(
        self, load_frame: Frame, inputs: np.ndarray, statuses: np.ndarray,
        count: int,
    ) -> Optional[Tuple[int, int, int]]:
        """Returns (member, shift, matched) of an adoptable speculation,
        else None. shift = load_frame - anchor_frame: the member must ALSO
        match the inputs actually played for frames anchor..load (its
        trajectory baked them in) — rollback depth jitter then lands inside
        the same speculated window instead of invalidating it. `matched`
        is the longest leading run of the corrected script the member's
        rows cover (src/input_queue.rs:167-204's localization, fused): the
        suffix past it resimulates in the same adopt dispatch."""
        from .beam import match_beam_longest

        anchor_frame, beam_inputs, _ = self._spec
        shift = load_frame - anchor_frame
        if shift < 0 or shift >= beam_inputs.shape[1]:
            return None
        # a disconnected player's dummy inputs were not speculated: the
        # adopted prefix must stop before the first disconnect row (the
        # resimulated suffix handles them like any plain tick)
        clean = 0
        while clean < count and (
            statuses[clean] < int(InputStatus.DISCONNECTED)
        ).all():
            clean += 1
        if clean == 0:
            return None
        prefix_rows = []
        for j in range(shift):
            rec = self._played.get(anchor_frame + j)
            if rec is None:
                return None
            pin, pst = rec
            if (pst >= int(InputStatus.DISCONNECTED)).any():
                return None
            prefix_rows.append(pin)
        prefix = (
            np.stack(prefix_rows)
            if prefix_rows
            else np.zeros((0,) + inputs.shape[1:], dtype=np.uint8)
        )
        matched, member = match_beam_longest(
            beam_inputs, prefix, inputs[:clean]
        )
        if member is None or matched == 0:
            return None
        return (member, shift, matched)

    def flush(self) -> None:
        """Dispatch buffered lazy ticks as ONE fused multi-tick program
        (no-op when the buffer is empty or lazy_ticks is 0). Pads to the
        configured buffer depth with no-op rows so one length compiles
        once; materializes the future checksum batch the buffered saves'
        cells already hold. A single-row buffer dispatches through the
        plain (warmup-compiled) tick program instead — a flush-heavy
        configuration (e.g. beam speculation forcing a flush every tick)
        then pays the one-tick program, not the T-deep scan, and never a
        mid-session compile."""
        rows, future = self._tick_rows, self._tick_future
        n_staged = self._multi_count
        if not rows and not n_staged:
            return
        if GLOBAL_TELEMETRY.enabled:
            self._m_batch.observe(n_staged or len(rows))
        self._tick_rows = []
        self._tick_future = None
        # depth routing: scan only the variant covering the buffer's
        # deepest row (None = the full-window reference program)
        max_active = (
            self._buffered_last_active
            if self.depth_routing and self._buffered_last_active
            else None
        )
        self._buffered_last_active = 0
        core = self.core
        if n_staged:  # async: rows were packed straight into the pool
            buf = self._multi_active
            self._multi_active = None
            self._multi_count = 0
            if n_staged == 1:
                with GLOBAL_TRACER.span("tpu/fused_tick", absolute=True):
                    his, los = core.tick_row(buf[0], max_active)
            else:
                buf[n_staged:] = self._pad_row
                with GLOBAL_TRACER.span("tpu/fused_multi_tick", absolute=True):
                    his, los = core.tick_multi(buf, last_active=max_active)
        elif len(rows) == 1:
            with GLOBAL_TRACER.span("tpu/fused_tick", absolute=True):
                his, los = core.tick_row(rows[0], max_active)
        else:
            # eager mode has no fence bounding when a dispatch's read of
            # host memory retires (jax may alias aligned buffers), so the
            # staging is allocated fresh per flush
            buf = np.tile(core.pad_tick_row(), (self.lazy_ticks, 1))
            for j, r in enumerate(rows):
                buf[j] = r
            with GLOBAL_TRACER.span("tpu/fused_multi_tick", absolute=True):
                his, los = core.tick_multi(buf, last_active=max_active)
        self._note_inflight(his)
        future.batch = _ChecksumBatch(his, los, self.ledger)

    def _acquire_multi_buf(self) -> np.ndarray:
        """The active [lazy_ticks, L] staging buffer the async lazy path
        packs tick rows into directly (pack_tick_row_into). Rotates
        async_inflight + 1 pooled buffers — reuse is safe because the
        fence proves the dispatch that read a buffer retired before the
        pool comes back around. Rows past the staged count keep stale
        bytes until flush() pads the tail."""
        if self._multi_active is not None:
            return self._multi_active
        if self._multi_bufs is None:
            pad = self.core.pad_tick_row()
            self._multi_bufs = [
                np.tile(pad, (self.lazy_ticks, 1))
                for _ in range(self.async_inflight + 1)
            ]
            self._pad_row = pad
        self._multi_flip = (self._multi_flip + 1) % len(self._multi_bufs)
        self._multi_active = self._multi_bufs[self._multi_flip]
        return self._multi_active

    def _ranked_predictions(self, anchor: Frame, rollout: int, width: int):
        """Likelihood-ranked (player, offset, value_row) switch specs for
        branching_beam's prediction stream. The per-player hazard clock
        starts at the CONFIRMED frontier — rows played after it repeat the
        last confirmed value by prediction, so the real switch (the thing
        a rollback corrects) can land at any not-yet-confirmed frame.
        Frontier and run length come from the recorded play-time statuses
        in _played; frames confirmed only implicitly (predicted, never
        corrected) keep the frontier conservative, which merely shifts
        probability toward earlier offsets."""
        frontiers = []
        for p in range(self.num_players):
            frontier = None
            for f in range(self.current_frame - 1, -1, -1):
                rec = self._played.get(f)
                if rec is None:
                    break
                if rec[1][p] == int(InputStatus.CONFIRMED):
                    frontier = f
                    break
            if frontier is None:
                frontiers.append(None)
                continue
            value = self._played[frontier][0][p].tobytes()
            run = 1
            f = frontier - 1
            while f >= 0:
                rec = self._played.get(f)
                if (
                    rec is None
                    or rec[1][p] != int(InputStatus.CONFIRMED)
                    or rec[0][p].tobytes() != value
                ):
                    break
                run += 1
                f -= 1
            frontiers.append((frontier, value, run))
        if all(fr is None for fr in frontiers):
            return None
        # cap the model's share at ~2/3 of the branch members: the
        # ranked specs come first, but the uniform offset families and
        # XOR novel-value perturbations must keep guaranteed coverage —
        # a confidently wrong model (opponent switches to a value the
        # transition table has never seen) would otherwise monopolize
        # every member and turn recoverable partial hits into full misses
        preds = self.input_model.rank_branches(
            frontiers, anchor, rollout,
            limit=max((width - 1) * 2 // 3, 1),
        )
        return preds or None

    def _launch_speculation(self, load: Optional[LoadGameState],
                            start_frame: Frame, count: int,
                            width: Optional[int] = None) -> None:
        """Anchor one frame DEEPER than the observed rollback depth
        predicts for the next tick, so the next load lands at shift 1 and
        depth jitter of ±1 still falls inside the member window (the
        shift-flexible match absorbs it). The anchor's snapshot is in the
        ring by dense-saving construction. Candidate scripts branch between
        each player's last and previous-distinct inputs at every plausible
        offset (see beam.branching_beam); member 0 is the reference's
        repeat-last prediction. `width` (default: the full beam_width) is
        the adaptive gate's launch width — the history width rolls out
        member 0 alone at 1/B the rollout FLOPs."""
        from .beam import branching_beam

        core = self.core
        if count == 0:
            return
        if width is None:
            width = self.beam_width
        # the rollout anchors on a ring snapshot: buffered ticks must land
        self.flush()
        current_after = start_frame + count
        anchor = current_after - self._depth
        # the anchor snapshot must still be live in the ring (and a frame
        # that actually exists)
        anchor = max(anchor, current_after - core.max_prediction, 0)
        anchor = min(anchor, current_after - 1)
        # consecutive depths coalesce to one length (5,5,7,7,...) so jit
        # compiles O(1) rollout-length variants as the depth jitters
        rollout = min(self._depth + 3 + (self._depth & 1), core.window)
        # pin known history (beam.branching_beam): the frames between the
        # anchor and now were already played, and their rows are recorded —
        # local inputs and confirmed remote inputs are ground truth every
        # member must reproduce verbatim (the played-prefix compatibility
        # check rejects anything else), while unconfirmed remote
        # predictions are exactly the cells worth branching on. Without
        # the pin, the local player's newest input (already folded into
        # _last_inputs) stamps over prefix frames where the old value was
        # played, and every family member dies on the prefix check.
        S = current_after - anchor
        base_rows = np.empty((S,) + self._last_inputs.shape, dtype=np.uint8)
        fixed = np.empty((S, self.num_players), dtype=bool)
        for j in range(S):
            rec = self._played.get(anchor + j)
            if rec is None:  # GC'd past the horizon: no context to pin
                base_rows = fixed = None
                break
            pin, pst = rec
            base_rows[j] = pin
            fixed[j] = pst != int(InputStatus.PREDICTED)
        beam_inputs = branching_beam(
            self._last_inputs,
            self._prev_inputs,
            core.window,
            width,
            # branches must cover prefix + script anywhere the rollout can
            # be matched (offset 0 first: the likeliest switch point)
            max_offset=rollout,
            base_rows=base_rows,
            fixed=fixed,
            # only full-width launches carry branch members; history
            # launches (width-1 / replicated member 0) would discard the
            # ranking, so don't pay the host-side scoring for them
            predictions=(
                self._ranked_predictions(anchor, rollout, width)
                if width == self.beam_width
                else None
            ),
        )
        if width != self.beam_width and width > 1:
            # sharded history launch: the minimal legal width is the beam
            # axis, but a history launch means MEMBER 0 SEMANTICS — so
            # replicate member 0 across the shard axis instead of letting
            # branching_beam fill the extra slots with branch candidates.
            # A serve from this launch then always attributes as a
            # member-0 (history) serve, matching what the launch paid for
            # (r4 advisor: branch serves from a history launch reopened
            # full width while crediting history-launch cost)
            beam_inputs[1:] = beam_inputs[0]
        # roll out only as deep as a rollback can reach while this
        # speculation stands (shift ~1 + depth + reuse/growth margin): on
        # big worlds the speculation's B*L step cost is the beam's
        # overhead, so L tracks need, not the window
        beam_inputs = beam_inputs[:, :rollout]
        beam_statuses = np.zeros(
            (width, rollout, self.num_players), dtype=np.int32
        )
        with GLOBAL_TRACER.span("tpu/beam_speculate", absolute=True):
            spec = core.speculate(anchor % core.ring_len, beam_inputs, beam_statuses)
        self._spec = (anchor, beam_inputs, spec)
        self._spec_consulted = False
        self._spec_tick = self._tick_index
        self._launches_since_consult += 1

    # ------------------------------------------------------------------

    def reset(self) -> None:
        """Fresh-session state without recompilation: the core returns to
        its initial world/ring, every counter and speculation artifact
        clears, but compiled programs and the measured speculation cost
        survive — back-to-back sessions (benchmark arms, rematches) skip
        the tens-of-seconds tunnel compile a new backend would pay."""
        # materialize any staged lazy ticks first: cells from the old
        # session already hold this buffer's future checksums, and an
        # orphaned future would turn their later reads into errors
        self.flush()
        self.core.reset()
        self.current_frame = 0
        self.ledger = ChecksumLedger()
        self._inflight.clear()
        self.dispatch_signatures.clear()
        self._spec = None
        self._last_segment = None
        self.beam_hits = 0
        self.beam_partial_hits = 0
        self.beam_misses = 0
        self.beam_gated = 0
        self.beam_history_launches = 0
        self.rollback_frames = 0
        self.rollback_frames_adopted = 0
        self._last_inputs[:] = 0
        self._prev_inputs[:] = 0
        self._played.clear()
        # the input model SURVIVES reset on purpose: hold/transition
        # statistics describe the players, not the session — a rematch
        # (or a benchmark arm) keeps what it learned, exactly like the
        # measured speculation costs. Frame bookkeeping restarts; the
        # jump-past-gap guard severs runs at the discontinuity.
        for p in range(self.num_players):
            self.input_model.break_run(p)
        self._finalized_to = -1
        self._depth = 2
        self._idle_ema_s = None
        self._last_tick_end = None
        self._launch_value.clear()
        self._spec_consulted = False
        self._launches_since_consult = 0
        self._value_gated_streak = 0
        self._tick_index = 0
        self._spec_tick = -10

    def warmup(self) -> None:
        """Compile every device program this backend can dispatch (tick,
        speculation, adoption) before entering a real-time loop: first
        compilation takes seconds — enough to trip peers' disconnect
        timeouts mid-session. Game state is left untouched."""
        with warmup_scope("TpuRollbackBackend.warmup"):
            self._warmup_impl()

    def _warmup_impl(self) -> None:
        import jax.numpy as jnp

        core = self.core
        W, P, I = core.window, self.num_players, self.input_size
        inputs = np.zeros((W, P, I), dtype=np.uint8)
        statuses = np.zeros((W, P), dtype=np.int32)
        scratch = np.full((W,), core.scratch_slot, dtype=np.int32)
        # tick/adopt DONATE their ring+state buffers (invalidated on real
        # devices), so both must be deep-copied before the dummy dispatches
        # and restored after
        ring0 = jax.tree.map(jnp.copy, core.ring)
        state0 = jax.tree.map(jnp.copy, core.state)
        core.tick(False, 0, inputs, statuses, scratch, 0)
        if core._tick_branchless_fn is not None:
            # row-content routing sends rollback rows to the branchless
            # program at a depth-coalesced slot variant — compile EVERY
            # variant, or the first rollback of a new depth pays the
            # mid-session compile stall warmup exists to prevent
            for v in core.branchless_variants():
                core.tick(True, 0, inputs, statuses, scratch, v)
            if core._t1_windowed:
                # trivial rows dispatch the WINDOWED cond program here
                # (the tick above compiled it at the smallest variant),
                # which leaves the full cond program cold — keep it
                # compiled too: it is still the route for full-depth
                # variants and the bit-parity reference, and a cold
                # program is a landmine
                row0 = core.pack_tick_row(
                    False, 0, inputs, statuses, scratch, 0
                )
                core.ring, core.state, core.verify, _, _ = core._tick_fn(
                    core.ring, core.state, row0, core.verify
                )
        if self.lazy_ticks:
            # compile the fused multi-tick program at the buffer depth
            # (all-padding rows: a true no-op on the game state). With
            # depth routing the live flush dispatches one scan body per
            # depth variant — compile EVERY variant, or the first flush
            # of a new max depth pays the mid-session compile stall
            # warmup exists to prevent. The pallas tick kernel route
            # (rows > 1) is depth-flat: one compile covers it.
            pad = np.tile(core.pad_tick_row(), (self.lazy_ticks, 1))
            if (
                self.depth_routing
                and self.lazy_ticks > 1
                and core._tick_pallas_fn is None
            ):
                for v in core.branchless_variants():
                    core.tick_multi(pad, last_active=v)
            core.tick_multi(pad)
        if self.beam_width:
            from .beam import branching_beam

            # compile EVERY (width, rollout length) the live path can
            # dispatch — widths: the full beam and the adaptive gate's
            # history-only width; lengths: depth coalescing yields
            # 5, 7, 9, ... up to the window. A mid-session width or depth
            # change must not pay the seconds-long speculate/adopt compile
            # stall warmup exists to prevent (adopt's jit keys on the
            # trajectory's member-axis shape, so BOTH widths need it)
            rollouts = sorted(
                {min(d + 3 + (d & 1), W) for d in range(1, W + 1)}
            )
            # only the adaptive gate ever dispatches the history width;
            # with gate='always' compiling+timing it would roughly double
            # warmup's beam section (seconds per program on the tunnel)
            # for programs that never run (r4 advisor)
            widths = (
                sorted({self.beam_width, self._history_width})
                if self.speculation_gate == "adaptive"
                else [self.beam_width]
            )
            beams = {
                width: branching_beam(
                    np.zeros((P, I), dtype=np.uint8),
                    np.zeros((P, I), dtype=np.uint8),
                    W,
                    width,
                )
                for width in widths
            }
            for width in widths:
                for rollout in rollouts:
                    beam_statuses = np.zeros(
                        (width, rollout, P), dtype=np.int32
                    )
                    spec = core.speculate(
                        0, beams[width][:, :rollout], beam_statuses
                    )
                    # full hits route to the branchless adopt program and
                    # partial hits to the cond one (ResimCore.adopt):
                    # compile BOTH, or the first live partial hit pays a
                    # mid-session compile
                    core.adopt(spec, 0, 0, scratch, 1)
                    core.adopt(
                        spec, 0, 0, scratch, 2,
                        inputs=inputs, statuses=statuses, matched=1,
                    )
            # measure the post-compile speculation cost PER WIDTH for the
            # adaptive gate's budget conditions: a few amortized
            # dispatches at the mid rollout length under a TRUE barrier
            # (block_until_ready is dispatch-ack only on the tunnel)
            import time as _time

            from ..utils.barrier import true_barrier

            rollout = rollouts[len(rollouts) // 2]
            costs = {}
            for width in widths:
                beam_statuses = np.zeros((width, rollout, P), dtype=np.int32)
                spec = core.speculate(
                    0, beams[width][:, :rollout], beam_statuses
                )
                true_barrier(spec[1])
                # the barrier itself costs a device->host round trip
                # (~100ms on the tunnel); measure it on the already-ready
                # result and subtract, or every per-launch cost inflates
                # by rtt/n — enough to make the adaptive gate see a ~1ms
                # width-1 launch as a ~20ms one and veto it forever. The
                # rtt sample is itself noisy (a single reading can exceed
                # the whole chain's barrier), so take the MEDIAN of three
                # and never let the subtraction push the estimate below
                # 1/4 of the raw per-dispatch figure.
                rtts = []
                for _ in range(3):
                    t0 = _time.perf_counter()
                    true_barrier(spec[1])
                    rtts.append(_time.perf_counter() - t0)
                rtt = sorted(rtts)[1]
                n = 10
                t0 = _time.perf_counter()
                for _ in range(n):
                    spec = core.speculate(
                        0, beams[width][:, :rollout], beam_statuses
                    )
                true_barrier(spec[1])
                raw = (_time.perf_counter() - t0) / n
                costs[width] = max(raw - rtt / n, raw / 4)
            self._spec_cost_s = costs[self.beam_width]
            # None when the history width wasn't timed (gate != adaptive);
            # _launch_width's conservative fallback covers that case
            self._spec_hist_cost_s = costs.get(self._history_width)
        core.ring, core.state = ring0, state0
        self.block_until_ready()

    def check(self) -> None:
        """Fetch the device-verify verdict (one small readback); raises
        MismatchedChecksum on the first recorded divergence. Requires
        device_verify=True."""
        from ..errors import MismatchedChecksum

        self.flush()
        mismatch, frame = self.core.check_device_verdict()
        if mismatch:
            raise MismatchedChecksum(frame)

    def state_numpy(self):
        """Host copy of the live game state (parity checks / rendering)."""
        self.flush()
        return self.core.fetch_state()

    def block_until_ready(self) -> None:
        self.flush()
        jax.block_until_ready(self.core.state)
        self._inflight.clear()  # everything older than the state retired

    # ------------------------------------------------------------------
    # durable checkpoint/resume (beyond the reference, SURVEY.md §5)
    # ------------------------------------------------------------------

    def save(self, path: str) -> None:
        from ..utils.checkpoint import save_device_checkpoint

        self.flush()
        tree = {"ring": self.core.ring, "state": self.core.state}
        if self.core.device_verify:
            # the accumulated first-seen history + mismatch latch resume
            # with the run: without it a restored device-verify run would
            # silently restart its history (and check() would trip on the
            # missing pytree)
            tree["verify"] = self.core.verify
        save_device_checkpoint(
            path,
            tree,
            {
                "kind": "TpuRollbackBackend",
                "current_frame": self.current_frame,
                "max_prediction": self.core.max_prediction,
                "num_players": self.num_players,
                "beam_width": self.beam_width,
                "device_verify": self.core.device_verify,
                # performance knobs ride the checkpoint too: a restored
                # backend must run with the characteristics of the session
                # that saved it, not silently revert to defaults (r3
                # advisor)
                "lazy_ticks": self.lazy_ticks,
                "async_dispatch": self.async_dispatch,
                "async_inflight": self.async_inflight,
                "depth_routing": self.depth_routing,
                "speculation_gate": self.speculation_gate,
                "defer_speculation": self.defer_speculation,
                "spec_backend": self.core.spec_backend,
                "tick_backend": self.core.tick_backend,
            },
        )

    @classmethod
    def restore(cls, path: str, game, mesh=None) -> "TpuRollbackBackend":
        from ..utils.checkpoint import load_device_checkpoint

        tree, meta = load_device_checkpoint(path)
        assert meta["kind"] == "TpuRollbackBackend"
        # saved backends resolved concrete spec/tick backends; a restore
        # onto a different topology (e.g. sharded -> unsharded or another
        # platform) may not support them, so restore the knob as a REQUEST
        # ("auto" when the checkpoint predates the fields) and let the
        # constructor re-resolve — the durable bits are the ring/state,
        # which are backend-agnostic by the bit-parity contract
        def _backend_knob(key):
            # "xla" is honored everywhere; a saved "pallas*" re-resolves
            # via "auto" (picks pallas wherever the restored platform and
            # mesh support it, xla otherwise)
            return "xla" if meta.get(key) == "xla" else "auto"

        backend = cls(
            game,
            max_prediction=meta["max_prediction"],
            num_players=meta["num_players"],
            beam_width=meta.get("beam_width", 0),
            mesh=mesh,
            device_verify=meta.get("device_verify", False),
            lazy_ticks=meta.get("lazy_ticks", 0),
            async_dispatch=meta.get("async_dispatch", False),
            async_inflight=meta.get("async_inflight", 2),
            depth_routing=meta.get("depth_routing", True),
            speculation_gate=meta.get("speculation_gate", "always"),
            defer_speculation=meta.get("defer_speculation", False),
            spec_backend=_backend_knob("spec_backend"),
            tick_backend=_backend_knob("tick_backend"),
        )
        # re-place onto the freshly-built core's shardings (sharded under a
        # mesh, single-device otherwise) — checkpoints are layout-agnostic
        backend.core.ring = jax.device_put(
            tree["ring"], jax.tree.map(lambda a: a.sharding, backend.core.ring)
        )
        backend.core.state = jax.device_put(
            tree["state"], jax.tree.map(lambda a: a.sharding, backend.core.state)
        )
        if meta.get("device_verify", False):
            backend.core.verify = jax.device_put(
                tree["verify"],
                jax.tree.map(lambda a: a.sharding, backend.core.verify),
            )
        backend.current_frame = meta["current_frame"]
        return backend


class MultiSessionDeviceCore:
    """N independent session worlds stacked on a leading `session` axis of
    one device-resident pytree, ticked by ONE fused cross-session
    megabatch dispatch — the batch-across-sessions entry point behind
    ggrs_tpu.serve.SessionHost.

    Every hosted session keeps the exact request/cell contract of
    TpuRollbackBackend (ordered Save/Load/Advance lists in, SnapshotRefs
    and lazy checksums out), but instead of one device dispatch per
    session per tick, the host collects each ready session's packed
    control row and executes them all as one program: gather the active
    slots' (ring, state) from the stacked pytrees, vmap the
    single-session packed tick over them, scatter the results back.
    Rows are DATA (the packed control-word layout), so sessions at
    different frames, mid-rollback or freshly attached all ride the same
    jitted program — only the row count shapes the jit key, and it pads
    to a small set of bucket sizes so the cache stays bounded at
    O(len(buckets)) programs regardless of fleet churn.

    Capacity is fixed at construction; slot `capacity` is a dummy world
    that padding rows no-op tick against (pad rows skip every save and
    advance, so the dummy never changes and duplicate pad scatters write
    identical values)."""

    def __init__(self, game, max_prediction: int, num_players: int,
                 capacity: int, *, async_inflight: int = 4,
                 plan_cache: Optional[DispatchPlanCache] = None,
                 buckets: Optional[Sequence[int]] = None,
                 depth_buckets: Optional[Sequence[int]] = None,
                 depth_routing: bool = True, speculation: bool = False,
                 sdc_audit: bool = False):
        """`num_players` is the HOST-WIDE player layout (the widest
        session the host admits): every hosted session's rows are packed
        at this width, with absent players padded as DISCONNECTED so the
        game model substitutes its deterministic dummy input — both peers
        of a match pad identically, so checksums still agree.

        `buckets`: megabatch row-count pad targets (default: powers of
        two up to capacity, plus capacity itself).

        `depth_buckets`: windowed-program pad targets for the 1-based
        last-active slot (default: powers of two up to the window, plus
        the window). A workload that only ever dispatches known shapes —
        the RL env, whose rows are zero-rollback steps plus last_active=1
        snapshot/restore rows — can restrict the grid (e.g. `(2,)`) so
        warmup compiles a fraction of the programs and the jit budget
        shrinks to match; `depth_bucket_for` raises past the coverage.

        `speculation`: enable the SPECULATIVE BUBBLE-FILLING programs —
        `draft()` rolls input-starved slots' futures forward from a ring
        anchor as a vmapped batch (a ring-parked branch: per-frame
        trajectories + checksums off to the side, confirmed state never
        touched), and `adopt_slot()` serves (a prefix of) a later session
        tick row from a standing draft through the proven
        ResimCore._adopt_impl route — one adopt instead of a full-window
        resim; the mispredicted suffix resimulates inside the same
        dispatch. One draft + one adopt program per row bucket, compiled
        at warmup and counted in dispatch_bucket_budget().

        `depth_routing`: dispatch one vmapped program per (row-count
        bucket x depth bucket) instead of always vmapping the full-window
        tick — under vmap the per-slot lax.cond lowers to selects, so a
        zero-rollback row in a full-window program executes the same
        device work as an 8-frame rollback. Depth buckets are powers of
        two up to the window (the jit cache stays
        O(log capacity x log window) programs), plus a dedicated
        ZERO-ROLLBACK FAST PATH for rows with no pending misprediction
        (no LoadGameState, i.e. first_incorrect_frame == NULL_FRAME at
        the session): no ring gather/scatter at all — one step, two
        checksums, per-slot ring writes — since those rows dominate real
        traffic. False pins the single full-window program (the parity
        suite's reference arm)."""
        import jax.numpy as jnp
        from collections import deque as _deque

        assert capacity >= 1
        # the template core supplies the packed-row layout and the
        # single-session tick program the megabatch vmaps; its own
        # (single) ring/state are only the stack's init template
        self.core = ResimCore(game, max_prediction, num_players)
        self.capacity = capacity
        self.num_players = num_players
        self.input_size = game.input_size
        self.async_inflight = async_inflight
        self.depth_routing = depth_routing
        self.plan_cache = plan_cache or DispatchPlanCache()
        self.ledger = ChecksumLedger()
        if buckets is None:
            buckets, b = {capacity}, 1
            while b < capacity:
                buckets.add(b)
                b *= 2
        self.buckets = tuple(sorted(set(buckets)))
        assert self.buckets[-1] >= capacity, (
            "largest bucket must cover a full-capacity megabatch"
        )
        # depth-bucket pad targets for the windowed megabatch program:
        # powers of two up to the window, window included — O(log W)
        # programs per row bucket
        W = self.core.window
        if depth_buckets is None:
            depths, d = {W}, 2
            while d < W:
                depths.add(d)
                d *= 2
        else:
            depths = set(int(d) for d in depth_buckets)
            assert depths and max(depths) <= W
        self.depth_buckets = tuple(sorted(depths))
        # stacked worlds: capacity live slots + >= 1 dummy pad slot (the
        # sharded subclass pads the dummy tail further so the session
        # mesh axis divides the stack, and places the trees on the mesh)
        S = self.stack_slots = self._stack_size()
        self.states = self._place_states(
            jax.tree.map(lambda x: jnp.stack([x] * S), self.core.state)
        )
        self.rings = self._place_rings(
            jax.tree.map(
                lambda x: jnp.zeros((S,) + x.shape, x.dtype), self.core.ring
            )
        )
        # logical slot -> physical stack index (identity on one device;
        # the sharded subclass interleaves live slots across the session
        # mesh shards and spreads the dummy padding, so every shard
        # carries its share of live worlds). `pad_slot` is the PHYSICAL
        # index pad rows no-op against.
        self._init_slot_layout()
        # one pristine world for the masked batch reset (the env
        # workload's auto-reset): built once, passed as a plain argument
        # so the reset program doesn't bake the init state in as a const
        self._init_state = self.core.game.init_state()
        self._dispatch_fn = jax.jit(
            self._dispatch_impl, static_argnums=(4,), donate_argnums=(0, 1)
        )
        self._dispatch_fast_fn = jax.jit(
            self._dispatch_fast_impl, donate_argnums=(0, 1)
        )
        self._reset_mask_fn = jax.jit(
            self._reset_masked_impl, donate_argnums=(0, 1)
        )
        # slot export/import (live migration): the slot index is TRACED
        # data, so one cached program covers every slot — an eager
        # `.at[slot].set` would bake the index in as a constant and pay
        # a fresh XLA compile per distinct migrated slot
        self._export_slot_fn = jax.jit(self._export_slot_impl)
        self._import_slot_fn = jax.jit(
            self._import_slot_impl, donate_argnums=(0, 1)
        )
        self._pad_row = self.core.pad_tick_row()
        # speculative bubble-filling programs (serve/speculation drives
        # them): the draft rollout reads rings only (no donation — the
        # confirmed worlds are reused untouched), the per-slot adopt
        # writes one slot through the proven ResimCore adopt body
        self.speculation = speculation
        self.drafts_launched = 0
        self.spec_adopts = 0
        if speculation:
            self._draft_fn = jax.jit(self._draft_impl)
            self._adopt_slot_fn = jax.jit(
                self._adopt_slot_impl, donate_argnums=(0, 1)
            )
            # draft packed row: [anchor_ring_slot] + statuses[P] +
            # inputs[W * P * I]. The per-player statuses are STATIC for
            # the whole rollout: CONFIRMED for the lane's real players
            # (the drafting contract) and DISCONNECTED for host-layout
            # pad columns, so a narrow session's draft substitutes the
            # same deterministic dummy inputs its resim would
            self._draft_len = (
                1
                + num_players
                + self.core.window * num_players * game.input_size
            )
            self._draft_pad_row = np.zeros((self._draft_len,), np.int32)
            self._draft_stage_pools: dict = {}
        # SDC audit lane (serve/host.py's sampled double-compute): ONE
        # read-only reference program per row bucket — gather sampled
        # slots, replay each from its ring anchor through the
        # full-window parity tick (the depth_routing=False reference),
        # and return the recomputed final-state checksum beside the live
        # world's, so silent corruption in either is a host-visible
        # mismatch. Compiled at warmup, counted in the bucket budget.
        self.sdc_audit = sdc_audit
        if sdc_audit:
            # NO donation: the audit must never touch the worlds it
            # checks — rings/states flow through untouched
            self._audit_fn = jax.jit(self._audit_impl)
        self.audit_dispatches = 0
        # deterministic fault-injection seam (serve/faults.py): consulted
        # at every dispatch/drive entry point BEFORE the program runs and
        # at mailbox staging. None (the default) costs one attribute read.
        self.fault_seam = None
        # device-resident serving loop (attach_mailbox builds all three):
        # the donated [S, K, L] input mailbox and the jitted
        # lax.while_loop virtual-tick driver that consumes it — one host
        # dispatch ticks the whole fleet for up to K virtual ticks
        self.mailbox = None
        self._driver_fn = None
        self._driver_fast_fn = None
        self.driver_dispatches = 0
        self.vticks_executed = 0
        # per-row-bucket pooled (idx, rows) staging, async_inflight + 1
        # deep — the dispatch compaction packs straight into these
        # instead of allocating + re-tiling pad rows every megabatch
        # (rows escape into jax, which may alias aligned host memory;
        # reuse is safe because the fence proves the dispatch that read
        # a buffer retired before the pool rotates back to it)
        self._stage_pools: dict = {}
        # async fence over megabatches: (result handle, live row count);
        # inflight_rows is the host's backpressure signal
        self._inflight: "_deque" = _deque()
        self.inflight_rows = 0
        self.megabatches = 0
        self.rows_dispatched = 0
        _reg = GLOBAL_TELEMETRY.registry
        self._m_batch_rows = _reg.histogram(
            "ggrs_host_megabatch_rows",
            "session tick rows fused into one cross-session dispatch",
            buckets=SESSION_COUNT_BUCKETS,
        )
        self._m_occupancy = _reg.gauge(
            "ggrs_host_megabatch_occupancy",
            "live rows / padded bucket size of the last megabatch",
        )

    @classmethod
    def create(cls, game, max_prediction: int, num_players: int,
               capacity: int, *, mesh=None, **kw):
        """THE mesh-dispatching factory: `mesh=None` builds a
        single-device core, a session mesh builds
        ShardedMultiSessionDeviceCore — one site for the choice, so the
        host, the env and checkpoint restore can't drift on how the
        knob maps to a core class."""
        if mesh is not None:
            return ShardedMultiSessionDeviceCore(
                game, max_prediction, num_players, capacity,
                mesh=mesh, **kw,
            )
        return MultiSessionDeviceCore(
            game, max_prediction, num_players, capacity, **kw
        )

    # ------------------------------------------------------------------
    # stack-layout hooks (the sharded subclass overrides these three; the
    # dispatch/scheduling machinery above and below is layout-agnostic)
    # ------------------------------------------------------------------

    def _stack_size(self) -> int:
        """Slots in the stacked pytrees: capacity live + the dummy pad
        slot at index `capacity` that padding rows no-op against."""
        return self.capacity + 1

    def _place_states(self, tree):
        """Placement hook for the stacked states (identity on one
        device; the sharded subclass device_puts per the session-axis
        placement policy in parallel/sharded.py)."""
        return tree

    def _place_rings(self, tree):
        """Placement hook for the stacked rings — see `_place_states`."""
        return tree

    def _place_mailbox(self, rows):
        """Placement hook for the [S, K, L] mailbox row ring (identity on
        one device; the sharded subclass splits the slot axis over the
        session mesh via parallel/sharded.shard_mailbox)."""
        return rows

    def _init_slot_layout(self) -> None:
        """Build the logical-slot -> physical-stack-index map. One
        device: identity, the single dummy at index `capacity`. The
        public slot API (dispatch entries, reset/export/import, masks,
        checkpoints) is always LOGICAL; only this layout knows where a
        slot physically lives in the stack."""
        self._phys = np.arange(self.capacity, dtype=np.int32)
        # inverse: physical index -> logical slot (dummies -> capacity,
        # the checkpoint's canonical dummy row)
        self._phys_inverse = np.arange(self.stack_slots, dtype=np.int32)
        self._phys_inverse[self.capacity :] = self.capacity
        self.pad_slot = self.capacity
        self.session_shards = 1

    def shard_of(self, slot: int) -> int:
        """Session-mesh shard a logical slot's world lives on. One
        device: everything is shard 0. The host scheduler's slot->shard
        affinity (admission spreading, lane packing) reads THIS so the
        affinity policy can't drift from the physical layout."""
        return 0

    def phys_index(self, slots) -> np.ndarray:
        """Physical stack indices of logical slots — the gather indices
        any consumer reading `states`/`rings` directly (the env's
        obs/checksum passes) must use instead of the logical slot."""
        return self._phys[np.asarray(slots, dtype=np.int32)]

    # ------------------------------------------------------------------

    def _dispatch_impl(self, rings, states, idx, rows, nslots):
        """Gather [B] session worlds, vmap the packed tick windowed at
        the STATIC depth bucket `nslots` (= the window for the unrouted
        full program), scatter back. Duplicate pad indices (all pointing
        at the dummy slot) compute identical results, so the scatter
        stays deterministic."""
        g_ring = jax.tree.map(lambda a: a[idx], rings)
        g_state = jax.tree.map(lambda a: a[idx], states)

        def one(ring, state, row):
            ring, state, _, his, los = self.core._tick_windowed_impl(
                ring, state, row, {}, nslots
            )
            return ring, state, his, los

        new_ring, new_state, his, los = jax.vmap(one)(g_ring, g_state, rows)
        rings = jax.tree.map(lambda a, b: a.at[idx].set(b), rings, new_ring)
        states = jax.tree.map(
            lambda a, b: a.at[idx].set(b), states, new_state
        )
        return rings, states, his, los

    def _dispatch_fast_impl(self, rings, states, idx, rows):
        """The zero-rollback megabatch program: every row is guaranteed
        (dispatch asserts it) to carry no load, at most one advance and
        no active slot past 1 — the shape of a tick with no pending
        misprediction. So: NO per-row ring gather/scatter (the full
        program moves ring_len+1 world copies per row either way), no
        resim scan — one vmapped step, two checksums (slot 0 pre-step,
        slot 1 post-step for the trailing-save shape) and two masked
        single-slot ring writes addressed directly into the stacked
        rings. Masked (scratch) saves write the slot's OLD value back to
        ring slot 0 — the branchless trick — so even the ring's bytes
        stay bit-identical to the cond program; pad rows (advance 0) are
        inert. Checksums land at window slots 0/1 of a zero [B, W] batch,
        keeping the flat k*W + i indexing."""
        import jax.numpy as jnp

        core = self.core
        W, P, I = core.window, self.num_players, self.input_size
        B = rows.shape[0]

        def where_rows(pred, a, b):
            return jax.tree.map(
                lambda x, y: jnp.where(
                    pred.reshape((-1,) + (1,) * (x.ndim - 1)), x, y
                ),
                a,
                b,
            )

        g_state = jax.tree.map(lambda a: a[idx], states)
        advance = rows[:, 2]
        s0 = rows[:, core._off_save]
        s1 = rows[:, core._off_save + 1]
        statuses0 = rows[:, core._off_status : core._off_status + P]
        inputs0 = (
            rows[:, core._off_input : core._off_input + P * I]
            .astype(jnp.uint8)
            .reshape(B, P, I)
        )
        zero = jnp.uint32(0)
        # slot 0: masked save of the pre-step state
        hi0, lo0 = jax.vmap(core.game.checksum)(g_state)
        do0 = s0 < core.ring_len
        w0 = jnp.where(do0, s0, 0)
        old0 = jax.tree.map(lambda r: r[idx, w0], rings)
        rings = jax.tree.map(
            lambda r, v: r.at[idx, w0].set(v),
            rings,
            where_rows(do0, g_state, old0),
        )
        # the one advance (masked only so pad rows stay inert)
        nxt = jax.vmap(core.game.step)(g_state, inputs0, statuses0)
        new_state = where_rows(advance > 0, nxt, g_state)
        # slot 1: masked trailing save of the post-step state
        hi1, lo1 = jax.vmap(core.game.checksum)(new_state)
        do1 = s1 < core.ring_len
        w1 = jnp.where(do1, s1, 0)
        old1 = jax.tree.map(lambda r: r[idx, w1], rings)
        rings = jax.tree.map(
            lambda r, v: r.at[idx, w1].set(v),
            rings,
            where_rows(do1, new_state, old1),
        )
        states = jax.tree.map(
            lambda a, b: a.at[idx].set(b), states, new_state
        )
        his = jnp.zeros((B, W), dtype=hi0.dtype)
        los = jnp.zeros((B, W), dtype=lo0.dtype)
        his = his.at[:, 0].set(jnp.where(do0, hi0, zero))
        his = his.at[:, 1].set(jnp.where(do1, hi1, zero))
        los = los.at[:, 0].set(jnp.where(do0, lo0, zero))
        los = los.at[:, 1].set(jnp.where(do1, lo1, zero))
        return rings, states, his, los

    def bucket_for(self, n: int) -> int:
        """Smallest configured pad target covering n rows."""
        for b in self.buckets:
            if b >= n:
                return b
        raise ContractViolation(f"{n} rows exceed the largest bucket")

    def depth_bucket_for(self, last_active: int) -> int:
        """Smallest depth-bucket pad target covering a 1-based last
        active slot."""
        for d in self.depth_buckets:
            if d >= last_active:
                return d
        raise ContractViolation(
            f"{last_active} slots exceed the window ({self.core.window})"
        )

    def dispatch_bucket_budget(self) -> int:
        """The jit-cache bound depth routing guarantees: one program per
        (row bucket x depth bucket) plus the fast path per row bucket —
        O(log capacity x log window) — plus, under speculation, one
        draft rollout and one per-slot adopt program per row bucket.
        The soak tests pin the live signature population inside this."""
        base = len(self.buckets) * (len(self.depth_buckets) + 1)
        if self.speculation:
            base += 2 * len(self.buckets)
        if self.sdc_audit:
            # one read-only reference-recompute program per row bucket
            base += len(self.buckets)
        if self.mailbox is not None:
            # resident driver: one windowed variant per depth bucket
            # plus the all-fast variant, plus one commit scatter per
            # pow2 commit bucket
            base += len(self.depth_buckets) + 1
            base += len(self.mailbox.commit_buckets)
        return base

    def megabatch_programs(self) -> List[Tuple[int, Optional[int], int]]:
        """The plan cache's megabatch-program population as structured
        (row_bucket, depth, dispatch_count) records — depth 0 is the
        zero-rollback fast path, an int the windowed depth bucket, None
        the unrouted full-window program. THE accessor for benches,
        gates and tests: the raw signature tuple layout stays private to
        this module (it already changed shape once)."""
        out = []
        for sig, c in self.plan_cache.signatures.items():
            if isinstance(sig, tuple) and sig and sig[0] == "megabatch":
                out.append((sig[1], sig[2] if len(sig) > 2 else None, c))
        return out

    def fast_eligible(
        self, row: np.ndarray, last_active: Optional[int] = None
    ) -> bool:
        """May this packed row ride the zero-rollback fast program? No
        load, exactly one advance, no active slot past 1 (a save of the
        current frame and/or a trailing save of the advanced frame).
        `last_active` (the row's 1-based last active slot) skips the
        save-slot rescan when the caller's parse already knows it."""
        if int(row[0]) != 0 or int(row[2]) != 1:
            return False
        if last_active is None:
            core = self.core
            tail = row[core._off_save + 2 : core._off_status]
            return bool((np.asarray(tail) >= core.ring_len).all())
        return last_active <= 2

    def _acquire_stage(self, bucket: int):
        """Rotate the pooled (idx, rows) staging pair for one row-count
        bucket, restoring pad defaults only over the entries the LAST
        use of this buffer actually wrote (re-tiling the whole pad rows
        every megabatch is exactly the host copy depth bucketing set out
        to remove)."""
        pool = self._stage_pools.get(bucket)
        if pool is None:
            pool = {
                "flip": 0,
                "bufs": [
                    [
                        np.full((bucket,), self.pad_slot, dtype=np.int32),
                        np.tile(self._pad_row, (bucket, 1)),
                        0,  # rows written by this buffer's last use
                    ]
                    for _ in range(self.async_inflight + 1)
                ],
            }
            self._stage_pools[bucket] = pool
        pool["flip"] = (pool["flip"] + 1) % len(pool["bufs"])
        return pool["bufs"][pool["flip"]]

    def dispatch(
        self, entries, *, last_active: Optional[int] = None,
        fast: bool = False,
    ) -> Tuple[_ChecksumBatch, int]:
        """Run one cross-session megabatch. `entries` is a list of
        (slot, packed_row) with AT MOST ONE row per slot — a session's
        second staged row (sparse-saving keepalive) rides the next
        megabatch, preserving its in-session order. Returns
        (checksum_batch, bucket): entry k's window-slot i checksum lives
        at flat index k * window + i of the batch. Non-blocking beyond
        the async-inflight fence.

        Depth routing (the host's scheduler groups rows accordingly):
        `fast=True` runs the zero-rollback program — every row must be
        fast_eligible; `last_active` (the MAX 1-based last active slot
        across the rows) runs the windowed program at the depth bucket
        covering it; neither runs the legacy full-window program."""
        n = len(entries)
        assert 0 < n <= self.capacity
        assert len({slot for slot, _ in entries}) == n, (
            "one row per session slot per megabatch"
        )
        if self.fault_seam is not None:
            # BEFORE any state or staging changes: a raise here leaves
            # the stacked worlds untouched, so the host can retry or
            # re-dispatch survivors bit-exactly
            self.fault_seam.before_dispatch(
                "megabatch", [slot for slot, _ in entries]
            )
        bucket = self.bucket_for(n)
        staged = self._acquire_stage(bucket)
        idx, rows, used = staged
        for k, (slot, row) in enumerate(entries):
            assert 0 <= slot < self.capacity
            idx[k] = self._phys[slot]
            rows[k] = row
        for k in range(n, used):  # re-pad only what the last use dirtied
            idx[k] = self.pad_slot
            rows[k] = self._pad_row
        staged[2] = n
        if fast:
            assert all(
                self.fast_eligible(rows[k]) for k in range(n)
            ), (
                "fast dispatch carries a row with a load, a multi-advance "
                "or a save past window slot 1"
            )
        return self._dispatch_staged(
            staged, n, bucket, last_active=last_active, fast=fast
        )

    def dispatch_rows(
        self, idx_block: np.ndarray, rows_block: np.ndarray, *,
        last_active: Optional[int] = None, fast: bool = False,
    ) -> Tuple[_ChecksumBatch, int]:
        """dispatch() for callers that already hold a whole [n, L] packed
        row block with its [n] slot vector (the batched RL env builds its
        fleet's step rows vectorized): the per-row Python pack loop
        becomes two numpy block copies into the pooled bucket staging.
        Same contract as dispatch() — at most one row per slot, rows are
        host-copied before return, non-blocking beyond the fence."""
        n = int(idx_block.shape[0])
        assert 0 < n <= self.capacity
        assert rows_block.shape[0] == n
        if self.fault_seam is not None:
            self.fault_seam.before_dispatch(
                "megabatch_rows", [int(s) for s in idx_block]
            )
        bucket = self.bucket_for(n)
        staged = self._acquire_stage(bucket)
        idx, rows, used = staged
        idx[:n] = self._phys[idx_block]
        rows[:n] = rows_block
        if used > n:  # re-pad only what the last use dirtied
            idx[n:used] = self.pad_slot
            rows[n:used] = self._pad_row
        staged[2] = n
        if fast:
            # vectorized fast_eligible over the block: no load, exactly
            # one advance, no active slot past 1
            core = self.core
            tail = rows_block[:, core._off_save + 2 : core._off_status]
            assert (
                (rows_block[:, 0] == 0).all()
                and (rows_block[:, 2] == 1).all()
                and (tail >= core.ring_len).all()
            ), (
                "fast dispatch_rows block carries a row with a load, a "
                "multi-advance or a save past window slot 1"
            )
        return self._dispatch_staged(
            staged, n, bucket, last_active=last_active, fast=fast
        )

    def _dispatch_staged(
        self, staged, n: int, bucket: int, *,
        last_active: Optional[int], fast: bool,
    ) -> Tuple[_ChecksumBatch, int]:
        """Common dispatch tail over a filled bucket-staging buffer:
        program selection (fast / windowed depth bucket / full window),
        plan-cache tally, the sanitizer's jit-budget assertion, telemetry
        and the async fence."""
        idx, rows, _used = staged
        if fast:
            sig_depth, nslots, fn_args = 0, 1, ()
            fn = self._dispatch_fast_fn
        elif last_active is not None:
            nslots = self.depth_bucket_for(last_active)
            sig_depth, fn_args = nslots, (nslots,)
            fn = self._dispatch_fn
        else:
            nslots = self.core.window
            sig_depth, fn_args = None, (nslots,)
            fn = self._dispatch_fn
        # each (row bucket, depth bucket) is one cached jitted program:
        # tally it beside the per-row signatures, but OUT of the segment
        # hit/miss counters (a different cache population with its own
        # hit dynamics). sig_depth 0 = the fast path, None = unrouted
        # full window.
        self.plan_cache.note(("megabatch", bucket, sig_depth), metrics=False)
        with transfer_guard_scope("megabatch dispatch"):
            # no-op unless GGRS_SANITIZE armed the sanitizer AND warmup
            # froze it: then an implicit device->host read inside the
            # dispatch (a stray float()/.item() on a live buffer) raises
            # ImplicitHostTransfer with its call site instead of
            # silently serializing the pipeline
            self.rings, self.states, his, los = fn(
                self.rings, self.states, idx, rows, *fn_args
            )
        san = active_sanitizer()
        if san is not None:
            # GGRS_SANITIZE: the megabatch jit cache must stay on the
            # (row bucket x depth bucket) grid — a dispatch that just
            # compiled past the budget names its call site and raises
            # instead of silently growing the cache mid-serve
            san.check_dispatch_budget(
                self._budget_fns(),
                self.dispatch_bucket_budget(),
                context="MultiSessionDeviceCore.dispatch",
            )
        self.megabatches += 1
        self.rows_dispatched += n
        if GLOBAL_TELEMETRY.enabled:
            self._m_batch_rows.observe(n)
            self._m_occupancy.set(n / bucket)
            if fast or last_active is not None:
                # fast dispatches observe depth 1 (the le=1 bucket is
                # exactly the fast-path counter the smoke gate asserts)
                self.core._m_depth.observe(nslots)
                self.core._m_waste.inc((self.core.window - nslots) * n)
        self._note_inflight(his, n)
        return _ChecksumBatch(his, los, self.ledger), bucket

    def _note_inflight(self, handle, n_rows: int) -> None:
        """Same fence discipline as TpuRollbackBackend._note_inflight:
        admit the dispatch, then block on the OLDEST once more than
        async_inflight megabatches are outstanding."""
        self._inflight.append((handle, n_rows))
        self.inflight_rows += n_rows
        while len(self._inflight) > self.async_inflight:
            oldest, rows = self._inflight.popleft()
            jax.block_until_ready(oldest)
            self.inflight_rows -= rows

    def poll_retired(self) -> int:
        """Drop already-retired megabatches from the fence without
        blocking; returns the rows still in flight (the host's
        backpressure budget reads this)."""
        while self._inflight and _array_is_ready(self._inflight[0][0]):
            _, rows = self._inflight.popleft()
            self.inflight_rows -= rows
        return self.inflight_rows

    # ------------------------------------------------------------------
    # speculative bubble-filling (serve/speculation.py drives this):
    # draft input-starved slots' futures into the megabatch, adopt on
    # arrival — the serving twin of the TpuRollbackBackend beam
    # ------------------------------------------------------------------

    def _budget_fns(self) -> dict:
        """Every jitted dispatch function whose cache the bucket budget
        bounds — THE one dict the sanitizer's budget assertion checks at
        every dispatch site, so the draft/adopt programs can never grow
        the cache invisibly."""
        fns = {
            "_dispatch_impl": self._dispatch_fn,
            "_dispatch_fast_impl": self._dispatch_fast_fn,
        }
        if self.speculation:
            fns["_draft_impl"] = self._draft_fn
            fns["_adopt_slot_impl"] = self._adopt_slot_fn
        if self.sdc_audit:
            fns["_audit_impl"] = self._audit_fn
        if self.mailbox is not None:
            fns["_driver_impl"] = self._driver_fn
            fns["_driver_fast_impl"] = self._driver_fast_fn
            fns["mailbox._commit_impl"] = self.mailbox._commit_fn
        return fns

    def _draft_impl(self, rings, idx, rows):
        """Vectorized speculative rollout over [B] input-starved slots:
        gather each slot's anchor snapshot from its ring, scan the
        drafted input script forward W frames with each row's STATIC
        per-player statuses — CONFIRMED for real players (the
        statuses_contract='disconnect-only' adoption contract),
        DISCONNECTED for host-layout pad columns — and return
        per-member per-frame trajectories plus
        post-step checksums — a ring-parked branch. rings are READ ONLY
        (no donation): a draft can never clobber confirmed state, and
        the confirmed worlds keep flowing through the ordinary megabatch
        programs while the draft stands."""
        import jax.numpy as jnp

        core = self.core
        W, P, I = core.window, self.num_players, self.input_size
        g_ring = jax.tree.map(lambda a: a[idx], rings)

        def one(ring, row):
            anchor_slot = row[0]
            statuses = row[1 : 1 + P]
            inputs = row[1 + P :].astype(jnp.uint8).reshape(W, P, I)
            anchor = jax.tree.map(
                lambda r: jax.lax.dynamic_index_in_dim(
                    r, anchor_slot, 0, keepdims=False
                ),
                ring,
            )
            a_hi, a_lo = core.game.checksum(anchor)

            def body(s, inp):
                nxt = core.game.step(s, inp, statuses)
                hi, lo = core.game.checksum(nxt)
                return nxt, (nxt, hi, lo)

            _, (traj, his, los) = jax.lax.scan(body, anchor, inputs)
            return traj, his, los, a_hi, a_lo

        return jax.vmap(one)(g_ring, rows)

    def _adopt_slot_impl(self, rings, states, slot, traj, his, los,
                         a_hi, a_lo, packed):
        """Serve one slot's tick row from a standing draft: gather the
        slot's ring, run the proven single-session adopt body (prefix
        states/checksums from the trajectory, mispredicted suffix
        resimulated in the same program), scatter back. packed is the
        ResimCore adopt layout; packed[0] (member) picks the draft-batch
        row this slot owns."""
        member = packed[0]
        ring = jax.tree.map(lambda a: a[slot], rings)
        ring, state, _, out_his, out_los = self.core._adopt_impl(
            ring, traj, his, los,
            jax.lax.dynamic_index_in_dim(a_hi, member, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(a_lo, member, 0, keepdims=False),
            {}, packed,
        )
        rings = jax.tree.map(lambda a, b: a.at[slot].set(b), rings, ring)
        states = jax.tree.map(
            lambda a, b: a.at[slot].set(b), states, state
        )
        return rings, states, out_his, out_los

    def pack_draft_row_into(self, out: np.ndarray, anchor_slot: int,
                            statuses: np.ndarray,
                            inputs: np.ndarray) -> np.ndarray:
        """Pack one slot's draft row ([anchor_ring_slot] + the static
        per-player i32[P] statuses + the u8[W,P,I] drafted input script)
        into a caller-owned int32 buffer."""
        P = self.num_players
        out[0] = anchor_slot
        out[1 : 1 + P] = statuses
        out[1 + P :] = inputs.reshape(-1)
        return out

    def _acquire_draft_stage(self, bucket: int):
        """Rotate the pooled (idx, rows) draft staging for one row-count
        bucket — the draft twin of _acquire_stage, under the same fence
        reuse guarantee."""
        pool = self._draft_stage_pools.get(bucket)
        if pool is None:
            pool = {
                "flip": 0,
                "bufs": [
                    [
                        np.full((bucket,), self.pad_slot, dtype=np.int32),
                        np.tile(self._draft_pad_row, (bucket, 1)),
                        0,
                    ]
                    for _ in range(self.async_inflight + 1)
                ],
            }
            self._draft_stage_pools[bucket] = pool
        pool["flip"] = (pool["flip"] + 1) % len(pool["bufs"])
        return pool["bufs"][pool["flip"]]

    def draft(self, entries) -> DraftBatch:
        """Launch one speculative draft megabatch: `entries` is a list of
        (slot, draft_row) — at most one per slot — packed into the same
        pow2 row buckets as ordinary dispatches, so the fleet's starved
        lanes fill device bubbles with ONE extra program per bucket.
        Returns the device-resident DraftBatch (member k = entry k);
        non-blocking beyond the async fence, confirmed state untouched."""
        assert self.speculation, "core built without speculation=True"
        n = len(entries)
        assert 0 < n <= self.capacity
        bucket = self.bucket_for(n)
        staged = self._acquire_draft_stage(bucket)
        idx, rows, used = staged
        for k, (slot, row) in enumerate(entries):
            assert 0 <= slot < self.capacity
            idx[k] = self._phys[slot]
            rows[k] = row
        for k in range(n, used):
            idx[k] = self.pad_slot
            rows[k] = self._draft_pad_row
        staged[2] = n
        self.plan_cache.note(("spec_draft", bucket), metrics=False)
        traj, his, los, a_hi, a_lo = self._draft_fn(self.rings, idx, rows)
        san = active_sanitizer()
        if san is not None:
            san.check_dispatch_budget(
                self._budget_fns(),
                self.dispatch_bucket_budget(),
                context="MultiSessionDeviceCore.draft",
            )
        self.drafts_launched += 1
        self._note_inflight(his, n)
        return DraftBatch(traj, his, los, a_hi, a_lo, bucket)

    def adopt_slot(self, slot: int, draft: DraftBatch,
                   packed: np.ndarray) -> _ChecksumBatch:
        """Serve (a prefix of) one session tick row from a standing
        draft instead of dispatching its resim: ring writes and saved
        checksums for the matched prefix come from the draft trajectory,
        the mispredicted suffix resimulates in the same program — a
        misprediction costs an adopt/truncate, never a full-window
        resim. `packed` is ResimCore.pack_adopt_row's layout with
        packed[0] = the slot's member index in `draft`. Returns the [W]
        checksum batch for the row's save bindings (flat index = window
        slot)."""
        assert self.speculation, "core built without speculation=True"
        assert 0 <= slot < self.capacity
        advance_count, matched = int(packed[2]), int(packed[5])
        assert 1 <= matched <= advance_count
        self.plan_cache.note(("spec_adopt", draft.bucket), metrics=False)
        self.rings, self.states, his, los = self._adopt_slot_fn(
            self.rings, self.states, np.int32(self._phys[slot]),
            draft.traj, draft.his, draft.los, draft.a_hi, draft.a_lo,
            packed,
        )
        san = active_sanitizer()
        if san is not None:
            san.check_dispatch_budget(
                self._budget_fns(),
                self.dispatch_bucket_budget(),
                context="MultiSessionDeviceCore.adopt_slot",
            )
        self.megabatches += 1
        self.rows_dispatched += 1
        self.spec_adopts += 1
        if GLOBAL_TELEMETRY.enabled:
            self._m_batch_rows.observe(1)
            # the depth histogram records what the device actually
            # resimulated: the mispredicted suffix (1 on a full hit) —
            # the "adopt, not full-window resim" acceptance surface
            depth = max(advance_count - matched, 1)
            self.core._m_depth.observe(depth)
            self.core._m_waste.inc(self.core.window - depth)
        self._note_inflight(his, 1)
        return _ChecksumBatch(his, los, self.ledger)

    # ------------------------------------------------------------------
    # SDC audit lane (serve/host.py's sampled double-compute drives it)
    # ------------------------------------------------------------------

    def _audit_impl(self, rings, states, idx, rows):
        """Reference recompute over [B] sampled slots, READ-ONLY: gather
        each slot's (ring, state), replay its audit row — load at the
        ring anchor, re-advance the recorded played inputs — through the
        FULL-WINDOW parity tick (the depth_routing=False reference
        program, deliberately a different compiled artifact from the
        fast/driver paths that produced the live bytes), and return the
        replayed final state's checksum beside the live world's. On an
        uncorrupted slot the two agree bitwise by the rollback
        contract; a flipped bit in the live world OR in the anchor ring
        row makes them diverge — either way a host-visible SDC verdict
        within the sampling cadence. Nothing is donated and nothing is
        scattered back: an audit can never perturb the worlds it
        checks."""
        g_ring = jax.tree.map(lambda a: a[idx], rings)
        g_state = jax.tree.map(lambda a: a[idx], states)

        def one(ring, state, row):
            _, replayed, _, _, _ = self.core._tick_windowed_impl(
                ring, state, row, {}, self.core.window
            )
            ref_hi, ref_lo = self.core.game.checksum(replayed)
            live_hi, live_lo = self.core.game.checksum(state)
            # every ring row's checksum recomputed at rest: the host
            # compares them against the values recorded when each row
            # was SAVED, so a bit that flipped in a stored snapshot is
            # caught before a future rollback can load and serve it
            ring_hi, ring_lo = jax.vmap(self.core.game.checksum)(ring)
            return ref_hi, ref_lo, live_hi, live_lo, ring_hi, ring_lo

        return jax.vmap(one)(g_ring, g_state, rows)

    def audit_rows(self, entries):
        """Launch one sampled SDC audit batch: `entries` is a list of
        (slot, packed audit row) — a row whose load slot is the lane's
        last ring anchor and whose advances replay the recorded played
        inputs up to the live frame, saves all scratch. Returns the
        device handles (ref_hi, ref_lo, live_hi, live_lo, ring_hi[R],
        ring_lo[R]), entry k at index k — the host resolves them lazily
        and quarantines any slot whose replay/live pair or recorded
        ring-row checksums mismatch. Pads to the megabatch row buckets;
        non-blocking (no fence admission needed: the audit allocates
        its own staging and touches no donated state)."""
        assert self.sdc_audit, "core built without sdc_audit=True"
        n = len(entries)
        assert 0 < n <= self.capacity
        bucket = self.bucket_for(n)
        # fresh staging per audit: audits are sampled (default one in
        # `sdc_audit_every` host ticks), so this is not a hot path and
        # pooling it would only grow the fence-protected surface
        idx = np.full((bucket,), self.pad_slot, dtype=np.int32)
        rows = np.tile(self._pad_row, (bucket, 1))
        for k, (slot, row) in enumerate(entries):
            assert 0 <= slot < self.capacity
            idx[k] = self._phys[slot]
            rows[k] = row
        self.plan_cache.note(("sdc_audit", bucket), metrics=False)
        out = self._audit_fn(self.rings, self.states, idx, rows)
        san = active_sanitizer()
        if san is not None:
            san.check_dispatch_budget(
                self._budget_fns(),
                self.dispatch_bucket_budget(),
                context="MultiSessionDeviceCore.audit_rows",
            )
        self.audit_dispatches += 1
        return out

    # ------------------------------------------------------------------
    # device-resident serving loop (serve/host.py's resident=True mode
    # drives this): a donated input mailbox the host feeds, and a jitted
    # lax.while_loop virtual-tick driver that consumes it — dispatch
    # cadence drops from one megabatch per host tick to one driver
    # dispatch per K virtual ticks, with checksums accumulating into
    # [K, S, W] output rings harvested lazily behind the async fence
    # ------------------------------------------------------------------

    def attach_mailbox(self, depth: int):
        """Build the device-resident input mailbox (tpu/mailbox.py) and
        the virtual-tick driver programs. `depth` = K, the maximum
        virtual ticks one driver dispatch executes per lane. Call before
        warmup() so the driver variants compile with the megabatch
        grid."""
        import jax

        from .mailbox import DeviceMailbox

        assert self.mailbox is None, "mailbox already attached"
        self.mailbox = DeviceMailbox(self, depth)
        self._driver_fn = jax.jit(
            self._driver_impl, static_argnums=(5,), donate_argnums=(0, 1)
        )
        self._driver_fast_fn = jax.jit(
            self._driver_fast_impl, donate_argnums=(0, 1)
        )
        return self.mailbox

    def _driver_impl(self, rings, states, mbox_rows, marks, vt_fast,
                     nslots):
        """The virtual-tick driver: a lax.while_loop over the mailbox's
        vtick axis, each iteration ticking the WHOLE stack — rollback
        rows load and resimulate in-loop, exactly the single-session
        tick body, without returning to Python between virtual ticks.
        Lane s consumes rows for vticks [0, marks[s]); rows above a
        lane's watermark (and every pad slot's rows) mask to the inert
        pad row, so lanes at different fill depths ride one program. The
        loop exits at the deepest watermark: a half-full mailbox pays
        for the vticks it actually has, not for K.

        Per-vtick depth routing rides INSIDE the loop: `vt_fast[t]`
        (host-computed: every row staged at vtick t was fast-eligible)
        conds each iteration between the vmapped zero-rollback fast step
        and the vmapped windowed scan at the STATIC depth bucket
        `nslots` — XLA executes only the taken branch, so one rollback
        row costs its own vtick the windowed scan, not the whole cycle.
        Bit-identical either way (the fast/windowed contract the
        megabatch depth routing already pins). Checksums land in
        [K, S, W] output rings (flat index j * S * W + s * W + i),
        harvested lazily by the host."""
        import jax.numpy as jnp

        K, S = mbox_rows.shape[1], mbox_rows.shape[0]
        W = self.core.window
        pad = jnp.asarray(self._pad_row)
        limit = jnp.max(marks)

        def one(ring, state, row):
            ring, state, _, hi, lo = self.core._tick_windowed_impl(
                ring, state, row, {}, nslots
            )
            return ring, state, hi, lo

        def cond(carry):
            return carry[0] < limit

        def body(carry):
            t, rings, states, his, los = carry
            rows_t = jax.lax.dynamic_index_in_dim(
                mbox_rows, t, 1, keepdims=False
            )
            valid = t < marks
            rows_t = jnp.where(valid[:, None], rows_t, pad[None, :])

            def fast_branch(args):
                rings, states = args
                return jax.vmap(self.core._tick_fast_impl)(
                    rings, states, rows_t
                )

            def windowed_branch(args):
                rings, states = args
                return jax.vmap(one)(rings, states, rows_t)

            rings, states, hi, lo = jax.lax.cond(
                vt_fast[t], fast_branch, windowed_branch, (rings, states)
            )
            his = jax.lax.dynamic_update_index_in_dim(his, hi, t, 0)
            los = jax.lax.dynamic_update_index_in_dim(los, lo, t, 0)
            return t + 1, rings, states, his, los

        his = jnp.zeros((K, S, W), dtype=jnp.uint32)
        los = jnp.zeros((K, S, W), dtype=jnp.uint32)
        _, rings, states, his, los = jax.lax.while_loop(
            cond, body, (jnp.int32(0), rings, states, his, los)
        )
        return rings, states, his, los

    def _driver_fast_impl(self, rings, states, mbox_rows, marks):
        """The driver's zero-rollback variant: when EVERY row of the fill
        cycle is fast-eligible (no load, one advance, no save past
        window slot 1 — the dominant live traffic), each iteration
        vmaps the per-slot zero-rollback fast tick
        (ResimCore._tick_fast_impl, the in-loop twin of the megabatch
        fast program) instead of the windowed scan body. Bit-identical
        to the windowed driver on eligible rows — masked saves write the
        old ring value back, pad rows are inert — by the same contract
        the megabatch fast path pins."""
        import jax.numpy as jnp

        K, S = mbox_rows.shape[1], mbox_rows.shape[0]
        W = self.core.window
        pad = jnp.asarray(self._pad_row)
        limit = jnp.max(marks)

        def cond(carry):
            return carry[0] < limit

        def body(carry):
            t, rings, states, his, los = carry
            rows_t = jax.lax.dynamic_index_in_dim(
                mbox_rows, t, 1, keepdims=False
            )
            valid = t < marks
            rows_t = jnp.where(valid[:, None], rows_t, pad[None, :])
            rings, states, hi, lo = jax.vmap(self.core._tick_fast_impl)(
                rings, states, rows_t
            )
            his = jax.lax.dynamic_update_index_in_dim(his, hi, t, 0)
            los = jax.lax.dynamic_update_index_in_dim(los, lo, t, 0)
            return t + 1, rings, states, his, los

        his = jnp.zeros((K, S, W), dtype=jnp.uint32)
        los = jnp.zeros((K, S, W), dtype=jnp.uint32)
        _, rings, states, his, los = jax.lax.while_loop(
            cond, body, (jnp.int32(0), rings, states, his, los)
        )
        return rings, states, his, los

    def stage_mailbox_row(self, slot: int, row: np.ndarray, *,
                          last_active: int, fast: bool):
        """Append one LOGICAL slot's packed tick row to the mailbox fill
        cycle; returns (checksum batch, base index) for the row's save
        bindings. A full lane — the host outran the virtual-tick depth —
        degrades to an EXTRA driver dispatch (counted in
        ggrs_mailbox_overflow_total), never a dropped input."""
        mbox = self.mailbox
        phys = int(self._phys[slot])
        storm = (
            self.fault_seam is not None and self.fault_seam.on_stage(phys)
        )
        if mbox.lane_full(phys) or storm:
            # a real full lane and an injected overflow storm take the
            # same path: degrade to an extra drive, never drop the row
            mbox.note_overflow()
            self.drive_mailbox()
        return mbox.stage(phys, row, last_active, fast)

    def commit_mailbox(self) -> None:
        """Land every row staged since the last commit on the device in
        ONE batched scatter (the host's one mailbox transfer per host
        tick); admits the write to the async fence so the pooled commit
        staging is provably reusable."""
        mbox = self.mailbox
        if mbox is None or mbox.staged_count == 0:
            return
        handle = mbox.commit()
        self._note_inflight(handle, 0)

    def drive_mailbox(self):
        """Consume the mailbox with ONE virtual-tick driver dispatch:
        commit any uncommitted rows, route the cycle to the fast or the
        depth-bucketed windowed driver variant, and fulfill the cycle's
        future checksum batch from the [K, S, W] output rings. Returns
        the batch (None when the mailbox is empty). Non-blocking beyond
        the async fence — the harvest stays lazy."""
        mbox = self.mailbox
        if mbox is None or (mbox.pending_rows == 0 and mbox.staged_count == 0):
            return None
        if self.fault_seam is not None:
            # every lane with rows this drive would execute, as LOGICAL
            # slots — consulted before commit/take so a raise leaves the
            # cycle intact for the host's retry/containment ladder
            phys_live = set(np.nonzero(mbox._counts)[0].tolist())
            phys_live.update(p for p, _, _ in mbox._staged)
            slots = sorted(
                int(self._phys_inverse[p])
                for p in phys_live
                if int(self._phys_inverse[p]) < self.capacity
            )
            self.fault_seam.before_dispatch("resident_drive", slots)
        self.commit_mailbox()
        marks, n_rows, max_la, all_fast, vt_fast, future = mbox.take_cycle()
        with transfer_guard_scope("resident drive"):
            # guards the driver dispatch only: `marks` is the mailbox's
            # host-side counts copy, so the `int(marks.max())` readback
            # below is host math, not a device sync
            if all_fast:
                nslots = 1
                self.plan_cache.note(("resident_drive", 0), metrics=False)
                self.rings, self.states, his, los = self._driver_fast_fn(
                    self.rings, self.states, mbox.rows_dev, marks
                )
            else:
                nslots = self.depth_bucket_for(max_la)
                self.plan_cache.note(
                    ("resident_drive", nslots), metrics=False
                )
                self.rings, self.states, his, los = self._driver_fn(
                    self.rings, self.states, mbox.rows_dev, marks, vt_fast,
                    nslots,
                )
        san = active_sanitizer()
        if san is not None:
            san.check_dispatch_budget(
                self._budget_fns(),
                self.dispatch_bucket_budget(),
                context="MultiSessionDeviceCore.drive_mailbox",
            )
        vticks = int(marks.max())
        self.driver_dispatches += 1
        self.vticks_executed += vticks
        self.rows_dispatched += n_rows
        if GLOBAL_TELEMETRY.enabled:
            mbox.observe_drive(n_rows, vticks)
            self.core._m_depth.observe(nslots)
            self.core._m_waste.inc((self.core.window - nslots) * n_rows)
        self._note_inflight(his, n_rows)
        batch = _ChecksumBatch(his, los, self.ledger)
        if future is not None:
            future.batch = batch
        return batch

    # ------------------------------------------------------------------
    # slot lifecycle
    # ------------------------------------------------------------------

    def reset_slot(self, slot: int) -> None:
        """Return one session slot to its initial world (attach/evict
        slot reuse): state back to init_state(), ring zeroed. Eager
        per-leaf updates — a lifecycle event, not a hot path."""
        import jax.numpy as jnp

        assert 0 <= slot < self.capacity
        # staged mailbox rows execute BEFORE any slot lifecycle event:
        # a reset must never race rows the ring still owes
        self.drive_mailbox()
        phys = int(self._phys[slot])
        init = self.core.game.init_state()
        self.states = jax.tree.map(
            lambda a, x: a.at[phys].set(x), self.states, init
        )
        self.rings = jax.tree.map(
            lambda a: a.at[phys].set(jnp.zeros(a.shape[1:], a.dtype)),
            self.rings,
        )

    def drop_mailbox_lane(self, slot: int) -> int:
        """QUARANTINE containment (resident mode): discard every row
        LOGICAL slot `slot` still owes the mailbox — its watermark drops
        to zero, so rows already committed to the device ring mask to
        the inert pad row and never execute, and its staged rows never
        commit. Survivor lanes' rows, watermarks and routing are
        untouched (a conservatively-wide depth bucket is bit-identical
        by the windowed contract). Returns the rows dropped."""
        if self.mailbox is None:
            return 0
        return self.mailbox.drop_lane(int(self._phys[slot]))

    def inject_slot_bitflip(self, slot: int, *, seed: int,
                            target: str = "ring",
                            ring_slot: Optional[int] = None) -> dict:
        """FAULT-INJECTION entry point (serve/faults.py's SDC arm; never
        called on a production path): flip ONE seeded bit of logical
        slot `slot`'s device residue — a snapshot-ring row
        (`target='ring'`, the at-rest corruption a future rollback
        would load and serve; `ring_slot` pins which row, default
        seeded over the real rows) or its live world
        (`target='state'`). Flushes the fence and the mailbox first so
        the flip lands on canonical bytes, then writes the flipped
        leaf back through an eager per-slot update, the reset_slot
        discipline. Survivors' slots are untouched. Returns a
        descriptor of what flipped, for the forensics bundle."""
        import jax.numpy as jnp
        from random import Random

        assert 0 <= slot < self.capacity
        assert target in ("state", "ring")
        self.block_until_ready()
        phys = int(self._phys[slot])
        tree = self.states if target == "state" else self.rings
        leaves = jax.tree_util.tree_leaves_with_path(tree)
        rng = Random(seed)
        path, leaf = leaves[rng.randrange(len(leaves))]
        if target == "ring":
            # confine the flip to ONE real ring row (never the scratch
            # row, which masked saves target and nothing ever loads)
            r = (
                int(ring_slot) % self.core.ring_len
                if ring_slot is not None
                else rng.randrange(self.core.ring_len)
            )
            row = np.array(jax.device_get(leaf[phys, r]), copy=True)
        else:
            r = None
            row = np.array(jax.device_get(leaf[phys]), copy=True)
        flat = row.reshape(-1).view(np.uint8)
        bit = rng.randrange(flat.size * 8)
        flat[bit // 8] ^= np.uint8(1 << (bit % 8))

        def patch(p, a):
            if p != path:
                return a
            if r is None:
                return a.at[phys].set(jnp.asarray(row))
            return a.at[phys, r].set(jnp.asarray(row))

        patched = jax.tree_util.tree_map_with_path(patch, tree)
        if target == "state":
            self.states = patched
        else:
            self.rings = patched
        return {
            "slot": slot,
            "target": target,
            "ring_slot": r,
            "leaf": jax.tree_util.keystr(path),
            "byte": bit // 8,
            "bit": bit % 8,
        }

    def _reset_masked_impl(self, rings, states, mask, init):
        """Masked batch reset over the stacked pytrees: every slot with
        mask[slot] set returns to the pristine init world, its ring
        zeroed; every other slot passes through untouched. mask is DATA
        (bool[stack_slots], the dummy tail always False), so one program
        covers every reset pattern — the env workload's auto-reset
        resets its whole done-set in one dispatch regardless of which
        episodes finished."""
        import jax.numpy as jnp

        def sel(a, x):
            m = mask.reshape((-1,) + (1,) * (a.ndim - 1))
            return jnp.where(m, x, a)

        states = jax.tree.map(sel, states, init)
        rings = jax.tree.map(
            lambda r: jnp.where(
                mask.reshape((-1,) + (1,) * (r.ndim - 1)),
                jnp.zeros((), r.dtype),
                r,
            ),
            rings,
        )
        return rings, states

    def reset_slots_masked(self, mask: np.ndarray) -> None:
        """Return every slot with mask[slot] == True to its initial world
        in ONE jitted masked pass (bool[capacity]). The batch twin of
        reset_slot: auto-resetting N finished episodes costs one program
        dispatch, not N eager per-leaf updates — and the mask is data,
        so the program compiles once (warmup covers it) no matter which
        slots finish."""
        assert mask.shape == (self.capacity,)
        self.drive_mailbox()  # lifecycle events drain the mailbox first
        m = np.zeros((self.stack_slots,), dtype=bool)
        m[self._phys[np.asarray(mask, dtype=bool)]] = True
        self.rings, self.states = self._reset_mask_fn(
            self.rings, self.states, m, self._init_state
        )

    def state_numpy(self, slot: int):
        """Host copy of one session slot's live world (parity checks)."""
        self.block_until_ready()
        phys = int(self._phys[slot])
        return jax.tree.map(
            lambda a: np.asarray(jax.device_get(a[phys])), self.states
        )

    # ------------------------------------------------------------------
    # per-slot export/import (live session migration rides this)
    # ------------------------------------------------------------------

    def _export_slot_impl(self, rings, states, slot):
        ring = jax.tree.map(lambda a: a[slot], rings)
        state = jax.tree.map(lambda a: a[slot], states)
        return ring, state

    def _import_slot_impl(self, rings, states, slot, ring, state):
        rings = jax.tree.map(lambda a, x: a.at[slot].set(x), rings, ring)
        states = jax.tree.map(
            lambda a, x: a.at[slot].set(x), states, state
        )
        return rings, states

    def export_slot(self, slot: int) -> dict:
        """Host copy of ONE slot's complete device residue — live world
        AND snapshot ring — as {"ring": tree, "state": tree} of numpy
        arrays: everything a sibling host needs to resume this session
        bit-exactly (the ring bytes matter — a post-migration rollback
        loads a pre-migration snapshot). Flushes the fence first so the
        copy observes every dispatched megabatch that wrote the slot."""
        assert 0 <= slot < self.capacity
        self.block_until_ready()
        ring, state = self._export_slot_fn(
            self.rings, self.states, np.int32(self._phys[slot])
        )
        return {
            "ring": jax.tree.map(
                lambda a: np.asarray(jax.device_get(a)), ring
            ),
            "state": jax.tree.map(
                lambda a: np.asarray(jax.device_get(a)), state
            ),
        }

    def import_slot(self, slot: int, payload: dict) -> None:
        """Adopt an export_slot() payload into one slot of THIS core —
        the receiving half of a live migration. Validates the payload's
        tree structure and per-leaf shapes/dtypes against this core's
        stacked layout and raises MigrationIncompatible naming the first
        mismatch (a different game config must fail at the handoff, not
        as an XLA shape error mid-megabatch). Eager per-leaf updates —
        a lifecycle event, not a hot path — behind a full fence flush,
        the same discipline as reset_slot."""
        from ..errors import MigrationIncompatible

        assert 0 <= slot < self.capacity
        for name, stacked in (("ring", self.rings), ("state", self.states)):
            flat_dst = jax.tree_util.tree_leaves_with_path(stacked)
            flat_src = jax.tree_util.tree_leaves_with_path(payload[name])
            if [p for p, _ in flat_dst] != [p for p, _ in flat_src]:
                raise MigrationIncompatible(
                    f"slot payload '{name}' tree does not match this "
                    f"core's layout (different game model?): "
                    f"{[jax.tree_util.keystr(p) for p, _ in flat_src]} vs "
                    f"{[jax.tree_util.keystr(p) for p, _ in flat_dst]}"
                )
            for (path, dst), (_, src) in zip(flat_dst, flat_src):
                want, got = dst.shape[1:], np.asarray(src).shape
                if want != got or dst.dtype != np.asarray(src).dtype:
                    raise MigrationIncompatible(
                        f"slot payload '{name}{jax.tree_util.keystr(path)}' "
                        f"is {got}/{np.asarray(src).dtype}, this core's "
                        f"slots are {want}/{dst.dtype} — the hosts run "
                        "different game configs"
                    )
        self.block_until_ready()
        self.rings, self.states = self._import_slot_fn(
            self.rings, self.states, np.int32(self._phys[slot]),
            payload["ring"], payload["state"],
        )

    def warmup(self) -> None:
        """Compile the megabatch program grid — every (row-count bucket x
        depth bucket) plus the zero-rollback fast path per row bucket —
        before serving: first compilation takes seconds, enough to stall
        every hosted session at once mid-tick, and depth routing must
        never trade the padding win for mid-serve compile stalls. All-pad
        dispatches are true no-ops on the stacked worlds (pad rows
        advance nothing and save nowhere, on the fast program included).
        With depth_routing=False only the full-window program per row
        bucket compiles, as before."""
        with warmup_scope("MultiSessionDeviceCore.warmup"):
            self._warmup_impl()

    def _warmup_impl(self) -> None:
        for b in self.buckets:
            idx = np.full((b,), self.pad_slot, dtype=np.int32)
            rows = np.tile(self._pad_row, (b, 1))
            if self.depth_routing:
                self.rings, self.states, _, _ = self._dispatch_fast_fn(
                    self.rings, self.states, idx, rows
                )
                for d in self.depth_buckets:
                    self.rings, self.states, _, _ = self._dispatch_fn(
                        self.rings, self.states, idx, rows, d
                    )
            else:
                self.rings, self.states, _, _ = self._dispatch_fn(
                    self.rings, self.states, idx, rows, self.core.window
                )
        if self.speculation:
            core = self.core
            W = core.window
            scratch = np.full((W,), core.scratch_slot, dtype=np.int32)
            statuses = np.zeros((W, self.num_players), dtype=np.int32)
            inputs = np.zeros(
                (W, self.num_players, self.input_size), dtype=np.uint8
            )
            for b in self.buckets:
                # draft rollout per bucket: pad rows anchor on the dummy
                # world's zeroed ring (discarded results, a pure compile)
                idx = np.full((b,), self.pad_slot, dtype=np.int32)
                rows = np.tile(self._draft_pad_row, (b, 1))
                traj, his, los, a_hi, a_lo = self._draft_fn(
                    self.rings, idx, rows
                )
                # per-slot adopt per bucket, against the DUMMY slot with
                # scratch-only saves: no ring bytes move, and the dummy
                # state the adopt steps is restored below — live slots
                # never observe the warmup
                packed = core.pack_adopt_row(
                    0, 0, 1, 1, 0, 1, scratch,
                    statuses=statuses, inputs=inputs,
                )
                self.rings, self.states, _, _ = self._adopt_slot_fn(
                    self.rings, self.states, np.int32(self.pad_slot),
                    traj, his, los, a_hi, a_lo, packed,
                )
            init = core.game.init_state()
            self.states = jax.tree.map(
                lambda a, x: a.at[self.pad_slot].set(x), self.states, init
            )
        if self.sdc_audit:
            # the audit lane's reference-recompute program per row
            # bucket: all-pad batches read the dummy slot only and
            # return discarded checksums — a pure compile, and the
            # worlds are untouched by construction (nothing is donated
            # or scattered)
            for b in self.buckets:
                self._audit_fn(
                    self.rings,
                    self.states,
                    np.full((b,), self.pad_slot, dtype=np.int32),
                    np.tile(self._pad_row, (b, 1)),
                )
        if self.mailbox is not None:
            # resident driver variants: compile the commit-bucket
            # scatters plus every driver program the live cycle router
            # can pick (fast + one windowed variant per depth bucket).
            # All-zero watermarks make each a true no-op — the
            # while_loop exits before its first virtual tick — so only
            # the compile happens, never a state change.
            self.mailbox.warmup()
            marks = np.zeros((self.stack_slots,), dtype=np.int32)
            vt_fast = np.ones((self.mailbox.depth,), dtype=bool)
            rows_dev = self.mailbox.rows_dev
            self.rings, self.states, _, _ = self._driver_fast_fn(
                self.rings, self.states, rows_dev, marks
            )
            for d in self.depth_buckets:
                self.rings, self.states, _, _ = self._driver_fn(
                    self.rings, self.states, rows_dev, marks, vt_fast, d
                )
        # the masked batch reset (env auto-reset) with an all-False mask:
        # a true no-op on the stacked worlds, but the program exists
        # before the first episode ever finishes mid-serve
        self.rings, self.states = self._reset_mask_fn(
            self.rings,
            self.states,
            np.zeros((self.stack_slots,), dtype=bool),
            self._init_state,
        )
        # one export->import round trip of slot 0 (same bytes back, a
        # true no-op): the eager per-leaf slot writes compile their XLA
        # programs HERE, so the first live migration pays a memcpy, not
        # a compile stall mid-serve
        self.import_slot(0, self.export_slot(0))
        self.block_until_ready()

    def block_until_ready(self) -> None:
        # "device state is current" includes the mailbox: rows the ring
        # still owes execute first, so exports/checkpoints/parity reads
        # always observe the canonical (fully ticked) worlds
        self.drive_mailbox()
        jax.block_until_ready(self.states)
        self._inflight.clear()
        self.inflight_rows = 0

    # ------------------------------------------------------------------
    # durable checkpoint (graceful drain rides this)
    # ------------------------------------------------------------------

    def stacked_canonical(self) -> Tuple[Any, Any]:
        """Host copy of the stacked worlds in the CANONICAL slot layout —
        `capacity` live slots in logical order plus ONE dummy row at
        index `capacity` — whatever the stack's physical layout
        (checkpoints and cross-host parity checks are always canonical,
        so a sharded host's bytes compare/restore against a
        single-device twin's directly). Returns (rings, states) numpy
        pytrees; `save()` writes exactly this and `load_stacked()`
        adopts it back."""
        self.block_until_ready()
        idx = np.append(self._phys, np.int32(self.pad_slot))
        canon = lambda a: np.asarray(jax.device_get(a))[idx]  # noqa: E731
        return (
            jax.tree.map(canon, self.rings),
            jax.tree.map(canon, self.states),
        )

    def checksum_slots(self) -> Tuple[np.ndarray, np.ndarray]:
        """(hi, lo) uint32[capacity] checksums of every live slot's
        world, logical slot order — the host-facing desync spot-check
        and the cross-layout parity witness (the sharded subclass
        overrides this with the EXPLICIT shard_map + psum pass from
        parallel/sharded.py; both must agree bitwise with vmapping the
        model's checksum). Not a hot path: flushes the fence."""
        self.block_until_ready()
        g = jax.tree.map(lambda a: a[self._phys], self.states)
        his, los = jax.vmap(self.core.game.checksum)(g)
        return (
            np.asarray(jax.device_get(his)),
            np.asarray(jax.device_get(los)),
        )

    def save(self, path: str) -> None:
        from ..utils.checkpoint import save_device_checkpoint

        rings, states = self.stacked_canonical()
        save_device_checkpoint(
            path,
            {"rings": rings, "states": states},
            {
                "kind": "MultiSessionDeviceCore",
                "capacity": self.capacity,
                "max_prediction": self.core.max_prediction,
                "num_players": self.num_players,
            },
        )

    @classmethod
    def restore(cls, path: str, game, mesh=None) -> "MultiSessionDeviceCore":
        """Rebuild a core from a save() checkpoint. Checkpoints are
        LAYOUT-AGNOSTIC: `mesh=` restores the same worlds onto a sharded
        core (and a sharded host's checkpoint restores single-device) —
        the serving twin of TpuRollbackBackend.restore's mesh knob."""
        from ..utils.checkpoint import load_device_checkpoint

        tree, meta = load_device_checkpoint(path)
        if meta.get("kind") != "MultiSessionDeviceCore":
            from ..errors import CheckpointIncompatible

            raise CheckpointIncompatible(
                f"checkpoint {path!r} holds a different core kind",
                found=meta.get("kind"), expected="MultiSessionDeviceCore",
            )
        core = cls.create(
            game,
            meta["max_prediction"],
            meta["num_players"],
            meta["capacity"],
            mesh=mesh,
        )
        core.load_stacked(tree["rings"], tree["states"])
        return core

    def load_stacked(self, rings, states) -> None:
        """Adopt checkpointed stacked worlds into THIS core (the env
        restore path: the env rebuilds its core from config, then loads
        the saved worlds) — the in-place twin of restore(). The trees
        carry the CANONICAL capacity + 1 slots (save() writes that
        layout whatever the stack's physical padding); this expands them
        into the core's own physical layout — dummy padding replicated
        from the canonical dummy row — and places per the layout's
        policy, so a single-device checkpoint restores onto a sharded
        core (and vice versa) bit-exactly."""
        self.block_until_ready()

        def expand(a):
            a = np.asarray(jax.device_get(a))
            assert a.shape[0] == self.capacity + 1, (
                f"stacked trees must be canonical (capacity + 1 = "
                f"{self.capacity + 1} slots; got {a.shape[0]})"
            )
            out = np.repeat(
                a[self.capacity : self.capacity + 1],
                self.stack_slots,
                axis=0,
            )
            out[self._phys] = a[: self.capacity]
            return out

        self.rings = self._place_rings(jax.tree.map(expand, rings))
        self.states = self._place_states(jax.tree.map(expand, states))


class ShardedMultiSessionDeviceCore(MultiSessionDeviceCore):
    """MultiSessionDeviceCore with the SESSION axis of the stacked
    pytrees split over the `session` axis of a device mesh (and, for big
    worlds, the entity axis over an `entity` mesh axis) — the serving
    megabatch GSPMD-partitioned across chips, so one host's capacity
    multiplies by the session-axis size instead of stacking the whole
    fleet on device 0.

    Placement is the ONE policy in parallel/sharded.py
    (`stacked_state_specs`/`stacked_ring_specs` via
    `shard_stacked_state`/`shard_stacked_ring`): sessions split over
    `session` on the stack's leading axis, entity arrays additionally
    over `entity` when the mesh carries one, ring-slot axes always
    local. The slot layout interleaves live slots round-robin across the
    session shards — logical slot i lives on shard i % n at local offset
    i // n — so a fleet that fills slots in admission order spreads over
    every chip, and the dummy pad tail is distributed so the session
    axis divides the stack. The public API stays LOGICAL-slot throughout
    (dispatch entries, reset masks, export/import, checkpoints — which
    stay canonical, so a sharded host's checkpoint restores on a
    single-device twin and vice versa).

    Every program of the base core — the (row-bucket x depth-bucket)
    megabatch grid, the zero-rollback fast path, `reset_slots_masked`,
    `dispatch_rows`, export/import, `load_stacked` — runs GSPMD-
    partitioned from the operand shardings; the dispatch impls
    additionally constrain the staged (idx, rows) batch onto the
    `session` axis, so the vmapped row work partitions across shards
    (the host's slot->shard affinity keeps most rows on the shard that
    owns their world, so the gather/scatter crosses ICI only for the
    stragglers). The per-megabatch [B, W] checksum reduction rides the
    models' concat-free partial sums (ops/fixed_point.
    weighted_checksum_parts — exact under any partitioning);
    `checksum_slots()` additionally pins the collective shape BY HAND
    via parallel/sharded.stacked_sharded_checksum (shard_map + psum over
    `entity`), the spot-check a partitioner regression is caught
    against.

    Bitwise contract (pinned by tests/test_sharded_serve.py and the
    dryrun's sharded-host stage): a sharded host produces bit-identical
    per-slot device state, ring bytes and checksum histories to a
    single-device twin fed the same traffic."""

    def __init__(self, game, max_prediction: int, num_players: int,
                 capacity: int, *, mesh, **kw):
        from jax.sharding import NamedSharding, PartitionSpec

        assert "session" in mesh.axis_names, (
            f"serving mesh needs a 'session' axis (got {mesh.axis_names};"
            " build it with parallel.mesh.make_session_mesh)"
        )
        self.mesh = mesh
        self.session_shards = int(mesh.shape["session"])
        self._row_sharding = NamedSharding(mesh, PartitionSpec("session"))
        super().__init__(game, max_prediction, num_players, capacity, **kw)
        _reg = GLOBAL_TELEMETRY.registry
        self._m_shard_rows = _reg.gauge(
            "ggrs_shard_rows",
            "live megabatch rows routed to this session-mesh shard in "
            "the last dispatch",
            labelnames=("shard",),
        )
        self._m_shard_imbalance = _reg.histogram(
            "ggrs_shard_imbalance",
            "max/mean live rows per session-mesh shard per megabatch "
            "dispatch (1.0 = perfectly balanced)",
            buckets=SHARD_IMBALANCE_BUCKETS,
        )
        # labeled children resolved once, not per dispatch: .labels() is
        # a str-key dict path and _dispatch_staged is the hot tick path
        self._shard_row_gauges = [
            self._m_shard_rows.labels(str(s))
            for s in range(self.session_shards)
        ]

    # ------------------------------------------------------------------
    # stack-layout hooks (see the base class: everything else — dispatch,
    # staging, fence, lifecycle — is layout-agnostic and inherited)
    # ------------------------------------------------------------------

    def _stack_size(self) -> int:
        """capacity live slots + a dummy tail padded so the session mesh
        axis divides the stack (>= 1 dummy total, so pad rows always
        have a world to no-op against)."""
        n = self.session_shards
        self._per_shard = -(-(self.capacity + 1) // n)  # ceil
        return self._per_shard * n

    def _place_states(self, tree):
        from ..parallel.sharded import shard_stacked_state

        return shard_stacked_state(tree, self.mesh)

    def _place_rings(self, tree):
        from ..parallel.sharded import shard_stacked_ring

        return shard_stacked_ring(tree, self.mesh)

    def _init_slot_layout(self) -> None:
        per, n = self._per_shard, self.session_shards
        slots = np.arange(self.capacity, dtype=np.int32)
        # round-robin: shard s owns physical rows [s*per, (s+1)*per) of
        # the equally-split stack; logical slot i -> shard i % n, local
        # offset i // n (< per by construction of _stack_size)
        self._phys = (slots % n) * per + slots // n
        self._phys_inverse = np.full(
            (self.stack_slots,), self.capacity, dtype=np.int32
        )
        self._phys_inverse[self._phys] = slots
        dummies = np.setdiff1d(
            np.arange(self.stack_slots, dtype=np.int32), self._phys
        )
        self.pad_slot = int(dummies[0])

    def shard_of(self, slot: int) -> int:
        return int(slot) % self.session_shards

    # ------------------------------------------------------------------
    # GSPMD dispatch: same impls, the staged batch constrained onto the
    # session axis so the row work partitions across shards
    # ------------------------------------------------------------------

    def _dispatch_impl(self, rings, states, idx, rows, nslots):
        idx = jax.lax.with_sharding_constraint(idx, self._row_sharding)
        rows = jax.lax.with_sharding_constraint(rows, self._row_sharding)
        return super()._dispatch_impl(rings, states, idx, rows, nslots)

    def _dispatch_fast_impl(self, rings, states, idx, rows):
        idx = jax.lax.with_sharding_constraint(idx, self._row_sharding)
        rows = jax.lax.with_sharding_constraint(rows, self._row_sharding)
        return super()._dispatch_fast_impl(rings, states, idx, rows)

    def _draft_impl(self, rings, idx, rows):
        # the draft batch partitions across the session shards like any
        # other staged row block (the host's slot->shard affinity orders
        # draft entries by owning shard, so the rollout's ring gathers
        # stay mostly shard-local); the per-slot adopt needs no
        # constraint — it is a single-slot gather/scatter GSPMD already
        # partitions from the operand shardings
        idx = jax.lax.with_sharding_constraint(idx, self._row_sharding)
        rows = jax.lax.with_sharding_constraint(rows, self._row_sharding)
        return super()._draft_impl(rings, idx, rows)

    def _audit_impl(self, rings, states, idx, rows):
        # the sampled audit batch partitions across the session shards
        # like any other staged row block; the replay itself is per-slot
        # local, so the constraint keeps the gathers shard-local
        idx = jax.lax.with_sharding_constraint(idx, self._row_sharding)
        rows = jax.lax.with_sharding_constraint(rows, self._row_sharding)
        return super()._audit_impl(rings, states, idx, rows)

    def _place_mailbox(self, rows):
        from ..parallel.sharded import shard_mailbox

        return shard_mailbox(rows, self.mesh)

    def _driver_impl(self, rings, states, mbox_rows, marks, vt_fast,
                     nslots):
        # the mailbox's slot axis is placed on the session mesh
        # (shard_mailbox); constrain it (and the watermarks) in-program
        # too so the vmapped vtick body partitions like every other
        # stacked computation — each shard walks its own lanes' rows
        # (vt_fast is a tiny replicated [K] routing vector)
        mbox_rows = jax.lax.with_sharding_constraint(
            mbox_rows, self._row_sharding
        )
        marks = jax.lax.with_sharding_constraint(marks, self._row_sharding)
        return super()._driver_impl(
            rings, states, mbox_rows, marks, vt_fast, nslots
        )

    def _driver_fast_impl(self, rings, states, mbox_rows, marks):
        mbox_rows = jax.lax.with_sharding_constraint(
            mbox_rows, self._row_sharding
        )
        marks = jax.lax.with_sharding_constraint(marks, self._row_sharding)
        return super()._driver_fast_impl(rings, states, mbox_rows, marks)

    def _dispatch_staged(self, staged, n, bucket, *, last_active, fast):
        if GLOBAL_TELEMETRY.enabled:
            # per-shard live-row census of THIS dispatch: the affinity
            # health surface (registry-driven, so both exporters and
            # host.telemetry() carry it with no extra code)
            counts = np.bincount(
                staged[0][:n] // self._per_shard,
                minlength=self.session_shards,
            )
            for s in range(self.session_shards):
                self._shard_row_gauges[s].set(int(counts[s]))
            self._m_shard_imbalance.observe(
                float(counts.max()) * self.session_shards / n
            )
        return super()._dispatch_staged(
            staged, n, bucket, last_active=last_active, fast=fast
        )

    # ------------------------------------------------------------------
    # the explicit cross-shard checksum pass
    # ------------------------------------------------------------------

    def checksum_slots(self) -> Tuple[np.ndarray, np.ndarray]:
        """(hi, lo) uint32[capacity], logical slot order, computed with
        the EXPLICIT shard_map + psum collective from
        parallel/sharded.stacked_sharded_checksum — bit-identical to the
        base class's vmapped model checksum (the parity tests pin both
        against each other), with the cross-shard word reduction's
        collective shape pinned by hand for entity-sharded worlds."""
        from ..parallel.sharded import stacked_sharded_checksum

        self.block_until_ready()
        his, los = stacked_sharded_checksum(
            self.states, self.mesh, keys=self.core.game.checksum_keys
        )
        his = np.asarray(jax.device_get(his))[self._phys]
        los = np.asarray(jax.device_get(los))[self._phys]
        return his, los

    def _warmup_impl(self) -> None:
        super()._warmup_impl()
        # the explicit cross-shard checksum pass compiles here too, so a
        # mid-serve desync spot-check never pays its first compile
        self.checksum_slots()
