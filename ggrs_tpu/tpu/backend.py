"""TpuRollbackBackend: fulfills a session's ordered request list on device.

This is the pluggable seam BASELINE.json prescribes: sessions
(SyncTestSession, P2PSession) keep emitting the reference's ordered
Save/Load/Advance requests (src/lib.rs:169-194), and this backend consumes
them — but instead of executing them one by one through user callbacks, it
parses the request grammar

    [Load?] (Save? Advance)* Save?

(the exact shape every session emits per tick: first-frame double save,
dense/sparse rollback blocks, trailing confirmed-frame saves) and lowers the
whole tick into ONE fused device dispatch via ResimCore. Snapshot data never
leaves the device; cells are filled with lightweight SnapshotRef handles and
lazy checksums that only force a device->host transfer when read.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from ..ops.fixed_point import combine_checksum
from ..types import AdvanceFrame, Frame, LoadGameState, Request, SaveGameState
from ..utils.tracing import GLOBAL_TRACER
from .resim import ResimCore


@dataclass(frozen=True)
class SnapshotRef:
    """Opaque handle stored in a GameStateCell: the snapshot lives in the
    device ring, addressed by frame (slot = frame % ring_len)."""

    frame: Frame
    ring_slot: int


class _ChecksumBatch:
    """One tick's worth of device checksums; fetched to host at most once,
    and only if some cell's checksum is actually read."""

    def __init__(self, his, los):
        self._his = his
        self._los = los
        self._np: Optional[Tuple[np.ndarray, np.ndarray]] = None

    def resolve(self, idx: int) -> int:
        if self._np is None:
            self._np = (np.asarray(self._his), np.asarray(self._los))
        return combine_checksum(self._np[0][idx], self._np[1][idx])


class TpuRollbackBackend:
    """Request-fulfilling rollback backend over a device game.

    Usage:
        backend = TpuRollbackBackend(game, max_prediction=8, num_players=2)
        requests = session.advance_frame()
        backend.handle_requests(requests)
    """

    def __init__(self, game, max_prediction: int, num_players: int):
        self.core = ResimCore(game, max_prediction, num_players)
        self.num_players = num_players
        self.input_size = game.input_size
        self.current_frame: Frame = 0

    # ------------------------------------------------------------------

    def handle_requests(self, requests: List[Request]) -> None:
        """A tick is usually one fused batch, but sparse-saving P2P ticks can
        legally contain two rollback blocks (misprediction rollback + ring
        keepalive rollback, p2p_session.rs:286+:792): split into one batch
        per LoadGameState and fuse each."""
        segment: List[Request] = []
        for req in requests:
            if isinstance(req, LoadGameState) and segment:
                self._run_segment(segment)
                segment = []
            segment.append(req)
        if segment:
            self._run_segment(segment)

    def _run_segment(self, requests: List[Request]) -> None:
        load: Optional[LoadGameState] = None
        slots: List[Tuple[Optional[SaveGameState], AdvanceFrame]] = []
        pending_save: Optional[SaveGameState] = None

        for req in requests:
            if isinstance(req, LoadGameState):
                assert load is None and not slots and pending_save is None, (
                    "unsupported request pattern: Load must lead a segment"
                )
                load = req
            elif isinstance(req, SaveGameState):
                if pending_save is not None:
                    # first-frame double save (p2p_session.rs:270-272 + :295)
                    assert pending_save.frame == req.frame
                pending_save = req
            elif isinstance(req, AdvanceFrame):
                slots.append((pending_save, req))
                pending_save = None
            else:
                raise TypeError(f"unknown request {req!r}")
        trailing_save = pending_save

        core = self.core
        W, P, I = core.window, self.num_players, self.input_size
        count = len(slots)
        assert count <= core.max_prediction + 1, "tick exceeds the fused window"
        assert trailing_save is None or count < W

        inputs = np.zeros((W, P, I), dtype=np.uint8)
        statuses = np.zeros((W, P), dtype=np.int32)
        save_slots = np.full((W,), core.scratch_slot, dtype=np.int32)

        start_frame = load.frame if load is not None else self.current_frame
        saves: List[Tuple[int, SaveGameState]] = []

        for i, (save, adv) in enumerate(slots):
            if save is not None:
                assert save.frame == start_frame + i, (
                    f"save of frame {save.frame} out of order (expected {start_frame + i})"
                )
                save_slots[i] = save.frame % core.ring_len
                saves.append((i, save))
            for p, (buf, status) in enumerate(adv.inputs):
                inputs[i, p] = np.frombuffer(buf, dtype=np.uint8)
                statuses[i, p] = int(status)
        if trailing_save is not None:
            assert trailing_save.frame == start_frame + count
            save_slots[count] = trailing_save.frame % core.ring_len
            saves.append((count, trailing_save))

        with GLOBAL_TRACER.span("tpu/fused_tick"):
            his, los = core.tick(
                do_load=load is not None,
                load_slot=(load.frame % core.ring_len) if load is not None else 0,
                inputs=inputs,
                statuses=statuses,
                save_slots=save_slots,
                advance_count=count,
            )
        self.current_frame = start_frame + count

        batch = _ChecksumBatch(his, los)
        for idx, save in saves:
            ref = SnapshotRef(save.frame, save.frame % core.ring_len)
            save.cell.save_lazy(
                save.frame, ref, (lambda b=batch, i=idx: b.resolve(i))
            )

    # ------------------------------------------------------------------

    def state_numpy(self):
        """Host copy of the live game state (parity checks / rendering)."""
        return self.core.fetch_state()

    def block_until_ready(self) -> None:
        jax.block_until_ready(self.core.state)

    # ------------------------------------------------------------------
    # durable checkpoint/resume (beyond the reference, SURVEY.md §5)
    # ------------------------------------------------------------------

    def save(self, path: str) -> None:
        from ..utils.checkpoint import save_device_checkpoint

        save_device_checkpoint(
            path,
            {"ring": self.core.ring, "state": self.core.state},
            {
                "kind": "TpuRollbackBackend",
                "current_frame": self.current_frame,
                "max_prediction": self.core.max_prediction,
                "num_players": self.num_players,
            },
        )

    @classmethod
    def restore(cls, path: str, game) -> "TpuRollbackBackend":
        from ..utils.checkpoint import load_device_checkpoint

        tree, meta = load_device_checkpoint(path)
        assert meta["kind"] == "TpuRollbackBackend"
        backend = cls(
            game,
            max_prediction=meta["max_prediction"],
            num_players=meta["num_players"],
        )
        backend.core.ring = jax.device_put(tree["ring"])
        backend.core.state = jax.device_put(tree["state"])
        backend.current_frame = meta["current_frame"]
        return backend
