"""Device-resident input mailbox: the feed half of the resident serving
loop (the drive half is MultiSessionDeviceCore's `lax.while_loop`
virtual-tick driver in backend.py).

The dispatch-per-tick serving path pays the per-dispatch tunnel floor
(~1.6ms of host time, any program content) once per host tick — the
device finishes a megabatch in microseconds and then idles waiting for
the host to hand it the next one. The mailbox retires that cadence: a
fixed [S, K, L] ring of packed tick rows lives ON DEVICE (S = stack
slots, K = virtual-tick depth, L = the packed control-word length), the
host's pump/stage pass appends each lane's decoded rows to a host-side
staging image as sessions advance, and ONE batched scatter per host tick
(`commit`) moves everything newly staged onto the device — the same
pooled-staging discipline as the PR 6 wire pump's decode buffers. Every
K host ticks (or on demand) the driver consumes the whole ring in one
dispatch, walking per-lane valid watermarks so lanes at different fill
depths each execute exactly their own staged rows, in order.

Watermark semantics: lane s's rows are valid for virtual ticks
[0, marks[s]); rows above the watermark are never consumed (the driver
masks them to the inert pad row), so a fill cycle only ever executes
rows written since the last drive. Overflow — the host outrunning K —
degrades to an EXTRA driver dispatch (`note_overflow` + drive), never a
dropped input: `stage` asserts the lane has room, and the core's
`stage_mailbox_row` entry point drives first when it doesn't.

Checksum harvest is lazy: each fill cycle owns one
`_FutureChecksumBatch`; staged saves bind `_LazyChecksum`s against it at
flat index j * S * W + phys * W + window_slot (the driver's [K, S, W]
output rings, raveled), and the first read of any of them forces the
drive — laziness composes with laziness, exactly like the single-session
lazy tick buffer.

Shared-state discipline: the pooled commit staging and the device row
ring are fence-protected state (reuse is safe only because the core's
async fence proves the dispatch that read a buffer retired) — the FEN001
policy for this module names the methods allowed to write them.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..errors import InvariantViolation, MailboxLaneFull
from ..obs import GLOBAL_TELEMETRY, LOG2_BUCKETS


class DeviceMailbox:
    """Donated [S, K, L] device row ring + host staging + watermarks.

    Built by `MultiSessionDeviceCore.attach_mailbox`; the host never
    constructs one directly. All slot indices here are PHYSICAL stack
    indices (the core's `stage_mailbox_row` translates logical slots)."""

    def __init__(self, core, depth: int):
        import jax
        import jax.numpy as jnp

        if depth < 1:
            raise InvariantViolation(
                f"mailbox depth must be >= 1 (got {depth})",
                invariant="mailbox_depth",
            )
        self.core = core
        self.depth = depth
        self.stack_slots = core.stack_slots
        self.row_len = core.core._packed_len
        self.window = core.core.window
        # the device row ring, placed by the core's layout policy (the
        # sharded core splits the slot axis over the session mesh)
        self.rows_dev = core._place_mailbox(
            jnp.tile(
                jnp.asarray(core._pad_row), (self.stack_slots, depth, 1)
            )
        )
        # per-lane fill watermarks (host image; the drive hands the
        # device a fresh copy per dispatch)
        self._counts = np.zeros((self.stack_slots,), dtype=np.int32)
        # rows staged since the last commit: (phys, vtick, row ref)
        self._staged: List[Tuple[int, int, np.ndarray]] = []
        self.pending_rows = 0  # committed + staged, i.e. rows a drive owes
        # cycle bookkeeping for driver-program routing: the cycle's max
        # depth, whole-cycle fast eligibility, and the per-vtick fast
        # vector the mixed driver conds on in-loop
        self._cycle_max_last_active = 0
        self._cycle_all_fast = True
        self._vt_fast = np.ones((depth,), dtype=bool)
        self._future = None  # _FutureChecksumBatch of the open cycle
        # pooled (idx, vt, rows) commit staging per pow2 bucket,
        # async_inflight + 1 deep (the fence-reuse guarantee)
        self._pools: dict = {}
        b, buckets = 1, set()
        cap = max(2 * core.capacity, 1)
        while b < cap:
            buckets.add(b)
            b *= 2
        buckets.add(cap)
        self.commit_buckets = tuple(sorted(buckets))
        self._commit_fn = jax.jit(self._commit_impl, donate_argnums=(0,))
        self.overflows = 0
        _reg = GLOBAL_TELEMETRY.registry
        self._m_occupancy = _reg.gauge(
            "ggrs_mailbox_occupancy",
            "staged mailbox rows / (capacity x depth) at the last driver "
            "dispatch",
        )
        self._m_overflow = _reg.counter(
            "ggrs_mailbox_overflow_total",
            "mailbox fill cycles cut short because a lane outran the "
            "virtual-tick depth (degrades to an extra dispatch; inputs "
            "are never dropped)",
        )
        self._m_vticks = _reg.histogram(
            "ggrs_vticks_per_dispatch",
            "virtual ticks executed per resident driver dispatch (the "
            "dispatch-amortization factor)",
            buckets=LOG2_BUCKETS,
        )

    # ------------------------------------------------------------------
    # staging (host side)
    # ------------------------------------------------------------------

    def lane_full(self, phys: int) -> bool:
        return int(self._counts[phys]) >= self.depth

    def max_fill(self) -> int:
        return int(self._counts.max())

    def note_overflow(self) -> None:
        self.overflows += 1
        if GLOBAL_TELEMETRY.enabled:
            self._m_overflow.inc()

    def stage(self, phys: int, row: np.ndarray, last_active: int,
              fast: bool):
        """Append one packed tick row to lane `phys`'s fill cycle.
        Returns (checksum batch, base index) for the row's save bindings
        — the batch is the open cycle's future, fulfilled at drive time.
        The row reference must stay valid until the next `commit` (the
        lane row pools guarantee it: commits happen within the tick)."""
        j = int(self._counts[phys])
        if j >= self.depth:
            # a runtime scheduling bug, not an API misuse: typed so the
            # operator sees which lane wedged at what depth (the core's
            # stage_mailbox_row drives first and can never hit this)
            raise MailboxLaneFull(
                "stage() on a full mailbox lane (caller must drive)",
                lane=phys, depth=self.depth,
            )
        self._staged.append((phys, j, row))
        self._counts[phys] = j + 1
        self.pending_rows += 1
        self._cycle_max_last_active = max(
            self._cycle_max_last_active, last_active
        )
        self._cycle_all_fast = self._cycle_all_fast and fast
        if not fast:
            self._vt_fast[j] = False
        if self._future is None:
            # lazy import once per process (not per staged row — this is
            # the hot staging path): backend also imports this module
            # lazily from attach_mailbox, so a module-level import would
            # be cycle-prone depending on which side loads first
            from .backend import _FutureChecksumBatch

            self._future = _FutureChecksumBatch(self._force_drive)
        base = j * self.stack_slots * self.window + phys * self.window
        return self._future, base

    def _force_drive(self) -> None:
        """A lazy-checksum read forced the cycle: route through the
        core's drive entry point (which installs the real batch)."""
        self.core.drive_mailbox()

    # ------------------------------------------------------------------
    # commit (the one batched host->device transfer per host tick)
    # ------------------------------------------------------------------

    def _commit_impl(self, rows_dev, idx, vt, new_rows):
        """Scatter [n] freshly staged rows into the donated device ring.
        Duplicate pad entries (pad_slot, vtick 0) all write the identical
        pad row, so the scatter stays deterministic. The second output is
        a small NON-donated token the async fence can block on — the ring
        itself is donated to the next commit, so a fence handle aliasing
        it would be a deleted buffer by the time the fence waits."""
        import jax.numpy as jnp

        return rows_dev.at[idx, vt].set(new_rows), jnp.max(vt)

    def _acquire_commit_stage(self, bucket: int):
        pool = self._pools.get(bucket)
        if pool is None:
            pool = {
                "flip": 0,
                "bufs": [
                    [
                        np.full((bucket,), self.core.pad_slot, np.int32),
                        np.zeros((bucket,), np.int32),
                        np.tile(self.core._pad_row, (bucket, 1)),
                        0,
                    ]
                    for _ in range(self.core.async_inflight + 1)
                ],
            }
            self._pools[bucket] = pool
        pool["flip"] = (pool["flip"] + 1) % len(pool["bufs"])
        return pool["bufs"][pool["flip"]]

    @property
    def staged_count(self) -> int:
        return len(self._staged)

    def commit_bucket_for(self, n: int) -> int:
        for b in self.commit_buckets:
            if b >= n:
                return b
        return self.commit_buckets[-1]

    def commit(self):
        """Move every row staged since the last commit onto the device,
        bucketed scatters over pow2 pad targets (a batch past the
        largest bucket — a caller staging a whole fill cycle before its
        first commit — chunks through it; the steady host flow commits
        every tick, so one scatter per tick is the norm). Returns the
        last dispatch handle (None when nothing was staged). Called by
        the core's `commit_mailbox` entry point, which admits the handle
        to the async fence."""
        handle = None
        todo = self._staged
        while todo:
            chunk, todo = (
                todo[: self.commit_buckets[-1]],
                todo[self.commit_buckets[-1] :],
            )
            self._staged = todo
            n = len(chunk)
            bucket = self.commit_bucket_for(n)
            staged = self._acquire_commit_stage(bucket)
            idx, vt, rows, used = staged
            for k, (phys, j, row) in enumerate(chunk):
                idx[k] = phys
                vt[k] = j
                rows[k] = row
            for k in range(n, used):  # re-pad what the last use dirtied
                idx[k] = self.core.pad_slot
                vt[k] = 0
                rows[k] = self.core._pad_row
            staged[3] = n
            self.core.plan_cache.note(
                ("mailbox_commit", bucket), metrics=False
            )
            self.rows_dev, handle = self._commit_fn(
                self.rows_dev, idx, vt, rows
            )
        return handle

    def warmup(self) -> None:
        """Compile every commit-bucket scatter with all-pad entries — a
        true no-op on the ring (pad lanes' rows are never consumed), so
        the first live commit of any size pays a memcpy, not a compile
        stall mid-serve."""
        for bucket in self.commit_buckets:
            staged = self._acquire_commit_stage(bucket)
            idx, vt, rows, _used = staged
            idx.fill(self.core.pad_slot)
            vt.fill(0)
            rows[:] = self.core._pad_row
            staged[3] = bucket
            self.core.plan_cache.note(
                ("mailbox_commit", bucket), metrics=False
            )
            self.rows_dev, _ = self._commit_fn(self.rows_dev, idx, vt, rows)

    # ------------------------------------------------------------------
    # drive-side bookkeeping (the core's drive_mailbox consumes these)
    # ------------------------------------------------------------------

    def take_cycle(self):
        """Close the fill cycle for a driver dispatch: returns
        (marks i32[S], n_rows, max_last_active, all_fast, vt_fast
        bool[K], future) and resets the staging bookkeeping for the next
        cycle. `commit` must have landed every staged row first
        (drive_mailbox guarantees it)."""
        if self._staged:
            # a drive that would execute rows the device never received:
            # the watermark/row-ring invariant the resident loop's
            # correctness rests on, surfaced typed instead of asserted
            raise InvariantViolation(
                "take_cycle() with uncommitted staged rows",
                invariant="mailbox_uncommitted_rows",
            )
        marks = self._counts.copy()
        n = self.pending_rows
        max_la = self._cycle_max_last_active
        all_fast = self._cycle_all_fast
        vt_fast = self._vt_fast.copy()
        future = self._future
        self._counts.fill(0)
        self.pending_rows = 0
        self._cycle_max_last_active = 0
        self._cycle_all_fast = True
        self._vt_fast.fill(True)
        self._future = None
        return marks, n, max_la, all_fast, vt_fast, future

    def drop_lane(self, phys: int) -> int:
        """QUARANTINE containment: discard every row PHYSICAL lane
        `phys` still owes this fill cycle — staged entries are scrubbed
        before they can commit, and the lane's watermark drops to zero
        so rows already committed to the device ring mask to the inert
        pad row at the next drive. Other lanes' rows, watermarks and
        the cycle's routing flags are untouched (leftover conservative
        routing — a wider depth bucket, a windowed instead of fast
        drive — is bit-identical by the driver contract). Returns the
        rows dropped. Lazy checksums already bound against the cycle's
        future for the dropped rows resolve to pad values; the caller
        quarantined the owning session, so no live cell reads them."""
        n = int(self._counts[phys])
        if n == 0:
            return 0
        if self._staged:
            self._staged = [
                (p, j, row) for (p, j, row) in self._staged if p != phys
            ]
        self._counts[phys] = 0
        self.pending_rows -= n
        return n

    def observe_drive(self, n_rows: int, vticks: int) -> None:
        """Telemetry for one driver dispatch (behind the enabled check at
        the call site, the Tracer.span idiom)."""
        self._m_vticks.observe(vticks)
        self._m_occupancy.set(
            n_rows / float(self.core.capacity * self.depth)
        )
