"""Online per-player input statistics feeding the speculation candidates.

The reference predicts one future per player — repeat the last confirmed
input (src/input_queue.rs:126-139) — and that floor is exactly what the
beam's member 0 already provides. What the branch members need is a model
of WHEN a player will stop repeating and WHAT they will switch to. Real
input streams are runs of held values; this module learns, per player,

- the HOLD-LENGTH distribution (how long values get held before a switch),
  turned into a discrete hazard: given the current value has been held r
  frames, the probability the switch lands exactly k frames out; and
- the VALUE-TRANSITION distribution (given the held value, which values
  follow it), learned from observed switches.

Both are learned online from FINALIZED history only — frames old enough
that no rollback can rewrite them — so the statistics never ingest a
prediction that later turns out wrong. The product of the two
distributions ranks every (player, switch offset, next value) branch
candidate; `TpuRollbackBackend` hands the top of that ranking to
`beam.branching_beam(predictions=...)`, which allocates beam members by
likelihood instead of sweeping offsets uniformly. The uniform sweep and
the XOR perturbations remain the fallback for players with no history.
"""

from __future__ import annotations

from collections import Counter, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

# Smoothing pseudo-count for the hazard estimate: with few observed holds
# the model should spread probability over nearby offsets rather than
# spike on the single length it happened to see first.
HAZARD_PRIOR = 0.5


class _PlayerStats:
    """Sequential run tracker + bounded hold/transition statistics for one
    player. observe() consumes finalized rows strictly in frame order."""

    __slots__ = (
        "cur_value", "cur_len", "holds", "hold_counts", "transitions",
        "trans_log", "max_holds", "max_transitions",
    )

    def __init__(self, max_holds: int = 64, max_transitions: int = 64):
        self.cur_value: Optional[bytes] = None
        self.cur_len = 0
        # trailing window of hold lengths; the Counter mirrors the deque so
        # hazard queries are O(support), not O(window)
        self.holds: deque = deque()
        self.hold_counts: Counter = Counter()
        # value -> Counter of successor values, with a trailing log so old
        # behavior ages out of the counts
        self.transitions: Dict[bytes, Counter] = {}
        self.trans_log: deque = deque()
        self.max_holds = max_holds
        self.max_transitions = max_transitions

    def observe(self, row: bytes) -> None:
        if row == self.cur_value:
            self.cur_len += 1
            return
        if self.cur_value is not None:
            self._record_hold(self.cur_len)
            self._record_transition(self.cur_value, row)
        self.cur_value = row
        self.cur_len = 1

    def _record_hold(self, length: int) -> None:
        self.holds.append(length)
        self.hold_counts[length] += 1
        if len(self.holds) > self.max_holds:
            old = self.holds.popleft()
            self.hold_counts[old] -= 1
            if self.hold_counts[old] <= 0:
                del self.hold_counts[old]

    def _record_transition(self, src: bytes, dst: bytes) -> None:
        self.transitions.setdefault(src, Counter())[dst] += 1
        self.trans_log.append((src, dst))
        if len(self.trans_log) > self.max_transitions:
            osrc, odst = self.trans_log.popleft()
            c = self.transitions.get(osrc)
            if c is not None:
                c[odst] -= 1
                if c[odst] <= 0:
                    del c[odst]
                if not c:
                    del self.transitions[osrc]

    # -- queries -------------------------------------------------------

    def n_holds(self) -> int:
        return len(self.holds)

    def hazard(self, t: int) -> float:
        """P(hold == t | hold >= t) from the trailing hold window, with a
        flat pseudo-count so sparse data yields a spread, not a spike."""
        if not self.holds:
            return 0.0
        support = len(self.hold_counts) + 1  # +1: unseen-length mass
        at = self.hold_counts.get(t, 0) + HAZARD_PRIOR
        ge = sum(c for ln, c in self.hold_counts.items() if ln >= t)
        ge += HAZARD_PRIOR * support
        return at / ge

    def next_values(self, src: bytes, limit: int = 3) -> List[Tuple[bytes, float]]:
        """Ranked successor values for `src` with probability shares."""
        c = self.transitions.get(src)
        if not c:
            return []
        total = sum(c.values())
        if total <= 0:
            return []
        ranked = c.most_common(limit)
        return [(v, n / total) for v, n in ranked if n > 0]

    # -- migration carry (JSON-safe: byte values travel hex-encoded) ---

    def state_dict(self) -> dict:
        return {
            "cur_value": (
                self.cur_value.hex() if self.cur_value is not None else None
            ),
            "cur_len": self.cur_len,
            "holds": list(self.holds),
            "trans_log": [(s.hex(), d.hex()) for s, d in self.trans_log],
        }

    def load_state_dict(self, state: dict) -> None:
        cv = state.get("cur_value")
        self.cur_value = bytes.fromhex(cv) if cv is not None else None
        self.cur_len = int(state.get("cur_len", 0))
        # the deques are the source of truth; the Counters mirror them
        self.holds = deque(int(h) for h in state.get("holds", ()))
        self.hold_counts = Counter(self.holds)
        self.transitions = {}
        self.trans_log = deque()
        for s_hex, d_hex in state.get("trans_log", ()):
            src, dst = bytes.fromhex(s_hex), bytes.fromhex(d_hex)
            self.transitions.setdefault(src, Counter())[dst] += 1
            self.trans_log.append((src, dst))


class InputHistoryModel:
    """Per-player hold/transition statistics over finalized input rows.

    Feed rows with `observe(player, row)` strictly in frame order (the
    backend does this for frames beyond rollback reach). Query ranked
    branch candidates with `rank_branches`.
    """

    # minimum observed holds before a player's hazard ranking is trusted;
    # below this the generic offset sweep covers the player instead
    MIN_HOLDS = 3
    # per-player cap on emitted specs: the hazard of one imminent switch
    # smears over adjacent offsets, and members are too scarce to spend
    # more than this on a single player's timing uncertainty
    MAX_SPECS_PER_PLAYER = 3

    # state_dict discriminator: a migration ticket's exported statistics
    # only load into the same kind of model (learn.ArrayInputModel
    # overrides this — its tables are frozen and travel by registry
    # version, not by ticket)
    kind = "online"

    def __init__(self, num_players: int, input_size: int):
        self.num_players = num_players
        self.input_size = input_size
        self._stats = [_PlayerStats() for _ in range(num_players)]

    def observe(self, player: int, row: bytes) -> None:
        self._stats[player].observe(row)

    def break_run(self, player: int) -> None:
        """Sever the run without recording anything (disconnect dummy
        rows are not player behavior)."""
        st = self._stats[player]
        st.cur_value = None
        st.cur_len = 0

    def reset(self) -> None:
        self._stats = [_PlayerStats() for _ in self._stats]

    def state_dict(self) -> dict:
        """Everything learned, by value and JSON-safe — what a migration
        ticket carries so a migrated session's speculation resumes warm
        instead of relearning from MIN_HOLDS."""
        return {
            "kind": self.kind,
            "num_players": self.num_players,
            "input_size": self.input_size,
            "players": [st.state_dict() for st in self._stats],
        }

    def load_state_dict(self, state: dict) -> None:
        from ..errors import ModelIncompatible

        for field in ("kind", "num_players", "input_size"):
            found, expected = state.get(field), getattr(self, field)
            if found != expected:
                raise ModelIncompatible(
                    f"input-model state {field} mismatch",
                    found=found, expected=expected,
                )
        for st, sd in zip(self._stats, state["players"]):
            st.load_state_dict(sd)

    def rank_branches(
        self,
        confirmed: List[Optional[Tuple[int, bytes, int]]],
        anchor_frame: int,
        rollout: int,
        limit: int,
    ) -> List[Tuple[int, int, np.ndarray]]:
        """Rank (player, beam-row offset, next value) switch candidates.

        `confirmed[p]` is (frontier_frame, value_bytes, run_len): the last
        frame whose input for player p is confirmed, the value held there,
        and how many consecutive confirmed frames it has been held. None
        means no confirmed signal for that player (no candidates emitted).
        Beam row j carries the input fed at frame anchor_frame + j, so a
        switch first visible at frame F maps to offset F - anchor_frame.

        Returns up to `limit` (player, offset, value_row) specs, allocated
        ROUND-ROBIN across players (ordered by each player's top score,
        hazard(run + delta) * P(value | held value)) with at most
        MAX_SPECS_PER_PLAYER specs each; only offsets inside [0, rollout)
        survive. Round-robin, not global rank order: hazard mass smears
        over adjacent offsets of the SAME imminent switch, and a pure
        rank sort lets one player's smear crowd every other player out of
        the beam entirely (measured: a 4-player staggered toggle lost a
        third of its adoptions that way). The caller composes the specs
        into beam members (beam.branching_beam's prediction stream).

        The score is the EXACT switch-at-offset-d probability: the
        hazard h(run + d - 1) times the survival product over the
        intervening frames, prod(1 - h(t)) for t in [run, run + d - 1),
        times P(value | held value). (Until PR 18 the survival factor
        was dropped — a documented approximation that biased scores
        toward LATER offsets whenever hazard rises with hold length,
        because later offsets skipped more of the shrinking product.)"""
        per_player: List[List[Tuple[float, int, int, bytes]]] = []
        for p in range(self.num_players):
            if confirmed[p] is None:
                continue
            st = self._stats[p]
            if st.n_holds() < self.MIN_HOLDS:
                continue
            frontier, value, run = confirmed[p]
            succ = st.next_values(value)
            if not succ:
                continue
            scored: List[Tuple[float, int, int, bytes]] = []
            # the switch can land at any not-yet-confirmed frame: frame
            # frontier + d (d >= 1) means the value was held run + d - 1
            # frames in total before switching; `surv` carries
            # prod(1 - h(t)) for t in [run, run + d - 1) and must
            # accumulate across EVERY d, including offsets outside the
            # beam window — survival through them still discounts later
            # candidates
            surv = 1.0
            for d in range(1, rollout + 1):
                h = st.hazard(run + d - 1)
                offset = frontier + d - anchor_frame
                if offset < 0 or offset >= rollout:
                    surv *= 1.0 - h
                    continue
                if h > 0.0 and surv > 0.0:
                    w = h * surv
                    for v, pv in succ:
                        scored.append((w * pv, p, offset, v))
                surv *= 1.0 - h
            if scored:
                scored.sort(key=lambda t: (-t[0], t[2]))
                per_player.append(scored[: self.MAX_SPECS_PER_PLAYER])
        # players ordered by their best score; then take one spec per
        # player per round so every predicted switch keeps coverage
        per_player.sort(key=lambda specs: -specs[0][0])
        out: List[Tuple[int, int, np.ndarray]] = []
        rank = 0
        while len(out) < limit and any(rank < len(s) for s in per_player):
            for specs in per_player:
                if rank < len(specs) and len(out) < limit:
                    _w, p, offset, v = specs[rank]
                    row = np.frombuffer(v, dtype=np.uint8).copy()
                    out.append((p, offset, row))
            rank += 1
        return out

    # per-player cap on successor values sampled by draft_script draws
    DRAFT_SUCC_LIMIT = 8
    # a width-1 draft only deviates from repeat-last when the learned
    # transition is CONFIDENT: the verify pass ANDs every cell of a row
    # (one wrong player kills the frame), so betting a cell on a value
    # the model gives < ~half its mass is negative-EV — with the floor,
    # unpredictable streams degrade to exactly the repeat-last floor
    # (which is what serves no-rollback recoveries), while streams with
    # a dominant successor keep the switch bets that serve rollbacks
    MIN_SWITCH_CONF = 0.45

    def draft_script(
        self,
        base_rows: np.ndarray,
        pinned: np.ndarray,
        *,
        anchor_frame: int,
        seed: int,
        init_values: np.ndarray,
        init_holds: np.ndarray,
    ) -> np.ndarray:
        """Fill the unpinned cells of `base_rows` (u8[D, P, I], row j =
        the input fed at frame anchor_frame + j) with hold/switch draws
        from the learned statistics — the WIDTH-1 drafted script the
        serving host's speculative bubble-filling rolls out for an
        input-starved session.

        `pinned` (bool[D, P]) marks ground-truth cells (played local
        inputs and confirmed remote inputs): they are left verbatim and
        RE-ANCHOR the per-player hold run. Every other cell draws like
        env/opponents.InputModelOpponent: at each frame the player
        switches with probability hazard(current hold length) — a
        counter-based splitmix64 uniform of (seed, absolute frame,
        player) decides, never a stateful RNG stream (the DET-lint
        determinism contract), so re-drafting the same anchor with the
        same statistics reproduces a byte-identical script — and a
        switching player samples its next value from the learned
        transition distribution (a second counter uniform). Players with
        no learned signal hold forever: exactly the reference's
        repeat-last prediction floor, which is also what maximizes the
        verify pass's prefix hits on streams of held values.

        `init_values` (u8[P, I]) / `init_holds` (int[P]) are each
        player's value and run length entering row 0 (derived from the
        played history before the anchor). The per-frame switch and
        successor uniforms are drawn VECTORIZED across the player axis
        (two unit_uniform calls per frame); the sequential frame
        loop is irreducible — each draw's hazard depends on the hold run
        the previous draw produced. Fills in place and returns
        base_rows."""
        # runtime import: ggrs_tpu.env's package init pulls the env
        # workload; the draw helper is all this module needs from it
        from ..env.opponents import unit_uniform

        D, P, I = base_rows.shape
        assert pinned.shape == (D, P)
        ids = np.arange(P)
        cur = np.array(init_values, dtype=np.uint8, copy=True)
        hold = np.array(init_holds, dtype=np.int64, copy=True)
        for j in range(D):
            frame = anchor_frame + j
            u = unit_uniform(seed, frame, ids)
            u2 = unit_uniform(seed ^ 0x5EED, frame, ids)
            for p in range(P):
                if pinned[j, p]:
                    v = base_rows[j, p]
                    if np.array_equal(v, cur[p]):
                        hold[p] += 1
                    else:
                        cur[p] = v
                        hold[p] = 1
                    continue
                st = self._stats[p]
                if st.n_holds():
                    if u[p] < st.hazard(int(hold[p])):
                        succ = [
                            sv
                            for sv in st.next_values(
                                cur[p].tobytes(),
                                limit=self.DRAFT_SUCC_LIMIT,
                            )
                            if sv[1] >= self.MIN_SWITCH_CONF
                        ]
                        if succ:
                            probs = np.array(
                                [w for _, w in succ], dtype=np.float64
                            )
                            cum = np.cumsum(probs / probs.sum())
                            k = int(
                                np.searchsorted(cum, u2[p], side="right")
                            )
                            k = min(k, len(succ) - 1)
                            cur[p] = np.frombuffer(
                                succ[k][0], dtype=np.uint8
                            )
                            hold[p] = 0
                hold[p] += 1
                base_rows[j, p] = cur[p]
        return base_rows
