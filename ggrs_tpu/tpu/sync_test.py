"""Fully-fused SyncTest: the determinism harness as a device-resident loop.

The host SyncTestSession + TpuRollbackBackend pair already fuses each tick
into one dispatch, but still returns to Python every frame and resolves
checksums. This session goes further: T ticks per dispatch via `lax.scan`,
with the snapshot ring, the input history, the checksum history and the
mismatch verdict all living on device. Only (a) the input batch goes down
and (b) a single mismatch flag comes back per batch.

Semantics mirror src/sessions/sync_test_session.rs:85-146: each tick, once
past `check_distance`, load the snapshot `check_distance` frames back,
resimulate forward (re-saving each frame), then save + advance the new
frame. The checksum history records the FIRST checksum seen for a frame and
every later re-save is compared against it (equivalent to the reference's
compare-then-rollback ordering); the first disagreement latches a mismatch
flag + frame. Input delay follows the reference's clamp-at-zero behavior
(input_queue.rs:313-326: frame f plays the input submitted at f-delay,
frames < delay play input 0).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import MismatchedChecksum
from ..types import InputStatus


def _pick_backend(game, check_distance: int, mesh) -> str:
    """Resolve backend="auto": the fastest kernel this configuration
    supports, by construction-time-checkable criteria only (adapter
    registered, 128-aligned entities, VMEM envelope, tileability, shard
    divisibility). Non-TPU platforms always get the XLA scan — the pallas
    kernels compile for TPU hardware (tests opt into interpret mode
    explicitly)."""
    if jax.devices()[0].platform != "tpu":
        return "xla"
    from .pallas_core import PallasSyncTestCore, get_adapter

    try:
        # adapter CONSTRUCTION can reject a config outright (no adapter
        # registered: KeyError; a model-envelope assert like arena's
        # centroid division bound: AssertionError/ValueError) — any such
        # rejection means "auto" answers "xla", never a construction-time
        # crash. Narrow on purpose: an adapter whose construction raises
        # anything else is BROKEN (e.g. a typo'd third-party registration)
        # and must surface, not silently demote to the XLA path.
        adapter = get_adapter(game)
    except (KeyError, AssertionError, ValueError):
        return "xla"
    if game.num_entities % 128 != 0:
        return "xla"
    if mesh is None:
        vmem_est = PallasSyncTestCore.vmem_estimate(
            game, check_distance, adapter
        )
        if vmem_est <= PallasSyncTestCore.VMEM_BUDGET_BYTES:
            return "pallas"
        if getattr(adapter, "tileable", False):
            return "pallas-tiled"
        return "xla"
    # sharded: tileable adapters run the shard_map'd tiled kernel;
    # reduction-phase adapters (arena) run it too via per-tick reduce
    # injection (ShardedPallasTiledCore.reduce_mode)
    if (
        getattr(adapter, "tileable", False)
        or getattr(adapter, "reduce_len", 0) > 0
    ) and game.num_entities % (mesh.shape["entity"] * 128) == 0:
        return "pallas-tiled"
    return "xla"


class TpuSyncTestSession:
    def __init__(
        self,
        game,
        num_players: int,
        check_distance: int,
        input_delay: int = 0,
        flush_interval: Optional[int] = None,
        mesh=None,
        backend: str = "auto",
        _defer_carry: bool = False,
    ):
        """`mesh`: optional jax Mesh with an `entity` axis — the world state
        and snapshot ring shard across it (BASELINE.json configs[4]); GSPMD
        partitions the fused scan, and the checksum reduction becomes the
        only cross-shard collective.

        `flush_interval`: None (the default) defers the determinism verdict
        entirely to explicit `check()` calls — the mismatch latch is
        device-resident and durable (the first divergence stays latched
        with its frame), so nothing is lost by checking late, and the
        out-of-box configuration pays ZERO per-batch host readbacks (on a
        tunneled device each costs ~100ms — the exact overhead the fused
        design exists to avoid). BEHAVIOR CHANGE (r3): earlier releases
        defaulted to flushing every tick, so advance_frames() itself
        raised on divergence — a driver that never calls check() now
        silently ignores mismatches; call check() at least once at the
        end of a run (every in-repo driver does). Pass an integer to
        auto-check every that many ticks instead (a periodic safety net
        for long unattended runs).

        `backend`: "auto" (the default) resolves to the fastest kernel the
        configuration supports — on TPU, the whole-batch pallas kernel
        inside its VMEM envelope, the entity-tiled kernel for larger
        tileable worlds (sharded or not), the XLA scan otherwise (and
        always on non-TPU platforms) — so the out-of-box session runs at
        the tuned-bench backend, not the fallback. Explicit choices:
        "xla" (lax.scan; works everywhere; the mesh-sharded scan),
        "pallas" (whole batch as one TPU kernel, every carry resident in
        VMEM — see ggrs_tpu.tpu.pallas_core; bit-identical carries, much
        faster on small worlds where per-op overhead dominates; capped by
        the VMEM envelope), or "pallas-tiled" (grid over entity tiles with
        the time loop inside per-tile VMEM — any world size, for models
        whose step is per-entity independent; ggrs_tpu.tpu.pallas_tiled).
        The "-interpret" suffixed variants run the same kernels in
        interpreter mode (CPU tests)."""
        assert check_distance >= 1
        assert backend in (
            "auto", "xla", "pallas", "pallas-interpret",
            "pallas-tiled", "pallas-tiled-interpret",
        )
        if backend == "auto":
            backend = _pick_backend(game, check_distance, mesh)
        self.backend = backend
        assert (
            backend == "xla"
            or backend.startswith("pallas-tiled")
            or mesh is None
        ), "the whole-batch pallas kernel is unsharded"
        self.game = game
        self.num_players = num_players
        self.check_distance = check_distance
        self.input_delay = input_delay
        self.flush_interval = (
            None if flush_interval is None else max(1, flush_interval)
        )
        self.mesh = mesh

        d = check_distance
        self.ring_len = d + 2
        self.hist_len = d + 2

        if _defer_carry:
            # restore() installs a checkpointed carry right after
            # construction: building the initial one (a full init_state
            # plus ring_len world-sized zero buffers) would be a
            # multi-hundred-MB transient at large-world scale
            self.carry = None
        else:
            self._build_initial_carry()
        self._core = None  # kernel core owning host-side program selection
        if backend == "xla":
            self._batch_fn = jax.jit(self._batch_impl, donate_argnums=(0,))
        elif backend.startswith("pallas-tiled"):
            if mesh is not None:
                from .pallas_tiled import ShardedPallasTiledCore

                core = ShardedPallasTiledCore(
                    game,
                    num_players,
                    check_distance,
                    mesh,
                    interpret=backend.endswith("-interpret"),
                )
            else:
                from .pallas_tiled import PallasTiledSyncTestCore

                core = PallasTiledSyncTestCore(
                    game,
                    num_players,
                    check_distance,
                    interpret=backend.endswith("-interpret"),
                )
            # self-jitting cores (the sharded reduce-injection path)
            # manage their own boot/steady programs — a host-tracked
            # static that an outer jit would bake at first trace
            self._batch_fn = (
                core.batch
                if getattr(core, "self_jitting", False)
                else jax.jit(core.batch, donate_argnums=(0,))
            )
            self._core = core
        else:
            from .pallas_core import PallasSyncTestCore

            core = PallasSyncTestCore(
                game,
                num_players,
                check_distance,
                interpret=backend == "pallas-interpret",
            )
            self._batch_fn = jax.jit(core.batch, donate_argnums=(0,))
        self._raw_inputs: list = []  # host-side delay shift buffer
        self._ticks_since_flush = 0
        self.current_frame = 0

    def _build_initial_carry(self) -> None:
        game, mesh = self.game, self.mesh
        num_players, d = self.num_players, self.check_distance
        state = game.init_state()
        if mesh is not None:
            from ..parallel.sharded import shard_ring, shard_state

            state = shard_state(state, mesh)
            zeros = lambda extra: shard_ring(
                jax.tree.map(
                    lambda x: jnp.zeros((extra,) + x.shape, x.dtype), state
                ),
                mesh,
            )
        else:
            zeros = lambda extra: jax.tree.map(
                lambda x: jnp.zeros((extra,) + x.shape, x.dtype), state
            )
        self.carry = {
            "state": state,
            "ring": zeros(self.ring_len),
            "input_ring": jnp.zeros(
                (d + 2, num_players, game.input_size), dtype=jnp.uint8
            ),
            "h_tag": jnp.full((self.hist_len,), -1, dtype=jnp.int32),
            "h_hi": jnp.zeros((self.hist_len,), dtype=jnp.uint32),
            "h_lo": jnp.zeros((self.hist_len,), dtype=jnp.uint32),
            "mismatch": jnp.zeros((), dtype=jnp.bool_),
            "mismatch_frame": jnp.full((), -1, dtype=jnp.int32),
            "frame": jnp.zeros((), dtype=jnp.int32),
        }

    # ------------------------------------------------------------------

    def _save_and_check(self, carry, state, frame):
        """Write `state` (of frame `frame`) into the ring; record or compare
        its checksum in the first-seen history."""
        hi, lo = self.game.checksum(state)
        slot = frame % self.ring_len
        carry = dict(carry)
        carry["ring"] = jax.tree.map(
            lambda r, s: jax.lax.dynamic_update_index_in_dim(r, s, slot, 0),
            carry["ring"],
            state,
        )
        h = frame % self.hist_len
        seen = carry["h_tag"][h] == frame
        differs = seen & ((carry["h_hi"][h] != hi) | (carry["h_lo"][h] != lo))
        first = differs & ~carry["mismatch"]
        carry["mismatch"] = carry["mismatch"] | differs
        carry["mismatch_frame"] = jnp.where(
            first, frame, carry["mismatch_frame"]
        )
        carry["h_tag"] = carry["h_tag"].at[h].set(frame)
        carry["h_hi"] = jnp.where(seen, carry["h_hi"], carry["h_hi"].at[h].set(hi))
        carry["h_lo"] = jnp.where(seen, carry["h_lo"], carry["h_lo"].at[h].set(lo))
        return carry

    def _tick(self, carry, new_inputs):
        d = self.check_distance
        statuses = jnp.full((self.num_players,), int(InputStatus.CONFIRMED), jnp.int32)
        c = carry["frame"]

        # --- forced rollback once past check_distance
        do_rollback = c > d
        base = jnp.maximum(c - d, 0)
        loaded = jax.tree.map(
            lambda r: jax.lax.dynamic_index_in_dim(r, base % self.ring_len, 0, False),
            carry["ring"],
        )
        state = jax.tree.map(
            lambda a, b: jnp.where(do_rollback, a, b), loaded, carry["state"]
        )
        for i in range(d):
            f = base + i
            if i > 0:
                rolled = self._save_and_check(carry, state, f)
                carry = jax.tree.map(
                    lambda a, b: jnp.where(do_rollback, a, b), rolled, carry
                )
            inp = jax.lax.dynamic_index_in_dim(
                carry["input_ring"], f % (d + 2), 0, False
            )
            nxt = self.game.step(state, inp, statuses)
            state = jax.tree.map(
                lambda a, b: jnp.where(do_rollback, a, b), nxt, state
            )

        # --- save current frame, record input, advance
        carry = self._save_and_check(carry, state, c)
        carry["input_ring"] = jax.lax.dynamic_update_index_in_dim(
            carry["input_ring"], new_inputs, c % (d + 2), 0
        )
        carry["state"] = self.game.step(state, new_inputs, statuses)
        carry["frame"] = c + 1
        return carry

    def _batch_impl(self, carry, inputs):
        def body(carry, inp):
            return self._tick(carry, inp), None

        carry, _ = jax.lax.scan(body, carry, inputs)
        return carry

    # ------------------------------------------------------------------

    def advance_frames(self, raw_inputs: np.ndarray) -> None:
        """Advance T frames in ONE device dispatch.

        raw_inputs: u8[T, P, input_size] — the inputs submitted at each tick;
        input delay shifts which frame actually plays them.
        """
        t = raw_inputs.shape[0]
        start = self.current_frame
        if self.input_delay:
            # frame f plays the input submitted at f-delay; the first `delay`
            # frames play the blank input (queue-head replication of the
            # pristine slot, input_queue.rs:207-239). The raw history is tiny
            # (bytes/frame), keep it whole.
            self._raw_inputs.extend(np.asarray(raw_inputs, dtype=np.uint8))
            blank = np.zeros_like(self._raw_inputs[0])
            eff = np.stack(
                [
                    self._raw_inputs[f - self.input_delay]
                    if f >= self.input_delay
                    else blank
                    for f in range(start, start + t)
                ]
            )
        else:
            eff = np.asarray(raw_inputs, dtype=np.uint8)
        if self._core is not None and getattr(self._core, "self_jitting", False):
            # the reduce-injection core picks its boot/steady program from
            # a HOST frame counter: a drift from the carry's frame (core
            # reused with a fresh carry, restored checkpoint without
            # reset()) would select the steady program for a boot-phase
            # carry and roll a reduction table whose base was never
            # pinned — wrong checksums, no error. Trip here instead.
            assert self._core.frames_seen == self.current_frame, (
                f"core program-selection counter ({self._core.frames_seen}) "
                f"out of sync with the session frame ({self.current_frame}); "
                "call core.reset(start_frame) when installing a new carry"
            )
        self.carry = self._batch_fn(self.carry, jnp.asarray(eff))
        self.current_frame += t
        self._ticks_since_flush += t
        if (
            self.flush_interval is not None
            and self._ticks_since_flush >= self.flush_interval
        ):
            self.check()

    def check(self) -> None:
        """Fetch the device verdict; raises MismatchedChecksum on divergence."""
        self._ticks_since_flush = 0
        if bool(self.carry["mismatch"]):
            raise MismatchedChecksum(int(self.carry["mismatch_frame"]))

    def state_numpy(self):
        return jax.device_get(self.carry["state"])

    def block_until_ready(self) -> None:
        jax.block_until_ready(self.carry["state"])

    # ------------------------------------------------------------------
    # durable checkpoint/resume (beyond the reference: its snapshots are
    # memory-only and nothing survives process death, SURVEY.md §5)
    # ------------------------------------------------------------------

    def save(self, path: str) -> None:
        from ..utils.checkpoint import save_device_checkpoint

        meta = {
            "kind": "TpuSyncTestSession",
            "num_players": self.num_players,
            "check_distance": self.check_distance,
            "input_delay": self.input_delay,
            "current_frame": self.current_frame,
            "raw_inputs": [r.tolist() for r in self._raw_inputs],
        }
        save_device_checkpoint(path, self.carry, meta)

    @classmethod
    def restore(cls, path: str, game, flush_interval: Optional[int] = None,
                backend: str = "auto") -> "TpuSyncTestSession":
        """Checkpoints are backend-agnostic (the carry pytree is identical
        across the XLA scan and both pallas kernels), so a run saved under
        one backend can resume under any other."""
        import jax as _jax

        from ..utils.checkpoint import load_device_checkpoint

        tree, meta = load_device_checkpoint(path)
        assert meta["kind"] == "TpuSyncTestSession"
        sess = cls(
            game,
            num_players=meta["num_players"],
            check_distance=meta["check_distance"],
            input_delay=meta["input_delay"],
            flush_interval=flush_interval,
            backend=backend,
            _defer_carry=True,  # the checkpoint replaces the initial carry
        )
        sess.carry = _jax.device_put(tree)
        sess.current_frame = meta["current_frame"]
        if sess._core is not None and hasattr(sess._core, "reset"):
            # re-arm host-side program selection to the restored carry's
            # frame (the reduce-injection core would otherwise boot-select
            # for a mid-run carry, or worse on later re-restores)
            sess._core.reset(meta["current_frame"])
        sess._raw_inputs = [np.asarray(r, dtype=np.uint8) for r in meta["raw_inputs"]]
        return sess
