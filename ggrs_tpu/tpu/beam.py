"""Speculative input beam: evaluate many candidate input futures in parallel.

The reference predicts ONE future per player — repeat the last input
(src/input_queue.rs:126-145) — and pays a full rollback when wrong. On TPU
the marginal cost of evaluating B candidate input sequences is ~zero (one
vmap axis), so we speculate over a beam: roll the same snapshot forward under
B different input scripts in one dispatch. When real inputs arrive, if any
beam member's script matches, its final state is already computed — the
rollback becomes a select instead of a resimulation (BASELINE.json
configs[2]: 16-way beam).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class BeamSpeculator:
    """vmap-batched W-frame rollout of B candidate input sequences."""

    def __init__(self, game, window: int, beam_width: int, num_players: int):
        self.game = game
        self.window = window
        self.beam_width = beam_width
        self.num_players = num_players

        def rollout_one(state, inputs, statuses):
            # inputs: u8[W, P, I]; statuses: i32[W, P]
            def body(s, xs):
                inp, stat = xs
                s = game.step(s, inp, stat)
                return s, None

            final, _ = jax.lax.scan(body, state, (inputs, statuses))
            hi, lo = game.checksum(final)
            return final, hi, lo

        # one snapshot, B input futures
        self._rollout = jax.jit(
            jax.vmap(rollout_one, in_axes=(None, 0, 0))
        )

    def rollout(self, state, beam_inputs: np.ndarray, beam_statuses: np.ndarray):
        """beam_inputs: u8[B, W, P, I]; returns (states[B], hi[B], lo[B])."""
        assert beam_inputs.shape[0] == self.beam_width
        return self._rollout(state, jnp.asarray(beam_inputs), jnp.asarray(beam_statuses))

    def select(self, beam_states, index: int):
        """Commit one beam member as the new live state."""
        return jax.tree.map(lambda x: x[index], beam_states)


def repeat_last_beam(
    last_inputs: np.ndarray,
    window: int,
    beam_width: int,
) -> np.ndarray:
    """Candidate generator: beam member 0 is the reference's repeat-last
    prediction; member b>0 XORs bit pattern ((b-1)//P + 1) into one player's
    input for the whole window — cheap, distinct, plausible futures for
    bitmask inputs.

    last_inputs: u8[P, I]. Returns u8[B, W, P, I].
    """
    p, _i = last_inputs.shape
    beam = np.tile(last_inputs, (beam_width, window, 1, 1))
    for b in range(1, beam_width):
        player = (b - 1) % p
        pattern = ((b - 1) // p + 1) & 0xFF
        beam[b, :, player, 0] ^= pattern
    return beam


def match_beam(
    beam_inputs: np.ndarray, actual_inputs: np.ndarray
) -> Optional[int]:
    """Find a beam member whose first `actual_inputs.shape[0]` frames match
    the now-confirmed inputs; None means full resimulation is needed.

    actual_inputs: u8[K, P, I] with K <= window.
    """
    k = actual_inputs.shape[0]
    for b in range(beam_inputs.shape[0]):
        if np.array_equal(beam_inputs[b, :k], actual_inputs):
            return b
    return None
