"""Speculative input beam: evaluate many candidate input futures in parallel.

The reference predicts ONE future per player — repeat the last input
(src/input_queue.rs:126-145) — and pays a full rollback when wrong. On TPU
the marginal cost of evaluating B candidate input sequences is ~zero (one
vmap axis), so we speculate over a beam: roll the same snapshot forward under
B different input scripts in one dispatch. When real inputs arrive, if any
beam member's script matches, its final state is already computed — the
rollback becomes a select instead of a resimulation (BASELINE.json
configs[2]: 16-way beam).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class BeamSpeculator:
    """vmap-batched W-frame rollout of B candidate input sequences."""

    def __init__(self, game, window: int, beam_width: int, num_players: int):
        self.game = game
        self.window = window
        self.beam_width = beam_width
        self.num_players = num_players

        def rollout_one(state, inputs, statuses):
            # inputs: u8[W, P, I]; statuses: i32[W, P]
            def body(s, xs):
                inp, stat = xs
                s = game.step(s, inp, stat)
                return s, None

            final, _ = jax.lax.scan(body, state, (inputs, statuses))
            hi, lo = game.checksum(final)
            return final, hi, lo

        # one snapshot, B input futures
        self._rollout = jax.jit(
            jax.vmap(rollout_one, in_axes=(None, 0, 0))
        )

    def rollout(self, state, beam_inputs: np.ndarray, beam_statuses: np.ndarray):
        """beam_inputs: u8[B, W, P, I]; returns (states[B], hi[B], lo[B])."""
        assert beam_inputs.shape[0] == self.beam_width
        return self._rollout(state, jnp.asarray(beam_inputs), jnp.asarray(beam_statuses))

    def select(self, beam_states, index: int):
        """Commit one beam member as the new live state."""
        return jax.tree.map(lambda x: x[index], beam_states)


def repeat_last_beam(
    last_inputs: np.ndarray,
    window: int,
    beam_width: int,
) -> np.ndarray:
    """Candidate generator: beam member 0 is the reference's repeat-last
    prediction; member b>0 XORs bit pattern ((b-1)//P + 1) into one player's
    input for the whole window — cheap, distinct, plausible futures for
    bitmask inputs.

    last_inputs: u8[P, I]. Returns u8[B, W, P, I].
    """
    p, i = last_inputs.shape
    beam = np.tile(last_inputs, (beam_width, window, 1, 1))
    for b in range(1, beam_width):
        player = (b - 1) % p
        k = (b - 1) // p
        # cycle the perturbed byte across the full input width and keep the
        # XOR value in [1, 255] so no candidate ever collapses into member 0
        byte = (k // 255) % i
        pattern = k % 255 + 1
        beam[b, :, player, byte] ^= np.uint8(pattern)
    return beam


def branching_beam(
    last_inputs: np.ndarray,
    prev_inputs: np.ndarray,
    window: int,
    beam_width: int,
    max_offset: Optional[int] = None,
    base_rows: Optional[np.ndarray] = None,
    fixed: Optional[np.ndarray] = None,
    predictions: Optional[List[Tuple[int, int, np.ndarray]]] = None,
) -> np.ndarray:
    """Candidate generator for live sessions: per-frame branching scripts.

    Member 0 is the reference's repeat-last prediction
    (src/input_queue.rs:126-145) for every player. Real input rows are runs
    of held values; a rollback means someone switched mid-window, almost
    always between two recently-held values (press/release toggling). So
    each further member branches between the tracked `last` and
    previous-distinct (`prev`) rows at ONE offset, in four families per
    offset k, likeliest first:

      all-switch@k   every toggling player: last before k, prev from k
      all-back@k     every toggling player: prev before k, last from k
                     (the toggle landed just before the anchor, so replayed
                     frames start on the OLD value and return to last)
      one-switch@k   a single player switches last->prev at k, others repeat
      one-back@k     a single player switches prev->last at k, others repeat

    Offsets are covered breadth-first from 0 (the first unconfirmed frame,
    the most likely switch point) and capped at `max_offset` (pass the
    expected rollout depth: a branch at an offset the rollback never
    replays can only duplicate member 0's matched prefix). Players with no
    toggle history yet (prev == last) have no meaningful branch, so the
    remaining members fall back to single-pattern XOR perturbations (value
    diversity over timing diversity).

    KNOWN HISTORY IS PINNED. The speculation anchors `S` frames in the
    past, and the caller already knows what happened there: `base_rows`
    (u8[S, P, I]) carries the rows actually fed for frames anchor..anchor+S
    and `fixed` (bool[S, P]) marks the cells that are ground truth — the
    local players' own inputs and every confirmed remote input. Every
    member reproduces `base_rows` verbatim at fixed cells, and branch
    families only ever rewrite free cells (unconfirmed remote predictions,
    and everything at offsets >= S). Without this, candidates re-guess
    history the session already played: the tracked `last` for a LOCAL
    player includes its newest input, so every branch family stamps that
    value over prefix frames where the OLD value was played, the
    played-prefix compatibility check (match_beam_longest) rejects the
    member, and live adoption collapses to near zero on exactly the
    scripts the beam exists for (measured: 1 hit / 9 misses on a 2-player
    4-frame-hold toggle, every miss a prefix mismatch of this shape).

    Distinctness is enforced by construction: members that collapse to an
    already-emitted candidate (e.g. a switch at an offset whose cells are
    all fixed) are skipped, not kept as dead weight.

    MODEL-RANKED CANDIDATES COME FIRST. `predictions` is an ordered list of
    (player, offset, value_row) switch specs from the online input model
    (input_model.InputHistoryModel.rank_branches): "player p's next real
    input is value_row, first visible at beam row `offset`". When present,
    members are allocated to these likelihood-ranked specs BEFORE the
    uniform offset sweep — the first prediction member combines every
    player's top-ranked spec (the joint future: multiple players switching
    inside one rollback window needs one member carrying all the
    switches), then each spec lands in its own member in rank order. The
    caller caps the prediction share (TpuRollbackBackend passes at most
    ~2/3 of the branch members) so the uniform families and XOR
    perturbations always keep guaranteed coverage of novel values and
    unranked offsets; a cold model (predictions=None) degrades to
    exactly the pre-model generator.

    last_inputs/prev_inputs: u8[P, I]. Returns u8[B, W, P, I].
    """
    p, _i = last_inputs.shape
    S = 0 if base_rows is None else int(base_rows.shape[0])
    assert S <= window, (S, window)
    if fixed is None:
        fixed = np.zeros((S, p), dtype=bool)
    beam = np.tile(last_inputs, (beam_width, window, 1, 1))
    if S:
        beam[:, :S] = np.asarray(base_rows, dtype=np.uint8)[None]
    # [W, P] mask of cells a family may rewrite: everything at offsets
    # >= S, plus unconfirmed predictions inside the pinned prefix
    free_mask = np.ones((window, p), dtype=bool)
    if S:
        free_mask[:S] = ~np.asarray(fixed, dtype=bool)

    has_hist = [
        not np.array_equal(prev_inputs[pl], last_inputs[pl]) for pl in range(p)
    ]
    toggling = [pl for pl in range(p) if has_hist[pl]]
    if max_offset is None:
        max_offset = window
    max_offset = min(max_offset, window)

    # one candidate stream per player (offset branches for toggling
    # players, then endless XOR patterns; pure-XOR for the rest), plus the
    # correlated all-players stream — round-robined so no player's pool
    # can crowd out another's
    def player_stream(pl):
        if has_hist[pl]:
            for k in range(max_offset):
                yield ("one", k, False, pl)
                yield ("one", k, True, pl)
        # cycle over every input byte (arena's analog throttle byte gets
        # candidate diversity too) with XOR values in [1, 255] — a zero
        # value would emit a duplicate of member 0. ONE full cycle only:
        # yields past 255 * input_size are byte-identical repeats, and with
        # duplicates skipped (not padded) an endless stream would spin the
        # fill loop forever once beam_width exceeds the distinct pool.
        for k in range(255 * _i):
            yield ("xor", pl, (k // 255) % _i, k % 255 + 1)

    def all_stream():
        for k in range(max_offset):
            yield ("all", k, False)
            yield ("all", k, True)

    def prediction_stream():
        """Model-ranked switch specs, joint-first (see docstring)."""
        assert predictions
        top: dict = {}
        for pl, k, row in predictions:
            if pl not in top:
                top[pl] = (k, row)
        if len(top) >= 2:
            yield ("predjoint", tuple(sorted(top.items())))
        for pl, k, row in predictions:
            yield ("pred", pl, k, row)

    streams = [player_stream(pl) for pl in range(p)]
    if len(toggling) >= 2:
        streams.insert(0, all_stream())
    if predictions:
        # the model stream rides the round-robin WITH the generic
        # families, first slot each round (joint member leads) — it must
        # supplement coverage, not displace it: draining the ranked
        # specs exhaustively first measurably LOST adoptions on
        # staggered multi-player scripts (the smeared hazard of one
        # player's switch crowded out the uniform offset families that
        # were serving everyone else), while interleaving keeps both
        # coverage classes alive at every width
        streams.insert(0, prediction_stream())

    seen = {beam[0].tobytes()}
    b = 1
    iota = np.arange(window)

    def apply_switch(cand, pl, k, row):
        """Rows >= k take `row` for player pl (free cells only)."""
        rows = np.where((iota >= k)[:, None], row, beam[0][:, pl])
        m = free_mask[:, pl]
        cand[m, pl] = rows[m]

    exhausted = [False] * len(streams)
    # every stream is finite (offset families bounded by max_offset, XOR
    # bounded to one distinct cycle), so this terminates even when
    # beam_width exceeds the distinct candidate pool — the surplus members
    # simply stay copies of member 0, as before dedup existed
    while b < beam_width and not all(exhausted):
        for si, stream in enumerate(streams):
            if b >= beam_width:
                break
            spec = next(stream, None)
            if spec is None:
                exhausted[si] = True
                continue
            cand = beam[0].copy()
            if spec[0] == "predjoint":
                for pl, (k, row) in spec[1]:
                    apply_switch(cand, pl, k, row)
            elif spec[0] == "pred":
                _, pl, k, row = spec
                apply_switch(cand, pl, k, row)
            elif spec[0] == "xor":
                _, pl, byte, pattern = spec
                cand[free_mask[:, pl], pl, byte] ^= np.uint8(pattern)
            else:
                kind, k, back = spec[0], spec[1], spec[2]
                players = toggling if kind == "all" else [spec[3]]
                for pl in players:
                    before, after = (
                        (prev_inputs[pl], last_inputs[pl])
                        if back
                        else (last_inputs[pl], prev_inputs[pl])
                    )
                    rows = np.where((iota >= k)[:, None], after, before)
                    m = free_mask[:, pl]
                    cand[m, pl] = rows[m]
            key = cand.tobytes()
            if key in seen:
                continue
            seen.add(key)
            beam[b] = cand
            b += 1
    return beam


def match_beam(
    beam_inputs: np.ndarray, actual_inputs: np.ndarray
) -> Optional[int]:
    """Find a beam member whose first `actual_inputs.shape[0]` frames match
    the now-confirmed inputs; None means full resimulation is needed.

    actual_inputs: u8[K, P, I] with K <= window.
    """
    k = actual_inputs.shape[0]
    for b in range(beam_inputs.shape[0]):
        if np.array_equal(beam_inputs[b, :k], actual_inputs):
            return b
    return None


def match_beam_longest(
    beam_inputs: np.ndarray,
    prefix_inputs: np.ndarray,
    actual_inputs: np.ndarray,
) -> Tuple[int, Optional[int]]:
    """Shift-flexible, longest-prefix beam match: the speculation was
    anchored `S` frames before the rollback's load frame
    (S = prefix_inputs.shape[0]), so a member is considered only if its
    first S rows equal the inputs ACTUALLY PLAYED between anchor and load
    (its trajectory baked them in). Returns (matched, member) where
    `member` is the played-prefix-compatible member whose rows
    match the LONGEST leading run of the corrected script, and `matched` is
    that run's length (0, None when no member clears the played prefix or
    matches even the first corrected row). The TPU analog of the
    reference's per-player misprediction localization
    (src/input_queue.rs:167-204): one wrong byte costs the suffix, not the
    whole precomputed trajectory. Full matches win ties by construction
    (matched == actual_inputs.shape[0]).

    prefix_inputs: u8[S, P, I]; actual_inputs: u8[K, P, I].
    """
    s, k = prefix_inputs.shape[0], actual_inputs.shape[0]
    best_m, best_b = 0, None
    for b in range(beam_inputs.shape[0]):
        if not np.array_equal(beam_inputs[b, :s], prefix_inputs):
            continue
        kmax = min(k, beam_inputs.shape[1] - s)
        m = 0
        while m < kmax and np.array_equal(
            beam_inputs[b, s + m], actual_inputs[m]
        ):
            m += 1
        if m > best_m:
            best_m, best_b = m, b
            if m == k:
                break
    return best_m, best_b
