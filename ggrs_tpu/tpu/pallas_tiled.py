"""Entity-tiled pallas kernel: VMEM-resident fused SyncTest at ANY world
size.

The whole-batch kernel (pallas_core) holds the entire world + snapshot ring
in VMEM, which caps it at ~262k entities. Past that the XLA scan runs the
step as dozens of unfused elementwise passes over HBM (~2% of peak
bandwidth at 1M entities). This kernel tiles the ENTITY axis instead: a
1-D pallas grid where each grid step streams one entity tile's state +
ring into VMEM and runs the ENTIRE T-tick batch on it — per batch, every
state/ring byte crosses HBM exactly once in and once out, the ideal-fusion
bound.

What makes the time-inside-tile order legal: the model's step must be
per-entity independent (no cross-entity reductions) and its checksum a
per-entity weighted modular sum. Adapters declare `tileable = True`
(ex_game qualifies; arena's per-team centroids do not — it stays on the
whole-batch kernel or the XLA scan). Checksums are emitted as PARTIAL
per-tile sums accumulated across grid steps in an SMEM revisit buffer
(uint32 wraparound sums are order-invariant, so the total is bit-identical
to the unsharded checksum); the first-seen history compare — a few hundred
scalar ops — moves to a jnp post-pass over the per-save totals, carrying
the same h_tag/h_hi/h_lo/mismatch state as TpuSyncTestSession's carry, so
the tiled core is a drop-in `backend="pallas-tiled"`.

Save-event layout the post-pass decodes (mirroring TpuSyncTestSession._tick
for tick frame c = c0 + t):
  parts[t, j], j < d-1: rollback re-save of frame (c-d)+1+j  (valid iff c > d)
  parts[t, d-1]:        the save of the current frame c      (always valid)
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .pallas_core import (
    KernelCtx,
    choose_tile_rows,
    derive_checksum_weights,
    get_adapter,
    make_gi_owner,
    partial_checksum_planes,
    plane_groups,
    rebuild_from_planes,
)

LANE = 128


class PallasTiledSyncTestCore:
    """Drop-in batch executor for TpuSyncTestSession's carry, tiled over
    entities (unsharded; any world size that fits HBM)."""

    # per-tile VMEM budget for the streamed windows (state+ring in/out).
    # Mosaic DOUBLE-BUFFERS grid-step windows to overlap DMA with compute,
    # so the effective VMEM cost is ~2x this figure plus temporaries — 28MB
    # keeps the total under the 100MB scoped limit (verified on v5e at 1M
    # entities, check_distance 8)
    VMEM_TILE_BUDGET = 28 * 1024 * 1024

    def __init__(self, game, num_players: int, check_distance: int,
                 interpret: bool = False, tile_rows: int = 0,
                 local_entities: int = 0, external_reduce: bool = False):
        """`local_entities`: when nonzero, the kernel operates on that many
        entities (one shard's slice of the world) while checksum weights
        keep using the GLOBAL entity count — the sharded composition
        (ShardedPallasTiledCore) runs one such local kernel per mesh device
        and psums the partial checksums, which then match the unsharded
        total bit-for-bit.

        `external_reduce`: for reduction-phase adapters — the kernel takes
        a COMPLETE per-frame raw-reduction table `red_raw [d+1, R]` as an
        input instead of computing reductions inline (rows 0..d-1: the
        resim frames base..c-1; row d: the frontier frame c). With the
        reductions injected, the time-inside-tile order and entity
        sharding both become legal for reduce models (the injected values
        don't depend on tile/shard data); the caller owns producing them
        (ShardedPallasTiledCore: local partial sums + psum per tick).
        Single-tick batches only — reductions for tick t+1's frontier
        don't exist at tick t's launch."""
        self.n = local_entities or game.num_entities
        assert self.n % LANE == 0, "entity count must be 128-aligned"
        self.game = game
        self.adapter = get_adapter(game)
        tileable = getattr(self.adapter, "tileable", False)
        self.R = getattr(self.adapter, "reduce_len", 0)
        self.external_reduce = external_reduce
        if external_reduce:
            assert self.R > 0, "external_reduce needs a reduction adapter"
        whole_world = not tileable and not external_reduce
        if whole_world:
            # reduction-phase adapters computing reductions INLINE
            # (arena, unsharded): single whole-world tile only — see
            # PallasTickCore for the rationale
            assert self.R > 0, (
                f"{type(self.adapter).__name__} is neither tileable nor "
                "reduction-declaring; use the whole-batch kernel or XLA"
            )
            assert self.n == game.num_entities, (
                "reduction-phase adapters cannot run on a shard's slice "
                "(local sums would replace the global reduction); use "
                "external_reduce for the sharded composition"
            )
        self.num_players = num_players
        self.input_size = game.input_size
        self.d = check_distance
        self.ring_len = check_distance + 2
        self.hist_len = check_distance + 2
        self.n_rows = self.n // LANE
        self.interpret = interpret
        n_planes = len(self.adapter.planes)
        per_row = n_planes * (1 + self.ring_len) * LANE * 4 * 2
        if tile_rows <= 0:
            if whole_world:
                tile_rows = self.n_rows
            else:
                tile_rows = choose_tile_rows(
                    self.n_rows, per_row, self.VMEM_TILE_BUDGET
                )
        if whole_world:
            from .pallas_core import WHOLE_WORLD_TILE_BUDGET

            assert tile_rows == self.n_rows, (
                "reduction-phase adapters require a single whole-world tile"
            )
            assert interpret or per_row * self.n_rows <= WHOLE_WORLD_TILE_BUDGET, (
                f"world too large for the single-tile reduction path "
                f"(~{per_row * self.n_rows >> 20}MB); use the whole-batch "
                "kernel or XLA"
            )
        assert self.n_rows % tile_rows == 0, (
            f"tile_rows {tile_rows} must divide {self.n_rows}"
        )
        # Mosaic block constraint: second-to-last dim divisible by 8, or
        # equal to the full array dim
        assert tile_rows >= 8 or tile_rows == self.n_rows, (
            f"tile_rows {tile_rows} violates the 8-sublane block constraint"
        )
        self.tile_rows = tile_rows
        self.n_tiles = self.n_rows // tile_rows
        self._batch = functools.lru_cache(maxsize=4)(self._build)
        self._cs_entries, self._cs_frame_weight = derive_checksum_weights(
            game, self.adapter
        )

    # -- carry packing (same layout as the whole-batch core) -------------

    def pack(self, carry):
        rows = self.n_rows

        def comp(a, c):
            plane = a if c is None else a[..., c]
            return plane.reshape(plane.shape[: plane.ndim - 1] + (rows, LANE))

        s, r = carry["state"], carry["ring"]
        packed = {}
        for name, key, c in self.adapter.planes:
            packed[name] = comp(s[key], c)
            packed["r_" + name] = comp(r[key], c)
        packed["r_frame"] = r["frame"].astype(jnp.int32)
        packed["iring"] = carry["input_ring"].reshape(
            self.d + 2, self.num_players * self.input_size
        ).astype(jnp.int32)
        return packed

    def unpack(self, p, carry, verdict):
        n = self.n
        groups = plane_groups(self.adapter)
        state = rebuild_from_planes(groups, lambda nm: p[nm], (), n)
        state["frame"] = verdict["frame"]
        ring = rebuild_from_planes(
            groups, lambda nm: p["r_" + nm], (self.ring_len,), n
        )
        ring["frame"] = p["r_frame"]
        return {
            "state": state,
            "ring": ring,
            "input_ring": p["iring"].astype(jnp.uint8).reshape(
                self.d + 2, self.num_players, self.input_size
            ),
            "h_tag": verdict["h_tag"],
            "h_hi": verdict["h_hi"],
            "h_lo": verdict["h_lo"],
            "mismatch": verdict["mismatch"],
            "mismatch_frame": verdict["mismatch_frame"],
            "frame": verdict["frame"],
        }

    # -- kernel ----------------------------------------------------------

    def _build(self, t_ticks: int):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        d, ring_len = self.d, self.ring_len
        rows, tile_rows, P, I = self.n_rows, self.tile_rows, self.num_players, self.input_size
        adapter = self.adapter
        plane_names = [name for name, _, _ in adapter.planes]
        n_tiles = self.n_tiles
        R = self.R if self.external_reduce else 0
        if R:
            assert t_ticks == 1, (
                "external-reduce kernels are single-tick (tick t+1's "
                "frontier reduction doesn't exist at launch)"
            )

        vmem_names = plane_names + ["r_" + n_ for n_ in plane_names]

        def kernel(inputs_ref, c0_ref, iring0_ref, rframe0_ref, red_ref,
                   gi_ref, owner_ref, *refs):
            n_io = len(vmem_names)
            ins = dict(zip(vmem_names, refs[:n_io]))
            outs = dict(zip(vmem_names, refs[n_io : 2 * n_io]))
            parts_hi_ref = refs[2 * n_io]
            parts_lo_ref = refs[2 * n_io + 1]
            rframe_ref = refs[2 * n_io + 2]
            iring_out_ref = refs[2 * n_io + 3]
            iring_scratch = refs[2 * n_io + 4]

            first_tile = pl.program_id(0) == 0

            # local copy of the (tiny, tile-invariant) input ring; every
            # tile evolves it identically from the same batch inputs
            for a in range(d + 2):
                for b in range(P * I):
                    iring_scratch[a, b] = iring0_ref[a, b]
            # seed the revisit buffers from the carry on the first tile
            # (out blocks start uninitialized; later tiles read them after
            # tile 0 ran — the grid is sequential)
            for s in range(ring_len):
                rframe_ref[s] = jnp.where(
                    first_tile, rframe0_ref[s], rframe_ref[s]
                )

            ctx = KernelCtx(gi_ref[:], owner_ref[:])
            out = {n_: outs[n_] for n_ in vmem_names}
            # initialize output windows EXPLICITLY from the input refs:
            # relying on input_output_aliases to pre-fill gridded output
            # windows silently fails past ~1MB planes on real TPUs (one
            # plane reads as zeros — same Mosaic behavior the whole-batch
            # kernel documents for SMEM outs); an in-VMEM copy is cheap
            for n_ in vmem_names:
                out[n_][...] = ins[n_][...]

            def read_state():
                return {n_: out[n_][:] for n_ in plane_names}

            def ring_slot(name, slot):
                return out[name][pl.ds(slot, 1)][0]

            def partial_checksum(state):
                # PARTIAL sums over this tile's entities; global weights
                # ride in via the sliced gi plane. The frame term is folded
                # once by the _verdict post-pass (NOT here), so sharded
                # runs can psum the per-shard partials without multiply-
                # counting it — int32 wraparound adds commute, keeping the
                # total bit-identical to the unsharded checksum.
                return partial_checksum_planes(self._cs_entries, ctx.gi, state)

            def save_tile(state, frame, mask, t, j):
                """Masked ring write + partial-checksum emission into the
                cross-tile accumulator at event (t, j)."""
                hi, lo = partial_checksum(state)
                slot = frame % ring_len
                for name in plane_names:
                    old = ring_slot("r_" + name, slot)
                    out["r_" + name][pl.ds(slot, 1)] = jnp.where(
                        mask, state[name], old
                    )[None]
                old_f = rframe_ref[slot]
                rframe_ref[slot] = jnp.where(
                    first_tile & mask, frame, old_f
                )
                acc_hi = parts_hi_ref[t, j]
                acc_lo = parts_lo_ref[t, j]
                base_hi = jnp.where(first_tile, jnp.int32(0), acc_hi)
                base_lo = jnp.where(first_tile, jnp.int32(0), acc_lo)
                parts_hi_ref[t, j] = base_hi + jnp.where(mask, hi, 0)
                parts_lo_ref[t, j] = base_lo + jnp.where(mask, lo, 0)

            def red_for(row):
                """Finalized reduction values from the injected COMPLETE
                raw sums (row i: resim frame base+i; row d: the frontier).
                None for non-reduce / inline-reduce kernels — step then
                takes its default path."""
                if not R:
                    return None
                raw = [red_ref[row, j] for j in range(R)]
                return adapter.reduce_finalize(raw, ctx)

            def tick(t, _):
                c = c0_ref[0] + t
                do_rb = c > d
                base = jnp.maximum(c - d, 0)
                bslot = base % ring_len
                loaded = {
                    n_: ring_slot("r_" + n_, bslot) for n_ in plane_names
                }
                cur = read_state()
                state = {
                    n_: jnp.where(do_rb, loaded[n_], cur[n_])
                    for n_ in plane_names
                }

                for i in range(d):
                    f = base + i
                    if i > 0:
                        save_tile(state, f, do_rb, t, i - 1)
                    islot = f % (d + 2)
                    inps = [
                        [iring_scratch[islot, p * I + j] for j in range(I)]
                        for p in range(P)
                    ]
                    nxt = (
                        adapter.step(state, inps, ctx, red=red_for(i))
                        if R
                        else adapter.step(state, inps, ctx)
                    )
                    state = {
                        n_: jnp.where(do_rb, nxt[n_], state[n_])
                        for n_ in plane_names
                    }

                save_tile(state, c, jnp.bool_(True), t, d - 1)
                cslot = c % (d + 2)
                new_inps = [
                    [inputs_ref[t, p * I + j] for j in range(I)]
                    for p in range(P)
                ]
                for p in range(P):
                    for j in range(I):
                        iring_scratch[cslot, p * I + j] = new_inps[p][j]
                state = (
                    adapter.step(state, new_inps, ctx, red=red_for(d))
                    if R
                    else adapter.step(state, new_inps, ctx)
                )
                for n_ in plane_names:
                    out[n_][:] = state[n_]
                return 0

            jax.lax.fori_loop(0, t_ticks, tick, 0)

            # evolved input ring out (identical on every tile; revisit
            # buffer keeps the last write)
            for a in range(d + 2):
                for b in range(P * I):
                    iring_out_ref[a, b] = iring_scratch[a, b]

        def state_spec():
            return pl.BlockSpec(
                (tile_rows, LANE), lambda g: (g, 0), memory_space=pltpu.VMEM
            )

        def ring_spec():
            return pl.BlockSpec(
                (ring_len, tile_rows, LANE),
                lambda g: (0, g, 0),
                memory_space=pltpu.VMEM,
            )

        def run(packed, inputs_i32, c0, gi, owner, red_raw=None):
            assert not R or red_raw is not None, (
                "external_reduce kernel launched without its red_raw "
                "table — the caller owns producing the complete per-frame "
                "reduction sums (see ShardedPallasTiledCore)"
            )
            if red_raw is None:
                # dummy row so the operand list is shape-stable across
                # reduce and non-reduce kernels (never read when R == 0)
                red_raw = jnp.zeros((1, 1), jnp.int32)
            in_specs = (
                [
                    pl.BlockSpec(memory_space=pltpu.SMEM),  # inputs [T, P*I]
                    pl.BlockSpec(memory_space=pltpu.SMEM),  # c0 [1]
                    pl.BlockSpec(memory_space=pltpu.SMEM),  # iring0
                    pl.BlockSpec(memory_space=pltpu.SMEM),  # rframe0
                    pl.BlockSpec(memory_space=pltpu.SMEM),  # red_raw [d+1, R]
                    state_spec(),  # gi
                    state_spec(),  # owner
                ]
                + [state_spec() for _ in plane_names]
                + [ring_spec() for _ in plane_names]
            )
            out_specs = (
                [state_spec() for _ in plane_names]
                + [ring_spec() for _ in plane_names]
                + [
                    # cross-tile revisit accumulators: every grid step maps
                    # to the SAME block, so partial sums carry across tiles
                    pl.BlockSpec(
                        (t_ticks, d), lambda g: (0, 0), memory_space=pltpu.SMEM
                    ),
                    pl.BlockSpec(
                        (t_ticks, d), lambda g: (0, 0), memory_space=pltpu.SMEM
                    ),
                    pl.BlockSpec(
                        (ring_len,), lambda g: (0,), memory_space=pltpu.SMEM
                    ),
                    pl.BlockSpec(
                        (d + 2, P * I), lambda g: (0, 0), memory_space=pltpu.SMEM
                    ),
                ]
            )
            out_shapes = (
                [
                    jax.ShapeDtypeStruct((rows, LANE), jnp.int32)
                    for _ in plane_names
                ]
                + [
                    jax.ShapeDtypeStruct((ring_len, rows, LANE), jnp.int32)
                    for _ in plane_names
                ]
                + [
                    jax.ShapeDtypeStruct((t_ticks, d), jnp.int32),
                    jax.ShapeDtypeStruct((t_ticks, d), jnp.int32),
                    jax.ShapeDtypeStruct((ring_len,), jnp.int32),
                    jax.ShapeDtypeStruct((d + 2, P * I), jnp.int32),
                ]
            )
            n_p = len(plane_names)
            # alias state+ring ins (after the 7 leading operands) onto outs
            aliases = {7 + i: i for i in range(2 * n_p)}
            results = pl.pallas_call(
                kernel,
                grid=(n_tiles,),
                in_specs=in_specs,
                out_specs=out_specs,
                out_shape=out_shapes,
                input_output_aliases=aliases,
                scratch_shapes=[
                    pltpu.SMEM((d + 2, P * I), jnp.int32),
                ],
                compiler_params=(
                    None
                    if self.interpret
                    else pltpu.CompilerParams(
                        vmem_limit_bytes=100 * 1024 * 1024
                    )
                ),
                interpret=self.interpret,
            )(
                inputs_i32,
                c0,
                packed["iring"],
                packed["r_frame"],
                red_raw,
                gi,
                owner,
                *[packed[n_] for n_ in plane_names],
                *[packed["r_" + n_] for n_ in plane_names],
            )
            out = dict(zip(vmem_names, results[: 2 * n_p]))
            out["parts_hi"] = results[2 * n_p]
            out["parts_lo"] = results[2 * n_p + 1]
            out["r_frame_new"] = results[2 * n_p + 2]
            out["iring_new"] = results[2 * n_p + 3]
            return out

        return run

    # -- post-pass: first-seen history over the per-save totals ----------

    def _verdict(self, carry, parts_hi, parts_lo, c0, t_ticks):
        """jnp scan over the T*d save events (a few hundred scalars),
        carrying the session's h_tag/h_hi/h_lo/mismatch exactly like
        TpuSyncTestSession._save_and_check."""
        d, hist = self.d, self.hist_len
        t_idx = jnp.arange(t_ticks, dtype=jnp.int32)[:, None]
        j_idx = jnp.arange(d, dtype=jnp.int32)[None, :]
        c = c0 + t_idx
        frames = jnp.where(
            j_idx < d - 1, (c - d) + 1 + j_idx, c
        )  # event frame
        valid = (j_idx == d - 1) | (c > d)
        # fold the frame checksum term here, once per event — the kernel
        # emits pure entity partial sums so sharded runs can psum them
        flat_frames = frames.reshape(-1)
        ev_hi = parts_hi.reshape(-1) + flat_frames * self._cs_frame_weight
        ev_lo = parts_lo.reshape(-1) + flat_frames
        ev = (
            flat_frames,
            valid.reshape(-1),
            jax.lax.bitcast_convert_type(ev_hi, jnp.uint32),
            jax.lax.bitcast_convert_type(ev_lo, jnp.uint32),
        )

        def body(hc, e):
            frame, ok, hi, lo = e
            h = frame % hist
            seen = hc["h_tag"][h] == frame
            differs = ok & seen & ((hc["h_hi"][h] != hi) | (hc["h_lo"][h] != lo))
            first = differs & ~hc["mismatch"]
            return {
                "h_tag": hc["h_tag"].at[h].set(
                    jnp.where(ok, frame, hc["h_tag"][h])
                ),
                "h_hi": hc["h_hi"].at[h].set(
                    jnp.where(ok & ~seen, hi, hc["h_hi"][h])
                ),
                "h_lo": hc["h_lo"].at[h].set(
                    jnp.where(ok & ~seen, lo, hc["h_lo"][h])
                ),
                "mismatch": hc["mismatch"] | differs,
                "mismatch_frame": jnp.where(
                    first, frame, hc["mismatch_frame"]
                ),
            }, None

        hc = {
            "h_tag": carry["h_tag"],
            "h_hi": carry["h_hi"],
            "h_lo": carry["h_lo"],
            "mismatch": carry["mismatch"],
            "mismatch_frame": carry["mismatch_frame"],
        }
        hc, _ = jax.lax.scan(body, hc, ev)
        hc["frame"] = c0 + t_ticks
        return hc

    # -- public ----------------------------------------------------------

    def _planes_at(self, source, slot=None):
        rows = self.n_rows
        out = {}
        for name, key, comp in self.adapter.planes:
            arr = source[key]
            if slot is not None:
                arr = jax.lax.dynamic_index_in_dim(
                    arr, slot, 0, keepdims=False
                )
            plane = arr if comp is None else arr[..., comp]
            out[name] = plane.reshape(rows, LANE)
        return out

    def frontier_partial(self, carry, ctx):
        """Raw reduction partials of the LIVE state (the frontier frame)
        over this core's slice — the one genuinely new row per tick."""
        return jnp.stack(
            self.adapter.reduce_partial(self._planes_at(carry["state"]), ctx)
        )

    def reduce_sources(self, carry, ctx):
        """Per-frame raw-reduction partials over THIS core's (possibly
        local) slice, for one tick at carry["frame"]: rows 0..d-1 from the
        ring slots holding the resim frames base..c-1 (bit-identical to
        the resimulated states by determinism), row d from the live
        state. Early-session rows read zero-init slots — consumed only by
        masked-off resim steps. Sums only: sharded callers psum the
        stacked result before injecting it."""
        d, ring_len = self.d, self.ring_len
        c = carry["frame"]
        base = jnp.maximum(c - d, 0)
        raw = []
        for i in range(d):
            slot = (base + i) % ring_len
            raw.append(
                jnp.stack(
                    self.adapter.reduce_partial(
                        self._planes_at(carry["ring"], slot), ctx
                    )
                )
            )
        raw.append(self.frontier_partial(carry, ctx))
        return jnp.stack(raw)  # [d+1, R]

    def _build_reduce_table(self, S: int):
        """Entity-tiled pallas pre-pass: raw [S, R] reduction tables from S
        stacked plane sources in ONE sweep. Exists because the XLA
        equivalents are pathological at scale on this backend — measured
        at 512k entities / 16 teams: reduce_sources 294 ms and
        frontier_partial 24 ms as unfused masked sums, vs ~1-30 ms for
        the same math streamed through VMEM (the whole 512k 'injection
        boundary' of r4 was THIS, not ring restreaming)."""
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        plane_names = [name for name, _, _ in self.adapter.planes]
        R, tile_rows, rows = self.R, self.tile_rows, self.n_rows
        adapter = self.adapter

        def kernel(gi_ref, owner_ref, *refs):
            n_p = len(plane_names)
            srcs = dict(zip(plane_names, refs[:n_p]))
            out_ref = refs[n_p]
            first = pl.program_id(0) == 0
            ctx = KernelCtx(gi_ref[:], owner_ref[:])
            for s in range(S):
                planes = {n_: srcs[n_][s] for n_ in plane_names}
                vals = adapter.reduce_partial(planes, ctx)
                for j, v in enumerate(vals):
                    base = jnp.where(first, jnp.int32(0), out_ref[s, j])
                    out_ref[s, j] = base + v

        def state_spec():
            return pl.BlockSpec(
                (tile_rows, LANE), lambda g: (g, 0), memory_space=pltpu.VMEM
            )

        def src_spec():
            return pl.BlockSpec(
                (S, tile_rows, LANE),
                lambda g: (0, g, 0),
                memory_space=pltpu.VMEM,
            )

        def run(sources, gi, owner):
            return pl.pallas_call(
                kernel,
                grid=(self.n_tiles,),
                in_specs=[state_spec(), state_spec()]
                + [src_spec() for _ in plane_names],
                out_specs=[
                    pl.BlockSpec(
                        (S, R), lambda g: (0, 0), memory_space=pltpu.SMEM
                    )
                ],
                out_shape=[jax.ShapeDtypeStruct((S, R), jnp.int32)],
                compiler_params=(
                    None
                    if self.interpret
                    else pltpu.CompilerParams(
                        vmem_limit_bytes=100 * 1024 * 1024
                    )
                ),
                interpret=self.interpret,
            )(gi, owner, *[sources[n_] for n_ in plane_names])[0]

        return run

    def _reduce_runs(self, S: int):
        if not hasattr(self, "_reduce_cache"):
            self._reduce_cache = {}
        if S not in self._reduce_cache:
            self._reduce_cache[S] = self._build_reduce_table(S)
        return self._reduce_cache[S]

    def reduce_sources_kernel(self, carry, gi_offset=0):
        """Kernelized reduce_sources: same [d+1, R] raw table,
        bit-identical (int32 wraparound sums are order-invariant), at
        streaming cost instead of the XLA masked-sum pathology."""
        d, ring_len = self.d, self.ring_len
        c = carry["frame"]
        base = jnp.maximum(c - d, 0)
        sources = {}
        for name, key, comp in self.adapter.planes:
            parts = []
            for i in range(d):
                slot = (base + i) % ring_len
                arr = jax.lax.dynamic_index_in_dim(
                    carry["ring"][key], slot, 0, keepdims=False
                )
                plane = arr if comp is None else arr[..., comp]
                parts.append(plane.reshape(self.n_rows, LANE))
            sp = carry["state"][key]
            plane = sp if comp is None else sp[..., comp]
            parts.append(plane.reshape(self.n_rows, LANE))
            sources[name] = jnp.stack(parts)
        gi, owner = make_gi_owner(self.n_rows, self.num_players, gi_offset)
        return self._reduce_runs(d + 1)(sources, gi, owner)

    def frontier_partial_kernel(self, carry, gi_offset=0):
        """Kernelized frontier_partial: the live state's raw [R] row."""
        sources = {}
        for name, key, comp in self.adapter.planes:
            sp = carry["state"][key]
            plane = sp if comp is None else sp[..., comp]
            sources[name] = plane.reshape(1, self.n_rows, LANE)
        gi, owner = make_gi_owner(self.n_rows, self.num_players, gi_offset)
        return self._reduce_runs(1)(sources, gi, owner)[0]

    def run_kernel(self, carry, inputs, gi_offset=0, red_raw=None):
        """pack -> kernel -> raw outputs (parts NOT yet verdict-folded).
        `gi_offset` shifts the global entity-index plane to this kernel's
        slice of the world; owner derives from it so round-robin ownership
        follows GLOBAL entity ids regardless of sharding. `red_raw`: the
        COMPLETE per-frame reduction table for external_reduce kernels."""
        t = inputs.shape[0]
        run = self._batch(t)
        packed = self.pack(carry)
        inputs_i32 = inputs.reshape(
            t, self.num_players * self.input_size
        ).astype(jnp.int32)
        c0 = carry["frame"].reshape(1).astype(jnp.int32)
        gi, owner = make_gi_owner(self.n_rows, self.num_players, gi_offset)
        out = run(packed, inputs_i32, c0, gi, owner, red_raw)
        out["r_frame"] = out["r_frame_new"]
        out["iring"] = out["iring_new"]
        return out

    def batch(self, carry: Dict[str, Any], inputs) -> Dict[str, Any]:
        t = inputs.shape[0]
        out = self.run_kernel(carry, inputs)
        verdict = self._verdict(
            carry, out["parts_hi"], out["parts_lo"], carry["frame"], t
        )
        return self.unpack(out, carry, verdict)


class ShardedPallasTiledCore:
    """The entity-tiled kernel composed with a device mesh: shard_map over
    the `entity` axis runs one local tiled kernel per device on its slice
    of the world + ring, then psums the per-shard partial checksums (int32
    wraparound sums are order-invariant, so the totals are bit-identical
    to the unsharded kernel's) and runs the first-seen verdict post-pass on
    the replicated totals. Drop-in for TpuSyncTestSession's carry with
    `mesh=` — the multi-chip execution of the SyncTest loop
    (src/sessions/sync_test_session.rs:85-146) at the tiled kernel's
    bandwidth instead of the XLA scan's."""

    def __init__(self, game, num_players: int, check_distance: int,
                 mesh, interpret: bool = False):
        from ..parallel.sharded import entity_shardable

        self.mesh = mesh
        n_shards = mesh.shape.get("entity", 0)
        assert entity_shardable(game.num_entities, mesh, LANE), (
            f"num_entities {game.num_entities} must split into "
            f"{n_shards} 128-aligned shards over the mesh's `entity` axis"
        )
        self.local_n = game.num_entities // n_shards
        adapter = get_adapter(game)
        # reduction-phase adapters (arena) CAN shard — via reduce
        # injection: per tick, every reduction the SyncTest resim needs is
        # computable at launch (resim frames' states sit in the snapshot
        # ring bit-identically; the frontier is the live state), so each
        # tick psums the per-shard raw partials and hands the COMPLETE
        # table to a local external_reduce kernel. Single-tick kernel
        # calls in a scan replace the T-tick batch (the only extra
        # collective is the [d+1, R] psum per tick).
        self.reduce_mode = not getattr(adapter, "tileable", False)
        if self.reduce_mode:
            assert getattr(adapter, "reduce_len", 0) > 0, (
                f"{type(adapter).__name__} is neither tileable nor "
                "reduction-declaring; use the XLA backend"
            )
        self.inner = PallasTiledSyncTestCore(
            game, num_players, check_distance, interpret=interpret,
            local_entities=self.local_n, external_reduce=self.reduce_mode,
        )
        self.game = game
        # reduce-injection cores manage their own jitted programs: the
        # boot-phase table rebuild rides a lax.cond whose both branches
        # execute under SPMD, so steady-state batches compile a SEPARATE
        # program without the cond — selected by a host-tracked frame
        # count an outer jit could never see (sync_test honors
        # self_jitting by not wrapping batch)
        self.self_jitting = self.reduce_mode
        # host-side frame counter DRIVING PROGRAM SELECTION for the
        # self-jitting reduce path: once it passes d, batch() dispatches
        # the steady-state (cond-free) program, whose rolling reduction
        # table assumes every frame in the batch is >= d. It therefore
        # MUST track the carry's frame: reusing this core with a fresh or
        # restored carry without reset() would select the wrong program
        # and emit wrong checksums with no error (the owning session
        # asserts the two counters agree before every dispatch).
        self._frames_seen = 0
        self._programs: Dict[Any, Any] = {}

    def reset(self, start_frame: int = 0) -> None:
        """Re-arm program selection for a fresh or restored carry whose
        frame is `start_frame`: compiled programs survive (they are keyed
        on (batch length, boot?) and carry no frame state), only the
        host-side frame counter rewinds. Call whenever a new carry is
        installed into a reused core — a fresh carry under a stale
        steady-state selection would roll a reduction table whose base the
        boot phase never pinned, silently corrupting checksums."""
        assert start_frame >= 0
        self._frames_seen = start_frame

    @property
    def frames_seen(self) -> int:
        """Frames this core has dispatched (or was reset() to): the owning
        session cross-checks it against its own frame counter so a
        core/carry mismatch trips an assertion instead of selecting the
        wrong program."""
        return self._frames_seen

    def _carry_specs(self, carry):
        from jax.sharding import PartitionSpec as P

        from ..parallel.sharded import ring_specs, state_specs

        return {
            "state": state_specs(carry["state"]),
            "ring": ring_specs(carry["ring"]),
            "input_ring": P(),
            "h_tag": P(),
            "h_hi": P(),
            "h_lo": P(),
            "mismatch": P(),
            "mismatch_frame": P(),
            "frame": P(),
        }

    def batch(self, carry: Dict[str, Any], inputs) -> Dict[str, Any]:
        if self.self_jitting:
            t = int(inputs.shape[0])
            boot = self._frames_seen < self.inner.d
            key = (t, boot)
            if key not in self._programs:
                self._programs[key] = jax.jit(
                    functools.partial(self._batch_program, boot=boot),
                    donate_argnums=(0,),
                )
            self._frames_seen += t
            return self._programs[key](carry, inputs)
        return self._batch_program(carry, inputs, boot=True)

    def _batch_program(self, carry: Dict[str, Any], inputs,
                       boot: bool = True) -> Dict[str, Any]:
        from jax.sharding import PartitionSpec as P

        from .pallas_core import KernelCtx

        inner = self.inner
        t = inputs.shape[0]
        specs = self._carry_specs(carry)

        def body(carry, inputs):
            idx = jax.lax.axis_index("entity")
            offset = idx.astype(jnp.int32) * jnp.int32(self.local_n)
            if not self.reduce_mode:
                out = inner.run_kernel(carry, inputs, offset)
                # the ONLY cross-shard collective in the hot loop:
                # wraparound partial-checksum sums ride ICI; everything
                # else is local
                out["parts_hi"] = jax.lax.psum(out["parts_hi"], "entity")
                out["parts_lo"] = jax.lax.psum(out["parts_lo"], "entity")
                verdict = inner._verdict(
                    carry, out["parts_hi"], out["parts_lo"], carry["frame"],
                    t,
                )
                return inner.unpack(out, carry, verdict)

            # reduce injection: one kernel call per tick, with the
            # per-frame reduction table carried ROLLING through the scan —
            # in steady state (c >= d) this tick's rows 1..d become the
            # next tick's rows 0..d-1 verbatim (same frames, same complete
            # sums), so each tick pays ONE new frontier row + one [R] psum
            # instead of recomputing and psumming all d+1 rows; before the
            # window fills (base pinned at 0, no row shift) the table is
            # rebuilt in full. The boundary tick is exercised by the
            # parity tests (40 frames, d=4). The table math runs through
            # the kernelized pre-passes (reduce_sources_kernel /
            # frontier_partial_kernel) — the XLA masked-sum equivalents
            # cost 294 ms / 24 ms at 512k entities on this backend. The
            # boot-phase rebuild rides a lax.cond whose BOTH branches
            # execute under SPMD (collectives must run uniformly), so the
            # steady-state program (self._booted, host-tracked) drops the
            # cond entirely: once every frame in a batch is >= d, only
            # the frontier row is ever new.
            d = inner.d

            def roll(new_carry, red_raw):
                return jnp.concatenate(
                    [
                        red_raw[1:],
                        jax.lax.psum(
                            inner.frontier_partial_kernel(new_carry, offset),
                            "entity",
                        )[None],
                    ]
                )

            def tick(carry_red, inp_row):
                carry, red_raw = carry_red
                out = inner.run_kernel(
                    carry, inp_row[None], offset, red_raw=red_raw
                )
                out["parts_hi"] = jax.lax.psum(out["parts_hi"], "entity")
                out["parts_lo"] = jax.lax.psum(out["parts_lo"], "entity")
                verdict = inner._verdict(
                    carry, out["parts_hi"], out["parts_lo"], carry["frame"],
                    1,
                )
                new_carry = inner.unpack(out, carry, verdict)
                if boot:
                    next_red = jax.lax.cond(
                        carry["frame"] >= d,  # next base = base+1: rows shift
                        lambda nc: roll(nc, red_raw),
                        lambda nc: jax.lax.psum(
                            inner.reduce_sources_kernel(nc, offset), "entity"
                        ),
                        new_carry,
                    )
                else:
                    next_red = roll(new_carry, red_raw)
                return (new_carry, next_red), None

            red0 = jax.lax.psum(
                inner.reduce_sources_kernel(carry, offset), "entity"
            )
            (carry, _red), _ = jax.lax.scan(tick, (carry, red0), inputs)
            return carry

        from ..parallel.sharded import shard_map as _shard_map

        shard_fn = _shard_map(
            body,
            mesh=self.mesh,
            in_specs=(specs, P()),
            out_specs=specs,
            # pallas outputs defeat replication inference; the replicated
            # outs (iring, verdict carry) are computed identically on every
            # shard from replicated inputs (+psum'd totals)
            check_vma=False,
        )
        return shard_fn(carry, inputs)
