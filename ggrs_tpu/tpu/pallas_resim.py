"""Entity-tiled pallas kernel for ResimCore's generic tick program.

The request path (P2P rollbacks, plain ticks, the lazy multi-tick buffer)
runs ResimCore's control-word-driven tick: optional ring load, then W
masked (save?, advance?) micro-slots. Under XLA that is dozens of unfused
elementwise passes per step — cheap at 4k entities, several ms at 65k+.
This kernel runs T packed tick rows per dispatch tiled over entities:
each grid step streams one tile's state + snapshot ring into VMEM and
executes every row's window loop on it, with the SAME packed control-word
layout ResimCore.pack_tick_row builds (rows ride in SMEM), in-kernel
per-player disconnect-input substitution, and cross-tile partial
checksums. Scalar lanes (state/ring frame fields, the device-verify
history, the returned per-slot checksums with their frame terms) are a
tiny jnp post-pass — a few hundred scalar ops mirroring _tick_impl.

Correctness contract: bit-identical ring/state/checksum outputs to
ResimCore._tick_impl for session-driven control words (the session
invariant start_frame == frame of the first window slot holds by
construction; _verify_update relies on the same invariant). Tileable
adapters only; the XLA scan remains the fallback.

Mesh composition: ShardedPallasTickCore shard_maps one LOCAL kernel per
device over the `entity` axis (exactly the ShardedPallasTiledCore
recipe) and psums the per-shard partial checksums — the flagship
"partitioned world inside a live P2P session" config
(src/sessions/p2p_session.rs:621-673 scaled multi-chip) then runs at the
tiled kernel's bandwidth instead of the XLA scan's.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..types import InputStatus
from .pallas_core import (
    KernelCtx,
    choose_tile_rows,
    derive_checksum_weights,
    get_adapter,
    make_gi_owner,
    partial_checksum_planes,
    plane_groups,
    rebuild_from_planes,
)

LANE = 128


class PallasTickCore:
    """Executor for ResimCore's packed tick rows on the entity-tiled
    kernel. One instance per ResimCore; T (rows per dispatch) is the
    compile key (1 for per-tick dispatch, lazy_ticks for the buffer)."""

    VMEM_TILE_BUDGET = 28 * 1024 * 1024

    def __init__(self, core, interpret: bool = False, tile_rows: int = 0,
                 local_entities: int = 0):
        """`local_entities`: when nonzero, the kernel operates on that many
        entities (one shard's slice of the world) while checksum weights
        keep using the GLOBAL entity count — ShardedPallasTickCore runs one
        such local kernel per mesh device and psums the partial checksums,
        which then match the unsharded totals bit-for-bit (the same
        composition ShardedPallasTiledCore uses for the SyncTest batch)."""
        game = core.game
        self.n = local_entities or game.num_entities
        assert self.n % LANE == 0
        self.core = core
        self.game = game
        self.adapter = get_adapter(game)
        tileable = getattr(self.adapter, "tileable", False)
        whole_world = not tileable
        if whole_world:
            # reduction-phase adapters (arena): legal ONLY with whole-world
            # visibility — the kernel runs a single tile so the adapter's
            # inline full-plane reductions are complete. P2P resim states
            # are fresh (corrected inputs), so no per-frame cache applies;
            # a shard's slice would make the sums silently local => wrong.
            assert getattr(self.adapter, "reduce_len", 0) > 0, (
                f"{type(self.adapter).__name__} is neither tileable nor "
                "reduction-declaring; use the XLA backend"
            )
            assert self.n == game.num_entities, (
                "reduction-phase adapters cannot run on a shard's slice "
                "(local sums would replace the global reduction)"
            )
        self.whole_world = whole_world
        self.num_players = core.num_players
        self.input_size = game.input_size
        self.W = core.window
        self.ring_len = core.ring_len
        self.n_rows = self.n // LANE
        self.interpret = interpret
        # the disconnect-substitution row (the reference's dummy input,
        # ex_game.rs:268): games declare it; substitution is per player,
        # exactly the where(status==DISCONNECTED, ...) the model step does
        disc = getattr(game, "disconnect_input", None)
        assert disc is not None and len(disc) == self.input_size, (
            f"{type(game).__name__} must declare disconnect_input "
            "(bytes, input_size long) for the pallas tick path"
        )
        self.disconnect_input = np.frombuffer(
            bytes(disc), dtype=np.uint8
        ).astype(np.int32)
        n_planes = len(self.adapter.planes)
        per_row = n_planes * (1 + self.ring_len + 1) * LANE * 4 * 2
        if tile_rows <= 0:
            if whole_world:
                tile_rows = self.n_rows  # single tile: full-plane sums legal
            else:
                tile_rows = choose_tile_rows(
                    self.n_rows, per_row, self.VMEM_TILE_BUDGET
                )
        if whole_world:
            from .pallas_core import WHOLE_WORLD_TILE_BUDGET

            assert tile_rows == self.n_rows, (
                "reduction-phase adapters require a single whole-world tile"
            )
            assert interpret or per_row * self.n_rows <= WHOLE_WORLD_TILE_BUDGET, (
                f"world too large for the single-tile reduction path "
                f"(~{per_row * self.n_rows >> 20}MB of plane windows); use "
                "the XLA backend"
            )
        assert self.n_rows % tile_rows == 0
        assert tile_rows >= 8 or tile_rows == self.n_rows
        self.tile_rows = tile_rows
        self.n_tiles = self.n_rows // tile_rows
        self._run = functools.lru_cache(maxsize=4)(self._build)
        self._cs_entries, self._cs_frame_weight = derive_checksum_weights(
            game, self.adapter
        )

    @classmethod
    def whole_world_fits(cls, game, ring_len) -> bool:
        """Can a reduction-phase (non-tileable) adapter's world run as ONE
        VMEM tile? THE sizing rule the constructor enforces, exposed for
        ResimCore's backend auto-selection."""
        from .pallas_core import WHOLE_WORLD_TILE_BUDGET

        n_planes = len(get_adapter(game).planes)
        per_row = n_planes * (1 + ring_len + 1) * LANE * 4 * 2
        return per_row * (game.num_entities // LANE) <= WHOLE_WORLD_TILE_BUDGET

    # -- packing (ring has ring_len+1 slots; the scratch slot is never
    # -- read or written by a masked save, but it rides along so the
    # -- pytree shape matches ResimCore's exactly) -----------------------

    def pack(self, ring, state):
        rows = self.n_rows
        packed = {}
        for name, key, c in self.adapter.planes:
            s = state[key] if c is None else state[key][..., c]
            r = ring[key] if c is None else ring[key][..., c]
            packed[name] = s.reshape(rows, LANE)
            packed["r_" + name] = r.reshape(r.shape[0], rows, LANE)
        return packed

    def unpack(self, outs, ring, state):
        n = self.n
        groups = plane_groups(self.adapter)
        new_state = rebuild_from_planes(
            groups, lambda nm: outs[nm], (), n
        )
        new_ring = rebuild_from_planes(
            groups, lambda nm: outs["r_" + nm], (self.ring_len + 1,), n
        )
        return new_ring, new_state

    # -- kernel ----------------------------------------------------------

    def _build(self, T: int):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        W, P, I = self.W, self.num_players, self.input_size
        ring_len, rows, tile_rows = self.ring_len, self.n_rows, self.tile_rows
        adapter = self.adapter
        plane_names = [name for name, _, _ in adapter.planes]
        core = self.core
        off_save, off_status, off_input = (
            core._off_save, core._off_status, core._off_input,
        )
        disc = [int(v) for v in self.disconnect_input]
        disconnected = int(InputStatus.DISCONNECTED)

        def kernel(rows_ref, gi_ref, owner_ref, *refs):
            n_p = len(plane_names)
            state_out = dict(zip(plane_names, refs[2 * n_p : 3 * n_p]))
            ring_out = dict(
                zip(plane_names, refs[3 * n_p : 4 * n_p])
            )
            parts_hi_ref = refs[4 * n_p]
            parts_lo_ref = refs[4 * n_p + 1]

            first_tile = pl.program_id(0) == 0
            ctx = KernelCtx(gi_ref[:], owner_ref[:])

            # initialize output windows explicitly from the inputs (the
            # same Mosaic aliasing caveat pallas_tiled documents)
            ins_state = dict(zip(plane_names, refs[:n_p]))
            ins_ring = dict(zip(plane_names, refs[n_p : 2 * n_p]))
            for n_ in plane_names:
                state_out[n_][...] = ins_state[n_][...]
                ring_out[n_][...] = ins_ring[n_][...]

            def ring_slot(name, slot):
                return ring_out[name][pl.ds(slot, 1)][0]

            def tick(t, _):
                do_load = rows_ref[t, 0] != 0
                load_slot = rows_ref[t, 1]
                advance_count = rows_ref[t, 2]
                cur = {n_: state_out[n_][:] for n_ in plane_names}
                loaded = {
                    n_: ring_slot(n_, load_slot) for n_ in plane_names
                }
                state = {
                    n_: jnp.where(do_load, loaded[n_], cur[n_])
                    for n_ in plane_names
                }
                for i in range(W):
                    save_slot = rows_ref[t, off_save + i]
                    do_save = save_slot < ring_len
                    hi, lo = partial_checksum_planes(
                        self._cs_entries, ctx.gi, state
                    )
                    base_hi = jnp.where(
                        first_tile, jnp.int32(0), parts_hi_ref[t, i]
                    )
                    base_lo = jnp.where(
                        first_tile, jnp.int32(0), parts_lo_ref[t, i]
                    )
                    parts_hi_ref[t, i] = base_hi + jnp.where(do_save, hi, 0)
                    parts_lo_ref[t, i] = base_lo + jnp.where(do_save, lo, 0)
                    # masked ring write: scratch-or-beyond slots clamp to 0
                    # with the mask off, leaving slot 0 unchanged
                    wslot = jnp.where(do_save, save_slot, 0)
                    for n_ in plane_names:
                        old = ring_slot(n_, wslot)
                        ring_out[n_][pl.ds(wslot, 1)] = jnp.where(
                            do_save, state[n_], old
                        )[None]
                    # masked step with in-kernel disconnect substitution
                    inps = []
                    for p in range(P):
                        status = rows_ref[t, off_status + i * P + p]
                        row_bytes = []
                        for j in range(I):
                            b = rows_ref[t, off_input + (i * P + p) * I + j]
                            row_bytes.append(
                                jnp.where(
                                    status == disconnected, disc[j], b
                                )
                            )
                        inps.append(row_bytes)
                    nxt = adapter.step(state, inps, ctx)
                    do_adv = i < advance_count
                    state = {
                        n_: jnp.where(do_adv, nxt[n_], state[n_])
                        for n_ in plane_names
                    }
                for n_ in plane_names:
                    state_out[n_][:] = state[n_]
                return 0

            jax.lax.fori_loop(0, T, tick, 0)

        def state_spec():
            return pl.BlockSpec(
                (tile_rows, LANE), lambda g: (g, 0), memory_space=pltpu.VMEM
            )

        def ring_spec():
            return pl.BlockSpec(
                (ring_len + 1, tile_rows, LANE),
                lambda g: (0, g, 0),
                memory_space=pltpu.VMEM,
            )

        def run(packed, rows_i32, gi, owner):
            n_p = len(plane_names)
            in_specs = (
                [
                    pl.BlockSpec(memory_space=pltpu.SMEM),  # rows [T, L]
                    state_spec(),  # gi
                    state_spec(),  # owner
                ]
                + [state_spec() for _ in plane_names]
                + [ring_spec() for _ in plane_names]
            )
            out_specs = (
                [state_spec() for _ in plane_names]
                + [ring_spec() for _ in plane_names]
                + [
                    pl.BlockSpec(
                        (T, W), lambda g: (0, 0), memory_space=pltpu.SMEM
                    ),
                    pl.BlockSpec(
                        (T, W), lambda g: (0, 0), memory_space=pltpu.SMEM
                    ),
                ]
            )
            out_shapes = (
                [
                    jax.ShapeDtypeStruct((rows, LANE), jnp.int32)
                    for _ in plane_names
                ]
                + [
                    jax.ShapeDtypeStruct(
                        (ring_len + 1, rows, LANE), jnp.int32
                    )
                    for _ in plane_names
                ]
                + [
                    jax.ShapeDtypeStruct((T, W), jnp.int32),
                    jax.ShapeDtypeStruct((T, W), jnp.int32),
                ]
            )
            aliases = {3 + i: i for i in range(2 * n_p)}
            results = pl.pallas_call(
                kernel,
                grid=(self.n_tiles,),
                in_specs=in_specs,
                out_specs=out_specs,
                out_shape=out_shapes,
                input_output_aliases=aliases,
                compiler_params=(
                    None
                    if self.interpret
                    else pltpu.CompilerParams(
                        vmem_limit_bytes=100 * 1024 * 1024
                    )
                ),
                interpret=self.interpret,
            )(
                rows_i32,
                gi,
                owner,
                *[packed[n_] for n_ in plane_names],
                *[packed["r_" + n_] for n_ in plane_names],
            )
            outs = dict(zip(plane_names, results[: n_p]))
            outs.update(
                zip(["r_" + n_ for n_ in plane_names], results[n_p : 2 * n_p])
            )
            return outs, results[-2], results[-1]

        return run

    # -- scalar post-pass: frame fields, verify carry, returned checksums

    def _scalar_pass(self, ring_frame, state_frame, verify, rows, parts_hi,
                     parts_lo):
        """jnp mirror of _tick_impl's scalar behavior over the T x W save
        events: ring/state frame updates, the device-verify first-seen
        history, and the per-slot (hi, lo) outputs with their frame terms
        (zeros for skipped saves, exactly like the XLA path)."""
        core = self.core
        W, ring_len = self.W, self.ring_len
        off_save = core._off_save

        def row_body(carry, xs):
            ring_frame, state_frame, verify = carry
            row, p_hi, p_lo = xs
            do_load = row[0] != 0
            load_slot = row[1]
            advance_count = row[2]
            start_frame = row[3]
            # the state's OWN frame drives saved checksums and ring frame
            # fields (exactly what the XLA path's game.checksum(state)
            # reads); the verify history keys on start_frame + i, exactly
            # like _tick_impl's _verify_update call. Sessions keep the two
            # identical by construction; matching both independently makes
            # the backends bit-equal even for hand-driven streams.
            state_frame = jnp.where(
                do_load, ring_frame[load_slot], state_frame
            )
            his = []
            los = []
            for i in range(W):
                save_slot = row[off_save + i]
                do_save = save_slot < ring_len
                # state frame entering slot i: advances stop at
                # advance_count, exactly like the state itself (a save
                # past the last advance checksums the frozen state)
                frame_i = state_frame + jnp.minimum(i, advance_count)
                hi = jax.lax.bitcast_convert_type(
                    p_hi[i] + frame_i * self._cs_frame_weight, jnp.uint32
                )
                lo = jax.lax.bitcast_convert_type(
                    p_lo[i] + frame_i, jnp.uint32
                )
                hi = jnp.where(do_save, hi, jnp.uint32(0))
                lo = jnp.where(do_save, lo, jnp.uint32(0))
                his.append(hi)
                los.append(lo)
                wslot = jnp.where(do_save, save_slot, 0)
                ring_frame = ring_frame.at[wslot].set(
                    jnp.where(do_save, frame_i, ring_frame[wslot])
                )
                if core.device_verify:
                    upd = core._verify_update(
                        verify, start_frame + i, hi, lo
                    )
                    verify = jax.tree.map(
                        lambda new, old: jnp.where(do_save, new, old),
                        upd,
                        verify,
                    )
            state_frame = state_frame + advance_count
            return (ring_frame, state_frame, verify), (
                jnp.stack(his), jnp.stack(los),
            )

        (ring_frame, state_frame, verify), (his, los) = jax.lax.scan(
            row_body,
            (ring_frame, state_frame, verify),
            (rows, parts_hi, parts_lo),
        )
        return ring_frame, state_frame, verify, his, los

    # -- public ----------------------------------------------------------

    def run_kernel(self, ring, state, rows, gi_offset=0):
        """pack -> kernel -> (plane outs, partial checksums). `gi_offset`
        shifts the global entity-index plane to this kernel's slice of the
        world (the sharded composition's seam); the scalar post-pass is NOT
        applied — sharded callers psum the partials first."""
        T = rows.shape[0]
        run = self._run(int(T))
        packed = self.pack(ring, state)
        gi, owner = make_gi_owner(self.n_rows, self.num_players, gi_offset)
        return run(packed, rows.astype(jnp.int32), gi, owner)

    def tick_multi(self, ring, state, rows, verify):
        """Run T packed tick rows; returns (ring, state, verify, his[T,W],
        los[T,W]) with the same semantics as ResimCore._tick_multi_impl."""
        outs, parts_hi, parts_lo = self.run_kernel(ring, state, rows)
        new_ring, new_state = self.unpack(outs, ring, state)
        ring_frame, state_frame, verify, his, los = self._scalar_pass(
            ring["frame"],
            state["frame"],
            verify,
            rows.astype(jnp.int32),
            parts_hi,
            parts_lo,
        )
        new_ring["frame"] = ring_frame
        new_state["frame"] = state_frame
        return new_ring, new_state, verify, his, los


class ShardedPallasTickCore:
    """The entity-tiled tick kernel composed with a device mesh: shard_map
    over the `entity` axis runs one local kernel per device on its slice of
    the world + snapshot ring, psums the per-shard partial checksums (int32
    wraparound sums are order-invariant, so the totals are bit-identical to
    the unsharded kernel's), then runs the scalar post-pass on the
    replicated scalars. Drop-in for ResimCore's (ring, state, rows, verify)
    tick program under `mesh=` — the request path's multi-chip execution at
    the tiled kernel's bandwidth (completing for P2P/lazy ticks what
    ShardedPallasTiledCore did for the fused SyncTest batch)."""

    def __init__(self, core, mesh, interpret: bool = False):
        from ..parallel.sharded import entity_shardable

        self.mesh = mesh
        n_shards = mesh.shape.get("entity", 0)
        game = core.game
        assert getattr(get_adapter(game), "tileable", False), (
            "the sharded tick kernel needs a per-entity-independent "
            "(tileable) adapter: a reduction-phase adapter's full-plane "
            "sums would be silently local per shard; sharded reduce models "
            "run the XLA path (GSPMD inserts the psums)"
        )
        assert entity_shardable(game.num_entities, mesh, LANE), (
            f"num_entities {game.num_entities} must split into "
            f"{n_shards} 128-aligned shards over the mesh's `entity` axis"
        )
        self.local_n = game.num_entities // n_shards
        self.inner = PallasTickCore(
            core, interpret=interpret, local_entities=self.local_n
        )
        self.core = core

    def tick_multi(self, ring, state, rows, verify):
        from jax.sharding import PartitionSpec as P

        from ..parallel.sharded import ring_specs, state_specs

        inner = self.inner
        local_n = self.local_n
        s_specs = state_specs(state)
        r_specs = ring_specs(ring)
        verify_specs = jax.tree.map(lambda x: P(), verify)

        def body(ring, state, rows, verify):
            idx = jax.lax.axis_index("entity")
            offset = idx.astype(jnp.int32) * jnp.int32(local_n)
            outs, parts_hi, parts_lo = inner.run_kernel(
                ring, state, rows, offset
            )
            # the ONLY cross-shard collective in the hot loop: wraparound
            # partial-checksum sums ride ICI; everything else is local
            parts_hi = jax.lax.psum(parts_hi, "entity")
            parts_lo = jax.lax.psum(parts_lo, "entity")
            new_ring, new_state = inner.unpack(outs, ring, state)
            ring_frame, state_frame, verify, his, los = inner._scalar_pass(
                ring["frame"],
                state["frame"],
                verify,
                rows.astype(jnp.int32),
                parts_hi,
                parts_lo,
            )
            new_ring["frame"] = ring_frame
            new_state["frame"] = state_frame
            return new_ring, new_state, verify, his, los

        from ..parallel.sharded import shard_map as _shard_map

        shard_fn = _shard_map(
            body,
            mesh=self.mesh,
            in_specs=(r_specs, s_specs, P(), verify_specs),
            out_specs=(r_specs, s_specs, verify_specs, P(), P()),
            # pallas outputs defeat replication inference; the replicated
            # outs (scalar-pass results) are computed identically on every
            # shard from replicated inputs (+psum'd totals)
            check_vma=False,
        )
        return shard_fn(ring, state, rows, verify)
