"""Entity-tiled pallas kernel for the speculative beam rollout.

The beam's device cost is B x L full-world steps per tick. Under the XLA
vmap+scan path that work runs as dozens of unfused elementwise passes —
the same per-op overhead that makes the XLA SyncTest scan ~2% of HBM peak
— so speculation taxed ~15ms/tick on a 65k world (BENCH r3 exec phase),
swamping what adoption saves. This kernel runs the ENTIRE rollout as one
pallas program tiled over entities: each grid step streams one entity
tile's anchor state into VMEM and evaluates all B members x L steps on
it, writing the per-member per-frame trajectory planes and accumulating
per-(member, frame) partial checksums across tiles (SMEM revisit buffers,
exactly like pallas_tiled's save events). Legal for `tileable` adapters
(per-entity-independent step); the time/member-inside-tile order changes
nothing the model can observe.

Outputs are bit-identical to ResimCore._speculate_impl's XLA path — same
adapter math, same derived checksum weights, frame terms folded in the
post-pass — so adoption (which commits these trajectories into the ring)
is oblivious to which backend speculated.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .pallas_core import (
    KernelCtx,
    choose_tile_rows,
    derive_checksum_weights,
    get_adapter,
    make_gi_owner,
    partial_checksum_planes,
    plane_groups,
    rebuild_from_planes,
)

LANE = 128


class PallasBeamRollout:
    """Beam rollout executor for one (game, beam_width) pair; rollout
    length is a per-call compile key (the backend coalesces depths so only
    a handful of lengths ever compile)."""

    VMEM_TILE_BUDGET = 24 * 1024 * 1024

    def __init__(self, game, num_players: int, beam_width: int,
                 interpret: bool = False, tile_rows: int = 0,
                 max_rollout: int = 12):
        """`max_rollout`: the deepest rollout length the caller can
        request (ResimCore passes its window) — the VMEM tile budget is
        sized to it, so deep prediction windows get smaller tiles instead
        of silently oversubscribing the budget."""
        assert game.num_entities % LANE == 0, "entity count must be 128-aligned"
        self.game = game
        self.adapter = get_adapter(game)
        tileable = getattr(self.adapter, "tileable", False)
        whole_world = not tileable
        if whole_world:
            # reduction-phase adapters (arena): single whole-world tile
            # only — the rollout's inline full-plane reductions must see
            # every entity (ResimCore falls back to XLA when rejected here)
            assert getattr(self.adapter, "reduce_len", 0) > 0, (
                f"{type(self.adapter).__name__} is neither tileable nor "
                "reduction-declaring; the XLA vmap rollout handles this model"
            )
        self.num_players = num_players
        self.input_size = game.input_size
        self.B = beam_width
        self.n_rows = game.num_entities // LANE
        self.interpret = interpret
        n_planes = len(self.adapter.planes)
        # in: anchor planes; out: B*L trajectory windows per plane —
        # double-buffered by Mosaic
        per_row = n_planes * (1 + self.B * max_rollout) * LANE * 4 * 2
        if tile_rows <= 0:
            if whole_world:
                tile_rows = self.n_rows
            else:
                tile_rows = choose_tile_rows(
                    self.n_rows, per_row, self.VMEM_TILE_BUDGET
                )
        if whole_world:
            from .pallas_core import WHOLE_WORLD_TILE_BUDGET

            assert tile_rows == self.n_rows, (
                "reduction-phase adapters require a single whole-world tile"
            )
            assert interpret or per_row * self.n_rows <= WHOLE_WORLD_TILE_BUDGET, (
                f"B={self.B} x L={max_rollout} trajectory windows "
                f"(~{per_row * self.n_rows >> 20}MB) exceed the single-tile "
                "budget for a reduction-phase adapter"
            )
        assert self.n_rows % tile_rows == 0
        assert tile_rows >= 8 or tile_rows == self.n_rows
        self.tile_rows = tile_rows
        self.n_tiles = self.n_rows // tile_rows
        self._run = functools.lru_cache(maxsize=8)(self._build)
        self._cs_entries, self._cs_frame_weight = derive_checksum_weights(
            game, self.adapter
        )

    # -- packing ---------------------------------------------------------

    def pack_state(self, state) -> Dict[str, Any]:
        rows = self.n_rows
        packed = {}
        for name, key, c in self.adapter.planes:
            plane = state[key] if c is None else state[key][..., c]
            packed[name] = plane.reshape(rows, LANE)
        return packed

    def unpack_traj(self, outs, L: int, anchor_frame):
        """Trajectory planes [B*L, rows, LANE] -> state pytree with leaves
        [B, L, ...] (+ the scaffolding-managed frame leaf)."""
        n = self.game.num_entities
        traj = rebuild_from_planes(
            plane_groups(self.adapter), lambda nm: outs[nm], (self.B, L), n
        )
        steps = jnp.arange(L, dtype=jnp.int32)[None, :]
        traj["frame"] = jnp.broadcast_to(
            anchor_frame.astype(jnp.int32) + 1 + steps, (self.B, L)
        )
        return traj

    # -- kernel ----------------------------------------------------------

    def _build(self, L: int):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        B, rows, tile_rows = self.B, self.n_rows, self.tile_rows
        P, I = self.num_players, self.input_size
        adapter = self.adapter
        plane_names = [name for name, _, _ in adapter.planes]
        n_tiles = self.n_tiles

        def kernel(inputs_ref, gi_ref, owner_ref, *refs):
            n_p = len(plane_names)
            anchors = dict(zip(plane_names, refs[:n_p]))
            trajs = dict(zip(plane_names, refs[n_p : 2 * n_p]))
            parts_hi_ref = refs[2 * n_p]
            parts_lo_ref = refs[2 * n_p + 1]

            first_tile = pl.program_id(0) == 0
            ctx = KernelCtx(gi_ref[:], owner_ref[:])

            def partial_checksum(state):
                return partial_checksum_planes(self._cs_entries, ctx.gi, state)

            anchor = {n_: anchors[n_][:] for n_ in plane_names}
            for b in range(B):
                state = anchor
                for l in range(L):
                    inps = [
                        [inputs_ref[b, l, p * I + j] for j in range(I)]
                        for p in range(P)
                    ]
                    state = adapter.step(state, inps, ctx)
                    for n_ in plane_names:
                        trajs[n_][pl.ds(b * L + l, 1)] = state[n_][None]
                    hi, lo = partial_checksum(state)
                    base_hi = jnp.where(
                        first_tile, jnp.int32(0), parts_hi_ref[b, l]
                    )
                    base_lo = jnp.where(
                        first_tile, jnp.int32(0), parts_lo_ref[b, l]
                    )
                    parts_hi_ref[b, l] = base_hi + hi
                    parts_lo_ref[b, l] = base_lo + lo

        def state_spec():
            return pl.BlockSpec(
                (tile_rows, LANE), lambda g: (g, 0), memory_space=pltpu.VMEM
            )

        def traj_spec():
            return pl.BlockSpec(
                (B * L, tile_rows, LANE),
                lambda g: (0, g, 0),
                memory_space=pltpu.VMEM,
            )

        def run(packed, inputs_i32, gi, owner):
            in_specs = (
                [
                    pl.BlockSpec(memory_space=pltpu.SMEM),  # inputs [B,L,P*I]
                    state_spec(),  # gi
                    state_spec(),  # owner
                ]
                + [state_spec() for _ in plane_names]
            )
            out_specs = [traj_spec() for _ in plane_names] + [
                # cross-tile checksum accumulators (every grid step maps to
                # the same block, so partial sums carry across tiles)
                pl.BlockSpec(
                    (B, L), lambda g: (0, 0), memory_space=pltpu.SMEM
                ),
                pl.BlockSpec(
                    (B, L), lambda g: (0, 0), memory_space=pltpu.SMEM
                ),
            ]
            out_shapes = [
                jax.ShapeDtypeStruct((B * L, rows, LANE), jnp.int32)
                for _ in plane_names
            ] + [
                jax.ShapeDtypeStruct((B, L), jnp.int32),
                jax.ShapeDtypeStruct((B, L), jnp.int32),
            ]
            results = pl.pallas_call(
                kernel,
                grid=(n_tiles,),
                in_specs=in_specs,
                out_specs=out_specs,
                out_shape=out_shapes,
                compiler_params=(
                    None
                    if self.interpret
                    else pltpu.CompilerParams(
                        vmem_limit_bytes=100 * 1024 * 1024
                    )
                ),
                interpret=self.interpret,
            )(
                inputs_i32,
                gi,
                owner,
                *[packed[n_] for n_ in plane_names],
            )
            outs = dict(zip(plane_names, results[: len(plane_names)]))
            return outs, results[-2], results[-1]

        return run

    # -- public ----------------------------------------------------------

    def rollout(self, anchor_state, beam_inputs):
        """anchor_state: the game-state pytree at the anchor frame;
        beam_inputs: u8[B, L, P, I]. Returns (traj pytree [B, L, ...],
        his u32[B, L], los u32[B, L]) bit-identical to the XLA vmap+scan
        rollout under all-CONFIRMED statuses."""
        B, L = beam_inputs.shape[0], beam_inputs.shape[1]
        assert B == self.B
        run = self._run(int(L))
        packed = self.pack_state(anchor_state)
        inputs_i32 = beam_inputs.reshape(
            B, L, self.num_players * self.input_size
        ).astype(jnp.int32)
        gi, owner = make_gi_owner(self.n_rows, self.num_players)
        outs, parts_hi, parts_lo = run(packed, inputs_i32, gi, owner)
        # frame checksum term folded here, once per (member, step)
        steps = jnp.arange(L, dtype=jnp.int32)[None, :]
        frames = anchor_state["frame"].astype(jnp.int32) + 1 + steps
        his = jax.lax.bitcast_convert_type(
            parts_hi + frames * self._cs_frame_weight, jnp.uint32
        )
        los = jax.lax.bitcast_convert_type(parts_lo + frames, jnp.uint32)
        traj = self.unpack_traj(outs, int(L), anchor_state["frame"])
        return traj, his, los
