"""Entity-tiled pallas kernel for the speculative beam rollout.

The beam's device cost is B x L full-world steps per tick. Under the XLA
vmap+scan path that work runs as dozens of unfused elementwise passes —
the same per-op overhead that makes the XLA SyncTest scan ~2% of HBM peak
— so speculation taxed ~15ms/tick on a 65k world (BENCH r3 exec phase),
swamping what adoption saves. This kernel runs the ENTIRE rollout as one
pallas program tiled over entities: each grid step streams one entity
tile's anchor state into VMEM and evaluates all B members x L steps on
it, writing the per-member per-frame trajectory planes and accumulating
per-(member, frame) partial checksums across tiles (SMEM revisit buffers,
exactly like pallas_tiled's save events). Legal for `tileable` adapters
(per-entity-independent step); the time/member-inside-tile order changes
nothing the model can observe.

Outputs are bit-identical to ResimCore._speculate_impl's XLA path — same
adapter math, same derived checksum weights, frame terms folded in the
post-pass — so adoption (which commits these trajectories into the ring)
is oblivious to which backend speculated.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .pallas_core import (
    KernelCtx,
    choose_tile_rows,
    derive_checksum_weights,
    get_adapter,
    make_gi_owner,
    partial_checksum_planes,
    plane_groups,
    rebuild_from_planes,
)

LANE = 128


class PallasBeamRollout:
    """Beam rollout executor for one (game, beam_width) pair; rollout
    length is a per-call compile key (the backend coalesces depths so only
    a handful of lengths ever compile)."""

    VMEM_TILE_BUDGET = 24 * 1024 * 1024

    def __init__(self, game, num_players: int, beam_width: int,
                 interpret: bool = False, tile_rows: int = 0,
                 max_rollout: int = 12, local_entities: int = 0):
        """`max_rollout`: the deepest rollout length the caller can
        request (ResimCore passes its window) — the VMEM tile budget is
        sized to it, so deep prediction windows get smaller tiles instead
        of silently oversubscribing the budget.

        `local_entities`: when nonzero, the kernel operates on that many
        entities (one shard's slice of the world) while checksum weights
        keep using the GLOBAL entity count — ShardedPallasBeamRollout
        runs one such local kernel per mesh device and psums the partial
        checksums, the same composition ShardedPallasTickCore uses."""
        self.n = local_entities or game.num_entities
        assert self.n % LANE == 0, "entity count must be 128-aligned"
        self.game = game
        self.adapter = get_adapter(game)
        tileable = getattr(self.adapter, "tileable", False)
        whole_world = not tileable
        if whole_world:
            # reduction-phase adapters (arena): single whole-world tile
            # only — the rollout's inline full-plane reductions must see
            # every entity (ResimCore falls back to XLA when rejected here)
            assert getattr(self.adapter, "reduce_len", 0) > 0, (
                f"{type(self.adapter).__name__} is neither tileable nor "
                "reduction-declaring; the XLA vmap rollout handles this model"
            )
            assert self.n == game.num_entities, (
                "reduction-phase adapters cannot run on a shard's slice "
                "(local sums would replace the global reduction)"
            )
        self.num_players = num_players
        self.input_size = game.input_size
        self.B = beam_width
        self.n_rows = self.n // LANE
        self.interpret = interpret
        n_planes = len(self.adapter.planes)
        # in: anchor planes; out: B*L trajectory windows per plane —
        # double-buffered by Mosaic
        per_row = n_planes * (1 + self.B * max_rollout) * LANE * 4 * 2
        if tile_rows <= 0:
            if whole_world:
                tile_rows = self.n_rows
            else:
                tile_rows = choose_tile_rows(
                    self.n_rows, per_row, self.VMEM_TILE_BUDGET
                )
        if whole_world:
            from .pallas_core import WHOLE_WORLD_TILE_BUDGET

            assert tile_rows == self.n_rows, (
                "reduction-phase adapters require a single whole-world tile"
            )
            assert interpret or per_row * self.n_rows <= WHOLE_WORLD_TILE_BUDGET, (
                f"B={self.B} x L={max_rollout} trajectory windows "
                f"(~{per_row * self.n_rows >> 20}MB) exceed the single-tile "
                "budget for a reduction-phase adapter"
            )
        assert self.n_rows % tile_rows == 0
        assert tile_rows >= 8 or tile_rows == self.n_rows
        self.tile_rows = tile_rows
        self.n_tiles = self.n_rows // tile_rows
        self._run = functools.lru_cache(maxsize=8)(self._build)
        self._cs_entries, self._cs_frame_weight = derive_checksum_weights(
            game, self.adapter
        )

    # -- packing ---------------------------------------------------------

    def pack_state(self, state) -> Dict[str, Any]:
        rows = self.n_rows
        packed = {}
        for name, key, c in self.adapter.planes:
            plane = state[key] if c is None else state[key][..., c]
            packed[name] = plane.reshape(rows, LANE)
        return packed

    def unpack_traj(self, outs, L: int, anchor_frame):
        """Trajectory planes [B*L, rows, LANE] -> state pytree with leaves
        [B, L, ...] (+ the scaffolding-managed frame leaf)."""
        n = self.n
        traj = rebuild_from_planes(
            plane_groups(self.adapter), lambda nm: outs[nm], (self.B, L), n
        )
        steps = jnp.arange(L, dtype=jnp.int32)[None, :]
        traj["frame"] = jnp.broadcast_to(
            anchor_frame.astype(jnp.int32) + 1 + steps, (self.B, L)
        )
        return traj

    # -- kernel ----------------------------------------------------------

    def _build(self, L: int):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        B, rows, tile_rows = self.B, self.n_rows, self.tile_rows
        P, I = self.num_players, self.input_size
        adapter = self.adapter
        plane_names = [name for name, _, _ in adapter.planes]
        n_tiles = self.n_tiles

        def kernel(inputs_ref, gi_ref, owner_ref, *refs):
            n_p = len(plane_names)
            anchors = dict(zip(plane_names, refs[:n_p]))
            trajs = dict(zip(plane_names, refs[n_p : 2 * n_p]))
            parts_hi_ref = refs[2 * n_p]
            parts_lo_ref = refs[2 * n_p + 1]

            first_tile = pl.program_id(0) == 0
            ctx = KernelCtx(gi_ref[:], owner_ref[:])

            def partial_checksum(state):
                return partial_checksum_planes(self._cs_entries, ctx.gi, state)

            anchor = {n_: anchors[n_][:] for n_ in plane_names}
            for b in range(B):
                state = anchor
                for l in range(L):
                    inps = [
                        [inputs_ref[b, l, p * I + j] for j in range(I)]
                        for p in range(P)
                    ]
                    state = adapter.step(state, inps, ctx)
                    for n_ in plane_names:
                        trajs[n_][pl.ds(b * L + l, 1)] = state[n_][None]
                    hi, lo = partial_checksum(state)
                    base_hi = jnp.where(
                        first_tile, jnp.int32(0), parts_hi_ref[b, l]
                    )
                    base_lo = jnp.where(
                        first_tile, jnp.int32(0), parts_lo_ref[b, l]
                    )
                    parts_hi_ref[b, l] = base_hi + hi
                    parts_lo_ref[b, l] = base_lo + lo

        def state_spec():
            return pl.BlockSpec(
                (tile_rows, LANE), lambda g: (g, 0), memory_space=pltpu.VMEM
            )

        def traj_spec():
            return pl.BlockSpec(
                (B * L, tile_rows, LANE),
                lambda g: (0, g, 0),
                memory_space=pltpu.VMEM,
            )

        def run(packed, inputs_i32, gi, owner):
            in_specs = (
                [
                    pl.BlockSpec(memory_space=pltpu.SMEM),  # inputs [B,L,P*I]
                    state_spec(),  # gi
                    state_spec(),  # owner
                ]
                + [state_spec() for _ in plane_names]
            )
            out_specs = [traj_spec() for _ in plane_names] + [
                # cross-tile checksum accumulators (every grid step maps to
                # the same block, so partial sums carry across tiles)
                pl.BlockSpec(
                    (B, L), lambda g: (0, 0), memory_space=pltpu.SMEM
                ),
                pl.BlockSpec(
                    (B, L), lambda g: (0, 0), memory_space=pltpu.SMEM
                ),
            ]
            out_shapes = [
                jax.ShapeDtypeStruct((B * L, rows, LANE), jnp.int32)
                for _ in plane_names
            ] + [
                jax.ShapeDtypeStruct((B, L), jnp.int32),
                jax.ShapeDtypeStruct((B, L), jnp.int32),
            ]
            results = pl.pallas_call(
                kernel,
                grid=(n_tiles,),
                in_specs=in_specs,
                out_specs=out_specs,
                out_shape=out_shapes,
                compiler_params=(
                    None
                    if self.interpret
                    else pltpu.CompilerParams(
                        vmem_limit_bytes=100 * 1024 * 1024
                    )
                ),
                interpret=self.interpret,
            )(
                inputs_i32,
                gi,
                owner,
                *[packed[n_] for n_ in plane_names],
            )
            outs = dict(zip(plane_names, results[: len(plane_names)]))
            return outs, results[-2], results[-1]

        return run

    # -- public ----------------------------------------------------------

    def run_kernel(self, anchor_state, beam_inputs, gi_offset=0):
        """pack -> kernel -> (plane outs, partial checksums). `gi_offset`
        shifts the global entity-index plane to this kernel's slice of
        the world (the sharded composition's seam); the frame fold is NOT
        applied — sharded callers psum the partials first."""
        B, L = beam_inputs.shape[0], beam_inputs.shape[1]
        assert B == self.B
        run = self._run(int(L))
        packed = self.pack_state(anchor_state)
        inputs_i32 = beam_inputs.reshape(
            B, L, self.num_players * self.input_size
        ).astype(jnp.int32)
        gi, owner = make_gi_owner(self.n_rows, self.num_players, gi_offset)
        return run(packed, inputs_i32, gi, owner)

    def finish(self, outs, parts_hi, parts_lo, anchor_frame, L: int):
        """Fold the frame checksum terms (once per member x step, exactly
        like the XLA path's game.checksum of the stepped state) and
        rebuild the trajectory pytree. Sharded callers pass psum'd
        partials; the fold then matches the unsharded totals bit-for-bit."""
        steps = jnp.arange(L, dtype=jnp.int32)[None, :]
        frames = anchor_frame.astype(jnp.int32) + 1 + steps
        his = jax.lax.bitcast_convert_type(
            parts_hi + frames * self._cs_frame_weight, jnp.uint32
        )
        los = jax.lax.bitcast_convert_type(parts_lo + frames, jnp.uint32)
        traj = self.unpack_traj(outs, L, anchor_frame)
        return traj, his, los

    def rollout(self, anchor_state, beam_inputs):
        """anchor_state: the game-state pytree at the anchor frame;
        beam_inputs: u8[B, L, P, I]. Returns (traj pytree [B, L, ...],
        his u32[B, L], los u32[B, L]) bit-identical to the XLA vmap+scan
        rollout under all-CONFIRMED statuses."""
        outs, parts_hi, parts_lo = self.run_kernel(anchor_state, beam_inputs)
        return self.finish(
            outs, parts_hi, parts_lo, anchor_state["frame"],
            int(beam_inputs.shape[1]),
        )


class ShardedPallasBeamRollout:
    """The entity-tiled beam rollout composed with a device mesh: one
    LOCAL kernel per device over the `entity` axis (each device rolls out
    every beam member on its slice of the world — the beam axis needs no
    collective), per-(member, frame) partial checksums psum'd across
    shards (int32 wraparound sums are order-invariant, so the totals are
    bit-identical to the unsharded kernel's). Exactly the
    ShardedPallasTickCore recipe applied to speculation — the flagship
    sharded config then speculates at the fused kernel's cost instead of
    the unfused XLA vmap+scan's (the restriction VERDICT r4 flagged at
    resim.py:204-207). The adopted trajectory keeps its entity sharding,
    so the (XLA) adopt dispatch consumes it in place under GSPMD."""

    def __init__(self, game, num_players: int, beam_width: int, mesh,
                 interpret: bool = False, max_rollout: int = 12):
        from ..parallel.sharded import entity_shardable

        self.mesh = mesh
        n_shards = mesh.shape.get("entity", 0)
        assert getattr(get_adapter(game), "tileable", False), (
            "the sharded beam rollout needs a per-entity-independent "
            "(tileable) adapter: a reduction-phase adapter's full-plane "
            "sums would be silently local per shard; sharded reduce "
            "models speculate via the XLA path (GSPMD inserts the psums)"
        )
        assert entity_shardable(game.num_entities, mesh, LANE), (
            f"num_entities {game.num_entities} must split into "
            f"{n_shards} 128-aligned shards over the mesh's `entity` axis"
        )
        self.local_n = game.num_entities // n_shards
        self.inner = PallasBeamRollout(
            game, num_players, beam_width,
            interpret=interpret, max_rollout=max_rollout,
            local_entities=self.local_n,
        )
        self.game = game

    def rollout(self, anchor_state, beam_inputs):
        from jax.sharding import PartitionSpec as P

        from ..parallel.sharded import state_specs

        inner = self.inner
        local_n = self.local_n
        L = int(beam_inputs.shape[1])
        s_specs = state_specs(anchor_state)
        # trajectory leaves carry a leading [B, L] over each state leaf;
        # the frame leaf ([B, L], built from the replicated anchor frame)
        # is replicated
        t_specs = jax.tree.map(
            lambda x: P(None, None, "entity") if x.ndim >= 1 else P(),
            anchor_state,
        )

        def body(anchor, inputs):
            idx = jax.lax.axis_index("entity")
            offset = idx.astype(jnp.int32) * jnp.int32(local_n)
            outs, parts_hi, parts_lo = inner.run_kernel(
                anchor, inputs, offset
            )
            # the ONLY cross-shard collective: wraparound partial-checksum
            # sums ride ICI; the rollout itself is embarrassingly local
            parts_hi = jax.lax.psum(parts_hi, "entity")
            parts_lo = jax.lax.psum(parts_lo, "entity")
            return inner.finish(outs, parts_hi, parts_lo, anchor["frame"], L)

        from ..parallel.sharded import shard_map as _shard_map

        shard_fn = _shard_map(
            body,
            mesh=self.mesh,
            in_specs=(s_specs, P()),
            out_specs=(t_specs, P(), P()),
            # pallas outputs defeat replication inference; the replicated
            # outs (checksums) are computed identically on every shard
            # from psum'd totals
            check_vma=False,
        )
        return shard_fn(anchor_state, beam_inputs)
