"""Generate (or record) a human input trace for the latency demo.

The reference's playable driver reads a keyboard at 60 fps
(/root/reference/examples/ex_game/ex_game_p2p.rs:160-321 key polling via
macroquad); a TPU host has no keyboard, so the latency demo
(`ex_game_p2p.py --trace`) replays a RECORDED trace instead. Two sources:

- `--from-tty`: record a real keyboard session — raw-mode stdin sampled at
  60 fps for `--seconds`; keys a/d/w/s map to the ex_game direction bits,
  space to thrust. Requires a TTY.
- default (no TTY): synthesize from a human-motor model — per-player
  press/hold/release processes with lognormal hold lengths (median ~280 ms
  — held inputs, not per-frame noise), reaction-time gaps, occasional
  double-taps, and value persistence (players re-press recent chords).
  Deterministic under --seed.

Output: JSON {fps, seconds, players: [[byte/frame...], ...]} consumed by
`ex_game_p2p.py --trace`.
"""

from __future__ import annotations

import argparse
import json
import math
import random
import sys


def synth_player(rng: random.Random, frames: int) -> list:
    out = []
    recent = [1, 4]  # recently-used chords (direction bits)
    t = 0
    cur = 0
    while t < frames:
        if cur == 0:
            # idle gap: reaction time + decision, 60-400 ms
            gap = int(rng.lognormvariate(math.log(0.12), 0.5) * 60) + 1
            out += [0] * min(gap, frames - t)
            t += gap
            # choose next chord: mostly a recent one (motor habit)
            if rng.random() < 0.7 and recent:
                cur = rng.choice(recent)
            else:
                cur = rng.randrange(1, 16)
                recent = ([cur] + recent)[:3]
        else:
            # hold: lognormal, median ~280 ms
            hold = int(rng.lognormvariate(math.log(0.28), 0.6) * 60) + 1
            out += [cur] * min(hold, frames - t)
            t += hold
            if rng.random() < 0.15:
                # double-tap: brief release then re-press the same chord
                gap = 1 + int(rng.random() * 3)
                out += [0] * min(gap, max(frames - t, 0))
                t += gap
                # cur unchanged -> re-press on next loop iteration
            else:
                cur = 0
    return out[:frames]


def record_tty(seconds: float, fps: int) -> list:
    import select
    import termios
    import time
    import tty

    fd = sys.stdin.fileno()
    old = termios.tcgetattr(fd)
    frames = int(seconds * fps)
    out = []
    keymap = {"w": 1, "s": 2, "a": 4, "d": 8}
    held = 0
    print(f"recording {seconds:.0f}s at {fps}fps; keys wasd, q to stop")
    try:
        tty.setcbreak(fd)
        t0 = time.perf_counter()
        for i in range(frames):
            while select.select([sys.stdin], [], [], 0)[0]:
                ch = sys.stdin.read(1)
                if ch == "q":
                    return out
                held = keymap.get(ch, held and 0)
            out.append(held)
            target = t0 + (i + 1) / fps
            dt = target - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, old)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("out", help="output trace path (JSON)")
    ap.add_argument("--players", type=int, default=2)
    ap.add_argument("--seconds", type=float, default=30.0)
    ap.add_argument("--fps", type=int, default=60)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--from-tty", action="store_true")
    args = ap.parse_args()

    frames = int(args.seconds * args.fps)
    if args.from_tty:
        streams = [record_tty(args.seconds, args.fps)]
        streams += [
            synth_player(random.Random(args.seed + p), frames)
            for p in range(1, args.players)
        ]
    else:
        streams = [
            synth_player(random.Random(args.seed + p), frames)
            for p in range(args.players)
        ]
    with open(args.out, "w") as fh:
        json.dump(
            {"fps": args.fps, "seconds": args.seconds, "players": streams},
            fh,
        )
    holds = [
        sum(1 for i in range(1, len(s)) if s[i] != s[i - 1])
        for s in streams
    ]
    print(
        f"wrote {args.out}: {len(streams)} players x {frames} frames, "
        f"~{[h // 2 for h in holds]} presses"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
