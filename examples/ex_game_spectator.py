"""Spectator driver (reference: examples/ex_game/ex_game_spectator.rs).

Connects to a P2P host that registered us with --spectators and replays its
confirmed inputs:

    python examples/ex_game_spectator.py --local-port 7002 --host localhost:7000 --num-players 2
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from examples.ex_game_common import FPS, HostGame
from ggrs_tpu import (
    NotSynchronized,
    PredictionThreshold,
    SessionBuilder,
    SpectatorTooFarBehind,
)
from ggrs_tpu.network.sockets import UdpNonBlockingSocket


def parse_addr(s: str):
    import socket

    host, port = s.rsplit(":", 1)
    # sessions route inbound packets by exact address equality, and UDP
    # receive reports numeric IPs — so resolve hostnames up front
    return (socket.gethostbyname(host), int(port))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--local-port", type=int, required=True)
    ap.add_argument("--host", required=True)
    ap.add_argument("--num-players", type=int, default=2)
    ap.add_argument("--frames", type=int, default=600)
    ap.add_argument("--entities", type=int, default=4096)
    ap.add_argument(
        "--native",
        action="store_true",
        help="run on the C++ session core (requires `make -C native`)",
    )
    ap.add_argument(
        "--auth-key",
        default=None,
        help="32 hex chars: authenticate every datagram (SipHash-2-4)",
    )
    ap.add_argument(
        "--replay-protect",
        action="store_true",
        help="with --auth-key: drop replayed datagrams too (all peers must "
        "enable it together)",
    )
    args = ap.parse_args()
    if args.replay_protect and not args.auth_key:
        ap.error("--replay-protect requires --auth-key")

    builder = (
        SessionBuilder(input_size=1)
        .with_num_players(args.num_players)
        .with_fps(FPS)
        .with_max_frames_behind(10)
        .with_catchup_speed(2)
    )
    if args.native:
        builder = builder.with_native_sessions(True)
    sock = UdpNonBlockingSocket(args.local_port)
    if args.auth_key:
        from ggrs_tpu.network.auth import AuthenticatedSocket

        sock = AuthenticatedSocket(
            sock, bytes.fromhex(args.auth_key), replay_protect=args.replay_protect
        )
    sess = builder.start_spectator_session(parse_addr(args.host), sock)
    game = HostGame(args.num_players, args.entities)

    frames = 0
    last = time.perf_counter()
    accumulator = 0.0
    while frames < args.frames:
        now = time.perf_counter()
        accumulator += now - last
        last = now

        sess.poll_remote_clients()
        for event in sess.events():
            print("event:", event)

        while accumulator > 1.0 / FPS:
            accumulator -= 1.0 / FPS
            try:
                requests = sess.advance_frame()
                frames += len(requests)
                game.handle_requests(requests)
                if frames % 120 == 0:
                    print(game.digest(), f"(behind host: {sess.frames_behind_host()})")
            except PredictionThreshold:
                pass  # host input not here yet
            except NotSynchronized:
                pass
            except SpectatorTooFarBehind:
                print("fell too far behind the host; giving up")
                return 1
        time.sleep(0.001)

    print("done:", game.digest())
    return 0


if __name__ == "__main__":
    sys.exit(main())
