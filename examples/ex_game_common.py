"""Shared driver glue for the example games (reference: examples/ex_game/).

Headless: instead of rendering ships, the drivers print periodic state
digests. The game itself is the framework's flagship device model
(ggrs_tpu.models.ex_game) run through the fused TPU backend, or — for the
host path — the numpy oracle fulfilling requests one by one.
"""

from __future__ import annotations

import numpy as np

from ggrs_tpu import AdvanceFrame, InputStatus, LoadGameState, SaveGameState
from ggrs_tpu.models import ex_game
from ggrs_tpu.ops.fixed_point import combine_checksum

FPS = 60

# scripted "keyboards": deterministic pseudo-input per player per frame
def scripted_input(frame: int, handle: int) -> bytes:
    x = (frame * (handle * 7 + 3)) >> 2
    return bytes([(x ^ (x >> 3)) & 0xF])


class HostGame:
    """Fulfills requests against the numpy oracle (the reference-style user
    side: save/load/advance callbacks on host, ex_game.rs:76-98)."""

    def __init__(self, num_players: int, num_entities: int = 4096):
        self.num_players = num_players
        self.state = ex_game.init_oracle(num_players, num_entities)
        self.last_checksum = (0, 0)

    def handle_requests(self, requests) -> None:
        for req in requests:
            if isinstance(req, SaveGameState):
                assert int(self.state["frame"]) == req.frame
                req.cell.save(
                    req.frame,
                    {k: np.copy(v) for k, v in self.state.items()},
                    combine_checksum(*ex_game.checksum_oracle(self.state)),
                )
            elif isinstance(req, LoadGameState):
                self.state = {k: np.copy(v) for k, v in req.cell.load().items()}
            elif isinstance(req, AdvanceFrame):
                inputs = np.array([b[0] for b, _ in req.inputs], dtype=np.uint8)
                statuses = np.array([int(s) for _, s in req.inputs], dtype=np.int32)
                self.state = ex_game.step_oracle(
                    self.state, inputs, statuses, self.num_players
                )
                self.last_checksum = (
                    int(self.state["frame"]),
                    combine_checksum(*ex_game.checksum_oracle(self.state)),
                )

    def digest(self) -> str:
        f, cs = self.last_checksum
        p0 = self.state["pos"][0]
        return f"frame {f:5d} checksum {cs:#034x} entity0 @ ({int(p0[0])},{int(p0[1])})"
