"""SyncTest driver (reference: examples/ex_game/ex_game_synctest.rs).

Runs the flagship 4096-entity world under the determinism harness: every
frame rolls back `--check-distance` frames, resimulates on device in one
fused dispatch, and compares checksums against history.

    python examples/ex_game_synctest.py --frames 300 --check-distance 7
    python examples/ex_game_synctest.py --host   # numpy request-by-request
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from examples.ex_game_common import HostGame, scripted_input
from ggrs_tpu import MismatchedChecksum, SessionBuilder


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--players", type=int, default=2)
    ap.add_argument("--frames", type=int, default=300)
    ap.add_argument("--check-distance", type=int, default=7)
    ap.add_argument("--max-prediction", type=int, default=8)
    ap.add_argument("--input-delay", type=int, default=0)
    ap.add_argument("--entities", type=int, default=4096)
    ap.add_argument("--host", action="store_true", help="numpy host path instead of TPU")
    ap.add_argument(
        "--native",
        action="store_true",
        help="run on the C++ session core (requires `make -C native`)",
    )
    ap.add_argument(
        "--model",
        choices=["ex_game", "arena", "swarm"],
        default="ex_game",
        help="which model family to run (device path only)",
    )
    ap.add_argument(
        "--fused",
        choices=["xla", "pallas", "pallas-tiled"],
        default=None,
        help="run the FULLY-FUSED device session (60 ticks per dispatch, "
        "ring/history/verdict device-resident) on the chosen kernel "
        "instead of the per-tick request path",
    )
    ap.add_argument(
        "--device-verify",
        action="store_true",
        help="request path: keep the SyncTest checksum history and verdict "
        "on device (zero readbacks until the final check)",
    )
    args = ap.parse_args()

    if args.fused and (args.host or args.native or args.device_verify):
        ap.error(
            "--fused bypasses the request path entirely; it cannot combine "
            "with --host, --native or --device-verify"
        )
    if args.fused == "pallas-tiled" and args.model == "arena":
        ap.error(
            "arena's cross-entity centroids are not tileable; use --fused "
            "pallas or --fused xla for the arena family"
        )
    if args.device_verify and (args.host or args.native):
        ap.error(
            "--device-verify needs the device backend (the verdict lives on "
            "device); it cannot combine with --host or --native"
        )

    if args.fused:
        return run_fused(args)

    builder = (
        SessionBuilder(input_size=1)
        .with_num_players(args.players)
        .with_max_prediction_window(args.max_prediction)
        .with_check_distance(args.check_distance)
        .with_input_delay(args.input_delay)
    )
    if args.native:
        builder = builder.with_native_sessions(True)
    if args.device_verify:
        builder = builder.with_device_checksum_verification()
    sess = builder.start_synctest_session()

    if args.host:
        game = HostGame(args.players, args.entities)
        digest = game.digest
    else:
        from ggrs_tpu.models import Arena, ExGame, Swarm
        from ggrs_tpu.tpu import TpuRollbackBackend

        model_cls = {"arena": Arena, "swarm": Swarm}.get(args.model, ExGame)
        game = TpuRollbackBackend(
            model_cls(args.players, args.entities),
            max_prediction=args.max_prediction,
            num_players=args.players,
            device_verify=args.device_verify,
        )

        def digest() -> str:
            st = game.state_numpy()
            p0 = st["pos"][0]
            extra = f" hp0={int(st['hp'][0])}" if "hp" in st else ""
            return (
                f"frame {int(st['frame']):5d} entity0 @ "
                f"({int(p0[0])},{int(p0[1])}){extra}"
            )

    t0 = time.perf_counter()
    try:
        for frame in range(args.frames):
            for handle in range(args.players):
                sess.add_local_input(handle, scripted_input(frame, handle))
            game.handle_requests(sess.advance_frame())
            if frame % 60 == 0:
                print(digest())
        if args.device_verify:
            game.check()  # the run's single device readback
    except MismatchedChecksum as exc:
        print(f"DESYNC: {exc}")
        return 1
    dt = time.perf_counter() - t0
    resim = args.frames * args.check_distance
    print(
        f"ok: {args.frames} frames, {resim} rollback-frames resimulated in "
        f"{dt:.3f}s ({resim / dt:.0f} frames/s)"
    )
    return 0


def run_fused(args) -> int:
    """The fully-fused session: batches of 60 ticks per device dispatch."""
    import numpy as np

    from ggrs_tpu.models import Arena, ExGame, Swarm
    from ggrs_tpu.tpu import TpuSyncTestSession
    from ggrs_tpu.utils.barrier import true_barrier

    model_cls = {"arena": Arena, "swarm": Swarm}.get(args.model, ExGame)
    sess = TpuSyncTestSession(
        model_cls(args.players, args.entities),
        num_players=args.players,
        check_distance=args.check_distance,
        input_delay=args.input_delay,
        flush_interval=60,
        backend=args.fused,
    )
    batch = 60
    script = np.zeros((args.frames, args.players, 1), dtype=np.uint8)
    for f in range(args.frames):
        for h in range(args.players):
            script[f, h, 0] = scripted_input(f, h)[0]
    t0 = time.perf_counter()
    try:
        for start in range(0, args.frames, batch):
            sess.advance_frames(script[start : start + batch])
        sess.check()
        true_barrier(sess.carry["state"])
    except MismatchedChecksum as exc:
        print(f"DESYNC: {exc}")
        return 1
    dt = time.perf_counter() - t0
    st = sess.state_numpy()
    resim = args.frames * args.check_distance
    print(
        f"fused[{args.fused}] frame {int(st['frame'])}: {resim} "
        f"rollback-frames in {dt:.3f}s ({resim / dt:.0f} frames/s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
