"""P2P driver (reference: examples/ex_game/ex_game_p2p.rs).

Runs one side of a 2-player session over real UDP with a 60fps accumulator
loop, slowing 10% when ahead of the remote (the reference's throttling,
ex_game_p2p.rs:91-94). Start both sides:

    python examples/ex_game_p2p.py --local-port 7000 --players localhost:7001 local --handle 0 &
    python examples/ex_game_p2p.py --local-port 7001 --players local localhost:7000 --handle 1

`--players` takes one entry per handle: `local` or `host:port`.
Spectators attach with `--spectators host:port ...`.
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from examples.ex_game_common import FPS, HostGame, scripted_input
from ggrs_tpu import (
    NotSynchronized,
    PlayerType,
    PredictionThreshold,
    SessionBuilder,
    SessionState,
)
from ggrs_tpu.errors import GGRSError
from ggrs_tpu.network.sockets import UdpNonBlockingSocket


def parse_addr(s: str):
    import socket

    host, port = s.rsplit(":", 1)
    # sessions route inbound packets by exact address equality, and UDP
    # receive reports numeric IPs — so resolve hostnames up front
    return (socket.gethostbyname(host), int(port))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--local-port", type=int, required=True)
    ap.add_argument("--players", nargs="+", required=True)
    ap.add_argument("--spectators", nargs="*", default=[])
    ap.add_argument("--frames", type=int, default=600)
    ap.add_argument("--input-delay", type=int, default=2)
    ap.add_argument("--entities", type=int, default=4096)
    ap.add_argument(
        "--native",
        action="store_true",
        help="run on the C++ session core (requires `make -C native`)",
    )
    ap.add_argument(
        "--tpu",
        action="store_true",
        help="fulfill requests on the device backend (one fused dispatch "
        "per tick) instead of the numpy host oracle",
    )
    ap.add_argument(
        "--beam",
        type=int,
        default=0,
        help="with --tpu: speculative input-beam width (0 = off); the "
        "speculation launch runs in loop idle time and stands down "
        "automatically when the frame budget cannot absorb its cost",
    )
    ap.add_argument(
        "--lazy-ticks",
        type=int,
        default=0,
        help="with --tpu: buffer up to N ticks per fused device dispatch "
        "(amortizes the per-program dispatch floor; the periodic digest "
        "still flushes, so rendering-style loops behave per-tick)",
    )
    ap.add_argument(
        "--auth-key",
        default=None,
        help="32 hex chars: authenticate every datagram (SipHash-2-4); all "
        "peers must share the key",
    )
    ap.add_argument(
        "--replay-protect",
        action="store_true",
        help="with --auth-key: drop replayed datagrams too (all peers must "
        "enable it together)",
    )
    ap.add_argument(
        "--transport",
        choices=("udp", "tcp"),
        default="udp",
        help="L1 transport: udp (default) or the TCP-backed datagram "
        "socket (the pluggable-transport seam; all peers must match)",
    )
    ap.add_argument(
        "--record",
        default=None,
        metavar="PATH",
        help="record the match: the confirmed input stream saves to PATH "
        "at exit (replay with examples/replay.py — bit-identical)",
    )
    ap.add_argument(
        "--trace",
        default=None,
        metavar="PATH",
        help="drive local players from a recorded human input trace (JSON "
        "{fps, players: [[byte,...],...]}; see examples/traces/) instead "
        "of the scripted stream — the latency-demo configuration "
        "(reference analog: the playable ex_game_p2p.rs driver)",
    )
    ap.add_argument(
        "--budget-report",
        action="store_true",
        help="at exit, print per-frame critical-path latency stats and "
        "the 60fps frame-budget hit rate as one JSON line",
    )
    args = ap.parse_args()
    trace = None
    if args.trace:
        import json as _json

        with open(args.trace) as fh:
            trace = _json.load(fh)
        assert trace.get("players"), "trace has no player streams"
    if args.replay_protect and not args.auth_key:
        ap.error("--replay-protect requires --auth-key")

    builder = (
        SessionBuilder(input_size=1)
        .with_num_players(len(args.players))
        .with_input_delay(args.input_delay)
        .with_fps(FPS)
    )
    if args.native:
        builder = builder.with_native_sessions(True)
    local_handles = []
    for handle, spec in enumerate(args.players):
        if spec == "local":
            builder = builder.add_player(PlayerType.local(), handle)
            local_handles.append(handle)
        else:
            builder = builder.add_player(PlayerType.remote(parse_addr(spec)), handle)
    for i, spec in enumerate(args.spectators):
        builder = builder.add_player(
            PlayerType.spectator(parse_addr(spec)), len(args.players) + i
        )

    if args.tpu:
        from ggrs_tpu.models.ex_game import ExGame
        from ggrs_tpu.tpu import TpuRollbackBackend

        backend = TpuRollbackBackend(
            ExGame(len(args.players), args.entities),
            max_prediction=builder.max_prediction,
            num_players=len(args.players),
            beam_width=args.beam,
            # real-time loop: launch speculation from idle time, stand
            # down when the budget can't absorb it, and batch ticks when
            # nothing needs device results between digests
            speculation_gate="adaptive",
            defer_speculation=bool(args.beam),
            lazy_ticks=args.lazy_ticks,
        )
        # compile before the session even exists: the first jit would stall
        # the 60fps loop past the peers' disconnect timeout
        backend.warmup()

    if args.transport == "tcp":
        from ggrs_tpu.network.tcp_socket import TcpDatagramSocket

        sock = TcpDatagramSocket(args.local_port)
    else:
        sock = UdpNonBlockingSocket(args.local_port)
    if args.auth_key:
        from ggrs_tpu.network.auth import AuthenticatedSocket

        sock = AuthenticatedSocket(
            sock, bytes.fromhex(args.auth_key), replay_protect=args.replay_protect
        )
    sess = builder.start_p2p_session(sock)
    recorder = None
    if args.record:
        from ggrs_tpu.utils.replay import InputRecorder

        recorder = InputRecorder()
    if args.tpu:

        class DeviceGameDriver:
            handle_requests = staticmethod(backend.handle_requests)

            @staticmethod
            def digest() -> str:
                st = backend.state_numpy()
                p0 = st["pos"][0]
                hits = (
                    f" beam {backend.beam_hits}+{backend.beam_partial_hits}p"
                    f"/{backend.beam_hits + backend.beam_partial_hits + backend.beam_misses}"
                    f" served {backend.rollback_frames_adopted}"
                    f"/{backend.rollback_frames} gated {backend.beam_gated}"
                    if args.beam
                    else ""
                )
                return (
                    f"frame {int(st['frame']):5d} entity0 @ "
                    f"({int(p0[0])},{int(p0[1])}){hits}"
                )

        game = DeviceGameDriver()
    else:
        game = HostGame(len(args.players), args.entities)

    def local_input(frame: int, handle: int) -> bytes:
        if trace is not None:
            stream = trace["players"][handle % len(trace["players"])]
            return bytes([stream[frame % len(stream)] & 0x0F])
        return scripted_input(frame, handle)

    # accumulator loop (ex_game_p2p.rs:80-129)
    frame = 0
    last = time.perf_counter()
    accumulator = 0.0
    frame_ms = []  # per-frame critical-path time (inputs -> requests done)
    skipped = 0  # prediction-threshold stalls (remote too far behind)
    wall_t0 = time.perf_counter()
    while frame < args.frames:
        now = time.perf_counter()
        accumulator += now - last
        last = now

        # run slower when ahead so remotes can catch up
        fps_delta = 1.0 / FPS
        if sess.frames_ahead_estimate() > 0:
            fps_delta *= 1.1

        sess.poll_remote_clients()
        for event in sess.events():
            print("event:", event)

        while accumulator > fps_delta:
            accumulator -= fps_delta
            if sess.current_state() != SessionState.RUNNING:
                continue
            try:
                t0 = time.perf_counter()
                for handle in local_handles:
                    sess.add_local_input(handle, local_input(frame, handle))
                reqs = sess.advance_frame()
                if recorder is not None:
                    recorder.observe(reqs)
                game.handle_requests(reqs)
                frame_ms.append((time.perf_counter() - t0) * 1000.0)
                frame += 1
                if frame % 120 == 0:
                    print(game.digest())
            except PredictionThreshold:
                skipped += 1  # skip a frame; remote is behind
            except NotSynchronized:
                pass
        if args.tpu and args.beam:
            # idle-time work: the deferred speculation launch happens after
            # the frame's critical path, exactly where a renderer would be
            backend.launch_pending_speculation()
        time.sleep(0.001)

    wall_s = time.perf_counter() - wall_t0
    print("done:", game.digest())
    if args.budget_report and frame_ms:
        import json as _json

        xs = sorted(frame_ms)
        q = lambda p: round(xs[min(int(p * len(xs)), len(xs) - 1)], 3)
        budget = 1000.0 / FPS
        print(
            _json.dumps(
                {
                    "frames": len(xs),
                    "budget_ms": round(budget, 3),
                    # the latency-demo headline: fraction of frames whose
                    # critical path (input ingest -> session advance ->
                    # request fulfillment dispatch) fit the 60fps budget
                    "budget_hit_rate": round(
                        sum(x <= budget for x in xs) / len(xs), 4
                    ),
                    "frame_p50_ms": q(0.50),
                    "frame_p95_ms": q(0.95),
                    "frame_p99_ms": q(0.99),
                    "frame_max_ms": round(xs[-1], 3),
                    "skipped_frames": skipped,
                    "achieved_fps": round(len(xs) / wall_s, 1),
                    "trace": args.trace or "scripted",
                }
            ),
            flush=True,
        )
    if recorder is not None:
        from ggrs_tpu.models.ex_game import ExGame as _ExGame

        recorder.confirm_through(sess.confirmed_frame() - 1)
        try:
            # both paths simulate ex_game dynamics (HostGame is its numpy
            # oracle), so the identity stamp is always ExGame-shaped —
            # replays against the wrong world must refuse loudly
            recorder.save(
                args.record,
                game=_ExGame(len(args.players), args.entities),
            )
            print(
                f"recorded {recorder.confirmed_frames} confirmed frames -> "
                f"{args.record}"
            )
        except ValueError:
            print("no confirmed frames at exit; nothing recorded")
    return 0


if __name__ == "__main__":
    sys.exit(main())
