"""Replay player: re-simulate a recorded match to bit-identical state.

    python examples/replay.py match.npz [--model ex_game] [--players 2] \
        [--entities 4096]

Recordings come from `examples/ex_game_p2p.py --record match.npz` (or any
code using ggrs_tpu.utils.replay.InputRecorder). The replay runs the
confirmed input stream from the initial world through fused multi-tick
device dispatches — determinism makes the result identical to what every
peer computed live, which this prints as the final digest + checksum.
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path", help="recording (.npz) to replay")
    ap.add_argument("--model", choices=["ex_game", "arena", "swarm"],
                    default="ex_game")
    ap.add_argument("--players", type=int, default=2)
    ap.add_argument("--entities", type=int, default=4096)
    args = ap.parse_args()

    from ggrs_tpu.models import Arena, ExGame, Swarm
    from ggrs_tpu.ops.fixed_point import combine_checksum
    from ggrs_tpu.utils.replay import load_replay, replay_to_state

    model_cls = {"arena": Arena, "swarm": Swarm}.get(args.model, ExGame)
    game = model_cls(args.players, args.entities)
    inputs, statuses = load_replay(args.path, game)
    print(f"replaying {inputs.shape[0]} confirmed frames "
          f"({args.model}, {args.entities} entities, {args.players} players)")

    t0 = time.perf_counter()
    final = replay_to_state(game, inputs, statuses)
    import jax
    import numpy as np

    jax.block_until_ready(final)
    hi, lo = jax.device_get(game.checksum(jax.device_put(final)))
    dt = time.perf_counter() - t0
    p0 = np.asarray(final["pos"])[0]
    print(
        f"done in {dt:.3f}s: frame {int(np.asarray(final['frame']))}, "
        f"entity0 @ ({int(p0[0])},{int(p0[1])}), "
        f"checksum {combine_checksum(int(hi), int(lo)):#034x}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
