"""Replay player: re-simulate a recorded match to bit-identical state.

    python examples/replay.py match.npz [--model ex_game] [--players 2] \
        [--entities 4096]

Recordings come from `examples/ex_game_p2p.py --record match.npz` (or any
code using ggrs_tpu.utils.replay.InputRecorder). The replay runs the
confirmed input stream from the initial world through fused multi-tick
device dispatches — determinism makes the result identical to what every
peer computed live, which this prints as the final digest + checksum.

Forensics (ggrs_tpu.utils.replay composed with utils.checkpoint):
    --save-seek out.npz    persist the final state as a SEEK POINT; a
                           later replay of a longer recording of the same
                           match resumes from it (--seek-from) instead of
                           frame 0
    --seek-from ckpt.npz   resume the replay from a seek point
    --postmortem hist.json desync post-mortem: compare the replay's
                           per-frame checksums against a peer's recorded
                           history (a JSON {frame: combined_checksum}
                           map, e.g. json.dump of
                           session.local_checksum_history) and report the
                           FIRST mismatching frame with both values
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("path", help="recording (.npz) to replay")
    ap.add_argument("--model", choices=["ex_game", "arena", "swarm"],
                    default="ex_game")
    ap.add_argument("--players", type=int, default=2)
    ap.add_argument("--entities", type=int, default=4096)
    ap.add_argument("--save-seek", metavar="OUT",
                    help="persist the final state as a replay seek point")
    ap.add_argument("--seek-from", metavar="CKPT",
                    help="resume the replay from a seek point")
    ap.add_argument("--postmortem", metavar="HIST",
                    help="JSON {frame: checksum} peer history to compare")
    args = ap.parse_args()

    from ggrs_tpu.models import Arena, ExGame, Swarm
    from ggrs_tpu.ops.fixed_point import combine_checksum
    from ggrs_tpu.utils.replay import (
        desync_postmortem,
        load_replay,
        load_seek_checkpoint,
        replay_to_state,
        save_seek_checkpoint,
    )

    model_cls = {"arena": Arena, "swarm": Swarm}.get(args.model, ExGame)
    game = model_cls(args.players, args.entities)
    inputs, statuses = load_replay(args.path, game)
    start_state, start_frame = None, 0
    if args.seek_from:
        start_state, start_frame = load_seek_checkpoint(args.seek_from, game)
        print(f"seeking: resume from checkpointed frame {start_frame}")
    print(f"replaying {inputs.shape[0] - start_frame} confirmed frames "
          f"({args.model}, {args.entities} entities, {args.players} players)")

    if args.postmortem:
        import json

        with open(args.postmortem) as f:
            peer = {int(k): int(v) for k, v in json.load(f).items()}
        verdict = desync_postmortem(
            game, inputs, statuses, peer,
            start_state=start_state, start_frame=start_frame,
        )
        if verdict is None:
            print(f"postmortem: all {len(peer)} recorded checksums agree "
                  "with the replay — no divergence in this recording")
            return 0
        frame, ours, theirs = verdict
        print(f"postmortem: FIRST DIVERGENCE at frame {frame}: "
              f"replay {ours:#034x} vs peer {theirs:#034x}")
        return 2

    t0 = time.perf_counter()
    final = replay_to_state(
        game, inputs, statuses, start_state=start_state,
        start_frame=start_frame,
    )
    import jax
    import numpy as np

    jax.block_until_ready(final)
    hi, lo = jax.device_get(game.checksum(jax.device_put(final)))
    dt = time.perf_counter() - t0
    p0 = np.asarray(final["pos"])[0]
    print(
        f"done in {dt:.3f}s: frame {int(np.asarray(final['frame']))}, "
        f"entity0 @ ({int(p0[0])},{int(p0[1])}), "
        f"checksum {combine_checksum(int(hi), int(lo)):#034x}"
    )
    if args.save_seek:
        save_seek_checkpoint(args.save_seek, final, game)
        print(f"seek point saved: {args.save_seek} "
              f"(frame {int(np.asarray(final['frame']))})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
