"""Lazy tick batching (TpuRollbackBackend(lazy_ticks=N)): ticks accumulate
as packed control words and dispatch as ONE fused multi-tick program when
the buffer fills or a device result is needed. On the tunneled device each
dispatch costs ~1ms of host time regardless of content, so this divides
the interactive request path's dominant cost by the buffer depth — while
staying bit-identical to per-tick dispatch (these tests are the proof)."""

import numpy as np
import pytest

from ggrs_tpu import SessionBuilder
from ggrs_tpu.models.ex_game import ExGame
from ggrs_tpu.tpu import TpuRollbackBackend

ENTITIES = 64
PLAYERS = 2


def make_backend(lazy_ticks=0, **kw):
    return TpuRollbackBackend(
        ExGame(num_players=PLAYERS, num_entities=ENTITIES),
        max_prediction=6,
        num_players=PLAYERS,
        lazy_ticks=lazy_ticks,
        **kw,
    )


def make_synctest(check_distance=4):
    return (
        SessionBuilder(input_size=1)
        .with_num_players(PLAYERS)
        .with_max_prediction_window(6)
        .with_check_distance(check_distance)
        .start_synctest_session()
    )


def drive_pair(lazy, plain, ticks, inputs_for):
    sess_lazy, sess_plain = make_synctest(), make_synctest()
    lazy_cells, plain_cells = [], []
    for t in range(ticks):
        for h in range(PLAYERS):
            buf = inputs_for(t, h)
            sess_lazy.add_local_input(h, buf)
            sess_plain.add_local_input(h, buf)
        rl = sess_lazy.advance_frame()
        rp = sess_plain.advance_frame()
        lazy.handle_requests(rl)
        plain.handle_requests(rp)
        lazy_cells += [r.cell for r in rl if hasattr(r, "cell")]
        plain_cells += [r.cell for r in rp if hasattr(r, "cell")]
    return lazy_cells, plain_cells


def assert_states_equal(a, b):
    sa, sb = a.state_numpy(), b.state_numpy()
    for k in sa:
        np.testing.assert_array_equal(np.asarray(sa[k]), np.asarray(sb[k]),
                                      err_msg=f"state[{k}]")


@pytest.mark.parametrize("lazy_ticks", [3, 8])
def test_lazy_bit_parity_with_per_tick_dispatch(lazy_ticks):
    """Same SyncTest request stream (forced rollbacks included, buffered
    mid-stream) through a lazy and a per-tick backend: final state and
    EVERY saved checksum bit-identical. Checksums resolve through the
    future batch, which forces the flush."""
    lazy, plain = make_backend(lazy_ticks), make_backend(0)
    lc, pc = drive_pair(
        lazy, plain, 25, lambda t, h: bytes([(t * (3 + h) + h) % 16])
    )
    assert_states_equal(lazy, plain)
    assert len(lc) == len(pc)
    for cl, cp in zip(lc, pc):
        assert cl.frame == cp.frame
        assert cl.checksum == cp.checksum, f"checksum at frame {cl.frame}"


def test_lazy_state_fetch_flushes_mid_buffer():
    """state_numpy() between flush points must materialize the buffered
    ticks (the rendering path gets per-tick behavior automatically)."""
    lazy, plain = make_backend(8), make_backend(0)
    sess_lazy, sess_plain = make_synctest(), make_synctest()
    for t in range(9):
        for h in range(PLAYERS):
            sess_lazy.add_local_input(h, bytes([t % 7]))
            sess_plain.add_local_input(h, bytes([t % 7]))
        lazy.handle_requests(sess_lazy.advance_frame())
        plain.handle_requests(sess_plain.advance_frame())
        # mid-buffer fetch every tick: identical to per-tick dispatch
        assert_states_equal(lazy, plain)


def test_lazy_composes_with_beam():
    """Lazy batching + speculation: the rollout flushes the buffer before
    anchoring, adoptions flush before committing — still bit-identical."""
    lazy = make_backend(4, beam_width=8)
    plain = make_backend(0)
    drive_pair(lazy, plain, 30, lambda t, h: bytes([3 + 2 * h]))
    assert_states_equal(lazy, plain)
    assert lazy.beam_hits > 0  # constant inputs: adoptions must still fire


def test_lazy_checkpoint_flushes(tmp_path):
    """save() must not checkpoint a stale (pre-flush) device state."""
    lazy, plain = make_backend(8), make_backend(0)
    drive_pair(lazy, plain, 10, lambda t, h: bytes([t % 5]))
    path = str(tmp_path / "lazy.npz")
    lazy.save(path)
    restored = TpuRollbackBackend.restore(
        path, ExGame(num_players=PLAYERS, num_entities=ENTITIES)
    )
    assert_states_equal(restored, plain)
    assert restored.current_frame == lazy.current_frame
